"""Bench the GROUPING SETS / ROLLUP / CUBE device-union path (VERDICT
r4 missing #4 "and a bench number") against the whole-statement pandas
fallback on the cached SSB dataset. Banks BENCH_GSETS.json.

Usage: python tools/bench_gsets.py   [GSETS_ROWS=6000000 GSETS_ITERS=5]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = {
    "rollup2": "SELECT brand, dyear, sum(revenue) AS rev, count(*) AS n "
               "FROM ssb GROUP BY ROLLUP(brand, dyear)",
    "cube2": "SELECT region, dyear, sum(revenue) AS rev "
             "FROM ssb GROUP BY CUBE(region, dyear)",
    "gsets3": "SELECT brand, region, dyear, sum(revenue) AS rev "
              "FROM ssb GROUP BY GROUPING SETS "
              "((brand, dyear), (region), ())",
}


def main():
    from tpu_olap.utils.platform import env_flag, force_cpu_platform
    if env_flag("BENCH_FORCE_CPU") or os.environ.get("JAX_PLATFORMS"):
        force_cpu_platform()
    import importlib.util

    import numpy as np
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    from tpu_olap import Engine
    from tpu_olap.planner.fallback import execute_fallback

    rows = int(os.environ.get("GSETS_ROWS", 6_000_000))
    iters = int(os.environ.get("GSETS_ITERS", 5))
    paths, dims = bench._prepare_dataset(rows, 0)
    eng = Engine()
    # one flat table with the grouping columns materialized (the union
    # path decomposes per set; star-join collapse is bench.py's job)
    import pandas as pd
    cols = ["lo_orderdate_ts", "p_brand1", "s_region", "d_year",
            "lo_revenue"]
    lo = pd.concat([pd.read_parquet(p, columns=cols) for p in paths[:2]],
                   ignore_index=True)
    df = pd.DataFrame({
        "ts": pd.to_datetime(lo["lo_orderdate_ts"]),
        "brand": lo["p_brand1"].astype(str),
        "region": lo["s_region"].astype(str),
        "dyear": lo["d_year"].astype(np.int64),
        "revenue": lo["lo_revenue"].astype(np.int64),
    })
    eng.register_table("ssb", df, time_column="ts")

    import jax
    backend = jax.devices()[0].platform

    out = {"rows": len(df), "iters": iters, "backend": backend,
           "per_query": {}}
    for name, sql in QUERIES.items():
        eng.sql(sql)  # warm compile caches
        plan = eng.last_plan
        legs = getattr(plan, "grouping_legs", None)
        n_dev = sum(1 for lp in legs if lp.rewritten) if legs else 0
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            eng.sql(sql)
            times.append((time.perf_counter() - t0) * 1000)
        fb_times = []
        stmt = eng.planner.plan(sql).stmt
        # pure-interpreter baseline: keep derived/inner statements OFF
        # the device so the comparison is fallback-vs-device, not
        # device-vs-device
        import dataclasses
        pure_cfg = dataclasses.replace(eng.config,
                                       fallback_derived_on_device=False)
        for _ in range(max(2, iters // 2)):
            t0 = time.perf_counter()
            execute_fallback(stmt, eng.catalog, pure_cfg)
            fb_times.append((time.perf_counter() - t0) * 1000)
        import numpy as np
        dev_p50 = round(float(np.percentile(times, 50)), 1)
        fb_p50 = round(float(np.percentile(fb_times, 50)), 1)
        out["per_query"][name] = {
            "union_p50_ms": dev_p50, "fallback_p50_ms": fb_p50,
            "speedup": round(fb_p50 / dev_p50, 2) if dev_p50 else None,
            "legs": len(legs) if legs else 0,
            "legs_device": n_dev,
        }
        print(f"[gsets] {name}: union {dev_p50}ms vs fallback {fb_p50}ms "
              f"({n_dev}/{len(legs) if legs else 0} legs on device)",
              file=sys.stderr, flush=True)
    out["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(os.path.join(REPO, "BENCH_GSETS.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"ok": True, **{k: v["speedup"]
                                     for k, v in out["per_query"].items()}}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
