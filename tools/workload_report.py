"""Workload report — the cube advisor's input artifact (ISSUE 11).

Prints the query-template profile (obs.workload): top templates by
count with latency percentiles, cache hit-rates, grouping dims, and
time-granularity histograms, followed by the ranked rollup-grain
recommendations — the literal (datasource, dim-set, grain) demand
signal ROADMAP item 1's cube materializer consumes.

Three sources:

    python tools/workload_report.py --url http://host:port
        Fetch GET /debug/workload from a live QueryServer.
    python tools/workload_report.py --selftest
        Build an in-process engine, run a small mixed SSB-shaped
        workload (repeats, literal variations, a fallback statement,
        warm cache hits), then report from the engine itself AND
        assert the sys.* introspection surface answers — the CI
        workload-smoke gate. Exits non-zero when the profile or
        `SELECT COUNT(*) FROM sys.queries` comes back empty.
    ... --json   emit the raw payload as JSON instead of the table.
    ... --emit-cubes out.json
        Additionally write the ranked recommendations as
        machine-readable cube specs (tpu_olap.cubes.advisor) that the
        materializer accepts VERBATIM: load them with
        `CREATE DRUID CUBES FROM 'out.json'` or
        `Engine.create_cube(spec)` — the advisor -> materializer loop
        (docs/CUBES.md). Requires --selftest (the spec assembly needs
        the engine's catalog metadata, not just the HTTP payload).
"""

import argparse
import json
import os
import sys
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _fetch(url: str) -> dict:
    with urllib.request.urlopen(url.rstrip("/") + "/debug/workload",
                                timeout=30) as r:
        return json.loads(r.read())


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{float(v):.2f}"


def render(payload: dict, top: int = 10) -> str:
    lines = []
    totals = payload.get("totals", {})
    lines.append(
        f"workload profile: {totals.get('templates', 0)} templates, "
        f"{totals.get('observations', 0)} observations")
    lines.append("")
    lines.append("top query templates (by count):")
    header = (f"  {'template':<13}{'count':>6}{'p50ms':>9}{'p95ms':>9}"
              f"{'p99ms':>9}{'hit%':>6}  {'type':<11}{'datasource':<14}"
              f"{'grain':<7}dims")
    lines.append(header)
    for r in payload.get("templates", [])[:top]:
        grains = json.loads(r.get("granularities") or "{}")
        grain = max(grains, key=grains.get) if grains else "-"
        hitpct = 100.0 * float(r.get("cache_hit_rate") or 0.0)
        lines.append(
            f"  {r['template_id']:<13}{r['count']:>6}"
            f"{_fmt_ms(r.get('p50_ms')):>9}{_fmt_ms(r.get('p95_ms')):>9}"
            f"{_fmt_ms(r.get('p99_ms')):>9}{hitpct:>5.0f}%"
            f"  {r.get('query_type', '?'):<11}"
            f"{r.get('datasource', '?'):<14}{grain:<7}"
            f"{r.get('dims') or '-'}")
    lines.append("")
    lines.append("recommended rollup grains (cube advisor input, "
                 "ranked by wall spent):")
    recs = payload.get("recommendations", [])
    if not recs:
        lines.append("  (no aggregate templates observed yet)")
    for i, g in enumerate(recs, 1):
        dims = ",".join(g.get("dims") or []) or "(global)"
        lines.append(
            f"  {i}. {g.get('datasource')}: dims [{dims}] @ "
            f"{g.get('granularity')} — {g.get('queries')} queries, "
            f"~{g.get('est_ms_saved', 0.0):.1f} ms total wall "
            f"({len(g.get('templates', []))} templates)")
    return "\n".join(lines)


def _selftest_payload():
    """In-process engine + a small mixed SSB-shaped workload; returns
    (payload, engine). Asserts the sys.* surface answers through the
    engine's own SQL — the CI workload-smoke contract."""
    from tpu_olap.utils.platform import force_cpu_devices
    force_cpu_devices(1)
    import numpy as np
    import pandas as pd
    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    from tpu_olap.obs.workload import recommend_rollups

    rng = np.random.default_rng(42)
    n = 50_000
    lineorder = pd.DataFrame({
        "lo_orderdate": pd.to_datetime("1995-01-01") + pd.to_timedelta(
            rng.integers(0, 365 * 2, n), unit="D"),
        "lo_quantity": rng.integers(1, 50, n).astype(np.int64),
        "lo_extendedprice": rng.integers(100, 50_000, n).astype(np.int64),
        "lo_discount": rng.integers(0, 10, n).astype(np.int64),
        "p_category": rng.choice(
            [f"MFGR#{i}" for i in range(1, 6)], n),
        "s_region": rng.choice(
            ["AMERICA", "ASIA", "EUROPE", "AFRICA"], n),
    })
    eng = Engine(EngineConfig(result_cache_enabled=True,
                              segment_cache_enabled=True))
    eng.register_table("lineorder", lineorder,
                       time_column="lo_orderdate")

    q1 = ("SELECT sum(lo_extendedprice * lo_discount) AS revenue "
          "FROM lineorder WHERE year(lo_orderdate) = {y} "
          "AND lo_discount >= 1 AND lo_discount <= 3 "
          "AND lo_quantity < 25")
    q2 = ("SELECT s_region, sum(lo_extendedprice) AS rev "
          "FROM lineorder WHERE lo_discount > {d} GROUP BY s_region "
          "ORDER BY rev DESC")
    q3 = ("SELECT year(lo_orderdate) AS y, p_category, "
          "sum(lo_extendedprice) AS rev FROM lineorder "
          "GROUP BY year(lo_orderdate), p_category ORDER BY y")
    for y in (1995, 1996, 1995):        # literal variants + a repeat
        eng.sql(q1.format(y=y))
    for d in (2, 5, 2, 2):              # the last two are cache-warm
        eng.sql(q2.format(d=d))
    eng.sql(q3)
    eng.sql_batch([q2.format(d=2), q3, q1.format(y=1995)])
    # one interpreter-path statement so fallback templates appear too
    eng.sql("SELECT p_category, rank() OVER (ORDER BY sum(lo_quantity) "
            "DESC) AS r FROM lineorder GROUP BY p_category")

    n_queries = int(eng.sql(
        "SELECT COUNT(*) AS n FROM sys.queries")["n"][0])
    top = eng.sql("SELECT template_id, count, p50_ms FROM "
                  "sys.query_templates ORDER BY count DESC LIMIT 5")
    if n_queries == 0 or len(top) == 0:
        raise SystemExit(
            f"workload selftest FAILED: sys.queries={n_queries} rows, "
            f"sys.query_templates={len(top)} rows")
    rows = eng.runner.workload.snapshot()
    payload = {"totals": eng.runner.workload.totals(),
               "templates": rows,
               "recommendations": recommend_rollups(rows)}
    print(f"selftest: {n_queries} recorded queries, "
          f"{len(rows)} templates, sys.* surface OK\n")
    return payload, eng


def emit_cube_specs(eng, out_path: str, top: int = 8) -> dict:
    """Write the advisor's ranked recommendations as cube specs the
    materializer accepts verbatim (docs/CUBES.md advisor workflow)."""
    from tpu_olap.cubes import cube_specs_from_workload
    rows = eng.runner.workload.snapshot()
    specs, notes = cube_specs_from_workload(rows, eng, top=top)
    payload = {"cubes": [s.to_json() for s in specs], "notes": notes}
    with open(out_path, "w") as f:
        json.dump(payload, f, indent=1)
    print(f"wrote {len(specs)} cube spec(s) to {out_path}"
          + (f" ({len(notes)} note(s))" if notes else ""))
    return payload


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Print the query-template workload profile and "
                    "rollup-cube recommendations.")
    p.add_argument("--url", help="live QueryServer base URL "
                                 "(reads GET /debug/workload)")
    p.add_argument("--selftest", action="store_true",
                   help="CI smoke: in-process engine + SSB-shaped "
                        "workload, asserts sys.* answers non-empty")
    p.add_argument("--top", type=int, default=10,
                   help="templates to print (default 10)")
    p.add_argument("--json", action="store_true",
                   help="emit the raw payload as JSON")
    p.add_argument("--emit-cubes", metavar="OUT.json", default=None,
                   help="write the ranked recommendations as cube "
                        "specs the materializer accepts verbatim "
                        "(CREATE DRUID CUBES FROM '<file>'); needs "
                        "--selftest")
    args = p.parse_args(argv)
    if bool(args.url) == bool(args.selftest):
        p.error("pass exactly one of --url or --selftest")
    if args.emit_cubes and not args.selftest:
        p.error("--emit-cubes needs --selftest (spec assembly reads "
                "catalog metadata)")
    if args.url:
        payload, eng = _fetch(args.url), None
    else:
        payload, eng = _selftest_payload()
    if args.emit_cubes:
        emit_cube_specs(eng, args.emit_cubes, top=args.top)
    if args.json:
        print(json.dumps(payload, indent=2, default=str))
    else:
        print(render(payload, args.top))
    return 0


if __name__ == "__main__":
    sys.exit(main())
