"""Real-time ingest smoke (ISSUE 13 satellite; the `ingest-smoke` CI
job in .github/workflows/tier1.yml).

End-to-end crash-recovery contract, seconds-scale:

1. a CHILD process registers a deterministic base table with a WAL
   directory, appends batches (each acknowledged only after the WAL
   frame is durable), proves the rows are visible in the same process,
   reports the acknowledged count on stdout, then SIGKILLs itself —
   no atexit, no flush, a real crash;
2. the parent starts a fresh engine over the same WAL directory,
   registers the same base, and the WAL replays to the exact
   acknowledged state;
3. query results must be sha256-identical to a one-shot
   `register_table` of base + acknowledged rows (never-lost /
   never-half-applied), before AND after compaction seals the delta.

Exit 0 on success, 1 on any violation.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_BASE = 2000
N_BATCHES = 7
ROWS_PER_BATCH = 3
BLOCK = 512

QUERIES = [
    "SELECT g, count(*) AS n, sum(v) AS s FROM t GROUP BY g ORDER BY g",
    "SELECT month(ts) AS mo, sum(v) AS s, min(v) AS lo FROM t "
    "GROUP BY month(ts) ORDER BY mo",
    "SELECT count(*) AS n, sum(v) AS s FROM t WHERE v < 500",
]


def base_frame():
    import numpy as np
    import pandas as pd
    rng = np.random.default_rng(42)
    return pd.DataFrame({
        "ts": pd.to_datetime("2022-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 45, N_BASE),
                          unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], N_BASE),
        "v": rng.integers(0, 1000, N_BASE).astype(np.int64),
    })


def batch(i):
    return [{"ts": f"2022-05-{10 + i:02d}T00:00:0{j}",
             "g": f"s{i % 3}", "v": i * 10 + j}
            for j in range(ROWS_PER_BATCH)]


def digest(frame):
    return hashlib.sha256(frame.to_csv(index=False).encode()) \
        .hexdigest()


def make_engine(wal_dir):
    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    eng = Engine(EngineConfig(ingest_wal_dir=wal_dir,
                              ingest_auto_compact=False))
    eng.register_table("t", base_frame(), time_column="ts",
                       block_rows=BLOCK)
    return eng


def child_main(wal_dir):
    eng = make_engine(wal_dir)
    acked = 0
    for i in range(N_BATCHES):
        out = eng.append("t", batch(i))
        assert out["wal_seq"] == i + 1
        acked += out["rows"]
    # rows are visible in the SAME process, pre-crash
    n = int(eng.sql("SELECT count(*) AS n FROM t")["n"][0])
    assert n == N_BASE + acked, f"visibility: {n}"
    print(json.dumps({"acked_batches": N_BATCHES,
                      "acked_rows": acked, "visible": n}), flush=True)
    os.kill(os.getpid(), signal.SIGKILL)  # the real thing


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main(sys.argv[2])
        return 1  # unreachable

    wal_dir = tempfile.mkdtemp(prefix="ingest-smoke-wal-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child",
         wal_dir], capture_output=True, text=True, env=env,
        timeout=300)
    if proc.returncode != -signal.SIGKILL:
        print(f"FAIL: child exited {proc.returncode}, expected "
              f"SIGKILL\nstdout: {proc.stdout}\nstderr: {proc.stderr}")
        return 1
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    acked = report["acked_rows"]
    print(f"child: acked {acked} rows over "
          f"{report['acked_batches']} batches, then SIGKILL")

    # --- recovery: fresh engine + same base -> WAL replay
    eng = make_engine(wal_dir)
    delta = eng.catalog.get("t").segments.delta_rows
    if delta != acked:
        print(f"FAIL: replay restored {delta} rows, acked {acked}")
        return 1
    replay_ev = [e for e in eng.runner.events.snapshot()
                 if e["event"] == "wal_replay"]
    if not replay_ev:
        print("FAIL: no wal_replay event")
        return 1
    print(f"replay: {replay_ev[0]['records']} records, "
          f"{replay_ev[0]['rows']} rows in {replay_ev[0]['ms']} ms")

    # --- sha256 parity vs one-shot registration of the same rows
    import pandas as pd
    from tpu_olap import Engine
    extra = [r for i in range(N_BATCHES) for r in batch(i)]
    ext = pd.DataFrame(extra)
    ext["ts"] = pd.to_datetime(ext["ts"])
    ref = Engine()
    ref.register_table("t", pd.concat([base_frame(), ext],
                                      ignore_index=True),
                       time_column="ts", block_rows=BLOCK)
    for q in QUERIES:
        if digest(eng.sql(q)) != digest(ref.sql(q)):
            print(f"FAIL: post-replay parity: {q}")
            return 1
    print("post-replay parity: OK")

    # --- compaction seals the delta; results must not move
    res = eng.compact_now("t")
    if res is None or eng.catalog.get("t").segments.delta_rows != 0:
        print("FAIL: compaction did not seal the delta")
        return 1
    for q in QUERIES:
        if digest(eng.sql(q)) != digest(ref.sql(q)):
            print(f"FAIL: post-compaction parity: {q}")
            return 1
    print(f"compaction: sealed {res['rows_sealed']} rows in "
          f"{res['ms']:.0f} ms; post-compaction parity: OK")
    eng.close()
    print("ingest smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
