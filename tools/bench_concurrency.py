"""Concurrent-load artifact for the BI server (VERDICT r4 weak #6): the
reference's ThriftServer wrapper existed so N BI clients could hit
accelerated tables at once (SURVEY.md §3.1). This drives a thread pool
of mixed clients against a live QueryServer over HTTP and banks
per-class p50/p99 wall latencies, throughput, and the pipelined-vs-
serialized A/B (ISSUE 10) to BENCH_CONCURRENCY.json.

The A/B: the same workload runs twice on the same host — once with
`pipeline_depth=0` (the serialized baseline: dispatch_lock held across
the whole query, the pre-pipeline behavior) and once pipelined
(`--pipeline-depth N`, default 2: the lock held only for stage-1
enqueue; transfer/finalize/assembly overlap other queries' device
work). Each run also banks the dispatch-lock-wait split (p50/p99 from
the `dispatch_lock_wait_ms` histogram), the device-occupancy fraction,
and per-stage occupancy + queue-wait columns from the stage scheduler
(executor/stages.py — runs/busy_frac/queue_wait per plan/enqueue/
transfer/finalize/assemble pool), so the artifact shows WHERE the
throughput came from and which stage pool the load convoys on.

Parity: deterministic classes (grouped / ungrouped / fallback) compare
every response against a reference computed before the load starts;
any mismatch banks as a parity failure and fails the run.

Query classes (assigned to clients in the CLIENT_MIX ratio — the
device-path BI classes carry double weight, matching the dashboard
workload the dispatch pipeline targets):
- grouped:   device-path GROUP BY (dense, the BI hot path)      x2
- ungrouped: device-path global aggregate (cheapest dispatch)   x2
- fallback:  window function -> whole-frame pandas path          x1
- statement: EXPLAIN DRUID REWRITE (planner only, no execution)  x1

Clients pace themselves with a think time (CONC_THINK_MS, default
100 ms): a closed loop with zero think time lets the cheapest class
(statements, ~15 ms of pure planning) pump the total-qps headline to
whatever the GIL allows, drowning the device-path signal the bench
exists to measure; with pacing, each client models a BI user and the
total is capacity-meaningful.

Usage:
    python tools/bench_concurrency.py            # full A/B, banks JSON
    python tools/bench_concurrency.py --smoke    # CI smoke: short
        pipelined-only parity run, no artifact written, exit 1 on
        starvation/parity/error
Env knobs: CONC_CLIENTS=16 CONC_SECONDS=20 CONC_ROWS=200000
           CONC_THINK_MS=100
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_olap.utils.platform import force_cpu_devices  # noqa: E402

CLASSES = {
    "grouped": "SELECT g, sum(v) AS s, count(*) AS n FROM t "
               "GROUP BY g ORDER BY g",
    "ungrouped": "SELECT sum(v) AS s, count(*) AS n FROM t WHERE v < 500",
    "fallback": "SELECT g, v, row_number() OVER "
                "(PARTITION BY g ORDER BY v DESC) AS r FROM t "
                "WHERE v > 990",
    "statement": "EXPLAIN DRUID REWRITE SELECT g, sum(v) AS s FROM t "
                 "GROUP BY g",
}
# classes whose response is deterministic (ORDER BY / single row /
# stable pandas order): every reply is compared against the reference
PARITY_CLASSES = ("grouped", "ungrouped", "fallback")

# client-assignment ratio (cycled over the client count): the device
# classes carry double weight — the BI-dashboard mix this server
# exists for, and the contention the dispatch pipeline targets
CLIENT_MIX = ("grouped", "ungrouped", "grouped", "ungrouped",
              "fallback", "statement")


def _post_sql(url, sql, timeout=120):
    req = urllib.request.Request(
        url + "/sql", data=json.dumps({"query": sql}).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _client(url, sql, stop, out, label, reference, think_s=0.0):
    # one persistent HTTP/1.1 connection per client thread (the server
    # speaks keep-alive): a fresh TCP handshake per request convoys on
    # the accept loop at high client counts and shows up as multi-
    # second p99s that have nothing to do with the engine
    import http.client
    host = url.split("//", 1)[1]
    conn = http.client.HTTPConnection(host, timeout=120)
    body_headers = {"Content-Type": "application/json"}
    payload = json.dumps({"query": sql})
    while not stop.is_set():
        t0 = time.perf_counter()
        ok = True
        parity_ok = True
        try:
            conn.request("POST", "/sql", body=payload,
                         headers=body_headers)
            resp = json.loads(conn.getresponse().read())
            if reference is not None and resp["rows"] != reference:
                parity_ok = False
        except Exception:  # noqa: BLE001 — recorded, not raised
            ok = False
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                pass
            conn = http.client.HTTPConnection(host, timeout=120)
        out.append((label, (time.perf_counter() - t0) * 1000.0, ok,
                    parity_ok))
        if think_s > 0:
            stop.wait(think_s)
    conn.close()


def _make_frame(rows: int):
    import numpy as np
    import pandas as pd
    rng = np.random.default_rng(5)
    return pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, rows), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(64)], rows),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })


def run_load(df, pipeline_depth: int, n_clients: int, seconds: float,
             think_s: float = 0.1):
    """One measured run at the given pipeline depth. Returns the stats
    dict banked per arm of the A/B."""
    import numpy as np

    from tpu_olap import Engine
    from tpu_olap.api.server import QueryServer
    from tpu_olap.executor import EngineConfig

    eng = Engine(EngineConfig(query_deadline_s=30.0,
                              pipeline_depth=pipeline_depth))
    eng.register_table("t", df, time_column="ts", block_rows=1 << 12)
    srv = QueryServer(eng)
    srv.start()
    url = srv.url

    # warm every class once so timed samples are cache hits (the BI
    # steady state; cold compiles are a separate, known cost) — and the
    # warm responses are the parity reference for the load clients
    reference = {}
    for label, sql in CLASSES.items():
        resp = _post_sql(url, sql)
        if label in PARITY_CLASSES:
            reference[label] = resp["rows"]

    labels = list(CLASSES)
    assigned = [CLIENT_MIX[i % len(CLIENT_MIX)]
                for i in range(n_clients)]
    results: list = []
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_client,
            args=(url, CLASSES[lb], stop, results, lb,
                  reference.get(lb), think_s),
            daemon=True)
        for lb in assigned]
    t0 = time.time()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=150)
    wall = time.time() - t0

    # lock-wait / occupancy split BEFORE stopping the server: the
    # histogram lives on the engine's registry
    lock_hist = eng.metrics.histogram("dispatch_lock_wait_ms")
    lock_p50 = lock_hist.quantile(0.50)
    lock_p99 = lock_hist.quantile(0.99)
    # device occupancy: summed device-execute wall over the run's wall —
    # >1.0 means overlapped execution (the pipeline's point)
    exec_ms = sum(m.get("execute_ms") or 0.0 for m in eng.history
                  if m.get("execute_ms"))
    # per-stage occupancy + queue wait from the stage scheduler
    # (executor/stages.py): busy_frac > the serialized arm's means the
    # stage genuinely overlapped other queries' work; queue_wait shows
    # which stage pool the load convoys on
    stage_stats = {}
    for name, pool in eng.runner.stages.snapshot()["pools"].items():
        if not pool["submitted"]:
            continue
        stage_stats[name] = {
            "runs": pool["submitted"],
            "busy_ms": round(pool["busy_ms"], 1),
            "busy_frac": round(pool["busy_ms"] / (wall * 1000), 3),
            "queue_wait_ms_total": round(pool["wait_ms"], 1),
            "queue_wait_ms_mean": round(
                pool["wait_ms"] / pool["submitted"], 3),
            "stranded": pool["stranded"],
        }
    srv.stop()

    per_class = {}
    for label in labels:
        ms = sorted(m for lb, m, ok, _ in results if lb == label and ok)
        errs = sum(1 for lb, _, ok, _ in results
                   if lb == label and not ok)
        bad_parity = sum(1 for lb, _, ok, par in results
                         if lb == label and ok and not par)
        if ms:
            per_class[label] = {
                "n": len(ms), "errors": errs,
                "parity_failures": bad_parity,
                "p50_ms": round(float(np.percentile(ms, 50)), 1),
                "p99_ms": round(float(np.percentile(ms, 99)), 1),
                "max_ms": round(ms[-1], 1),
            }
        else:
            per_class[label] = {"n": 0, "errors": errs,
                                "parity_failures": bad_parity}
    total_ok = sum(1 for _, _, ok, _ in results if ok)
    starved = [lb for lb in labels if per_class[lb]["n"] == 0]
    return {
        "pipeline_depth": pipeline_depth,
        "seconds": round(wall, 1),
        "total_requests_ok": total_ok,
        "throughput_qps": round(total_ok / wall, 1),
        "per_class": per_class,
        "starved_classes": starved,
        "parity_failures": sum(
            c.get("parity_failures", 0) for c in per_class.values()),
        "errors": sum(c.get("errors", 0) for c in per_class.values()),
        "lock_wait_p50_ms": None if lock_p50 is None
        else round(lock_p50, 3),
        "lock_wait_p99_ms": None if lock_p99 is None
        else round(lock_p99, 3),
        "device_busy_frac": round(exec_ms / (wall * 1000), 3),
        "device_dispatches": len(eng.history),
        "stages": stage_stats,
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="Concurrent mixed-load bench: pipelined vs "
                    "serialized A/B over a live QueryServer.")
    p.add_argument(
        "--pipeline-depth", type=int, default=4, metavar="N",
        help="in-flight stage-graph depth for the pipelined arm "
             "(default 4, matching the engine default); 0 runs ONLY "
             "the serialized baseline")
    p.add_argument(
        "--smoke", action="store_true",
        help="CI smoke: one short pipelined parity run (no artifact "
             "written); exit 1 on starvation, errors, or parity "
             "failures")
    args = p.parse_args(argv)

    force_cpu_devices(1)
    n_clients = int(os.environ.get(
        "CONC_CLIENTS", 8 if args.smoke else 16))
    seconds = float(os.environ.get(
        "CONC_SECONDS", 4 if args.smoke else 20))
    rows = int(os.environ.get(
        "CONC_ROWS", 50_000 if args.smoke else 200_000))
    think_s = float(os.environ.get("CONC_THINK_MS", 100)) / 1000.0
    df = _make_frame(rows)

    if args.smoke:
        depth = max(1, args.pipeline_depth)
        stats = run_load(df, depth, n_clients, seconds, think_s)
        # every foreground stage class must have seen traffic — a
        # silent stage (never entered) means the graph wiring broke
        missing_stages = [s for s in ("plan", "enqueue", "transfer",
                                      "finalize", "assemble")
                          if s not in stats["stages"]]
        bad = bool(stats["starved_classes"] or stats["errors"]
                   or stats["parity_failures"] or missing_stages)
        print(json.dumps({"ok": not bad, "qps": stats["throughput_qps"],
                          "starved": stats["starved_classes"],
                          "errors": stats["errors"],
                          "parity_failures": stats["parity_failures"],
                          "missing_stages": missing_stages,
                          "stages": sorted(stats["stages"])}))
        return 1 if bad else 0

    serialized = run_load(df, 0, n_clients, seconds, think_s)
    pipelined = None
    if args.pipeline_depth > 0:
        pipelined = run_load(df, args.pipeline_depth, n_clients,
                             seconds, think_s)

    head = pipelined or serialized
    out = {
        "clients": n_clients,
        "seconds": head["seconds"],
        # headline fields mirror the pre-A/B schema (bench_compare and
        # the roadmap trajectory read throughput_qps/per_class from the
        # top level): they describe the PIPELINED arm when it ran
        "total_requests_ok": head["total_requests_ok"],
        "throughput_qps": head["throughput_qps"],
        "per_class": head["per_class"],
        "starved_classes": head["starved_classes"],
        "parity_failures": head["parity_failures"],
        "pipeline_depth": head["pipeline_depth"],
        "stages": head["stages"],
        "serialized": serialized,
        "pipelined": pipelined,
        "speedup_vs_serialized": None if pipelined is None else round(
            pipelined["throughput_qps"]
            / max(serialized["throughput_qps"], 1e-9), 2),
        "deadline_s": 30.0,
        "device_dispatches": head["device_dispatches"],
        "backend": "cpu",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(os.path.join(REPO, "BENCH_CONCURRENCY.json"), "w") as f:
        json.dump(out, f, indent=1)
    bad = bool(head["starved_classes"] or head["parity_failures"])
    print(json.dumps({
        "ok": not bad, "qps": out["throughput_qps"],
        "serialized_qps": serialized["throughput_qps"],
        "speedup": out["speedup_vs_serialized"],
        "starved": head["starved_classes"],
        "parity_failures": head["parity_failures"]}))
    return 0 if not bad else 1


if __name__ == "__main__":
    sys.exit(main())
