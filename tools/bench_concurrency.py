"""Concurrent-load artifact for the BI server (VERDICT r4 weak #6): the
reference's ThriftServer wrapper existed so N BI clients could hit
accelerated tables at once (SURVEY.md §3.1); until now concurrency was
tested for SAFETY (cache races, device-lock serialization) but never for
BEHAVIOR under load. This drives a thread pool of mixed clients against
a live QueryServer over HTTP and banks per-class p50/p99 wall latencies,
throughput, and deadline/fallback interactions to BENCH_CONCURRENCY.json.

Query classes (one list per class, round-robin per client):
- grouped:   device-path GROUP BY (dense, the BI hot path)
- ungrouped: device-path global aggregate (cheapest dispatch)
- fallback:  window function -> whole-frame pandas path (no device lock)
- statement: EXPLAIN DRUID REWRITE (planner only, no execution)

Usage: python tools/bench_concurrency.py  [CONC_CLIENTS=8 CONC_SECONDS=20]
"""

import json
import os
import sys
import threading
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tpu_olap.utils.platform import force_cpu_devices  # noqa: E402

CLASSES = {
    "grouped": "SELECT g, sum(v) AS s, count(*) AS n FROM t "
               "GROUP BY g ORDER BY g",
    "ungrouped": "SELECT sum(v) AS s, count(*) AS n FROM t WHERE v < 500",
    "fallback": "SELECT g, v, row_number() OVER "
                "(PARTITION BY g ORDER BY v DESC) AS r FROM t "
                "WHERE v > 990",
    "statement": "EXPLAIN DRUID REWRITE SELECT g, sum(v) AS s FROM t "
                 "GROUP BY g",
}


def _client(url, sql, stop, out, label):
    while not stop.is_set():
        t0 = time.perf_counter()
        ok = True
        try:
            req = urllib.request.Request(
                url + "/sql", data=json.dumps({"query": sql}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=120) as r:
                json.loads(r.read())
        except Exception:  # noqa: BLE001 — recorded, not raised
            ok = False
        out.append((label, (time.perf_counter() - t0) * 1000.0, ok))


def main():
    force_cpu_devices(1)
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.api.server import QueryServer
    from tpu_olap.executor import EngineConfig

    n_clients = int(os.environ.get("CONC_CLIENTS", 8))
    seconds = float(os.environ.get("CONC_SECONDS", 20))
    rows = int(os.environ.get("CONC_ROWS", 200_000))

    rng = np.random.default_rng(5)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, rows), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(64)], rows),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    eng = Engine(EngineConfig(query_deadline_s=30.0))
    eng.register_table("t", df, time_column="ts", block_rows=1 << 12)
    srv = QueryServer(eng)
    srv.start()
    url = srv.url

    # warm every class once so timed samples are cache hits (the BI
    # steady state; cold compiles are a separate, known cost)
    for sql in CLASSES.values():
        eng.sql(sql)

    labels = list(CLASSES)
    results: list = []
    stop = threading.Event()
    threads = [
        threading.Thread(
            target=_client,
            args=(url, CLASSES[labels[i % len(labels)]], stop, results,
                  labels[i % len(labels)]),
            daemon=True)
        for i in range(n_clients)]
    t0 = time.time()
    for t in threads:
        t.start()
    time.sleep(seconds)
    stop.set()
    for t in threads:
        t.join(timeout=150)
    wall = time.time() - t0
    srv.stop()

    per_class = {}
    for label in labels:
        ms = sorted(m for lb, m, ok in results if lb == label and ok)
        errs = sum(1 for lb, _, ok in results if lb == label and not ok)
        if ms:
            per_class[label] = {
                "n": len(ms), "errors": errs,
                "p50_ms": round(float(np.percentile(ms, 50)), 1),
                "p99_ms": round(float(np.percentile(ms, 99)), 1),
                "max_ms": round(ms[-1], 1),
            }
        else:
            per_class[label] = {"n": 0, "errors": errs}
    total_ok = sum(1 for _, _, ok in results if ok)
    # starvation check: under a shared device lock every class must
    # still make progress — no class may be locked out entirely, and
    # no request may have waited unboundedly (>> deadline)
    starved = [lb for lb in labels if per_class[lb]["n"] == 0]
    out = {
        "clients": n_clients, "seconds": round(wall, 1),
        "total_requests_ok": total_ok,
        "throughput_qps": round(total_ok / wall, 1),
        "per_class": per_class,
        "starved_classes": starved,
        "deadline_s": eng.config.query_deadline_s,
        # engine.history counts DEVICE dispatches only: grouped +
        # ungrouped requests — the fallback/statement classes bypass it,
        # so this cross-checks that the device lock kept serving
        "device_dispatches": len(eng.history),
        "backend": "cpu",
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    with open(os.path.join(REPO, "BENCH_CONCURRENCY.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"ok": not starved, "qps": out["throughput_qps"],
                      "starved": starved}))
    return 0 if not starved else 1


if __name__ == "__main__":
    sys.exit(main())
