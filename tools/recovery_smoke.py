"""Durable-store recovery smoke (ISSUE 14 satellite; the
`recovery-smoke` CI job in .github/workflows/tier1.yml — the
checkpointed extension of tools/ingest_smoke.py).

End-to-end checkpoint + crash-recovery contract, seconds-scale:

1. a CHILD process registers a deterministic base with WAL + segment
   store, appends batches, runs `CHECKPOINT DRUID TABLE` (seal ->
   spill -> manifest advance -> WAL truncation), appends MORE batches,
   reports progress on stdout, then SIGKILLs itself — a real crash
   with a checkpoint on disk and a live WAL tail;
2. the parent recovers over the same directories and verifies
   TAIL-ONLY replay: the newest verifiable manifest restores the
   sealed scope and the wal_replay event's record count must equal
   only the post-checkpoint appends (O(tail), NOT O(total));
3. query results must be sha256-identical to a one-shot registration
   of base + every acknowledged batch;
4. a CORRUPTED-CHUNK run: flip one byte in a chunk file unique to the
   newest manifest and recover again — the ladder must detect it
   (store_fallback), fall back to the previous manifest + the lag-one
   WAL tail, and STILL reach sha256 parity. Never a wrong answer.

Exit 0 on success, 1 on any violation.
"""

import hashlib
import json
import os
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_BASE = 2000
PRE_BATCHES = 8          # acknowledged before the checkpoints
POST_BATCHES = 3         # the WAL tail the crash leaves behind
ROWS_PER_BATCH = 3
BLOCK = 512

QUERIES = [
    "SELECT g, count(*) AS n, sum(v) AS s FROM t GROUP BY g ORDER BY g",
    "SELECT month(ts) AS mo, sum(v) AS s, min(v) AS lo FROM t "
    "GROUP BY month(ts) ORDER BY mo",
    "SELECT count(*) AS n, sum(v) AS s FROM t WHERE v < 500",
]


def base_frame():
    import numpy as np
    import pandas as pd
    rng = np.random.default_rng(42)
    return pd.DataFrame({
        "ts": pd.to_datetime("2022-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 45, N_BASE),
                          unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], N_BASE),
        "v": rng.integers(0, 1000, N_BASE).astype(np.int64),
    })


def batch(i):
    return [{"ts": f"2022-05-{10 + (i % 15):02d}T00:00:0{j}",
             "g": f"s{i % 3}", "v": i * 10 + j}
            for j in range(ROWS_PER_BATCH)]


def digest(frame):
    return hashlib.sha256(frame.to_csv(index=False).encode()) \
        .hexdigest()


def make_engine(root):
    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    eng = Engine(EngineConfig(
        ingest_wal_dir=os.path.join(root, "wal"),
        ingest_store_dir=os.path.join(root, "store"),
        ingest_auto_compact=False))
    eng.register_table("t", base_frame(), time_column="ts",
                       block_rows=BLOCK, time_partition="month")
    return eng


def child_main(root):
    eng = make_engine(root)
    # two checkpoints so the second TRUNCATES the WAL through the
    # first's watermark (lag-one) — the crash must prove the truncated
    # log plus the manifest still cover every acknowledged row
    half = PRE_BATCHES // 2
    for i in range(half):
        eng.append("t", batch(i))
    ck1 = eng.checkpoint_now("t")
    assert ck1["status"] == "checkpointed", ck1
    for i in range(half, PRE_BATCHES):
        eng.append("t", batch(i))
    ck2 = eng.checkpoint_now("t")
    assert ck2["status"] == "checkpointed", ck2
    assert ck2["wal_frames_truncated"] == half, ck2
    for i in range(PRE_BATCHES, PRE_BATCHES + POST_BATCHES):
        eng.append("t", batch(i))
    n = int(eng.sql("SELECT count(*) AS n FROM t")["n"][0])
    total = (PRE_BATCHES + POST_BATCHES) * ROWS_PER_BATCH
    assert n == N_BASE + total, f"visibility: {n}"
    print(json.dumps({"acked_batches": PRE_BATCHES + POST_BATCHES,
                      "acked_rows": total,
                      "checkpoint_id": ck2["checkpoint_id"],
                      "wal_frames_truncated":
                          ck2["wal_frames_truncated"],
                      "visible": n}), flush=True)
    os.kill(os.getpid(), signal.SIGKILL)  # the real thing


def recover_and_check(root, ref, label, expect_tail,
                      expect_fallback=False):
    eng = make_engine(root)
    events = eng.runner.events.snapshot()
    loads = [e for e in events if e["event"] == "store_load"]
    replays = [e for e in events if e["event"] == "wal_replay"]
    falls = [e for e in events if e["event"] == "store_fallback"]
    if not loads:
        print(f"FAIL[{label}]: no store_load event — the checkpoint "
              "was not used")
        return None
    if expect_fallback and not falls:
        print(f"FAIL[{label}]: corruption was not detected (no "
              "store_fallback event)")
        return None
    if not expect_fallback and falls:
        print(f"FAIL[{label}]: unexpected fallbacks: {falls}")
        return None
    replayed = replays[0]["records"] if replays else 0
    total = PRE_BATCHES + POST_BATCHES
    if replayed != expect_tail:
        print(f"FAIL[{label}]: replayed {replayed} frames, expected "
              f"the {expect_tail}-frame tail (of {total} total "
              "appends)")
        return None
    print(f"[{label}] store_load ck={loads[0]['checkpoint_id']} "
          f"wal_seq={loads[0]['wal_seq']}; replayed {replayed}/"
          f"{total} frames (tail-only), fallbacks={len(falls)}")
    for q in QUERIES:
        if digest(eng.sql(q)) != digest(ref.sql(q)):
            print(f"FAIL[{label}]: parity: {q}")
            return None
    print(f"[{label}] sha256 parity: OK")
    return eng


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main(sys.argv[2])
        return 1  # unreachable

    root = tempfile.mkdtemp(prefix="recovery-smoke-")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child", root],
        capture_output=True, text=True, env=env, timeout=300)
    if proc.returncode != -signal.SIGKILL:
        print(f"FAIL: child exited {proc.returncode}, expected "
              f"SIGKILL\nstdout: {proc.stdout}\nstderr: {proc.stderr}")
        return 1
    report = json.loads(proc.stdout.strip().splitlines()[-1])
    print(f"child: acked {report['acked_rows']} rows over "
          f"{report['acked_batches']} batches, checkpoint "
          f"#{report['checkpoint_id']} truncated "
          f"{report['wal_frames_truncated']} WAL frames, then SIGKILL")

    # never-crashed oracle: one-shot registration of base + everything
    import pandas as pd
    from tpu_olap import Engine
    extra = pd.DataFrame(
        [r for i in range(PRE_BATCHES + POST_BATCHES)
         for r in batch(i)])
    extra["ts"] = pd.to_datetime(extra["ts"])
    ref = Engine()
    ref.register_table("t", pd.concat([base_frame(), extra],
                                      ignore_index=True),
                       time_column="ts", block_rows=BLOCK,
                       time_partition="month")

    # --- run 1: clean recovery must be tail-only
    eng = recover_and_check(root, ref, "clean", POST_BATCHES)
    if eng is None:
        return 1
    eng.close()

    # --- run 2: corrupt one chunk unique to the NEWEST manifest; the
    # ladder falls back to the previous manifest + the lag-one WAL
    # tail (which still holds the second half of the pre-crash
    # appends) and parity must hold
    d = os.path.join(root, "store", "t")
    manifests = sorted(n for n in os.listdir(d)
                       if n.startswith("manifest-"))

    def refs(mf):
        with open(os.path.join(d, mf), "rb") as f:
            p = json.load(f)["payload"]
        return {e["file"] for e in p["segments"]} \
            | {p["dictionary"]["file"]}

    only_newest = sorted(refs(manifests[-1]) - refs(manifests[0]))
    if not only_newest:
        print("FAIL: newest checkpoint wrote no fresh chunk to "
              "corrupt")
        return 1
    target = os.path.join(d, only_newest[0])
    with open(target, "r+b") as f:
        f.seek(os.path.getsize(target) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0x55]))
    print(f"corrupted {only_newest[0]} (one-byte flip)")
    # fallback rung covers batches half..end: tail past ck1 watermark
    tail2 = PRE_BATCHES - PRE_BATCHES // 2 + POST_BATCHES
    eng = recover_and_check(root, ref, "corrupted-chunk", tail2,
                            expect_fallback=True)
    if eng is None:
        return 1
    eng.close()

    import shutil
    shutil.rmtree(root, ignore_errors=True)
    print("recovery smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
