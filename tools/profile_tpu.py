"""Per-query profile of the SSB suite on the live backend.

For each of the 13 queries: two warm-up runs (compile + packed-buffer
resize), then ITERS timed runs recording wall time next to the engine's
own per-query history metrics (execute/lower/assemble breakdown, result
group counts, packed-path cache hits). Also measures the raw
dispatch+fetch round-trip floor (a trivial jitted op fetched back) so
query times can be read net of tunnel latency. Writes one JSON object to
PROFILE_TPU.json (or PROFILE_CPU.json off-hardware).

Usage: python tools/profile_tpu.py    [SSB_ROWS=... BENCH_ITERS=...]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main():
    force_cpu = bool(os.environ.get("PROFILE_FORCE_CPU"))
    if force_cpu:
        from tpu_olap.utils.platform import force_cpu_platform
        force_cpu_platform()
    import jax
    import jax.numpy as jnp

    if jax.default_backend() == "cpu" and not force_cpu:
        # invoked expecting hardware (the probe's leg): a tunnel that
        # closed between the liveness check and this process must not
        # burn the window on a minutes-long CPU profile, and must not
        # report success upstream (exit 3 = refused, probe retries)
        print("backend resolved to cpu without PROFILE_FORCE_CPU; refusing",
              file=sys.stderr)
        sys.exit(3)

    backend = jax.default_backend()
    rows = int(os.environ.get("SSB_ROWS", 6_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 5))

    import bench as B
    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES, register_ssb_parquet
    from tpu_olap.executor import EngineConfig

    paths, dims = B._prepare_dataset(rows, 0)
    eng = Engine(EngineConfig(hbm_budget_bytes=8 * 2**30))
    t0 = time.perf_counter()
    register_ssb_parquet(eng, paths, dims)
    ingest_s = time.perf_counter() - t0

    # raw round-trip floor: dispatch a trivial compiled op and fetch it
    one = jnp.ones((8, 128), jnp.float32)
    tiny = jax.jit(lambda x: x.sum())
    np.asarray(tiny(one))  # compile
    rtts = []
    for _ in range(7):
        t0 = time.perf_counter()
        np.asarray(tiny(one))
        rtts.append((time.perf_counter() - t0) * 1000)
    rtt_ms = float(np.percentile(rtts, 50))

    keep = ("execute_ms", "lower_ms", "assemble_ms", "result_groups",
            "result_cap", "packed", "jit_cache_hit", "query_type",
            "hbm_bytes", "strategy", "pallas")
    prof = {}
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        eng.sql(sql)
        eng.sql(sql)
        walls = []
        for _ in range(iters):
            t0 = time.perf_counter()
            eng.sql(sql)
            walls.append((time.perf_counter() - t0) * 1000)
        h = eng.history[-1]
        plan = eng.planner.plan(sql)
        from tpu_olap.executor.lowering import lower
        phys = lower(plan.query, plan.entry.segments, eng.config)
        prof[qname] = {
            "wall_p50_ms": round(float(np.percentile(walls, 50)), 2),
            "wall_min_ms": round(min(walls), 2),
            "pallas_reason": phys.pallas_reason,
            "total_groups": phys.total_groups
            if phys.kind == "agg" else None,
            **{k: (round(v, 2) if isinstance(v, float) else v)
               for k, v in h.items() if k in keep},
        }
        print(f"[profile] {qname}: {prof[qname]}", file=sys.stderr)

    # non-aggregate / auxiliary paths: the SSB 13 are all aggregates, so
    # exercise scan, paged select, search, raw-IR passthrough, and theta
    # set ops on the live backend too (smoke + timing, oracle-light)
    aux = {}

    def run_aux(name, fn):
        # failures must not discard the already-collected 13-query
        # profile (these raw-IR paths bypass Engine.sql's structural
        # fallback, and tunnel time is too scarce to lose the run)
        try:
            fn()  # warm
            t0 = time.perf_counter()
            r = fn()
            aux[name] = {
                "wall_ms": round((time.perf_counter() - t0) * 1000, 2),
                "rows": len(r) if hasattr(r, "__len__") else None}
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            aux[name] = {"error": f"{type(e).__name__}: {e}"[:300]}
        print(f"[profile] aux {name}: {aux[name]}", file=sys.stderr)

    run_aux("scan_limit", lambda: eng.sql(
        "SELECT lo_orderkey, lo_revenue FROM lineorder "
        "WHERE lo_discount = 5 LIMIT 100"))
    run_aux("select_page", lambda: eng.select_page(
        "lineorder", columns=("lo_orderkey", "lo_revenue"),
        page_size=64)[0])
    run_aux("search", lambda: eng.sql(
        "SEARCH DRUID DATASOURCE lineorder FOR 'MFGR#12' "
        "IN p_category LIMIT 10"))
    spec = json.dumps({
        "queryType": "timeseries", "granularity": "all",
        "aggregations": [
            {"type": "filtered", "name": "ta",
             "filter": {"type": "selector", "dimension": "lo_discount",
                        "value": 1},
             "aggregator": {"type": "thetaSketch", "name": "ta",
                            "fieldName": "lo_custkey", "size": 4096}},
            {"type": "filtered", "name": "tb",
             "filter": {"type": "selector", "dimension": "lo_discount",
                        "value": 2},
             "aggregator": {"type": "thetaSketch", "name": "tb",
                            "fieldName": "lo_custkey", "size": 4096}}],
        "postAggregations": [{
            "type": "thetaSketchEstimate", "name": "both",
            "field": {"type": "thetaSketchSetOp", "func": "INTERSECT",
                      "fields": [
                          {"type": "fieldAccess", "fieldName": "ta"},
                          {"type": "fieldAccess", "fieldName": "tb"}]}}]})
    run_aux("theta_setop", lambda: eng.sql(
        f"ON DRUID DATASOURCE lineorder EXECUTE QUERY '{spec}'"))

    out = {
        "backend": backend, "rows": rows, "ingest_s": round(ingest_s, 1),
        "rtt_floor_ms": round(rtt_ms, 2), "queries": prof, "aux": aux,
    }
    name = f"PROFILE_{'TPU' if backend != 'cpu' else 'CPU'}.json"
    with open(os.path.join(REPO, name), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"profile": name, "rtt_floor_ms": out["rtt_floor_ms"]}))


if __name__ == "__main__":
    main()
