"""Per-query profile of the SSB suite on the live backend.

For each of the 13 queries: two warm-up runs (compile + packed-buffer
resize), then ITERS timed runs recording wall time next to the engine's
own per-query history metrics (execute/lower/assemble breakdown, result
group counts, packed-path cache hits). Also measures the raw
dispatch+fetch round-trip floor (a trivial jitted op fetched back) so
query times can be read net of tunnel latency. Writes one JSON object to
PROFILE_TPU.json (or PROFILE_CPU.json off-hardware).

Usage: python tools/profile_tpu.py    [SSB_ROWS=... BENCH_ITERS=...]
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    backend = jax.default_backend()
    rows = int(os.environ.get("SSB_ROWS", 6_000_000))
    iters = int(os.environ.get("BENCH_ITERS", 5))

    import bench as B
    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES, register_ssb_parquet
    from tpu_olap.executor import EngineConfig

    paths, dims = B._prepare_dataset(rows, 0)
    eng = Engine(EngineConfig(hbm_budget_bytes=8 * 2**30))
    t0 = time.perf_counter()
    register_ssb_parquet(eng, paths, dims)
    ingest_s = time.perf_counter() - t0

    # raw round-trip floor: dispatch a trivial compiled op and fetch it
    one = jnp.ones((8, 128), jnp.float32)
    tiny = jax.jit(lambda x: x.sum())
    np.asarray(tiny(one))  # compile
    rtts = []
    for _ in range(7):
        t0 = time.perf_counter()
        np.asarray(tiny(one))
        rtts.append((time.perf_counter() - t0) * 1000)
    rtt_ms = float(np.percentile(rtts, 50))

    keep = ("execute_ms", "lower_ms", "assemble_ms", "result_groups",
            "result_cap", "packed", "cache_hit", "query_type",
            "hbm_bytes", "strategy", "pallas")
    prof = {}
    for qname in sorted(QUERIES):
        sql = QUERIES[qname]
        eng.sql(sql)
        eng.sql(sql)
        walls = []
        for _ in range(iters):
            t0 = time.perf_counter()
            eng.sql(sql)
            walls.append((time.perf_counter() - t0) * 1000)
        h = eng.history[-1]
        plan = eng.planner.plan(sql)
        from tpu_olap.executor.lowering import lower
        phys = lower(plan.query, plan.entry.segments, eng.config)
        prof[qname] = {
            "wall_p50_ms": round(float(np.percentile(walls, 50)), 2),
            "wall_min_ms": round(min(walls), 2),
            "pallas_reason": phys.pallas_reason,
            "total_groups": phys.total_groups
            if phys.kind == "agg" else None,
            **{k: (round(v, 2) if isinstance(v, float) else v)
               for k, v in h.items() if k in keep},
        }
        print(f"[profile] {qname}: {prof[qname]}", file=sys.stderr)

    out = {
        "backend": backend, "rows": rows, "ingest_s": round(ingest_s, 1),
        "rtt_floor_ms": round(rtt_ms, 2), "queries": prof,
    }
    name = f"PROFILE_{'TPU' if backend != 'cpu' else 'CPU'}.json"
    with open(os.path.join(REPO, name), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"profile": name, "rtt_floor_ms": out["rtt_floor_ms"]}))


if __name__ == "__main__":
    main()
