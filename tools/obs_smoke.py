"""Telemetry-plane smoke (CI `obs-smoke` job; ISSUE 17): boot a live
QueryServer and prove the self-monitoring loop end to end over HTTP —

1. the background telemetry graph samples the metrics registry into
   sys.metrics_history (queried via ordinary SQL over POST /sql, which
   must itself never self-attribute into the workload/sentinel stats);
2. GET /debug/health answers ok while the engine is healthy;
3. an induced transfer-stage slowdown (FaultInjector latency mode)
   fires a latency_drift alert NAMING the transfer stage, visible in
   /debug/health and the alerts_active{kind} gauge — and auto-clears
   after the condition stops;
4. a W3C `traceparent` request header round-trips: echoed on the
   response and stamped on the query's history record.

Exits non-zero on any violation. Seconds-scale — a pre-merge gate,
not a bench (docs/OBSERVABILITY.md "Telemetry plane")."""

import json
import os
import sys
import time
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

TRACEPARENT = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"


def main() -> int:
    from tpu_olap.utils.platform import force_cpu_devices
    force_cpu_devices(1)
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.api.server import QueryServer
    from tpu_olap.executor import EngineConfig
    from tpu_olap.resilience.faults import FaultInjector

    cfg = EngineConfig(
        telemetry_interval_s=0.2,       # fast sampler for the smoke
        sentinel_min_samples=3,
        sentinel_latency_factor=2.0,
        sentinel_latency_floor_ms=5.0,
        sentinel_clear_after_s=1.0,     # observable fire -> clear
    )
    eng = Engine(cfg)
    rng = np.random.default_rng(7)
    n = 40_000
    eng.register_table("sales", pd.DataFrame({
        "ts": pd.to_datetime("1996-01-01") + pd.to_timedelta(
            rng.integers(0, 86400 * 365, n), unit="s"),
        "cat": rng.choice([f"c{i}" for i in range(8)], n),
        "v": rng.integers(0, 10_000, n).astype(np.int64),
    }), time_column="ts")
    srv = QueryServer(eng, port=0).start()
    base = f"http://127.0.0.1:{srv.port}"

    def post(path, payload, headers=None):
        req = urllib.request.Request(
            base + path, json.dumps(payload).encode(),
            {"Content-Type": "application/json", **(headers or {})})
        with urllib.request.urlopen(req, timeout=30) as r:
            return json.load(r), dict(r.headers)

    def get(path):
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return (json.load(r) if "json" in r.headers.get(
                "Content-Type", "") else r.read().decode())

    try:
        # -- 4: traceparent round-trip, and a first query batch to
        # build the sentinel's per-template latency baseline. Literals
        # vary so the result cache cannot short-circuit the stages.
        for i in range(8):
            body, hdrs = post(
                "/sql",
                {"query": "SELECT cat, SUM(v) FROM sales "
                          f"WHERE v < {9000 + i} GROUP BY cat"},
                {"traceparent": TRACEPARENT})
            assert body["rows"], "query returned no rows"
            assert hdrs.get("traceparent") == TRACEPARENT, \
                f"traceparent not echoed: {hdrs}"
        rec = [m for m in list(eng.history) if m.get("traceparent")]
        assert rec and rec[-1]["traceparent"] == TRACEPARENT, \
            "traceparent missing from the query record"
        # an invalid header is ignored, never echoed, never an error
        _, hdrs = post("/sql", {"query": "SELECT COUNT(*) FROM sales"},
                       {"traceparent": "not-a-traceparent"})
        assert "traceparent" not in {k.lower() for k in hdrs}, \
            "invalid traceparent must not echo"

        # -- 1: the background sampler has ticked and sys.metrics_history
        # serves over ordinary SQL, without self-attribution
        deadline = time.time() + 10
        while time.time() < deadline and eng.runner.telemetry.samples < 2:
            time.sleep(0.1)
        assert eng.runner.telemetry.samples >= 2, "sampler never ticked"
        observed_before = eng.runner.sentinel.observed
        body, _ = post("/sql", {
            "query": "SELECT name, kind, value FROM sys.metrics_history "
                     "LIMIT 20"})
        assert len(body["rows"]) == 20, \
            f"sys.metrics_history empty: {len(body['rows'])} rows"
        assert eng.runner.sentinel.observed == observed_before, \
            "introspection leaked into the sentinel's baselines"
        ts = get("/debug/timeseries?n=2")
        assert ts["series"] > 0 and all(
            len(s["points"]) <= 2 for s in ts["timeseries"]), \
            "/debug/timeseries ?n= cap violated"

        # -- 2: healthy verdict before any fault
        h = get("/debug/health")
        assert h["ok"] and not h["alerts"], f"unexpectedly unwell: {h}"

        # -- 3: induced transfer-stage slowdown -> latency_drift alert
        # naming the stage, then auto-clear once the fault stops
        cfg.fault_injector = FaultInjector(
            rate=1.0, stages={"stage-transfer"}, latency_s=0.6)
        for i in range(2):
            post("/sql", {"query": "SELECT cat, SUM(v) FROM sales "
                                   f"WHERE v < {800 + i} GROUP BY cat"})
        cfg.fault_injector = None
        h = get("/debug/health")
        assert not h["ok"], "induced slowdown did not trip health"
        kinds = {(a["kind"], a.get("stage")) for a in h["alerts"]}
        assert ("latency_drift", "transfer") in kinds, \
            f"drift not attributed to transfer: {h['alerts']}"
        metrics = get("/metrics")
        assert 'alerts_active{kind="latency_drift"} 1' in metrics, \
            "alerts_active gauge not raised"
        deadline = time.time() + 15
        while time.time() < deadline and not get("/debug/health")["ok"]:
            time.sleep(0.2)
        h = get("/debug/health")
        assert h["ok"], f"alert never cleared: {h}"
        rows, _ = post("/sql", {
            "query": "SELECT kind, stage, status FROM sys.alerts"})
        assert any(r["status"] == "cleared" and r["stage"] == "transfer"
                   for r in rows["rows"]), \
            f"cleared alert missing from sys.alerts: {rows}"
    finally:
        srv.stop()
    print("obs_smoke: ok (sampler + health + drift attribution + "
          "auto-clear + traceparent round-trip)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
