"""On-chip tiling/path sweep for the grouped SSB outliers (round 4).

Hardware A/B of every grouped-reduce execution path for the three
worst grouped SSB queries (q2.2 K=8008, q4.3, q3.2):

- the factorized-lane-packing Pallas kernel across rows_per_block
  tile shapes (pallas_k_per_block no longer distinguishes kernels at
  these K — the factorized k1 axis fits one block);
- the XLA scatter kernel (use_pallas="never", dense path);
- the sparse sort-based path (dense_group_budget below each query's
  restricted K; asserted via phys.sparse so a dense run can never
  bank under the sparse label).

Writes PALLAS_SWEEP_TPU.json; exits 3 on CPU (never banked as hardware
evidence). Dataset comes from bench.py's cached SF1 parquet.

Usage: python tools/sweep_pallas_tpu.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = ("q2.2", "q4.3", "q3.2")
ITERS = 5


def main():
    import jax
    if jax.default_backend() == "cpu":
        print("backend is cpu; refusing to bank", file=sys.stderr)
        return 3

    import bench as B
    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES as SSB, register_ssb_parquet
    from tpu_olap.executor import EngineConfig

    rows = int(os.environ.get("SSB_ROWS", "6000000"))
    paths, dims = B._prepare_dataset(rows, 0)

    # each variant is a DISTINCT compiled path (under the factorized
    # lane packing, pallas_k_per_block no longer changes the kernel for
    # these K values — the k1 axis fits one block); pallas variants use
    # "force" and assert pallas_reason so a silently-declined plan can
    # never bank as kernel evidence
    variants = {
        "pallas_rb1024": dict(use_pallas="force"),
        "pallas_rb512": dict(use_pallas="force",
                             pallas_rows_per_block=512),
        "pallas_rb2048": dict(use_pallas="force",
                              pallas_rows_per_block=2048),
        # XLA scatter kernel (the pallas-declined dense path)
        "scatter": dict(use_pallas="never"),
        # dense budget below EVERY swept query's restricted K (q3.2:
        # 400, q4.3: 1640, q2.2: 8008) forces the sort-based path for
        # all three; asserted per query below
        "sparse": dict(use_pallas="never", dense_group_budget=256),
    }
    out = {"backend": jax.default_backend(), "rows": rows,
           "iters": ITERS, "variants": {}}
    from tpu_olap.executor.lowering import lower
    for name, kw in variants.items():
        eng = Engine(EngineConfig(**kw))
        register_ssb_parquet(eng, paths, dims)
        rec = {}
        try:
            for q in QUERIES:
                sql = SSB[q]
                if kw.get("use_pallas") == "force" or name == "sparse":
                    plan = eng.planner.plan(sql)
                    phys = lower(plan.query, plan.entry.segments,
                                 eng.config)
                    if name == "sparse":
                        assert phys.sparse, f"{name}/{q}: not sparse"
                    else:
                        assert phys.pallas_reason is None, (
                            f"{name}/{q}: {phys.pallas_reason}")
                eng.sql(sql)  # warm/compile
                times = []
                for _ in range(ITERS):
                    t0 = time.perf_counter()
                    res = eng.sql(sql)
                    times.append((time.perf_counter() - t0) * 1e3)
                digest = len(res)
                times.sort()
                rec[q] = {"p50_ms": round(times[len(times) // 2], 3),
                          "min_ms": round(times[0], 3),
                          "groups": digest}
        except Exception as err:  # noqa: BLE001 — a variant that fails
            rec["error"] = f"{type(err).__name__}: {err}"[:500]
        out["variants"][name] = rec
        eng.clear_cache()
        print(f"[sweep] {name}: "
              f"{ {q: v.get('p50_ms') for q, v in rec.items() if isinstance(v, dict)} }",
              file=sys.stderr, flush=True)
    # cross-variant result sanity: group counts must agree everywhere
    counts = {}
    for name, rec in out["variants"].items():
        for q, v in rec.items():
            if isinstance(v, dict):
                counts.setdefault(q, set()).add(v["groups"])
    out["result_consistent"] = all(len(s) == 1 for s in counts.values())
    with open(os.path.join(REPO, "PALLAS_SWEEP_TPU.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"result_consistent": out["result_consistent"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
