"""On-chip tiling/path sweep for the grouped SSB outliers (round 4).

q2.2 (K=8008) costs ~240 ms warm at SF1 — ~173 ms compute over the
67.5 ms tunnel RTT floor, ~37% MXU efficiency on the one-hot reduce
(docs/PERF_MODEL.md). This sweeps the knobs that could close the gap,
on real hardware, for the three worst grouped queries:

- pallas_k_per_block x pallas_rows_per_block tile shapes (MXU feed);
- the sparse sort-based path (pallas_group_cap below K forces it) —
  never benchmarked on hardware against the dense one-hot.

Writes PALLAS_SWEEP_TPU.json; exits 3 on CPU (never banked as hardware
evidence). Dataset comes from bench.py's cached SF1 parquet.

Usage: python tools/sweep_pallas_tpu.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUERIES = ("q2.2", "q4.3", "q3.2")
ITERS = 5


def main():
    import jax
    if jax.default_backend() == "cpu":
        print("backend is cpu; refusing to bank", file=sys.stderr)
        return 3

    import bench as B
    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES as SSB, register_ssb_parquet
    from tpu_olap.executor import EngineConfig

    rows = int(os.environ.get("SSB_ROWS", "6000000"))
    paths, dims = B._prepare_dataset(rows, 0)

    variants = {
        "dense_kb1024_rb1024": dict(pallas_k_per_block=1024,
                                    pallas_rows_per_block=1024),
        "dense_kb512_rb1024": dict(pallas_k_per_block=512,
                                   pallas_rows_per_block=1024),
        "dense_kb2048_rb1024": dict(pallas_k_per_block=2048,
                                    pallas_rows_per_block=1024),
        "dense_kb1024_rb512": dict(pallas_k_per_block=1024,
                                   pallas_rows_per_block=512),
        "dense_kb1024_rb2048": dict(pallas_k_per_block=1024,
                                    pallas_rows_per_block=2048),
        # group cap below q2.2's K forces the sparse sort-based path
        "sparse": dict(pallas_group_cap=64),
    }
    out = {"backend": jax.default_backend(), "rows": rows,
           "iters": ITERS, "variants": {}}
    baseline = None
    for name, kw in variants.items():
        eng = Engine(EngineConfig(use_pallas="auto", **kw))
        register_ssb_parquet(eng, paths, dims)
        rec = {}
        try:
            for q in QUERIES:
                sql = SSB[q]
                eng.sql(sql)  # warm/compile
                times = []
                for _ in range(ITERS):
                    t0 = time.perf_counter()
                    res = eng.sql(sql)
                    times.append((time.perf_counter() - t0) * 1e3)
                digest = len(res)
                if baseline is None:
                    pass
                times.sort()
                rec[q] = {"p50_ms": round(times[len(times) // 2], 3),
                          "min_ms": round(times[0], 3),
                          "groups": digest}
        except Exception as err:  # noqa: BLE001 — a variant that fails
            rec["error"] = f"{type(err).__name__}: {err}"[:500]
        out["variants"][name] = rec
        eng.clear_cache()
        print(f"[sweep] {name}: "
              f"{ {q: v.get('p50_ms') for q, v in rec.items() if isinstance(v, dict)} }",
              file=sys.stderr, flush=True)
    # cross-variant result sanity: group counts must agree everywhere
    counts = {}
    for name, rec in out["variants"].items():
        for q, v in rec.items():
            if isinstance(v, dict):
                counts.setdefault(q, set()).add(v["groups"])
    out["result_consistent"] = all(len(s) == 1 for s in counts.values())
    with open(os.path.join(REPO, "PALLAS_SWEEP_TPU.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"result_consistent": out["result_consistent"]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
