"""Fit the DruidQueryCostModel-analog constants from measurements
(VERDICT round-2 task #6; SURVEY.md §3.2 DruidQueryCostModel).

Runs a grid of (rows, group-cardinality) GROUP BY queries through the
engine on an 8-device mesh, timing BOTH dispatch strategies via the
force_strategy override:

- "historicals" (sharded per-chip partials + host broker merge), whose model is
      t = scan_us + merge_us
        = rows*cols*SCAN/1e3/D  +  hops*(LAT + bytes*MERGE/1e3)
  fitted by least squares over the grid (SCAN from the rows axis at tiny
  K, LAT+MERGE from the table-bytes axis at fixed rows);
- "broker" (one program under GSPMD), modeled as
      t = OVERHEAD * (scan_us + LAT*hops)
  fitted as the median ratio over the grid.

Writes tpu_olap/planner/cost_calibration.json keyed by jax backend
("cpu" when run under the virtual mesh, "tpu" on hardware) — decide()
prefers these over the coarse built-ins. Run:

    python tools/calibrate_cost.py            # default backend
    CAL_FORCE_CPU=1 python tools/calibrate_cost.py   # 8-dev CPU mesh

On a single-chip backend (the tunnel exposes one TPU) only the scan
slope is measurable — there is no ICI to fit merge/latency against — so
the script writes just `scan_ns_per_row_col` (+ a single-device dispatch
floor) and `constants()` falls back per-key for the rest. Set
CAL_REQUIRE_TPU=1 to exit(3) instead of writing when jax resolves to CPU
(the probe uses this so a closed tunnel cannot bank a CPU fit as "tpu").
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

from tpu_olap.utils.platform import (ensure_host_device_count,  # noqa: E402
                                     env_flag, force_cpu_platform)

SHARDS = 8
ITERS = 7


def _make_engine(force_strategy):
    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    return Engine(EngineConfig(num_shards=SHARDS,
                               force_strategy=force_strategy,
                               use_pallas="never"))


def _register(eng, rows, k):
    import pandas as pd
    rng = np.random.default_rng(7)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(np.arange(rows) % 86400, unit="s"),
        # numeric dim spanning exactly k dense ids (range [0, k))
        "g": np.concatenate([np.arange(k), rng.integers(0, k, rows - k)])
        .astype(np.int64),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    eng.register_table("t", df, time_column="ts", block_rows=1 << 13)


SQL = "SELECT g, sum(v) AS s FROM t GROUP BY g"


def _write(backend, fitted, cost_mod):
    path = os.path.join(REPO, "tpu_olap", "planner",
                        "cost_calibration.json")
    data = {}
    if os.path.exists(path):
        with open(path) as f:
            data = json.load(f)
    data[backend] = fitted
    with open(path, "w") as f:
        json.dump(data, f, indent=1)
    cost_mod._calibration_cache = None
    print(json.dumps({"backend": backend, **fitted}))


# v5e ICI figures (public: jax-ml.github.io/scaling-book hardware
# tables): ~45 GB/s per link per direction, us-scale collective launch.
# One tunnel chip cannot measure these, but the multi-chip decision
# terms must not run on generic fallbacks (VERDICT r4 missing #5): the
# MODEL FORM  t = hops*(lat + bytes*merge)  is validated by the 8-
# virtual-device CPU fit (same harness, "cpu" entry), and the v5e
# magnitudes are pinned from the datasheet until real ICI is reachable.
ICI_MERGE_NS_PER_BYTE = 1.0 / 45.0   # 45 GB/s/link/direction
ICI_COLLECTIVE_LAT_US = 1.0
GSPMD_OVERHEAD_TPU = 1.35            # XLA partitioner vs explicit psum


def _calibrate_single_device(backend, cost_mod):
    """One chip: fit the scan slope (the constant the SF100 projection
    runs on) from the rows axis; the merge/collective terms are pinned
    from the v5e ICI datasheet (no second device to move bytes to) with
    the model shape validated on the 8-virtual-device CPU mesh."""
    rows_a, rows_b, k0 = 1 << 19, 1 << 21, 8
    ta = _time_point(rows_a, k0, None)
    tb = _time_point(rows_b, k0, None)
    n_cols = 2
    scan = max(0.001, (tb - ta) * 1000.0 / ((rows_b - rows_a) * n_cols))
    fitted = {
        "scan_ns_per_row_col": round(float(scan), 5),
        "dispatch_floor_us": round(float(max(0.0, ta - rows_a * n_cols
                                             * scan / 1000.0)), 1),
        "merge_ns_per_byte": round(ICI_MERGE_NS_PER_BYTE, 5),
        "collective_lat_us": ICI_COLLECTIVE_LAT_US,
        "gspmd_overhead": GSPMD_OVERHEAD_TPU,
        "fitted_shards": 1,
        "fitted_iters": ITERS,
        "note": ("scan+floor measured on chip; merge/lat pinned from "
                 "v5e ICI datasheet (45 GB/s/link, us-scale launch); "
                 "gspmd_overhead v5e-class prior; model form validated "
                 "by the 8-virtual-device CPU fit"),
    }
    _write(backend, fitted, cost_mod)


def _time_point(rows, k, strategy):
    eng = _make_engine(strategy)
    _register(eng, rows, k)
    eng.sql(SQL)
    eng.sql(SQL)  # second warm: re-sized packed buffer compiles
    ts = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        eng.sql(SQL)
        ts.append((time.perf_counter() - t0) * 1e6)
    return float(np.percentile(ts, 50))  # microseconds


def main():
    global SHARDS
    if env_flag("CAL_FORCE_CPU"):
        ensure_host_device_count(SHARDS)
        force_cpu_platform()
    import jax
    backend = jax.default_backend()
    if backend == "cpu" and env_flag("CAL_REQUIRE_TPU"):
        print("backend is cpu; CAL_REQUIRE_TPU set — not writing",
              file=sys.stderr)
        sys.exit(3)
    if backend == "cpu" and jax.device_count() < SHARDS:
        ensure_host_device_count(SHARDS)
    # clamp to the device count only on hardware (one tunnel chip => the
    # single-device fit). On CPU the virtual 8-device mesh is the point:
    # a clamp there would silently overwrite the banked 8-shard fit with
    # a degraded single-device one when run without CAL_FORCE_CPU.
    if backend != "cpu":
        SHARDS = min(SHARDS, jax.device_count())
    SHARDS = min(SHARDS, int(os.environ.get("CAL_SHARDS", SHARDS)))
    from tpu_olap.planner import cost as cost_mod
    if SHARDS < 2:
        return _calibrate_single_device(backend, cost_mod)
    hops = max(1, int(np.ceil(np.log2(SHARDS))))

    # --- scan slope: tiny K, two row counts; historicals ---------------
    rows_a, rows_b, k0 = 1 << 17, 1 << 19, 8
    ta = _time_point(rows_a, k0, "historicals")
    tb = _time_point(rows_b, k0, "historicals")
    n_cols = 2  # g, v
    scan = max(0.001, (tb - ta) * 1000.0 * SHARDS
               / ((rows_b - rows_a) * n_cols))  # ns per row*col

    # --- merge slope: fixed rows, growing K; historicals ---------------
    rows_m = 1 << 17
    ks = [1 << 10, 1 << 14, 1 << 17]
    widths = 4 + 8 + 4  # _rows + int64 sum + _nn counter
    tms = [_time_point(rows_m, k, "historicals") for k in ks]
    xs = np.array([k * widths for k in ks], float)  # table bytes
    ys = np.array(tms, float)
    slope, intercept = np.polyfit(xs, ys, 1)
    merge = max(0.0001, slope * 1000.0 / hops)  # ns/byte/hop
    lat = max(1.0, (intercept - ta) / hops)     # us/hop over the scan base

    # --- broker overhead ratio -----------------------------------------
    ratios = []
    for rows, k in [(rows_a, k0), (rows_m, ks[1]), (rows_m, ks[2])]:
        tb_ = _time_point(rows, k, "broker")
        model_base = (rows * n_cols * scan / 1000.0 / SHARDS) + lat * hops
        ratios.append(tb_ / max(model_base, 1.0))
    overhead = float(np.median(ratios))

    fitted = {
        "scan_ns_per_row_col": round(float(scan), 5),
        "merge_ns_per_byte": round(float(merge), 5),
        "collective_lat_us": round(float(lat), 2),
        "gspmd_overhead": round(overhead, 3),
        "fitted_shards": SHARDS,
        "fitted_iters": ITERS,
    }
    _write(backend, fitted, cost_mod)


if __name__ == "__main__":
    main()
