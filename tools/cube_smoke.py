"""Cube smoke (CI `cube-smoke` job): materialize one rollup cube via
DDL, assert a covered aggregate is SERVED from it (record path="cube"),
assert exact parity against the base device path AND the independent
pandas fallback, and prove the invalidation contract (a re-ingest stops
cube serving instantly; REFRESH DRUID CUBES restores it). Exits
non-zero on any violation. Seconds-scale — a pre-merge gate, not a
bench (docs/CUBES.md)."""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main() -> int:
    from tpu_olap.utils.platform import force_cpu_devices
    force_cpu_devices(1)
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.bench.parity import check_query
    from tpu_olap.executor import EngineConfig

    def df(seed):
        rng = np.random.default_rng(seed)
        n = 60_000
        return pd.DataFrame({
            "ts": pd.to_datetime("1996-01-01") + pd.to_timedelta(
                rng.integers(0, 86400 * 500, n), unit="s"),
            "cat": rng.choice([f"c{i}" for i in range(12)], n),
            "region": rng.choice(["AM", "AS", "EU"], n),
            "v": rng.integers(0, 10_000, n).astype(np.int64),
            "u": rng.integers(0, 3_000, n).astype(np.int64),
        })

    eng = Engine(EngineConfig(cube_auto_refresh=False))
    eng.register_table("sales", df(1), time_column="ts",
                       time_partition="month")
    out = eng.sql(
        "CREATE DRUID CUBE smoke ON sales DIMENSIONS (cat, region) "
        "GRANULARITY month AGGREGATES (sum(v), count(*), avg(v), "
        "approx_count_distinct(u))")
    assert list(out["status"]) == ["ready"], out.to_dict("records")

    sql = ("SELECT cat, sum(v) AS s, count(*) AS n, avg(v) AS a, "
           "approx_count_distinct(u) AS d FROM sales "
           "WHERE region = 'EU' AND year(ts) = 1996 "
           "GROUP BY cat ORDER BY cat")
    served = eng.sql(sql)
    rec = dict(eng.history[-1])
    assert rec.get("path") == "cube" and rec.get("cube") == "smoke", \
        f"not served from the cube: path={rec.get('path')}"
    eng.config.cube_rewrite_enabled = False
    base = eng.sql(sql)
    eng.config.cube_rewrite_enabled = True
    pd.testing.assert_frame_equal(served, base)
    # vs the pandas oracle too: exact for sum/count/avg, the standard
    # approximate band for the HLL column (the oracle computes exact
    # COUNT DISTINCT; the device path is an HLL estimate by design)
    check_query(eng, sql, approx_cols=("d",), label="cube-smoke")

    # invalidation: re-ingest -> zero stale serves, refresh -> resumes
    eng.register_table("sales", df(2), time_column="ts",
                       time_partition="month")
    n0 = len(eng.history)
    fresh = eng.sql(sql)
    stale = [m for m in eng.history[n0:] if m.get("path") == "cube"]
    assert not stale, "STALE cube serve after re-ingest"
    eng.config.cube_rewrite_enabled = False
    fresh_base = eng.sql(sql)
    eng.config.cube_rewrite_enabled = True
    pd.testing.assert_frame_equal(fresh, fresh_base)
    refreshed = eng.sql("REFRESH DRUID CUBES")
    assert list(refreshed["status"]) == ["ok"]
    again = eng.sql(sql)
    rec = dict(eng.history[-1])
    assert rec.get("path") == "cube", "refresh did not restore serving"
    pd.testing.assert_frame_equal(again, fresh_base)
    n_serves = int(eng.sql(
        "SELECT serve_count FROM sys.cubes")["serve_count"][0])
    print(f"cube-smoke OK: {int(rec['rows_scanned'])} cube rows "
          f"served a {60_000}-row base scan, parity exact, "
          f"0 stale serves, {n_serves} total serves")
    return 0


if __name__ == "__main__":
    sys.exit(main())
