"""Bench regression gate: compare two BENCH_*.json artifacts.

The bench trajectory was unbanked — every PR prints one JSON line, but
nothing diffs consecutive runs, so a 20% p50 regression on one query
rides in silently as long as the worst-case metric holds. This tool is
the gate CI (and future PRs) call:

    python tools/bench_compare.py BASELINE.json NEW.json
    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json \
        --threshold 0.10
    python tools/bench_compare.py BENCH_CACHE_old.json BENCH_CACHE.json

It compares `detail.per_query_p50_ms` query by query, prints a delta
table, and exits non-zero when any query's p50 regressed beyond the
threshold (default 15%). When BOTH artifacts carry the cache bench's
`detail.cache` block (BENCH_CACHE.json), the table grows a cache-hit-
rate column and the gate ALSO checks the warm-path p50
(`per_query_warm_p50_ms`) against the same threshold — a cache that
stops hitting shows up as a warm regression even when the cold path
held. Queries present in only one artifact are reported but never gate
(a new query is not a regression; a removed one is visible in the
table). Sub-millisecond baselines are compared with a small absolute
floor so timer jitter on trivially fast queries cannot trip the gate.

Exit codes: 0 ok, 1 regression(s), 2 usage/artifact error.
"""

from __future__ import annotations

import argparse
import json
import sys

# relative regressions below this many ms of absolute growth never gate:
# at sub-ms scale the perf_counter jitter between two runs exceeds any
# honest percentage threshold
ABS_FLOOR_MS = 1.0


def _fail(msg: str):
    print(f"bench_compare: {msg}", file=sys.stderr)
    raise SystemExit(2)


def load_artifact(path: str) -> dict:
    """{"p50": {q: ms}, "warm": {q: ms}|None, "hit_rate": {q: f}|None}
    for latency artifacts, or {"kind": "concurrency", ...} for
    BENCH_CONCURRENCY.json-shaped throughput artifacts."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        _fail(f"cannot read {path}: {e}")
    if not isinstance(doc, dict):
        _fail(f"{path}: top-level JSON is {type(doc).__name__}, "
              "not an object (truncated/corrupt artifact?)")
    if isinstance(doc.get("parsed"), dict) and "detail" not in doc:
        doc = doc["parsed"]  # driver-banked wrapper (BENCH_rNN.json)
    if "throughput_qps" in doc and isinstance(doc.get("per_class"),
                                              dict):
        # concurrency artifact (tools/bench_concurrency.py): gate on
        # throughput + per-class p99 + per-stage queue wait (when both
        # artifacts carry the stage-scheduler occupancy block)
        stages = doc.get("stages")
        return {"kind": "concurrency",
                "qps": float(doc["throughput_qps"]),
                "p99": {str(c): float(v["p99_ms"])
                        for c, v in doc["per_class"].items()
                        if isinstance(v, dict) and "p99_ms" in v},
                "stages": {str(s): {
                    "wait_mean": float(v["queue_wait_ms_mean"]),
                    "busy_frac": float(v["busy_frac"])}
                    for s, v in stages.items()
                    if isinstance(v, dict)
                    and "queue_wait_ms_mean" in v}
                if isinstance(stages, dict) else None}
    if doc.get("mode") == "multichip" and \
            isinstance(doc.get("per_query"), dict):
        # sharded-serving artifact (bench.py --mesh N): gate mesh p50
        # + scaling efficiency + parity per query
        pq = doc["per_query"]
        return {"kind": "multichip",
                "n_devices": int(doc.get("n_devices", 0) or 0),
                "parity_ok": bool(doc.get("parity_ok")),
                "p50": {str(q): float(v["p50_mesh_ms"])
                        for q, v in pq.items()
                        if isinstance(v, dict) and "p50_mesh_ms" in v},
                "speedup": {str(q): float(v.get("speedup", 0.0))
                            for q, v in pq.items()
                            if isinstance(v, dict)}}
    detail = doc.get("detail") or {}
    per_query = detail.get("per_query_p50_ms")
    if not isinstance(per_query, dict) or not per_query:
        _fail(f"{path} has no detail.per_query_p50_ms and no "
              "throughput_qps (not a bench artifact?)")

    def _floats(d):
        try:
            return {str(q): float(v) for q, v in d.items()}
        except (TypeError, ValueError) as e:
            _fail(f"{path}: non-numeric p50 entry: {e}")

    out = {"kind": "latency", "p50": _floats(per_query), "warm": None,
           "hit_rate": None, "hbm_hwm": None}
    hbm = detail.get("hbm")
    if isinstance(hbm, dict) and \
            hbm.get("high_watermark_bytes") is not None:
        # telemetry-plane census (ISSUE 17): artifacts banked before
        # the sampler existed have no watermark — skipped, never gated
        out["hbm_hwm"] = float(hbm["high_watermark_bytes"])
    cache = detail.get("cache")
    if isinstance(cache, dict):
        warm = cache.get("per_query_warm_p50_ms")
        if isinstance(warm, dict) and warm:
            out["warm"] = _floats(warm)
        hr = cache.get("per_query_hit_rate")
        if isinstance(hr, dict) and hr:
            out["hit_rate"] = _floats(hr)
    return out


def compare(base: dict, new: dict, threshold: float):
    """Rows (query, base_ms, new_ms, delta_frac, regressed) for queries
    in both artifacts, plus the only-in-one leftovers."""
    rows = []
    for q in sorted(set(base) & set(new)):
        b, n = base[q], new[q]
        delta = (n - b) / b if b > 0 else (0.0 if n <= 0 else float("inf"))
        regressed = delta > threshold and (n - b) > ABS_FLOOR_MS
        rows.append((q, b, n, delta, regressed))
    only_base = sorted(set(base) - set(new))
    only_new = sorted(set(new) - set(base))
    return rows, only_base, only_new


def compare_concurrency(base: dict, new: dict, threshold: float) -> int:
    """Throughput-regression gate for BENCH_CONCURRENCY.json artifacts:
    exit 1 when throughput_qps dropped more than the threshold, any
    class's p99 grew beyond it, or any stage pool's mean queue wait
    grew beyond it (each with the absolute jitter floor)."""
    regressions = []
    bq, nq = base["qps"], new["qps"]
    dq = (nq - bq) / bq if bq > 0 else 0.0
    print(f"{'metric':<16}  {'base':>10}  {'new':>10}  {'delta':>8}  "
          "gate")
    flag = "ok"
    if dq < -threshold:
        regressions.append("throughput_qps")
        flag = "REGRESSED(qps)"
    print(f"{'throughput_qps':<16}  {bq:>10.1f}  {nq:>10.1f}  "
          f"{dq:>+7.1%}  {flag}")
    for cls in sorted(set(base["p99"]) & set(new["p99"])):
        b, n = base["p99"][cls], new["p99"][cls]
        d = (n - b) / b if b > 0 else 0.0
        reg = d > threshold and (n - b) > ABS_FLOOR_MS
        if reg:
            regressions.append(f"{cls}.p99")
        print(f"{cls + '.p99_ms':<16}  {b:>10.1f}  {n:>10.1f}  "
              f"{d:>+7.1%}  {'REGRESSED(p99)' if reg else 'ok'}")
    # per-stage queue-wait gate: a stage pool the load newly convoys
    # on is a regression even while total qps holds (the burst just
    # moved). Baselines banked before the stage scheduler existed have
    # no block — skipped, never gated. busy_frac is informational.
    if base.get("stages") and new.get("stages"):
        for s in sorted(set(base["stages"]) & set(new["stages"])):
            b = base["stages"][s]["wait_mean"]
            n = new["stages"][s]["wait_mean"]
            d = (n - b) / b if b > 0 else 0.0
            reg = d > threshold and (n - b) > ABS_FLOOR_MS
            if reg:
                regressions.append(f"{s}.queue_wait")
            print(f"{s + '.wait_ms':<16}  {b:>10.3f}  {n:>10.3f}  "
                  f"{d:>+7.1%}  "
                  f"{'REGRESSED(queue_wait)' if reg else 'ok'}"
                  f"  [busy {base['stages'][s]['busy_frac']:.3f}"
                  f" -> {new['stages'][s]['busy_frac']:.3f}]")
    if regressions:
        print(f"\nbench_compare: {len(regressions)} concurrency "
              f"metric(s) regressed past {threshold:.0%}: "
              f"{', '.join(regressions)}", file=sys.stderr)
        return 1
    print(f"\nbench_compare: ok (throughput + per-class p99 + stage "
          f"queue waits within {threshold:.0%})")
    return 0


def compare_multichip(base: dict, new: dict, threshold: float) -> int:
    """Sharded-serving gate for MULTICHIP_*.json artifacts (bench.py
    --mesh N): exit 1 when the candidate lost result parity vs the
    single-device path, any query's MESH p50 regressed past the
    threshold, or its mesh-vs-1-device speedup collapsed by more than
    the threshold. Prints the per-query scaling-efficiency column
    (speedup / n_devices) so ICI-merge or placement regressions are
    visible even while absolute p50s stay under the gate."""
    regressions = []
    if not new["parity_ok"]:
        regressions.append("parity")
    nd = max(1, new["n_devices"])
    rows, _, _ = compare(base["p50"], new["p50"], threshold)
    w = max([len(q) for q, *_ in rows] or [5])
    print(f"{'query':<{w}}  {'base ms':>10}  {'new ms':>10}  "
          f"{'delta':>8}  {'speedup':>8}  {'scale-eff':>9}  gate")
    for q, b, n, delta, regressed in rows:
        sp_b = base["speedup"].get(q, 0.0)
        sp_n = new["speedup"].get(q, 0.0)
        why = []
        if regressed:
            why.append("p50")
        if sp_b > 0 and (sp_n - sp_b) / sp_b < -threshold:
            why.append("speedup")
        if why:
            regressions.append(f"{q}({','.join(why)})")
        print(f"{q:<{w}}  {b:>10.3f}  {n:>10.3f}  {delta:>+7.1%}  "
              f"{sp_n:>7.2f}x  {sp_n / nd:>8.1%}  "
              f"{'REGRESSED(' + ','.join(why) + ')' if why else 'ok'}")
    if regressions:
        print(f"\nbench_compare: multichip regressed past "
              f"{threshold:.0%}: {', '.join(regressions)}",
              file=sys.stderr)
        return 1
    print(f"\nbench_compare: ok (mesh p50 + scaling within "
          f"{threshold:.0%}, parity held)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Compare per-query SSB p50s of two bench artifacts "
                    "(cold always; warm-path + hit rate when both are "
                    "cache-bench artifacts); exit 1 when any query "
                    "regressed beyond the threshold.")
    p.add_argument("baseline", help="older BENCH_*.json")
    p.add_argument("candidate", help="newer BENCH_*.json")
    p.add_argument(
        "--threshold", type=float, default=0.15, metavar="FRAC",
        help="max tolerated relative p50 growth per query "
             "(default 0.15 = 15%%)")
    args = p.parse_args(argv)
    if not (0.0 <= args.threshold < 100.0):
        p.error(f"--threshold {args.threshold}: must be a fraction >= 0")

    base_art = load_artifact(args.baseline)
    new_art = load_artifact(args.candidate)
    if base_art["kind"] != new_art["kind"]:
        _fail(f"artifact kinds differ: {args.baseline} is "
              f"{base_art['kind']}, {args.candidate} is "
              f"{new_art['kind']}")
    if base_art["kind"] == "concurrency":
        return compare_concurrency(base_art, new_art, args.threshold)
    if base_art["kind"] == "multichip":
        return compare_multichip(base_art, new_art, args.threshold)
    base, new = base_art["p50"], new_art["p50"]
    rows, only_base, only_new = compare(base, new, args.threshold)
    if not rows:
        print("bench_compare: no queries in common — nothing to gate",
              file=sys.stderr)
        return 2

    have_cache = base_art["warm"] is not None \
        and new_art["warm"] is not None
    hit_rates = new_art["hit_rate"] or {}

    w = max(len(q) for q, *_ in rows)
    hdr = (f"{'query':<{w}}  {'base ms':>10}  {'new ms':>10}  "
           f"{'delta':>8}")
    if have_cache:
        hdr += f"  {'warm ms':>9}  {'wdelta':>8}  {'hit%':>6}"
    print(hdr + "  gate")
    regressions = []
    warm_rows = {}
    if have_cache:
        wr, _, _ = compare(base_art["warm"], new_art["warm"],
                           args.threshold)
        warm_rows = {q: (b, n, d, r) for q, b, n, d, r in wr}
    for q, b, n, delta, regressed in rows:
        why = []
        if regressed:
            why.append("p50")
        line = f"{q:<{w}}  {b:>10.3f}  {n:>10.3f}  {delta:>+7.1%}"
        if have_cache:
            wrow = warm_rows.get(q)
            if wrow is not None:
                wb, wn, wd, wreg = wrow
                if wreg:
                    why.append("warm")
                hr = hit_rates.get(q)
                line += (f"  {wn:>9.3f}  {wd:>+7.1%}  "
                         f"{hr * 100 if hr is not None else 0:>5.0f}%")
            else:
                line += f"  {'-':>9}  {'':>8}  {'':>6}"
        flag = "REGRESSED(" + ",".join(why) + ")" if why else "ok"
        print(line + f"  {flag}")
        if why:
            regressions.append(q)
    for q in only_base:
        print(f"{q:<{w}}  {base[q]:>10.3f}  {'-':>10}  {'':>8}  "
              "only in baseline")
    for q in only_new:
        print(f"{q:<{w}}  {'-':>10}  {new[q]:>10.3f}  {'':>8}  "
              "only in candidate")

    # HBM high-watermark gate (ISSUE 17): peak device-memory growth
    # past the threshold is a regression even when steady-state bytes
    # and p50s hold — a transient spike is tomorrow's OOM. Gated only
    # when BOTH artifacts carry the watermark (older artifacts skip).
    have_hwm = base_art.get("hbm_hwm") is not None \
        and new_art.get("hbm_hwm") is not None
    if have_hwm:
        bh, nh = base_art["hbm_hwm"], new_art["hbm_hwm"]
        dh = (nh - bh) / bh if bh > 0 else 0.0
        hwm_reg = dh > args.threshold
        print(f"{'hbm_hwm_bytes':<{w}}  {bh:>10.0f}  {nh:>10.0f}  "
              f"{dh:>+7.1%}" + ("  " * (3 if have_cache else 0))
              + f"  {'REGRESSED(hbm_hwm)' if hwm_reg else 'ok'}")
        if hwm_reg:
            regressions.append("hbm_hwm")

    if regressions:
        print(f"\nbench_compare: {len(regressions)} metric"
              f"{'' if len(regressions) == 1 else 's'} regressed "
              f"past {args.threshold:.0%}: {', '.join(regressions)}",
              file=sys.stderr)
        return 1
    print(f"\nbench_compare: ok ({len(rows)} queries within "
          f"{args.threshold:.0%}"
          + (", warm path + hit rate checked" if have_cache else "")
          + (", hbm high-watermark checked" if have_hwm else "")
          + ")")
    return 0


if __name__ == "__main__":
    sys.exit(main())
