"""Execute the DCN-shaped multi-host path with REAL multiple processes
(VERDICT r3 missing #5): 2 x jax.distributed.initialize on the CPU
platform, make_multihost_mesh over the global device set, shard_put of a
segment-axis array from every host, and the engine's two merge shapes
under `jax.jit` + `NamedSharding` — a replicated-output reduce (GSPMD
inserts the cross-host psum) and a sharded-output per-chip partials
reduce (each host observes only its addressable shards) — exactly what
the sharded dispatch compiles (executor/sharding.py). Writes
MULTIHOST_2PROC.json.

The production analog swaps the CPU platform + localhost coordinator
for TPU pods — the jax API surface is identical (SURVEY.md §3.6: ICI
within a slice, DCN across). Across processes the engine forces the
GSPMD "broker" strategy (remote shards are not host-addressable, so the
host broker merge cannot see them — executor.sharding.is_multihost).

Usage: python tools/multihost_check.py            # parent: spawns 2 workers
       python tools/multihost_check.py <pid 0|1>  # worker mode
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PORT = int(os.environ.get("MULTIHOST_PORT", 47311))
NPROC = 2
DEVS_PER_PROC = 4


def worker(pid: int) -> None:
    # env (XLA_FLAGS, JAX_PLATFORMS) is set by the parent before spawn;
    # the platform config must still be applied before backend init
    from tpu_olap.utils.platform import force_cpu_platform
    force_cpu_platform()
    import jax
    jax.distributed.initialize(
        coordinator_address=f"localhost:{PORT}",
        num_processes=NPROC, process_id=pid)

    import numpy as np
    from tpu_olap.executor.sharding import (is_multihost,
                                            make_multihost_mesh,
                                            replicated_spec, shard_put,
                                            shard_spec)

    n_dev = jax.device_count()
    n_local = len(jax.local_devices())
    assert n_dev == NPROC * DEVS_PER_PROC, (n_dev, jax.devices())
    assert n_local == DEVS_PER_PROC, n_local

    mesh = make_multihost_mesh(n_dev)
    assert is_multihost(mesh)

    # segment-axis table: every process holds the full logical array and
    # shard_put materializes only ITS addressable shards (the engine's
    # DeviceDataset._put does the same)
    segs, rows = n_dev * 3, 128
    arr = np.arange(segs * rows, dtype=np.int64).reshape(segs, rows)
    x = shard_put(arr, mesh)
    assert len(x.addressable_shards) == DEVS_PER_PROC

    # the engine's two merge shapes under jit + NamedSharding
    # (executor.sharding.mesh_agg_kernel): a replicated-output global
    # reduce — GSPMD inserts the cross-host psum — and a sharded-output
    # per-chip partials reduce (one partial per segment block here;
    # each host observes only its addressable shards)
    total_f = jax.jit(lambda a: a.sum(),
                      out_shardings=replicated_spec(mesh))
    parts_f = jax.jit(lambda a: a.sum(axis=1),
                      out_shardings=shard_spec(mesh))
    expect = int(arr.sum())
    try:
        total = int(np.asarray(total_f(x)))
    except Exception as e:  # noqa: BLE001 — backend capability gate
        if "Multiprocess computations aren't implemented" not in str(e):
            raise
        # this jax build's CPU backend cannot compile cross-process
        # computations at all (newer builds can — CI runs the full
        # path). The DCN topology itself (distributed init, global
        # mesh, per-host shard materialization) was still proven above;
        # report the capability gap honestly instead of a fake pass.
        print(json.dumps({"pid": pid, "devices": n_dev,
                          "local_devices": n_local,
                          "compute_supported": False,
                          "reason": str(e).split("\n")[0][:200],
                          "ok": True}))
        jax.distributed.shutdown()
        return
    assert total == expect, (total, expect)
    parts = parts_f(x)
    # parts stays sharded across hosts (addressable shards only) — check
    # this process's slice carries real per-segment partials
    local_parts = [np.asarray(s.data) for s in parts.addressable_shards]
    assert len(local_parts) == DEVS_PER_PROC
    local_sum = int(sum(p.sum() for p in local_parts))
    assert 0 < local_sum < expect  # a real PARTIAL of the global sum

    # phase 2: a REAL engine query, SPMD across the two processes — both
    # run the identical program over the same registered table; the
    # sharded dispatch's psum merge rides the multi-host mesh and every
    # host assembles the same replicated answer
    import pandas as pd
    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    rng = np.random.default_rng(23)
    # >=1M rows (VERDICT r4 weak #4): realistic per-shard row counts
    # (128k rows/device here) so the SPMD dispatch exercises real
    # padding/capacity behavior, with a 512-wide group space
    rows_t = int(os.environ.get("MULTIHOST_ROWS", 1 << 20))
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 20, rows_t), unit="s"),
        "g": rng.choice([f"g{i:03d}" for i in range(512)], rows_t),
        "v": rng.integers(0, 1000, rows_t).astype(np.int64),
    })
    eng = Engine(EngineConfig(num_shards=n_dev))
    eng.register_table("t", df, time_column="ts", block_rows=1 << 13)
    q = ("SELECT g, sum(v) AS s, count(*) AS n FROM t "
         "WHERE v < 900 GROUP BY g ORDER BY g")
    res = eng.sql(q)
    sub = df[df.v < 900]
    exp_df = sub.groupby("g", as_index=False).agg(
        s=("v", "sum"), n=("v", "size")).sort_values("g")
    engine_ok = (res["g"].tolist() == exp_df["g"].tolist()
                 and res["s"].tolist() == exp_df["s"].tolist()
                 and res["n"].tolist() == exp_df["n"].tolist())
    assert engine_ok, (res, exp_df)

    print(json.dumps({"pid": pid, "devices": n_dev,
                      "local_devices": n_local, "psum_total": total,
                      "expect": expect,
                      "engine_query_ok": engine_ok,
                      "engine_rows": len(res),
                      "engine_table_rows": rows_t,
                      "ok": total == expect and engine_ok}))
    jax.distributed.shutdown()


def main() -> int:
    if len(sys.argv) > 1:
        worker(int(sys.argv[1]))
        return 0

    import re
    env = dict(os.environ)
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags + f" --xla_force_host_platform_device_count="
                        f"{DEVS_PER_PROC}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    t0 = time.time()
    procs = [subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), str(i)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, cwd=REPO) for i in range(NPROC)]
    outs = []
    ok = True
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=900)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            ok = False
        line = out.strip().splitlines()[-1] if out.strip() else ""
        rec = json.loads(line) if line.startswith("{") else \
            {"pid": i, "ok": False, "stderr": err[-1500:]}
        ok = ok and p.returncode == 0 and rec.get("ok", False)
        outs.append(rec)
    result = {"ok": ok, "processes": NPROC,
              "devices_per_process": DEVS_PER_PROC,
              "compute_supported": all(
                  w.get("compute_supported", True) for w in outs),
              "engine_table_rows": (outs[0] or {}).get(
                  "engine_table_rows"),
              "wall_s": round(time.time() - t0, 1), "workers": outs}
    out_path = os.environ.get(
        "MULTIHOST_OUT", os.path.join(REPO, "MULTIHOST_2PROC.json"))
    with open(out_path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps({"ok": ok, "wall_s": result["wall_s"]}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
