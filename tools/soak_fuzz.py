"""Out-of-CI fuzz soak with a banked artifact (VERDICT r3 weak #5: soak
evidence must be an artifact, not a claim). Runs SOAK_N seeds of the
exact CI fuzz case (tests/test_fuzz_parity.py — same seed derivation, so
any failure replays in pytest by seed number) on the 8-virtual-device CPU
mesh and writes SOAK_<tag>.json with the seed range, per-failure SQL,
fallback-shape counts, and wall time.

Usage: SOAK_N=1000 SOAK_SEED_START=0 SOAK_TAG=r04 python tools/soak_fuzz.py
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

from tpu_olap.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)

import numpy as np  # noqa: E402

import test_fuzz_parity as F  # noqa: E402
from tpu_olap import Engine  # noqa: E402
from tpu_olap.bench.parity import (ParityError, assert_frame_parity,  # noqa: E402
                                   run_both)
from tpu_olap.executor import EngineConfig  # noqa: E402


def _reason_bucket(reason) -> str:
    """Normalize a fallback reason into a clusterable bucket (VERDICT r4
    weak #5: an 8% fallback rate is only diagnosable when the artifact
    says WHICH grammar production each fallback came from): strip quoted
    identifiers and numbers so e.g. two unsupported-function reasons
    naming different columns count as one production."""
    import re
    if not reason:
        return "(no reason recorded)"
    s = re.sub(r"'[^']*'", "'_'", str(reason))
    s = re.sub(r"\d+", "N", s)
    return s[:120]


def run_seed(seed: int):
    """One CI-identical fuzz case. Returns (status, sql, reason) with
    status in {"ok", "fallback", "fail"}; reason is the normalized
    fallback bucket (None for ok)."""
    rng = np.random.default_rng(1000 + seed)
    frame = F._make_table(rng, int(rng.integers(500, 6000)))
    pallas = "force" if seed % 3 == 0 else "never"
    shards = 8 if seed % 5 == 0 else None
    eng = Engine(EngineConfig(use_pallas=pallas, num_shards=shards))
    eng.register_table("t", frame, time_column="ts",
                       block_rows=int(2 ** rng.integers(8, 11)),
                       star_schema=F._star())
    eng.register_table("citydim", F._city_dim(), accelerate=False)
    sql = F._gen_query(rng)
    try:
        device, fb, _ = run_both(eng, sql)
    except ParityError:
        plan = getattr(eng, "last_plan", None)
        return "fallback", sql, _reason_bucket(
            getattr(plan, "fallback_reason", None))
    assert_frame_parity(device, fb, ordered=False,
                        label=f"seed={seed} sql={sql!r}")
    return "ok", sql, None


def _run_range(start: int, n: int):
    counts = {"ok": 0, "fallback": 0, "fail": 0, "error": 0}
    reasons: dict = {}
    failures = []
    for seed in range(start, start + n):
        try:
            status, sql, reason = run_seed(seed)
            counts[status] += 1
            if reason is not None:
                reasons[reason] = reasons.get(reason, 0) + 1
        except Exception as err:  # noqa: BLE001 — every failure banked
            counts["fail" if isinstance(err, ParityError)
                   else "error"] += 1
            failures.append({"seed": seed,
                             "error": f"{type(err).__name__}: {err}"[:800]})
        if (seed - start + 1) % 100 == 0:
            print(f"[soak] seeds {start}..{seed} counts={counts}",
                  file=sys.stderr, flush=True)
    return counts, reasons, failures


def main():
    start = int(os.environ.get("SOAK_SEED_START", 0))
    n = int(os.environ.get("SOAK_N", 1000))
    tag = os.environ.get("SOAK_TAG", "r04")
    chunk = int(os.environ.get("SOAK_CHUNK", 25))
    t0 = time.time()

    if os.environ.get("SOAK_INLINE"):
        # hard address-space cap: a pathological seed must surface as a
        # caught per-seed MemoryError in the artifact, not grind the
        # host into swap and an OOM kill that voids the whole chunk
        import resource
        cap = int(float(os.environ.get("SOAK_RLIMIT_GB", 40)) * 2**30)
        resource.setrlimit(resource.RLIMIT_AS, (cap, cap))
        counts, reasons, failures = _run_range(start, n)
        print(json.dumps({"counts": counts, "fallback_reasons": reasons,
                          "failures": failures}))
        return 1 if failures else 0

    # chunked in subprocesses: every seed compiles fresh XLA executables
    # into process-global caches, so a single 1000-seed process grows
    # without bound (observed: OOM-killed at 127 GB RSS around seed 200;
    # a 100-seed chunk still reached ~100 GB — 25 keeps the peak ~25 GB)
    import subprocess
    counts = {"ok": 0, "fallback": 0, "fail": 0, "error": 0}
    reasons: dict = {}
    failures = []
    done = 0
    out = _write(start, n, tag, chunk, counts, reasons, failures, done, t0)
    while done < n:
        m = min(chunk, n - done)
        env = dict(os.environ)
        env.update({"SOAK_INLINE": "1",
                    "SOAK_SEED_START": str(start + done),
                    "SOAK_N": str(m)})
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env, capture_output=True, text=True, cwd=REPO)
        line = proc.stdout.strip().splitlines()[-1] \
            if proc.stdout.strip() else ""
        if line.startswith("{"):
            rec = json.loads(line)
            for k, v in rec["counts"].items():
                counts[k] += v
            for k, v in rec.get("fallback_reasons", {}).items():
                reasons[k] = reasons.get(k, 0) + v
            failures.extend(rec["failures"])
            if rec["failures"]:
                print("[soak] first failure this chunk: "
                      + rec["failures"][0]["error"][:400],
                      file=sys.stderr, flush=True)
        else:
            counts["error"] += m
            failures.append({"seed": start + done,
                             "error": "chunk crashed: "
                             + proc.stderr[-500:]})
            print(f"[soak] chunk {start + done} crashed rc="
                  f"{proc.returncode}: ...{proc.stderr[-300:]}",
                  file=sys.stderr, flush=True)
        done += m
        print(f"[soak] {done}/{n} counts={counts}",
              file=sys.stderr, flush=True)
        # incremental banking: a round boundary (or a crash) must not
        # lose hours of soak evidence — the artifact reflects every
        # completed chunk, seeds_completed recording partial coverage
        out = _write(start, n, tag, chunk, counts, reasons, failures,
                     done, t0)
    print(json.dumps({"counts": counts, "wall_s": out["wall_s"]}))
    return 1 if failures else 0


def _write(start, n, tag, chunk, counts, reasons, failures, done, t0):
    out = {
        "seed_start": start, "n": n,
        "seed_derivation": "default_rng(1000 + seed), CI-identical",
        "counts": counts,
        # per-production breakdown (VERDICT r4 weak #5): identifiers and
        # numbers are normalized out so each bucket is one grammar shape
        "fallback_reasons": dict(sorted(reasons.items(),
                                        key=lambda kv: -kv[1])),
        "failures": failures,
        "chunk_seeds_per_process": chunk,
        "wall_s": round(time.time() - t0, 1),
        "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "seeds_completed": done,
    }
    with open(os.path.join(REPO, f"SOAK_{tag}.json"), "w") as f:
        json.dump(out, f, indent=1)
    return out


if __name__ == "__main__":
    sys.exit(main())
