"""Hardware validation of the full Pallas kernel surface (VERDICT r3 #5).

Runs every parity query set from tests/test_pallas_reduce.py — the base
shapes, the round-3 min/max second-buffer leg, the widened
granularity/interval shapes, the remap/timeformat precomputed dims, the
K-tiling split, and the full-int32-range half-plane sums — on the LIVE
backend with use_pallas="force" vs "never", asserting frame equality.

Interpret mode on CPU hid four Mosaic miscompiles in round 3
(docs/TPU_NOTES.md); this script is how the remaining legs get the same
hardware truth. Writes PALLAS_TPU_VALIDATION.json on a real chip; exits 3
without writing anything if the backend is CPU (the probe must not bank a
CPU run as hardware evidence).

Usage: python tools/validate_pallas_tpu.py
"""

import json
import os
import sys
import time
import traceback

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def main():
    if os.environ.get("PALLAS_VALIDATE_SMOKE_CPU"):
        # local dry-run of the harness itself (interpret mode; NOT banked)
        from tpu_olap.utils.platform import force_cpu_platform
        force_cpu_platform()
    import jax
    backend = jax.default_backend()
    if backend == "cpu" and not os.environ.get("PALLAS_VALIDATE_SMOKE_CPU"):
        print("backend is cpu; refusing to bank as hardware validation",
              file=sys.stderr)
        return 3

    import pandas as pd
    import test_pallas_reduce as T
    from tpu_olap import Engine
    from tpu_olap.bench.parity import assert_frame_parity
    from tpu_olap.executor import EngineConfig
    from tpu_olap.executor.lowering import lower

    def compare(a, b, key):
        """Value-level parity (dtype-normalizing, float-tolerant: the
        two device paths may legally disagree on e.g. float64-vs-object
        for a nullable group key). On mismatch, embed both frames so a
        failure banked through the probe is diagnosable offline."""
        try:
            assert_frame_parity(a, b, ordered=True, label=key)
        except Exception as err:
            raise AssertionError(
                f"{err}\n--- never ({dict(a.dtypes.astype(str))}):\n"
                f"{a.head(24)}\n"
                f"--- force ({dict(b.dtypes.astype(str))}):\n"
                f"{b.head(24)}") from err

    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force"))
    df = T._table()
    for e in (plain, forced):
        e.register_table("t", df, time_column="ts", block_rows=512)

    suites = {
        "base": T.QUERIES,
        "minmax": T.MINMAX_QUERIES,
        "widened": T.WIDENED_QUERIES,
        "precomputed_dim": T.PRECOMPUTED_DIM_QUERIES,
        "colcmp": T.COLCMP_QUERIES,
    }
    results = {}
    n_pass = n_fail = 0
    for suite, queries in suites.items():
        for i, sql in enumerate(queries):
            key = f"{suite}[{i}]"
            t0 = time.perf_counter()
            try:
                a = plain.sql(sql)
                b = forced.sql(sql)
                plan = forced.planner.plan(sql)
                phys = lower(plan.query, plan.entry.segments, forced.config)
                compare(a, b, key)
                results[key] = {
                    "ok": True,
                    "pallas_active": phys.pallas_reason is None,
                    "pallas_reason": phys.pallas_reason,
                    "ms": round((time.perf_counter() - t0) * 1000, 1)}
                n_pass += 1
            except Exception:  # noqa: BLE001 — recorded per-query
                results[key] = {"ok": False,
                                "error": traceback.format_exc()[-2400:],
                                "sql": sql}
                n_fail += 1
            print(f"[pallas-hw] {key}: "
                  f"{'ok' if results[key]['ok'] else 'FAIL'}",
                  file=sys.stderr)

    # K-tiling on-chip: group space wider than pallas_k_per_block
    try:
        f2 = Engine(EngineConfig(use_pallas="force", pallas_k_per_block=16))
        f2.register_table("t", df, time_column="ts", block_rows=512)
        q = ("SELECT region, color, sum(price) AS s, count(*) AS n FROM t "
             "GROUP BY region, color ORDER BY region, color")
        compare(plain.sql(q), f2.sql(q), "k_tiling")
        results["k_tiling"] = {"ok": True}
        n_pass += 1
    except Exception:  # noqa: BLE001
        results["k_tiling"] = {"ok": False,
                               "error": traceback.format_exc()[-2400:]}
        n_fail += 1

    # full-int32-range sums: every 4-bit plane + half-sum recombination
    try:
        import numpy as np
        rng = np.random.default_rng(11)
        n = 2048
        big = pd.DataFrame({
            "ts": pd.to_datetime("2021-01-01")
            + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
            "g": rng.choice([f"g{i}" for i in range(7)], n),
            "big": rng.integers(0, 2**31 - 1, n).astype(np.int64),
            "neg": rng.integers(-(2**30), 2**30, n).astype(np.int64),
        })
        p2 = Engine(EngineConfig(use_pallas="never"))
        f3 = Engine(EngineConfig(use_pallas="force"))
        for e in (p2, f3):
            e.register_table("big_t", big, time_column="ts", block_rows=512)
        for q in ("SELECT g, sum(big) AS s FROM big_t GROUP BY g ORDER BY g",
                  "SELECT g, sum(neg) AS s FROM big_t GROUP BY g ORDER BY g"):
            compare(p2.sql(q), f3.sql(q), "large_values")
        results["large_values"] = {"ok": True}
        n_pass += 1
    except Exception:  # noqa: BLE001
        results["large_values"] = {"ok": False,
                                   "error": traceback.format_exc()[-2400:]}
        n_fail += 1

    out = {"backend": backend, "passed": n_pass, "failed": n_fail,
           "results": results,
           "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    name = ("/tmp/PALLAS_SMOKE.json"
            if os.environ.get("PALLAS_VALIDATE_SMOKE_CPU")
            else os.path.join(REPO, "PALLAS_TPU_VALIDATION.json"))
    with open(name, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"passed": n_pass, "failed": n_fail}))
    return 0 if n_fail == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
