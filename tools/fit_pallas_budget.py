"""Fit the pallas-vs-scatter crossover from the on-chip A/B pair and
write it as the 'auto' policy default (docs/PERF_MODEL.md decision
procedure #1; VERDICT r3 weak #1).

Inputs: BENCH_TPU_AUTO_r04.json (fresh auto run, this round's code) and
BENCH_TPU_PALLAS_never.json (XLA scatter leg, same data/scale). For each
SSB query the one-hot FLOP product is computed by lowering the query
locally (K is scale-free: SSB dimension cardinalities do not grow with
the fact row count), then:

- queries where auto is FASTER than never keep the Pallas kernel: the
  budget must sit above their FLOP product;
- queries where auto is SLOWER (beyond a noise margin) must take the
  scatter path: the budget must sit below theirs.

The fitted budget is the log-midpoint of the gap; contradictions (a
losing query below a winning one) widen the margin until consistent.
Writes tpu_olap/planner/pallas_tuning.json (consumed by
lowering._tuned_flop_budget as the default when EngineConfig leaves
pallas_auto_flop_budget unset).

Usage: python tools/fit_pallas_budget.py  [exit 3 if inputs missing]
"""

import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOISE = 1.15  # auto must be >15% slower before a query counts as a loss


def main():
    paths = {n: os.path.join(REPO, f)
             for n, f in (("auto", "BENCH_TPU_AUTO_r04.json"),
                          ("never", "BENCH_TPU_PALLAS_never.json"))}
    runs = {}
    for name, p in paths.items():
        if not os.path.exists(p):
            print(f"missing {p}; nothing to fit", file=sys.stderr)
            return 3
        with open(p) as f:
            runs[name] = json.load(f)
    if runs["auto"]["detail"]["rows"] != runs["never"]["detail"]["rows"]:
        print("A/B legs ran at different scales; refusing", file=sys.stderr)
        return 3

    from tpu_olap.utils.platform import force_cpu_platform
    force_cpu_platform()
    import bench as B
    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES, register_ssb_parquet
    from tpu_olap.executor.lowering import lower

    # lower each query at a small scale to read K (scale-free) and
    # compute the FLOP product at the A/B scale
    paths_small, dims = B._prepare_dataset(200_000, 0)
    eng = Engine()
    register_ssb_parquet(eng, paths_small, dims)
    n_rows = runs["auto"]["detail"]["rows"]
    seg = eng.catalog.get("lineorder").segments
    block = seg.block_rows
    flops = {}
    for qname, sql in QUERIES.items():
        plan = eng.planner.plan(sql)
        phys = lower(plan.query, plan.entry.segments, eng.config)
        kb = max(1, min(phys.total_groups, eng.config.pallas_k_per_block))
        k_pad = -(-phys.total_groups // kb) * kb
        n_pad = -(-n_rows // block) * block
        flops[qname] = 2.0 * k_pad * n_pad * 128

    auto = runs["auto"]["detail"]["per_query_p50_ms"]
    never = runs["never"]["detail"]["per_query_p50_ms"]
    wins = [flops[q] for q in QUERIES if auto[q] * NOISE < never[q]]
    losses = [flops[q] for q in QUERIES if auto[q] > never[q] * NOISE]
    lo = max(wins) if wins else None       # keep pallas at least here
    hi = min(losses) if losses else None   # force scatter from here

    if hi is None:
        budget = None          # pallas never lost: no cap
        verdict = "pallas never slower: no cap written"
    elif lo is None or lo >= hi:
        budget = hi * 0.99     # cap just below the cheapest loss
        verdict = ("cap below the cheapest losing query"
                   if lo is None else
                   "win/loss bands overlap: conservative cap below "
                   "the cheapest loss")
    else:
        budget = math.exp((math.log(lo) + math.log(hi)) / 2)
        verdict = "log-midpoint of the win/loss gap"

    out = {
        "auto_flop_budget": budget,
        "fit": {"verdict": verdict, "noise_margin": NOISE,
                "rows": n_rows,
                "per_query": {q: {"flops": flops[q], "auto_ms": auto[q],
                                  "never_ms": never[q]}
                              for q in sorted(QUERIES)}},
    }
    path = os.path.join(REPO, "tpu_olap", "planner", "pallas_tuning.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"auto_flop_budget": budget, "verdict": verdict}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
