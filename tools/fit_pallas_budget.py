"""Fit the pallas-vs-generic crossover from the on-chip A/B pair and
write it as the 'auto' policy default (docs/PERF_MODEL.md decision
procedure #1; VERDICT r3 weak #1).

Inputs: BENCH_TPU_AUTO_r04.json (fresh auto run, this round's code) and
BENCH_TPU_PALLAS_never.json (XLA scatter leg, same data/scale). For each
SSB query the one-hot FLOP product is computed by lowering the query
locally (K is scale-free: SSB dimension cardinalities do not grow with
the fact row count).

The first on-chip A/B (2026-07-31) showed TWO regimes, not the single
cap the perf model hypothesized:

- **K == 1 (ungrouped)**: no scatter is involved either way — the
  alternative to the Pallas kernel is XLA's fused masked reduce, which
  wins by a fixed ~20 ms dispatch margin. This is a structural class,
  not a FLOP threshold: fitted as `auto_ungrouped_pallas` (False when
  the K=1 queries lose beyond the noise margin).
- **K > 1 (grouped)**: the XLA scatter path measured ~500 ms nearly
  flat across K at SF1 while the one-hot MXU kernel won every grouped
  query by 4-6x, up through q2.2's 1.26e13 FLOPs. The O(K·n) asymptote
  must still lose eventually (SF100/chip projections in PERF_MODEL.md),
  so `auto_flop_budget` is fitted as an upper cap ONLY from grouped
  losses sitting above every grouped win; with no grouped loss observed
  there is no cap (null) and the SF10 leg's larger n can add one later.

Writes tpu_olap/planner/pallas_tuning.json (consumed by
lowering._tuned_pallas_policy as the default when EngineConfig leaves
pallas_auto_flop_budget unset).

Usage: python tools/fit_pallas_budget.py  [exit 3 if inputs missing]
"""

import json
import math
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

NOISE = 1.15  # auto must be >15% slower before a query counts as a loss


def main():
    # A/B input artifacts must come from the SAME kernel code the fit
    # will tune (overridable so each round's probe names its own pair)
    paths = {n: os.path.join(REPO, f)
             for n, f in (
                 ("auto", os.environ.get("FIT_AUTO_JSON",
                                         "BENCH_TPU_AUTO_r04.json")),
                 ("never", os.environ.get("FIT_NEVER_JSON",
                                          "BENCH_TPU_PALLAS_never.json")))}
    runs = {}
    for name, p in paths.items():
        if not os.path.exists(p):
            print(f"missing {p}; nothing to fit", file=sys.stderr)
            return 3
        with open(p) as f:
            runs[name] = json.load(f)
    if runs["auto"]["detail"]["rows"] != runs["never"]["detail"]["rows"]:
        print("A/B legs ran at different scales; refusing", file=sys.stderr)
        return 3

    from tpu_olap.utils.platform import force_cpu_platform
    force_cpu_platform()
    import bench as B
    from tpu_olap import Engine
    from tpu_olap.bench import QUERIES, register_ssb_parquet
    from tpu_olap.executor.lowering import lower
    from tpu_olap.kernels.pallas_reduce import tile_product

    # lower each query at a small scale to read K (scale-free) and
    # compute the FLOP product at the A/B scale
    paths_small, dims = B._prepare_dataset(200_000, 0)
    eng = Engine()
    register_ssb_parquet(eng, paths_small, dims)
    n_rows = runs["auto"]["detail"]["rows"]
    seg = eng.catalog.get("lineorder").segments
    block = seg.block_rows
    flops, groups = {}, {}
    for qname, sql in QUERIES.items():
        plan = eng.planner.plan(sql)
        phys = lower(plan.query, plan.entry.segments, eng.config)
        n_pad = -(-n_rows // block) * block
        # same units as lowering's budget gate: factorization-aware
        # tile product (kernels.pallas_reduce.tile_product)
        flops[qname] = 2.0 * n_pad * tile_product(
            phys, plan.entry.segments, eng.config)
        groups[qname] = phys.total_groups

    auto = runs["auto"]["detail"]["per_query_p50_ms"]
    never = runs["never"]["detail"]["per_query_p50_ms"]

    k1 = [q for q in QUERIES if groups[q] == 1]
    grouped = [q for q in QUERIES if groups[q] > 1]

    # the fit is self-referential: the auto leg ran under the PRIOR
    # tuned policy, so queries that policy routed to the generic kernel
    # measured generic-vs-generic — uninformative for this fit and, left
    # unguarded, noise would flip the policy back and forth between runs
    prior = {}
    tuning_path = os.path.join(REPO, "tpu_olap", "planner",
                               "pallas_tuning.json")
    if os.path.exists(tuning_path):
        try:
            with open(tuning_path) as f:
                prior = json.load(f)
        except Exception:  # noqa: BLE001 — a bad file just means no prior
            prior = {}
    prior_budget = prior.get("auto_flop_budget")

    # regime 1: ungrouped — a single yes/no, not a threshold
    ungrouped_pallas = prior.get("auto_ungrouped_pallas")
    if k1 and ungrouped_pallas is not False:
        losing = [q for q in k1 if auto[q] > never[q] * NOISE]
        winning = [q for q in k1 if auto[q] * NOISE < never[q]]
        if losing and not winning:
            ungrouped_pallas = False
        elif winning and not losing:
            ungrouped_pallas = True
        # mixed/noise-bound: keep the prior (within noise either way)

    # regime 2: grouped — upper FLOP cap, only where losses sit above
    # every win (the O(K·n) asymptote); queries the prior budget already
    # declined measured the generic kernel, not pallas — exclude them
    informative = [q for q in grouped
                   if prior_budget is None or flops[q] <= prior_budget]
    wins = [flops[q] for q in informative if auto[q] * NOISE < never[q]]
    losses = [flops[q] for q in informative if auto[q] > never[q] * NOISE]
    lo = max(wins) if wins else None       # keep pallas at least here
    hi = min([f for f in losses if lo is None or f > lo] or [None]) \
        if losses else None

    if hi is None:
        # no informative loss: a prior cap stays (runs under it cannot
        # prove queries above it are safe), absent cap stays absent
        budget = prior_budget
        verdict = ("no grouped loss observed: "
                   + ("prior cap kept" if prior_budget is not None
                      else "no cap")
                   if not losses else
                   "grouped losses all below wins: noise, no cap")
    elif lo is None:
        budget = hi * 0.99
        verdict = "cap below the cheapest grouped loss"
    else:
        budget = math.exp((math.log(lo) + math.log(hi)) / 2)
        verdict = "log-midpoint of the grouped win/loss gap"

    out = {
        "auto_flop_budget": budget,
        "auto_ungrouped_pallas": ungrouped_pallas,
        "fit": {"verdict": verdict, "noise_margin": NOISE,
                "rows": n_rows,
                "ungrouped_queries": k1,
                "per_query": {q: {"flops": flops[q], "groups": groups[q],
                                  "auto_ms": auto[q],
                                  "never_ms": never[q]}
                              for q in sorted(QUERIES)}},
    }
    path = os.path.join(REPO, "tpu_olap", "planner", "pallas_tuning.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({"auto_flop_budget": budget,
                      "auto_ungrouped_pallas": ungrouped_pallas,
                      "verdict": verdict}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
