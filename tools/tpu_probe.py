"""Round-long TPU availability probe (VERDICT.md round-2 task #3).

The axon tunnel to the TPU flaps: it can be up for minutes and then hang
PJRT client creation indefinitely. Probing and benching in separate
processes loses the up-window (observed: probe ok at T, bench's own probe
dead at T+seconds), so each attempt here IS the bench: run bench.py with
BENCH_SKIP_PROBE=1 (trust the default backend) under a hard subprocess
timeout. If the tunnel is down the attempt hangs in PJRT init and is
killed; if it is up the bench runs to completion on the chip and the
result is banked to BENCH_TPU.json immediately. Every attempt is logged
to TPU_PROBE_LOG.jsonl, so a round with zero successes still leaves a
record proving the tunnel never opened.

Usage: python tools/tpu_probe.py  (run detached; writes logs in repo root)
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")
BANK = os.path.join(REPO, "BENCH_TPU.json")

PERIOD = float(os.environ.get("PROBE_PERIOD_S", 240))
ATTEMPT_TIMEOUT = float(os.environ.get("PROBE_ATTEMPT_TIMEOUT_S", 2700))
TOTAL = float(os.environ.get("PROBE_TOTAL_S", 11 * 3600))


def log(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def attempt_bench(use_pallas: str | None = None):
    """Run bench.py on the default backend. Returns (status, rec|None):
    status in {"tpu", "cpu", "timeout", "error"}."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("SSB_USE_PALLAS", None)  # a stale export must not leak into
    env["BENCH_SKIP_PROBE"] = "1"    # the banked headline (auto) run
    env.setdefault("SSB_ROWS", "6000000")
    if use_pallas is not None:
        env["SSB_USE_PALLAS"] = use_pallas
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            timeout=ATTEMPT_TIMEOUT, capture_output=True, text=True,
            env=env, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        tail = ""
        if e.stderr:
            s = e.stderr if isinstance(e.stderr, str) else \
                e.stderr.decode(errors="replace")
            tail = s[-500:]
        return "timeout", {"stderr": tail}
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    if proc.returncode != 0 or not line.startswith("{"):
        return "error", {"stderr": proc.stderr[-1500:]}
    rec = json.loads(line)
    backend = rec.get("detail", {}).get("backend", "?")
    return ("cpu" if backend == "cpu" else "tpu"), rec


def main():
    start = time.time()
    n = 0
    banked = False
    if os.path.exists(BANK):
        with open(BANK) as f:
            banked = json.load(f).get("detail", {}).get("backend",
                                                        "cpu") != "cpu"
    while time.time() - start < TOTAL:
        n += 1
        t0 = time.time()
        status, rec = attempt_bench()
        log({"attempt": n, "status": status,
             "elapsed_s": round(time.time() - t0, 1),
             **({"error": rec} if status in ("error", "timeout") and rec
                else {})})
        if status == "tpu":
            with open(BANK, "w") as f:
                json.dump(rec, f, indent=1)
            banked = True
            log({"event": "banked TPU bench",
                 "value": rec.get("value")})
            # bank the XLA-scatter leg of the Pallas comparison while
            # the tunnel is up (the banked auto run IS the Pallas leg:
            # on TPU, auto uses the kernel for every eligible plan, and
            # all 13 SSB queries are eligible). Skipped once banked —
            # tunnel up-time is too scarce to re-measure hourly.
            cmp_path = os.path.join(REPO, "BENCH_TPU_PALLAS_never.json")
            if not os.path.exists(cmp_path):
                s2, r2 = attempt_bench(use_pallas="never")
                log({"event": "pallas-never bench", "status": s2,
                     "value": (r2 or {}).get("value"),
                     **({"error": r2} if s2 in ("error", "timeout")
                        and r2 else {})})
                if s2 == "tpu":
                    with open(cmp_path, "w") as f:
                        json.dump(r2, f, indent=1)
        time.sleep(PERIOD if not banked else max(PERIOD, 3600))
    log({"event": "probe loop done", "attempts": n, "banked": banked})


if __name__ == "__main__":
    main()
