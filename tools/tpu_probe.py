"""Round-long TPU availability probe (VERDICT.md round-2 task #3).

The axon tunnel to the TPU flaps: it can be up for minutes and then hang
PJRT client creation indefinitely. Probing and benching in separate
processes loses the up-window (observed: probe ok at T, bench's own probe
dead at T+seconds), so each attempt here IS the bench: run bench.py with
BENCH_SKIP_PROBE=1 (trust the default backend) under a hard subprocess
timeout. If the tunnel is down the attempt hangs in PJRT init and is
killed; if it is up the bench runs to completion on the chip and the
result is banked to BENCH_TPU.json immediately. Every attempt is logged
to TPU_PROBE_LOG.jsonl, so a round with zero successes still leaves a
record proving the tunnel never opened.

Usage: python tools/tpu_probe.py  (run detached; writes logs in repo root)
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")
BANK = os.path.join(REPO, "BENCH_TPU.json")

PERIOD = float(os.environ.get("PROBE_PERIOD_S", 240))
ATTEMPT_TIMEOUT = float(os.environ.get("PROBE_ATTEMPT_TIMEOUT_S", 2700))
TOTAL = float(os.environ.get("PROBE_TOTAL_S", 11 * 3600))


def log(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def attempt_bench(use_pallas: str | None = None, rows: int | None = None):
    """Run bench.py on the default backend. Returns (status, rec|None):
    status in {"tpu", "cpu", "timeout", "error"}."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("SSB_USE_PALLAS", None)  # a stale export must not leak into
    env["BENCH_SKIP_PROBE"] = "1"    # the banked headline (auto) run
    if rows is not None:
        env["SSB_ROWS"] = str(rows)
    else:
        env.setdefault("SSB_ROWS", "6000000")
    if use_pallas is not None:
        env["SSB_USE_PALLAS"] = use_pallas
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            timeout=ATTEMPT_TIMEOUT, capture_output=True, text=True,
            env=env, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        tail = ""
        if e.stderr:
            s = e.stderr if isinstance(e.stderr, str) else \
                e.stderr.decode(errors="replace")
            tail = s[-500:]
        return "timeout", {"stderr": tail}
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    if proc.returncode != 0 or not line.startswith("{"):
        return "error", {"stderr": proc.stderr[-1500:]}
    rec = json.loads(line)
    backend = rec.get("detail", {}).get("backend", "?")
    return ("cpu" if backend == "cpu" else "tpu"), rec


def tunnel_alive(timeout_s: float = 120) -> bool:
    """Cheap liveness check: PJRT init in a subprocess with a timeout —
    much cheaper than re-running the full headline bench once banked."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform if d else 'none')"],
            timeout=timeout_s, capture_output=True, text=True, env=env)
        return proc.returncode == 0 and "cpu" not in proc.stdout
    except subprocess.TimeoutExpired:
        return False


# Extra measurements banked opportunistically after the headline: the
# XLA-scatter leg of the Pallas comparison (the banked auto run IS the
# Pallas leg: on TPU, auto uses the kernel for every eligible plan), and
# the SF10 scale proof (dataset should be pre-generated under .ssb_data
# so the up-window is spent ingesting + querying, not writing parquet).
EXTRA_LEGS = [
    ("pallas-never bench", "BENCH_TPU_PALLAS_never.json",
     dict(use_pallas="never")),
    ("sf10 bench", "BENCH_TPU_SF10.json", dict(rows=60_000_000)),
]
MAX_LEG_FAILURES = 2  # deterministic failures must not eat the window


def main():
    start = time.time()
    n = 0
    banked = False
    leg_failures = {fname: 0 for _, fname, _ in EXTRA_LEGS}
    if os.path.exists(BANK):
        with open(BANK) as f:
            banked = json.load(f).get("detail", {}).get("backend",
                                                        "cpu") != "cpu"
    while time.time() - start < TOTAL:
        n += 1
        t0 = time.time()
        if not banked:
            status, rec = attempt_bench()
            log({"attempt": n, "status": status,
                 "elapsed_s": round(time.time() - t0, 1),
                 **({"error": rec} if status in ("error", "timeout")
                    and rec else {})})
            if status == "tpu":
                with open(BANK, "w") as f:
                    json.dump(rec, f, indent=1)
                banked = True
                log({"event": "banked TPU bench", "value": rec.get("value")})
            up = status == "tpu"
        else:
            up = tunnel_alive()
            log({"attempt": n, "status": "alive" if up else "down",
                 "elapsed_s": round(time.time() - t0, 1)})
        if up:
            for event, fname, kw in EXTRA_LEGS:
                path = os.path.join(REPO, fname)
                if os.path.exists(path) or \
                        leg_failures[fname] >= MAX_LEG_FAILURES:
                    continue
                s2, r2 = attempt_bench(**kw)
                log({"event": event, "status": s2,
                     "value": (r2 or {}).get("value"),
                     **({"error": r2} if s2 in ("error", "timeout")
                        and r2 else {})})
                if s2 == "tpu":
                    with open(path, "w") as f:
                        json.dump(r2, f, indent=1)
                elif s2 == "timeout" and not tunnel_alive():
                    break  # tunnel closed mid-run; retry next cycle
                else:
                    # deterministic error, or a leg too slow for the
                    # attempt timeout while the tunnel is still up: cap
                    # it so it cannot eat the whole window
                    leg_failures[fname] += 1
        legs_done = all(
            os.path.exists(os.path.join(REPO, f))
            or leg_failures[f] >= MAX_LEG_FAILURES
            for _, f, _ in EXTRA_LEGS)
        time.sleep(max(PERIOD, 3600) if banked and legs_done else PERIOD)
    log({"event": "probe loop done", "attempts": n, "banked": banked})


if __name__ == "__main__":
    main()
