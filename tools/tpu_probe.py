"""Round-long TPU availability probe (VERDICT.md round-2 task #3).

The axon tunnel to the TPU flaps: it can be up for minutes and then hang
PJRT client creation indefinitely. Probing and benching in separate
processes loses the up-window (observed: probe ok at T, bench's own probe
dead at T+seconds), so each attempt here IS the bench: run bench.py with
BENCH_SKIP_PROBE=1 (trust the default backend) under a hard subprocess
timeout. If the tunnel is down the attempt hangs in PJRT init and is
killed; if it is up the bench runs to completion on the chip and the
result is banked to BENCH_TPU.json immediately. Every attempt is logged
to TPU_PROBE_LOG.jsonl, so a round with zero successes still leaves a
record proving the tunnel never opened.

Usage: python tools/tpu_probe.py  (run detached; writes logs in repo root)
"""

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TPU_PROBE_LOG.jsonl")
BANK = os.path.join(REPO, "BENCH_TPU.json")

PERIOD = float(os.environ.get("PROBE_PERIOD_S", 240))
ATTEMPT_TIMEOUT = float(os.environ.get("PROBE_ATTEMPT_TIMEOUT_S", 2700))
TOTAL = float(os.environ.get("PROBE_TOTAL_S", 11 * 3600))


def log(rec):
    rec["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")


def attempt_bench(use_pallas: str | None = None, rows: int | None = None,
                  extra_env: dict | None = None,
                  timeout: float | None = None):
    """Run bench.py on the default backend. Returns (status, rec|None):
    status in {"tpu", "cpu", "timeout", "error"}."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.pop("SSB_USE_PALLAS", None)  # a stale export must not leak into
    env["BENCH_SKIP_PROBE"] = "1"    # the banked headline (auto) run
    if rows is not None:
        env["SSB_ROWS"] = str(rows)
    else:
        env.setdefault("SSB_ROWS", "6000000")
    if use_pallas is not None:
        env["SSB_USE_PALLAS"] = use_pallas
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            timeout=timeout or ATTEMPT_TIMEOUT, capture_output=True,
            text=True, env=env, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        tail = ""
        if e.stderr:
            s = e.stderr if isinstance(e.stderr, str) else \
                e.stderr.decode(errors="replace")
            tail = s[-500:]
        return "timeout", {"stderr": tail}
    line = proc.stdout.strip().splitlines()[-1] if proc.stdout.strip() else ""
    if proc.returncode != 0 or not line.startswith("{"):
        return "error", {"stderr": proc.stderr[-1500:]}
    rec = json.loads(line)
    backend = rec.get("detail", {}).get("backend", "?")
    return ("cpu" if backend == "cpu" else "tpu"), rec


def tunnel_alive(timeout_s: float = 120) -> bool:
    """Cheap liveness check: PJRT init in a subprocess with a timeout —
    much cheaper than re-running the full headline bench once banked."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; d = jax.devices(); "
             "print(d[0].platform if d else 'none')"],
            timeout=timeout_s, capture_output=True, text=True, env=env)
        return proc.returncode == 0 and "cpu" not in proc.stdout
    except subprocess.TimeoutExpired:
        return False


def attempt_cmd(argv, extra_env=None, timeout=None):
    """Run a tool subprocess on the live backend; the tool itself is
    responsible for refusing to bank CPU runs (exit 3). Returns status in
    {"ok", "refused-cpu", "timeout", "error"}."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env.update(extra_env or {})
    try:
        proc = subprocess.run(
            [sys.executable] + argv,
            timeout=timeout or ATTEMPT_TIMEOUT, capture_output=True,
            text=True, env=env, cwd=REPO)
    except subprocess.TimeoutExpired as e:
        s = e.stderr or b""
        s = s if isinstance(s, str) else s.decode(errors="replace")
        return "timeout", {"stderr": s[-500:]}
    if proc.returncode == 3:
        return "refused-cpu", None
    if proc.returncode != 0:
        return "error", {"stderr": proc.stderr[-1500:]}
    return "ok", None


# Each leg is (event, done() predicate, run() thunk).
def _bench_leg(fname, **kw):
    def run():
        s, rec = attempt_bench(**kw)
        if s == "tpu":
            with open(os.path.join(REPO, fname), "w") as f:
                json.dump(rec, f, indent=1)
            return "ok", {"value": rec.get("value")}
        return ("refused-cpu" if s == "cpu" else s), rec
    return run


def _file_done(fname):
    return lambda: os.path.exists(os.path.join(REPO, fname))


_PROBE_START = time.time()


def _fresh_done(fname, check=None):
    """Leg done when the artifact was (re)written by THIS probe run —
    round-5 legs rewrite round-4 artifacts in place (old content is in
    git), so existence alone cannot mean done."""
    path = os.path.join(REPO, fname)

    def done():
        try:
            if os.path.getmtime(path) < _PROBE_START:
                return False
            if check is not None:
                with open(path) as f:
                    return check(json.load(f))
            return True
        except Exception:  # noqa: BLE001
            return False
    return done


# Round-5 window plan (VERDICT r4 tasks #1-#3), in priority order for a
# possibly-short window: hardware-validate the byte-plane/chunked kernel
# first (interpret mode cannot catch Mosaic lowering regressions: the
# 3-D chunked output block, step%spc init, i//spc index maps), then the
# fresh SF1 auto bench + per-query profile (the after-trace of the
# roofline fix), then the scale proofs SF10 -> SF20 (the <=60 ms
# over-floor target) -> SF100-on-one-chip eviction churn (dataset
# pre-generated on the host so the window is ingest+queries only).
EXTRA_LEGS = [
    ("pallas hw validation r05",
     _fresh_done("PALLAS_TPU_VALIDATION.json",
                 lambda d: d.get("failed") == 0),
     lambda: attempt_cmd(["tools/validate_pallas_tpu.py"])),
    ("auto bench r05", _file_done("BENCH_TPU_AUTO_r05.json"),
     _bench_leg("BENCH_TPU_AUTO_r05.json")),
    ("per-query profile r05", _fresh_done("PROFILE_TPU.json"),
     lambda: attempt_cmd(["tools/profile_tpu.py"])),
    # the A/B pair must not straddle the round-4/round-5 kernel boundary:
    # refit the auto policy only from THIS round's pair
    ("pallas-never bench r05",
     _file_done("BENCH_TPU_PALLAS_never_r05.json"),
     _bench_leg("BENCH_TPU_PALLAS_never_r05.json", use_pallas="never")),
    ("fit pallas budget r05",
     _fresh_done(os.path.join("tpu_olap", "planner",
                              "pallas_tuning.json")),
     lambda: attempt_cmd(
         ["tools/fit_pallas_budget.py"],
         {"FIT_AUTO_JSON": "BENCH_TPU_AUTO_r05.json",
          "FIT_NEVER_JSON": "BENCH_TPU_PALLAS_never_r05.json"},
         timeout=900)
     if all(os.path.exists(os.path.join(REPO, f)) for f in
            ("BENCH_TPU_AUTO_r05.json", "BENCH_TPU_PALLAS_never_r05.json"))
     else ("skipped", None)),  # inputs pending: not a leg failure
    ("tpu cost calibration r05",
     _fresh_done(os.path.join("tpu_olap", "planner",
                              "cost_calibration.json")),
     lambda: attempt_cmd(["tools/calibrate_cost.py"],
                         {"CAL_REQUIRE_TPU": "1"}, timeout=900)),
    ("sf10 bench r05", _file_done("BENCH_TPU_SF10_r05.json"),
     _bench_leg("BENCH_TPU_SF10_r05.json", rows=60_000_000)),
    ("sf20 bench r05", _file_done("BENCH_TPU_SF20_r05.json"),
     _bench_leg("BENCH_TPU_SF20_r05.json", rows=120_000_000)),
    ("sf100 1-chip bench", _file_done("BENCH_TPU_SF100_1CHIP.json"),
     _bench_leg("BENCH_TPU_SF100_1CHIP.json", rows=600_000_000,
                extra_env={"BENCH_RESULT_DIGEST": "1",
                           "BENCH_RAM_CAP_GB": "64",
                           "BENCH_HBM_BUDGET_BYTES": str(12 * 2**30),
                           "BENCH_ITERS": "3"},
                timeout=7200)),
]
MAX_LEG_FAILURES = 2  # deterministic failures must not eat the window


def main():
    start = time.time()
    n = 0
    banked = False
    leg_failures = {event: 0 for event, _, _ in EXTRA_LEGS}
    if os.path.exists(BANK):
        with open(BANK) as f:
            banked = json.load(f).get("detail", {}).get("backend",
                                                        "cpu") != "cpu"
    while time.time() - start < TOTAL:
        n += 1
        t0 = time.time()
        if not banked:
            status, rec = attempt_bench()
            log({"attempt": n, "status": status,
                 "elapsed_s": round(time.time() - t0, 1),
                 **({"error": rec} if status in ("error", "timeout")
                    and rec else {})})
            if status == "tpu":
                with open(BANK, "w") as f:
                    json.dump(rec, f, indent=1)
                banked = True
                log({"event": "banked TPU bench", "value": rec.get("value")})
            up = status == "tpu"
        else:
            up = tunnel_alive()
            log({"attempt": n, "status": "alive" if up else "down",
                 "elapsed_s": round(time.time() - t0, 1)})
        if up:
            for event, done, run in EXTRA_LEGS:
                if done() or leg_failures[event] >= MAX_LEG_FAILURES:
                    continue
                s2, r2 = run()
                log({"event": event, "status": s2,
                     **({"value": r2.get("value")}
                        if isinstance(r2, dict) and "value" in r2 else {}),
                     **({"error": r2} if s2 in ("error", "timeout")
                        and r2 else {})})
                if s2 in ("ok", "skipped"):
                    continue
                if s2 in ("timeout", "refused-cpu") and not tunnel_alive():
                    break  # tunnel closed mid-run; retry next cycle
                # deterministic error, or a leg too slow for the attempt
                # timeout while the tunnel is still up: cap it so it
                # cannot eat the whole window
                leg_failures[event] += 1
        legs_done = all(
            done() or leg_failures[event] >= MAX_LEG_FAILURES
            for event, done, _ in EXTRA_LEGS)
        time.sleep(max(PERIOD, 3600) if banked and legs_done else PERIOD)
    log({"event": "probe loop done", "attempts": n, "banked": banked})


if __name__ == "__main__":
    main()
