"""Multi-chip smoke (CI `multichip-smoke` job): on a forced 8-device
host mesh, run four SSB queries (GroupBy / TopN-shaped / TimeSeries /
HLL count-distinct — the shapes the retired shard_map path used to
fail on) through the `jit` + `NamedSharding` sharded dispatch and
assert (1) sha256-identical result frames vs the single-device path,
(2) the records really rode the mesh (num_shards == 8, a merge
strategy stamped), (3) a time-filtered query pruned its PER-CHIP
working set (interleaved placement: the local window is a fraction of
each chip's resident segments), and (4) the sparse fan-out broker
merge answers with parity. Exits non-zero on any violation.
Seconds-scale — a pre-merge gate, not a bench (docs/TPU_NOTES.md)."""

import hashlib
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SMOKE_QUERIES = {
    "groupby": """
        SELECT p_brand1, sum(lo_revenue) AS rev, count(*) AS n
        FROM lineorder JOIN part ON lo_partkey = p_partkey
        WHERE p_category = 'MFGR#12' GROUP BY p_brand1
        ORDER BY p_brand1
    """,
    "timeseries": """
        SELECT year(__time) AS yr, sum(lo_revenue) AS rev
        FROM lineorder GROUP BY year(__time) ORDER BY yr
    """,
    "windowed": """
        SELECT s_region, sum(lo_revenue) AS rev
        FROM lineorder JOIN supplier ON lo_suppkey = s_suppkey
        WHERE __time >= '1993-03-01' AND __time < '1993-09-01'
        GROUP BY s_region ORDER BY s_region
    """,
    "hll": """
        SELECT s_region, approx_count_distinct(lo_custkey) AS u
        FROM lineorder JOIN supplier ON lo_suppkey = s_suppkey
        GROUP BY s_region ORDER BY s_region
    """,
}


def _digest(frame) -> str:
    return hashlib.sha256(
        frame.to_csv(float_format="%.6g").encode()).hexdigest()


def main() -> int:
    from tpu_olap.utils.platform import force_cpu_devices
    force_cpu_devices(8)

    from tpu_olap import Engine
    from tpu_olap.bench.ssb import generate_tables, register_ssb
    from tpu_olap.executor import EngineConfig

    tables = generate_tables(120_000, seed=5)
    e1 = Engine(EngineConfig())
    e8 = Engine(EngineConfig(num_shards=8))
    for e in (e1, e8):
        register_ssb(e, tables, block_rows=1 << 11)

    failures = []
    for name, sql in SMOKE_QUERIES.items():
        a = e1.sql(sql)
        b = e8.sql(sql)
        if not e8.last_plan.rewritten:
            failures.append(
                f"{name}: mesh plan fell back: "
                f"{e8.last_plan.fallback_reason}")
            continue
        da, db = _digest(a), _digest(b)
        rec = dict(e8.runner.history[-1])
        print(f"[multichip-smoke] {name}: sha256 "
              f"{'OK' if da == db else 'MISMATCH'} "
              f"num_shards={rec.get('num_shards')} "
              f"merge={rec.get('merge')} "
              f"win/chip={rec.get('segments_window_per_chip')}")
        if da != db:
            failures.append(f"{name}: digest mismatch {da} vs {db}")
        if rec.get("num_shards") != 8:
            failures.append(f"{name}: num_shards={rec.get('num_shards')}")
        if name == "windowed":
            # per-chip pruning: the interleaved placement must have cut
            # each chip's working set to a LOCAL window well under its
            # resident share
            per_chip_total = -(-len(
                e8.catalog.get("lineorder").segments.segments) // 8)
            w = rec.get("segments_window_per_chip")
            if not w or w >= per_chip_total:
                failures.append(
                    f"windowed: no per-chip window (w={w}, "
                    f"per_chip={per_chip_total})")

    # sparse fan-out + broker merge (high-cardinality GROUP BY)
    sparse_sql = ("SELECT lo_custkey, sum(lo_revenue) AS rev, "
                  "count(*) AS n FROM lineorder GROUP BY lo_custkey "
                  "ORDER BY lo_custkey LIMIT 20")
    es1 = Engine(EngineConfig(dense_group_budget=64))
    es8 = Engine(EngineConfig(dense_group_budget=64, num_shards=8))
    for e in (es1, es8):
        register_ssb(e, tables, block_rows=1 << 11)
    sa, sb = es1.sql(sparse_sql), es8.sql(sparse_sql)
    rec = dict(es8.runner.history[-1])
    ok = _digest(sa) == _digest(sb) and rec.get("sparse") \
        and rec.get("num_shards") == 8
    print(f"[multichip-smoke] sparse-fanout: "
          f"{'OK' if ok else 'FAIL'} groups={rec.get('result_groups')}")
    if not ok:
        failures.append(f"sparse-fanout: rec={rec}")

    # sys.devices census reflects the 8-chip placement
    devs = e8.sql("SELECT count(*) AS n FROM sys.devices")
    if int(devs.n[0]) != 8:
        failures.append(f"sys.devices rows={int(devs.n[0])} != 8")

    if failures:
        for f in failures:
            print("[multichip-smoke] FAIL:", f, file=sys.stderr)
        return 1
    print("[multichip-smoke] all checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
