"""Pallas fused one-hot reduce: parity vs the XLA scatter path.

Runs the kernel in interpret mode on the CPU backend (the conftest forces
the virtual-CPU platform), mirroring the reference's plan-level testing
philosophy (SURVEY.md §5): same engine, two physical execution strategies,
identical results required.
"""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.executor.lowering import lower
from tpu_olap.kernels.pallas_reduce import expr_int_bounds
from tpu_olap.ir.expr import BinOp, Col, Lit


def _table(n=4096, seed=3):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2020-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 200, n), unit="s"),
        "color": rng.choice(["red", "green", "blue", None], n),
        "region": rng.choice([f"r{i}" for i in range(12)], n),
        "qty": rng.integers(0, 50, n).astype(np.int64),
        "price": rng.integers(0, 10_000, n).astype(np.int64),
    })
    df.loc[rng.random(n) < 0.05, "qty"] = np.nan  # nullable numeric
    df["qty"] = df["qty"].astype("Int64")
    # columnComparison pairs, derived WITHOUT rng draws (keeps every
    # other column's per-seed values stable): same-vocabulary roll plus
    # deterministic out-of-vocabulary injections so the cross-dictionary
    # translation map carries absent values
    df["dest"] = np.roll(df["region"].to_numpy(), 5)
    df.loc[df.index[::97], "dest"] = "zX"
    df["color2"] = np.roll(df["color"].to_numpy(), 3)  # nullable pair
    return df


def _engines():
    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force"))
    df = _table()
    for e in (plain, forced):
        e.register_table("t", df, time_column="ts", block_rows=512)
    return plain, forced


QUERIES = [
    # single-group total with arithmetic projection + filters (Q1.1 shape)
    """SELECT sum(price * qty) AS rev, count(*) AS n FROM t
       WHERE qty BETWEEN 1 AND 25 AND price < 5000""",
    # group by string dim
    """SELECT color, sum(price) AS s, count(*) AS n FROM t
       GROUP BY color ORDER BY color""",
    # two dims incl. numeric-range dim + IN filter
    """SELECT region, qty, sum(price) AS s FROM t
       WHERE region IN ('r1','r2','r3') GROUP BY region, qty
       ORDER BY region, qty""",
    # filtered aggregator via CASE-less SQL: WHERE-free filtered sums
    """SELECT color, count(*) AS n FROM t
       WHERE NOT (region = 'r5' OR region = 'r6')
       GROUP BY color ORDER BY color""",
    # negative-capable sum (biased half-plane path, the SSB Q4.x profit
    # shape: revenue - cost can go below zero)
    """SELECT color, sum(price - qty * 300) AS profit FROM t
       GROUP BY color ORDER BY color""",
]


def _assert_parity(sql, check_eligible=False):
    plain, forced = _engines()
    a = plain.sql(sql)
    assert plain.last_plan.rewritten
    b = forced.sql(sql)
    assert forced.last_plan.rewritten
    if check_eligible:
        plan = forced.planner.plan(sql)
        phys = lower(plan.query, plan.entry.segments, forced.config)
        assert phys.pallas_reason is None, phys.pallas_reason
    pd.testing.assert_frame_equal(a, b)


@pytest.mark.parametrize("sql", QUERIES)
def test_pallas_parity(sql):
    _assert_parity(sql)


def test_pallas_kernel_is_active():
    _, forced = _engines()
    q = "SELECT color, sum(price) AS s FROM t GROUP BY color"
    plan = forced.planner.plan(q)
    phys = lower(plan.query, plan.entry.segments, forced.config)
    assert phys.pallas_reason is None
    assert "pallas" in phys.statics


def test_pallas_ineligible_falls_back():
    _, forced = _engines()
    # division makes the sum input DOUBLE-typed: outside the int32 kernel
    q = "SELECT color, sum(price / 2) AS m FROM t GROUP BY color"
    plan = forced.planner.plan(q)
    phys = lower(plan.query, plan.entry.segments, forced.config)
    assert phys.pallas_reason is not None
    assert "pallas" not in phys.statics
    # still correct via the generic kernel
    plain, _ = _engines()
    pd.testing.assert_frame_equal(plain.sql(q), forced.sql(q))


MINMAX_QUERIES = [
    # min/max ride a second VPU-accumulated output buffer (round 3);
    # max rides negated so one minimum-accumulate serves both
    """SELECT color, min(price) AS mn, max(price) AS mx, sum(price) AS s
       FROM t GROUP BY color ORDER BY color""",
    # with filters, a nullable input, and a filtered aggregator
    """SELECT region, min(qty) AS mn, max(qty) AS mx,
              min(price) FILTER (WHERE qty > 25) AS mf, count(*) AS n
       FROM t WHERE price < 8000 GROUP BY region ORDER BY region""",
    # global (single group): empty-filter max must render NULL
    """SELECT max(price) FILTER (WHERE qty > 9999) AS none_mx,
       min(price) AS mn FROM t""",
    # negative-capable expression input
    """SELECT color, min(price - 5000) AS mn, max(price - 5000) AS mx
       FROM t GROUP BY color ORDER BY color""",
]


@pytest.mark.parametrize("sql", MINMAX_QUERIES)
def test_pallas_minmax_parity(sql):
    _assert_parity(sql, check_eligible=True)


def test_pallas_group_cap_guard():
    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force", pallas_group_cap=4,
                                 pallas_group_cap_factorized=4))
    df = _table()
    for e in (plain, forced):
        e.register_table("t", df, time_column="ts", block_rows=512)
    q = "SELECT region, count(*) AS n FROM t GROUP BY region ORDER BY region"
    phys_plan = forced.planner.plan(q)
    phys = lower(phys_plan.query, phys_plan.entry.segments, forced.config)
    assert "exceeds pallas cap" in phys.pallas_reason
    pd.testing.assert_frame_equal(plain.sql(q), forced.sql(q))


def test_expr_int_bounds():
    b = {"x": (0, 10), "y": (-5, 5)}
    assert expr_int_bounds(Col("x"), b) == (0, 10)
    assert expr_int_bounds(BinOp("*", Col("x"), Col("y")), b) == (-50, 50)
    assert expr_int_bounds(BinOp("+", Col("x"), Lit(7)), b) == (7, 17)
    assert expr_int_bounds(BinOp("-", Col("x"), Col("y")), b) == (-5, 15)
    assert expr_int_bounds(BinOp("/", Col("x"), Lit(2)), b) is None
    assert expr_int_bounds(Col("z"), b) is None
    assert expr_int_bounds(Lit(1.5), b) is None


WIDENED_QUERIES = [
    # granularity buckets folded into the key (round-3 widening): monthly
    # timeseries — bucket ids computed outside the kernel on int64 time
    """SELECT date_trunc('month', ts) AS m, sum(price) AS s,
              count(*) AS n FROM t GROUP BY date_trunc('month', ts)
       ORDER BY m""",
    # bucket + string dim mixed-radix key
    """SELECT date_trunc('month', ts) AS m, color, sum(price) AS s FROM t
       GROUP BY date_trunc('month', ts), color ORDER BY m, color""",
    # interval mask (time-range predicate) ANDed into the validity mask
    # outside the kernel
    """SELECT color, sum(price) AS s FROM t
       WHERE ts >= '2020-02-01' AND ts < '2020-05-01'
       GROUP BY color ORDER BY color""",
    # interval mask + buckets together (mid-month edges so the mask is
    # not subsumed by bucket pruning)
    """SELECT date_trunc('month', ts) AS m, sum(qty) AS q FROM t
       WHERE ts >= '2020-02-15' AND ts < '2020-06-20'
       GROUP BY date_trunc('month', ts) ORDER BY m""",
]


@pytest.mark.parametrize("sql", WIDENED_QUERIES)
def test_pallas_widened_parity(sql):
    _assert_parity(sql, check_eligible=True)


def test_pallas_k_tiling_parity():
    """Group space wider than pallas_k_per_block tiles over grid axis 0."""
    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force", pallas_k_per_block=16))
    df = _table()
    for e in (plain, forced):
        e.register_table("t", df, time_column="ts", block_rows=512)
    # region(13) x color(4) = 52 groups -> 4 K-blocks of 16
    q = """SELECT region, color, sum(price) AS s, count(*) AS n FROM t
           GROUP BY region, color ORDER BY region, color"""
    plan = forced.planner.plan(q)
    phys = lower(plan.query, plan.entry.segments, forced.config)
    assert phys.pallas_reason is None, phys.pallas_reason
    pd.testing.assert_frame_equal(plain.sql(q), forced.sql(q))


def test_pallas_time_in_kernel_ineligible():
    """A filter on raw __time (not expressible as intervals) must reject."""
    _, forced = _engines()
    q = "SELECT color, sum(ts * 0 + price) AS s FROM t GROUP BY color"
    plan = forced.planner.plan(q)
    if not plan.rewritten:
        return  # planner may refuse the shape entirely — equally safe
    phys = lower(plan.query, plan.entry.segments, forced.config)
    assert phys.pallas_reason is not None


def test_pallas_multichip_parity():
    """Pallas plans under the 8-device virtual mesh: the mesh dispatch
    uses the generic key_fn path (a Pallas kernel is a single-chip
    program), so forced-Pallas configs stay parity-exact sharded."""
    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force", num_shards=8))
    df = _table()
    for e in (plain, forced):
        e.register_table("t", df, time_column="ts", block_rows=256)
    q = """SELECT color, sum(price) AS s, count(*) AS n FROM t
           WHERE qty < 30 GROUP BY color ORDER BY color"""
    a = plain.sql(q)
    b = forced.sql(q)
    pd.testing.assert_frame_equal(a, b)


PRECOMPUTED_DIM_QUERIES = [
    # IN-constrained string dim -> remap kind: ids are gathered on the
    # host side (Mosaic cannot lower 1-D dynamic gathers) and streamed
    # into the kernel as an int32 row input
    """SELECT region, sum(price) AS s FROM t
       WHERE region IN ('r1','r2','r3') GROUP BY region ORDER BY region""",
    # substring extraction dim -> remap
    """SELECT substr(region, 1, 2) AS r2, sum(price) AS s, count(*) AS n
       FROM t GROUP BY substr(region, 1, 2) ORDER BY r2""",
    # two timeformat dims (year + month) -> both precomputed
    """SELECT year(ts) AS y, month(ts) AS mo, sum(price) AS s FROM t
       GROUP BY year(ts), month(ts) ORDER BY y, mo""",
    # mixed in-kernel (codes) + precomputed (timeformat) digits in one
    # mixed-radix key — the SSB q2.1 shape that first failed on hardware
    """SELECT year(ts) AS y, color, sum(price) AS s FROM t
       GROUP BY year(ts), color ORDER BY y, color""",
    # remap + codes + filter together
    """SELECT substr(region, 1, 2) AS r2, color, sum(price) AS s FROM t
       WHERE qty < 40 GROUP BY substr(region, 1, 2), color
       ORDER BY r2, color""",
]


@pytest.mark.parametrize("sql", PRECOMPUTED_DIM_QUERIES)
def test_pallas_precomputed_dim_parity(sql):
    _assert_parity(sql, check_eligible=True)


COLCMP_QUERIES = [
    # string pair via the translation stream (incl. absent-vocab values)
    """SELECT color, sum(price) AS s, count(*) AS n FROM t
       WHERE region = dest GROUP BY color ORDER BY color""",
    # NOT composition: NULL rows match <>
    """SELECT region, count(*) AS n FROM t
       WHERE color <> color2 GROUP BY region ORDER BY region""",
    # nullable string pair + second filter + numeric dim
    """SELECT qty, sum(price) AS s FROM t
       WHERE color = color2 AND qty BETWEEN 0 AND 30
       GROUP BY qty ORDER BY qty""",
    # numeric pair (nullable Int64 vs int64) inside an AND tree
    """SELECT color, count(*) AS n FROM t
       WHERE qty = price OR region = dest GROUP BY color ORDER BY color""",
]


@pytest.mark.parametrize("sql", COLCMP_QUERIES)
def test_pallas_colcmp_parity(sql):
    """columnComparison inside the Pallas kernel: the translation stream
    enters as an ordinary int32 row and the compare is elementwise (no
    in-kernel gather — Mosaic only lowers 2-D gathers)."""
    _assert_parity(sql, check_eligible=True)


def test_pallas_precomputed_dim_kinds():
    """The remap/timeformat dims really take the precomputed path (guards
    against the planner silently reclassifying them as in-kernel)."""
    _, forced = _engines()
    q = """SELECT year(ts) AS y, color, sum(price) AS s FROM t
           GROUP BY year(ts), color"""
    plan = forced.planner.plan(q)
    phys = lower(plan.query, plan.entry.segments, forced.config)
    kinds = [dp.kind for dp in phys.dim_plans]
    assert "timeformat" in kinds and "codes" in kinds, kinds
    assert phys.pallas_reason is None


def test_pallas_large_value_sums():
    """Values spanning the full int32 range exercise every 4-bit plane and
    the f64 half-sum recombination (the int64-shift recombination was
    miscompiled on real hardware; interpret mode guards the math)."""
    rng = np.random.default_rng(11)
    n = 2048
    df = pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(7)], n),
        "big": rng.integers(0, 2**31 - 1, n).astype(np.int64),
        "neg": rng.integers(-(2**30), 2**30, n).astype(np.int64),
    })
    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force"))
    for e in (plain, forced):
        e.register_table("big_t", df, time_column="ts", block_rows=512)
    for q in (
        "SELECT g, sum(big) AS s FROM big_t GROUP BY g ORDER BY g",
        # negative values ride the biased half-plane path with a bias
        # whose magnitude needs both 16-bit halves of the un-shift
        "SELECT g, sum(neg) AS s FROM big_t GROUP BY g ORDER BY g",
    ):
        a = plain.sql(q)
        b = forced.sql(q)
        assert forced.last_plan.rewritten
        plan = forced.planner.plan(q)
        phys = lower(plan.query, plan.entry.segments, forced.config)
        assert phys.pallas_reason is None, phys.pallas_reason
        pd.testing.assert_frame_equal(a, b)


def test_pallas_factorized_boundary_sweep():
    """The factorized lane packing (Factorization: key -> (k1, k2v), k2
    groups per lane tile) must be value-identical to the direct one-hot
    across group counts spanning the direct/factorized decision boundary
    and the K % k2 != 0 tail-slice cases — including biased (negative)
    sums, filtered aggs, and NULL inputs."""
    from tpu_olap.kernels.pallas_reduce import factorization

    rng = np.random.default_rng(23)
    n = 4096
    for card in (2, 9, 16, 63, 200, 1001):
        df = pd.DataFrame({
            "ts": pd.to_datetime("2022-01-01")
            + pd.to_timedelta(rng.integers(0, 86400 * 10, n), unit="s"),
            "g": rng.integers(0, card, n).astype(np.int64),
            "v": rng.integers(-500, 500, n).astype(np.int64),
        })
        df.loc[rng.random(n) < 0.03, "v"] = np.nan
        df["v"] = df["v"].astype("Int64")
        plain = Engine(EngineConfig(use_pallas="never"))
        forced = Engine(EngineConfig(use_pallas="force"))
        for e in (plain, forced):
            e.register_table("f_t", df, time_column="ts", block_rows=512)
        q = ("SELECT g, sum(v) AS s, count(*) AS n, "
             "sum(v) FILTER (WHERE v > 0) AS sp "
             "FROM f_t GROUP BY g ORDER BY g")
        a = plain.sql(q)
        b = forced.sql(q)
        assert forced.last_plan.rewritten
        plan = forced.planner.plan(q)
        phys = lower(plan.query, plan.entry.segments, forced.config)
        assert phys.pallas_reason is None, phys.pallas_reason
        pd.testing.assert_frame_equal(a, b)
    # sanity: the sweep covered both layouts
    cfg = EngineConfig()
    assert factorization(2, 9, 0, cfg) is None
    assert factorization(1001, 9, 0, cfg) is not None


def test_pallas_plane_sizing():
    """Round-5 roofline fix: byte planes sized by the column value span,
    not a fixed 32 bits. A 14-bit span costs 2 planes; a negative span
    biases; a wide positive lo biases only when it saves net columns."""
    from tpu_olap.kernels.pallas_reduce import _sum_plane_spec

    assert _sum_plane_spec(0, 10_000) == (2, 0)
    assert _sum_plane_spec(0, 255) == (1, 0)
    assert _sum_plane_spec(0, 2**31 - 1) == (4, 0)
    # mandatory bias for negative lo
    n, bias = _sum_plane_spec(-500, 500)
    assert bias == -500 and n == 2
    # lo = 2**24: unbiased needs 4 planes, biased needs 1 + the extra
    # row-count column = cheaper
    n, bias = _sum_plane_spec(2**24, 2**24 + 100)
    assert (n, bias) == (1, 2**24)
    # narrow saving: biasing 0..255 span at lo=256 would cost 1+1 vs 2
    assert _sum_plane_spec(256, 511) == (2, 0)


def test_pallas_chunked_accumulator():
    """Grid runs longer than steps_per_chunk flush one accumulator chunk
    per run; the host recombines chunks in f64. Forced here by shrinking
    MAX_VALUE so spc drops to 2 grid steps (production: ~8M rows)."""
    from tpu_olap.kernels import pallas_reduce

    old = pallas_reduce.MAX_VALUE
    pallas_reduce.MAX_VALUE = 256 * 255 * 2 + 1  # spc = 2 at rb = 256
    try:
        rng = np.random.default_rng(47)
        n = 8192
        df = pd.DataFrame({
            "ts": pd.to_datetime("2023-01-01")
            + pd.to_timedelta(rng.integers(0, 86400 * 20, n), unit="s"),
            "gch": rng.choice([f"c{i}" for i in range(11)], n),
            "v": rng.integers(-200, 200, n).astype(np.int64),
            "w": rng.integers(0, 101, n).astype(np.int64),
        })
        df.loc[rng.random(n) < 0.04, "w"] = np.nan
        df["w"] = df["w"].astype("Int64")
        plain = Engine(EngineConfig(use_pallas="never"))
        forced = Engine(EngineConfig(use_pallas="force"))
        for e in (plain, forced):
            e.register_table("ch_t", df, time_column="ts", block_rows=256)
        # 8192 rows / rb 256 = 32 grid steps = 16 chunks; cover biased
        # sums, nullable inputs, filtered aggs, counts, and min/max
        # (unchunked second buffer) in one layout
        for q in (
            """SELECT gch, sum(v) AS s, count(*) AS n,
                      sum(w) FILTER (WHERE v > 0) AS sw
               FROM ch_t GROUP BY gch ORDER BY gch""",
            """SELECT gch, min(v) AS mn, max(v) AS mx, sum(w) AS sw
               FROM ch_t GROUP BY gch ORDER BY gch""",
            "SELECT sum(v * w) AS sv FROM ch_t",
        ):
            a, b = plain.sql(q), forced.sql(q)
            plan = forced.planner.plan(q)
            phys = lower(plan.query, plan.entry.segments, forced.config)
            assert phys.pallas_reason is None, phys.pallas_reason
            pd.testing.assert_frame_equal(a, b)
    finally:
        pallas_reduce.MAX_VALUE = old


def test_pallas_factorized_beyond_direct_cap():
    """Group spaces past pallas_group_cap stay on the kernel when the
    layout factorizes (pallas_group_cap_factorized); min/max layouts
    (no factorization) still reject legibly."""
    rng = np.random.default_rng(31)
    n = 4096
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 5, n), unit="s"),
        "g": rng.integers(0, 20000, n).astype(np.int64),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })
    plain = Engine(EngineConfig(use_pallas="never"))
    forced = Engine(EngineConfig(use_pallas="force"))
    for e in (plain, forced):
        e.register_table("big_k", df, time_column="ts", block_rows=512)
    q = ("SELECT g, sum(v) AS s, count(*) AS n FROM big_k "
         "GROUP BY g ORDER BY g")
    a, b = plain.sql(q), forced.sql(q)
    plan = forced.planner.plan(q)
    phys = lower(plan.query, plan.entry.segments, forced.config)
    assert phys.total_groups > forced.config.pallas_group_cap
    assert phys.pallas_reason is None, phys.pallas_reason
    pd.testing.assert_frame_equal(a, b)
    # a min/max agg blocks factorization -> legible decline past the cap
    q2 = "SELECT g, min(v) AS m FROM big_k GROUP BY g ORDER BY g"
    plan2 = forced.planner.plan(q2)
    phys2 = lower(plan2.query, plan2.entry.segments, forced.config)
    assert phys2.pallas_reason is not None
    assert "does not factorize" in phys2.pallas_reason
    a2, b2 = plain.sql(q2), forced.sql(q2)
    pd.testing.assert_frame_equal(a2, b2)
