"""Telemetry plane (ISSUE 17): the metrics-history sampler
(obs.timeseries), per-chip HBM accounting (executor.dataset.HbmLedger
breakdown + sys.devices), the regression sentinel (obs.sentinel) with
stage-attributed latency drift, W3C traceparent propagation
(obs.trace), and size-based JSONL event-sink rotation (obs.events)."""

import json
import os
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.obs.trace import parse_traceparent
from tpu_olap.resilience.faults import FaultInjector

TP = "00-0af7651916cd43dd8448eb211c80319c-b7ad6b7169203331-01"


def _df(n=4000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 60, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _engine(**kw):
    kw.setdefault("telemetry_interval_s", 0.0)  # manual ticks in tests
    eng = Engine(EngineConfig(**kw))
    eng.register_table("t", _df(), time_column="ts", block_rows=1 << 10)
    return eng


# ------------------------------------------------------ sampler / rings


def test_sampler_rings_bounded_and_match_registry():
    eng = _engine(telemetry_retention=5)
    try:
        for i in range(3):
            eng.sql(f"SELECT g, sum(v) FROM t WHERE v < {900 + i} "
                    "GROUP BY g")
        tel = eng.runner.telemetry
        for _ in range(9):  # > retention: rings must stay bounded
            tel.sample_once()
        snap = tel.snapshot()
        assert snap["samples"] == 9 and snap["retention"] == 5
        assert all(len(s["points"]) <= 5 for s in snap["timeseries"])
        # the newest point of a counter series equals the live registry
        # value — the sampler reports ground truth, not an estimate
        m = eng.runner.metrics
        live = sum(s.value
                   for s in m.counter("queries_total").series.values())
        pts = [s["points"] for s in snap["timeseries"]
               if s["name"].endswith("queries_total")]
        assert pts and sum(p[-1][1] for p in pts) == live
        # ?n=-style per-series cap
        assert all(len(s["points"]) <= 2 for s in
                   tel.snapshot(limit_per_series=2)["timeseries"])
    finally:
        eng.close()


def test_sys_metrics_history_matches_registry_ground_truth():
    eng = _engine()
    try:
        eng.sql("SELECT g, sum(v) FROM t GROUP BY g")
        eng.runner.telemetry.sample_once()
        observed = eng.runner.sentinel.observed
        out = eng.sql("SELECT name, kind, labels, value "
                      "FROM sys.metrics_history")
        assert len(out) > 0
        # cross-check the queries counter against the live registry
        rows = out[out["name"].str.endswith("queries_total")]
        assert len(rows) >= 1
        live = sum(s.value for s in eng.runner.metrics
                   .counter("queries_total").series.values())
        assert float(rows["value"].sum()) == live
        assert set(out["kind"]) <= {"counter", "gauge", "histogram"}
        # labels are JSON (dashboards parse them, not regex them)
        json.loads(out.iloc[0]["labels"])
        # introspection self-attribution ban: the SELECT over
        # sys.metrics_history reached neither sentinel nor workload
        assert eng.runner.sentinel.observed == observed
        assert not any(m.get("datasource") == "sys.metrics_history"
                       for m in list(eng.history))
    finally:
        eng.close()


def test_background_telemetry_graph_ticks():
    eng = _engine(telemetry_interval_s=0.05)
    try:
        deadline = time.time() + 10
        while time.time() < deadline and \
                eng.runner.telemetry.samples < 2:
            time.sleep(0.05)
        assert eng.runner.telemetry.samples >= 2
        assert eng.runner.sentinel.checks >= 1
    finally:
        eng.close()


# ------------------------------------------------- per-chip accounting


def test_per_chip_breakdown_sums_exactly_to_ledger():
    eng = _engine(num_shards=8)
    try:
        eng.sql("SELECT g, sum(v) FROM t GROUP BY g")  # builds the mesh
        led = eng.runner._hbm_ledger
        assert led.num_chips == 8
        bd = led.breakdown()
        core = sum(v for (c, o), v in bd.items() if o != "cache_pins")
        assert core == led.bytes_in_use  # EXACT, not approximate
        assert led.total_bytes() == led.bytes_in_use + sum(
            v for (c, o), v in bd.items() if o == "cache_pins")
        # snapshot rows mirror the ledger, chip by chip
        rows = eng.runner.device_snapshot()
        assert len(rows) == 8
        assert sum(r["hbm_bytes"] for r in rows) == led.bytes_in_use
        for r in rows:
            assert r["hbm_bytes"] == (r["table_column_bytes"]
                                      + r["cube_table_bytes"]
                                      + r["inflight_bytes"])
            assert r["hbm_high_watermark_bytes"] >= r["hbm_bytes"]
        # sys.devices serves the same columns over SQL
        out = eng.sql("SELECT hbm_bytes, cache_pin_bytes, "
                      "hbm_high_watermark_bytes FROM sys.devices")
        assert int(out["hbm_bytes"].sum()) == led.bytes_in_use
    finally:
        eng.close()


def test_per_chip_accounting_tracks_register_and_remove():
    eng = _engine(num_shards=8)
    try:
        eng.sql("SELECT g, sum(v) FROM t GROUP BY g")
        led = eng.runner._hbm_ledger
        before = dict(led.breakdown())
        eng.register_table("t2", _df(seed=9), time_column="ts",
                           block_rows=1 << 10)
        eng.sql("SELECT g, sum(v) FROM t2 GROUP BY g")
        grown = led.breakdown()
        assert sum(v for (c, o), v in grown.items()
                   if o != "cache_pins") == led.bytes_in_use
        assert led.bytes_in_use > sum(
            v for (c, o), v in before.items() if o != "cache_pins")
        wm = led.watermarks()
        assert wm["total"] >= led.bytes_in_use
        assert len(wm["per_chip"]) == 8
    finally:
        eng.close()


def test_hbm_chip_gauges_rendered():
    eng = _engine(num_shards=8)
    try:
        eng.sql("SELECT g, sum(v) FROM t GROUP BY g")
        eng.runner.refresh_resource_gauges()
        text = eng.runner.metrics.render()
        assert 'hbm_chip_bytes{chip="0",owner="table_columns"}' in text
        assert 'hbm_chip_high_watermark_bytes{chip="7"}' in text
        assert "tpu_olap_hbm_high_watermark_bytes" in text
    finally:
        eng.close()


# ------------------------------------------------------------ sentinel


def test_sentinel_attributes_injected_transfer_slowdown():
    eng = _engine(sentinel_min_samples=3, sentinel_latency_factor=2.0,
                  sentinel_latency_floor_ms=1.0,
                  sentinel_clear_after_s=0.3)
    try:
        for i in range(8):
            eng.sql(f"SELECT g, sum(v) FROM t WHERE v < {900 + i} "
                    "GROUP BY g")
        inj = FaultInjector(rate=1.0, stages={"stage-transfer"},
                            latency_s=0.6)
        eng.config.fault_injector = inj
        for i in range(2):
            eng.sql(f"SELECT g, sum(v) FROM t WHERE v < {100 + i} "
                    "GROUP BY g")
        eng.config.fault_injector = None
        assert inj.faults >= 2
        active = eng.runner.sentinel.active()
        assert active, "no alert fired"
        a = active[0]
        assert a["kind"] == "latency_drift"
        assert a["stage"] == "transfer"  # the STAGE, not just "slow"
        assert a["total_ms"] > a["threshold_ms"] > a["baseline_ms"]
        assert not eng.runner.sentinel.health()["ok"]
        out = eng.sql("SELECT kind, stage, status FROM sys.alerts")
        assert list(out["kind"]) == ["latency_drift"]
        assert list(out["stage"]) == ["transfer"]
        text = eng.runner.metrics.render()
        assert 'alerts_active{kind="latency_drift"} 1' in text
        # anomalous samples must NOT teach the baseline that slow is
        # normal — the EWMA stays at the fast-path level
        tid = a["subject"]
        b = eng.runner.sentinel.baseline(tid)
        assert b["anomalies"] >= 1
        assert b["ewma_ms"] < a["total_ms"] / 2
        # moments keep EVERY sample (mergeable by addition)
        assert b["moments"][0] == b["n"] + b["anomalies"]
        # auto-clear: no re-confirmation past clear_after_s
        time.sleep(0.4)
        eng.runner.sentinel.check()
        assert eng.runner.sentinel.health()["ok"]
        assert all(r["status"] == "cleared"
                   for r in eng.runner.sentinel.alert_rows())
        text = eng.runner.metrics.render()
        assert 'alerts_active{kind="latency_drift"} 0' in text
        events = [e["event"] for e in eng.runner.events.snapshot()
                  if e.get("event", "").startswith("alert")]
        assert "alert" in events and "alert_clear" in events
    finally:
        eng.close()


def test_sentinel_moments_merge_by_addition():
    # the PAPERS.md 1803.01969 property the baseline is built on:
    # merged moments == moments of the concatenated sample stream
    from tpu_olap.obs.sentinel import _Baseline
    a, b, both = _Baseline(), _Baseline(), _Baseline()
    xs, ys = [10.0, 12.0, 11.0], [50.0, 55.0]
    for x in xs:
        a.update(x, [], 0.2, False)
        both.update(x, [], 0.2, False)
    for y in ys:
        b.update(y, [], 0.2, False)
        both.update(y, [], 0.2, False)
    merged = [a.moments[i] + b.moments[i] for i in range(3)]
    assert merged == pytest.approx(both.moments)
    assert both.mean() == pytest.approx(sum(xs + ys) / 5)


def test_sentinel_resource_probes_fire_and_gate():
    eng = _engine(sentinel_wal_lag_records=4,
                  sentinel_eviction_thrash=2)
    try:
        s = eng.runner.sentinel
        s.add_probe("wal", lambda: {"t": 10})
        s.check()
        kinds = {(a["kind"], a["subject"]) for a in s.active()}
        assert ("wal_lag", "t") in kinds
        # eviction thrash is a per-tick DELTA: the runner's built-in
        # hbm probe baselined evictions at 0 on the first check, so a
        # sub-threshold growth stays quiet and a burst fires
        s.add_probe("hbm", lambda: {"bytes_in_use": 10, "budget": 100,
                                    "evictions": 1})
        s.check()
        assert not any(a["kind"] == "eviction_thrash"
                       for a in s.active())
        s.add_probe("hbm", lambda: {"bytes_in_use": 99, "budget": 100,
                                    "evictions": 9})
        s.check()
        kinds = {a["kind"] for a in s.active()}
        assert {"eviction_thrash", "hbm_pressure"} <= kinds
        # disabled sentinel goes quiet without tearing down state
        eng.config.sentinel_enabled = False
        before = s.checks
        s.check()
        assert s.checks == before
    finally:
        eng.close()


# ---------------------------------------------------------- traceparent


def test_parse_traceparent_validation():
    ok = parse_traceparent(TP)
    assert ok["trace_id"] == "0af7651916cd43dd8448eb211c80319c"
    assert ok["parent_id"] == "b7ad6b7169203331"
    assert parse_traceparent("  " + TP.upper() + " ")["traceparent"] \
        == TP  # normalized: trimmed + lowercased
    for bad in (None, "", "garbage", "ff-" + TP[3:],
                "00-" + "0" * 32 + "-b7ad6b7169203331-01",
                "00-0af7651916cd43dd8448eb211c80319c-" + "0" * 16
                + "-01"):
        assert parse_traceparent(bad) is None


def test_traceparent_stamped_on_record_and_span():
    eng = _engine()
    try:
        frame, trace = eng._sql_traced(
            "SELECT g, sum(v) FROM t GROUP BY g", traceparent=TP)
        assert len(frame) > 0
        rec = list(eng.history)[-1]
        assert rec["traceparent"] == TP
        assert trace.attrs["traceparent"] == TP
        assert trace.attrs["trace_id"] == TP.split("-")[1]
        # invalid header: ignored, not stamped, never an error
        eng._sql_traced("SELECT count(*) FROM t", traceparent="nope")
        assert "traceparent" not in list(eng.history)[-1]
    finally:
        eng.close()


def test_traceparent_covers_batch_and_ingest():
    eng = _engine()
    try:
        frames, qids = eng.sql_batch_ids(
            ["SELECT count(*) FROM t", "SELECT sum(v) FROM t"],
            traceparent=TP)
        assert len(frames) == 2
        stamped = [m for m in list(eng.history)
                   if m.get("traceparent") == TP]
        assert len(stamped) == 2
        ack = eng.append("t", [{"ts": "2024-02-01", "g": "g0", "v": 1}],
                         traceparent=TP)
        assert ack["traceparent"] == TP
    finally:
        eng.close()


# ------------------------------------------------------- sink rotation


def test_event_sink_rotation_keeps_n_files(tmp_path):
    log = tmp_path / "events.jsonl"
    eng = _engine(event_log_path=str(log), event_log_max_bytes=1500,
                  event_log_rotate_keep=2)
    try:
        ev = eng.runner.events
        for i in range(150):
            ev.emit("spam", i=i, pad="x" * 40)
        assert ev.flush(10.0)
        assert ev.rotations >= 2
        names = sorted(p.name for p in tmp_path.iterdir())
        assert names == ["events.jsonl", "events.jsonl.1",
                         "events.jsonl.2"]  # keep=2 bounds the set
        # every surviving file is intact JSONL and bounded
        for p in tmp_path.iterdir():
            lines = p.read_text().splitlines()
            for ln in lines:
                json.loads(ln)
            if p.name != "events.jsonl":
                assert os.path.getsize(p) >= 1500 - 200
        assert any(e.get("event") == "sink_rotate"
                   for e in ev.snapshot())
    finally:
        eng.close()


def test_event_sink_no_rotation_when_unlimited(tmp_path):
    log = tmp_path / "events.jsonl"
    eng = _engine(event_log_path=str(log), event_log_max_bytes=0)
    try:
        ev = eng.runner.events
        for i in range(100):
            ev.emit("spam", i=i, pad="y" * 60)
        assert ev.flush(10.0)
        assert ev.rotations == 0
        assert [p.name for p in tmp_path.iterdir()] == ["events.jsonl"]
    finally:
        eng.close()
