"""SF100-shaped data path (SURVEY.md §8.4 #4, BASELINE.json:5 "streams
Parquet→HBM"): row-group streaming ingest, multi-file datasets, narrow
int storage, incremental sorted dictionaries, and the HBM budget with LRU
column eviction."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.segments.dictionary import Dictionary
from tpu_olap.segments.ingest import (DictBuilder, _int_dtype_for,
                                      ingest_pandas, ingest_parquet_stream)


def _frame(n, seed, t0="2022-01-01"):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime(t0)
        + pd.to_timedelta(rng.integers(0, 86400 * 25, n), unit="s"),
        "city": rng.choice(["ams", "ber", "cdg", "dub", "edi"], n),
        "status": rng.choice(["ok", "err"], n),
        "qty": rng.integers(0, 90, n).astype(np.int64),         # int8 range
        "price": rng.integers(100, 20000, n).astype(np.int64),  # int16 range
        "wide": rng.integers(0, 10**10, n).astype(np.int64),    # int64 only
        "ratio": rng.random(n),
    })


# ---------------------------------------------------------------- unit

def test_int_dtype_selection():
    assert _int_dtype_for(0, 90) == np.int8
    assert _int_dtype_for(-100, 100) == np.int8
    assert _int_dtype_for(0, 200) == np.int16
    assert _int_dtype_for(-40000, 0) == np.int32
    assert _int_dtype_for(0, 2**40) == np.int64
    # most-negative value of each dtype stays free (sentinel convention)
    assert _int_dtype_for(-128, 0) == np.int16
    assert _int_dtype_for(np.iinfo(np.int32).min, 0) == np.int64


def test_dict_builder_matches_batch_build():
    """Incremental encode + finalize remap == one-shot sorted build."""
    rng = np.random.default_rng(0)
    vals = rng.choice(["pear", "apple", "fig", "kiwi", None], 5000)
    vals = np.asarray(vals, dtype=object)
    ref_dict, ref_codes = Dictionary.build(vals)

    b = DictBuilder()
    parts = [b.encode(vals[i:i + 700]) for i in range(0, 5000, 700)]
    d, remap = b.finalize()
    codes = remap[np.concatenate(parts)]
    assert list(d.values) == list(ref_dict.values)
    np.testing.assert_array_equal(codes, ref_codes)


def test_dict_builder_null_only_empty_string():
    b = DictBuilder()
    c1 = b.encode(np.array([None, "x", None], dtype=object))
    c2 = b.encode(np.array(["", "x"], dtype=object))
    d, remap = b.finalize()
    assert list(d.values) == ["", "x"]   # real "" kept, null-only "" never
    np.testing.assert_array_equal(remap[c1], [0, 2, 0])
    np.testing.assert_array_equal(remap[c2], [1, 2])


def test_narrow_storage_dtypes():
    t = ingest_pandas("t", _frame(3000, 1), time_column="ts", block_rows=512)
    s0 = t.segments[0]
    assert s0.columns["qty"].dtype == np.int8
    assert s0.columns["price"].dtype == np.int16
    assert s0.columns["wide"].dtype == np.int64
    assert s0.columns["city"].dtype == np.int8    # 5 values
    assert s0.columns["ratio"].dtype == np.float64
    assert s0.columns["__time"].dtype == np.int64
    # all segments share the global dtype (stacking stays uniform)
    assert all(s.columns["price"].dtype == np.int16 for s in t.segments)


# ------------------------------------------------------------ streaming

@pytest.fixture()
def multi_file(tmp_path):
    """Three parquet files with several row groups each."""
    frames = [_frame(4000, seed, t0)
              for seed, t0 in ((1, "2022-01-01"), (2, "2022-02-01"),
                               (3, "2022-03-01"))]
    paths = []
    for i, f in enumerate(frames):
        p = str(tmp_path / f"part{i}.parquet")
        pq.write_table(pa.Table.from_pandas(f, preserve_index=False), p,
                       row_group_size=900)
        paths.append(p)
    return paths, pd.concat(frames, ignore_index=True)


SQLS = [
    "SELECT city, sum(qty) AS s, count(*) AS n FROM t "
    "GROUP BY city ORDER BY city",
    "SELECT status, sum(price) AS p, min(wide) AS w FROM t "
    "GROUP BY status ORDER BY status",
    "SELECT sum(qty*price) AS v FROM t WHERE qty < 25",
]


def test_multi_file_streaming_parity(multi_file):
    paths, whole = multi_file
    eng = Engine()
    eng.register_table("t", paths, time_column="ts")
    ref = Engine()
    ref.register_table("t", whole, time_column="ts")
    for q in SQLS:
        got, exp = eng.sql(q), ref.sql(q)
        assert eng.last_plan.rewritten
        pd.testing.assert_frame_equal(got, exp)


def test_streaming_batches_bounded(multi_file):
    """iter_batches path: tiny batch size exercises the carry/flush
    logic; segment time ranges stay exact for pruning."""
    paths, whole = multi_file
    t = ingest_parquet_stream("t", paths, time_column="ts",
                              block_rows=1024, batch_rows=333)
    assert t.num_rows == len(whole)
    for s in t.segments:
        if s.meta.n_valid:
            tv = s.columns["__time"][:s.meta.n_valid].astype(np.int64)
            assert tv.min() == s.meta.time_min
            assert tv.max() == s.meta.time_max
    # dictionary is sorted (bound filters rely on it)
    d = t.dictionaries["city"]
    assert list(d.values) == sorted(d.values)


def test_streaming_interval_pruning(multi_file):
    """Month-disjoint files must prune to ~1/3 of segments."""
    paths, whole = multi_file
    eng = Engine()
    eng.register_table("t", paths, time_column="ts", block_rows=1024)
    got = eng.sql("SELECT sum(qty) AS s FROM t "
                  "WHERE ts >= '2022-03-01' AND ts < '2022-04-01'")
    m = whole[whole.ts >= "2022-03-01"]
    assert int(got.s[0]) == int(m.qty.sum())
    h = eng.history[-1]
    assert h["segments_scanned"] < h["segments_total"] / 2


def test_schema_mismatch_across_files(tmp_path):
    a = str(tmp_path / "a.parquet")
    b = str(tmp_path / "b.parquet")
    pd.DataFrame({"x": [1, 2]}).to_parquet(a)
    pd.DataFrame({"y": [1.0]}).to_parquet(b)
    with pytest.raises(ValueError, match="schema mismatch"):
        ingest_parquet_stream("t", [a, b])


def test_empty_table_finalize():
    ing_df = pd.DataFrame({"ts": pd.to_datetime([]), "g": pd.Series([], dtype=str),
                           "v": pd.Series([], dtype=np.int64)})
    t = ingest_pandas("t", ing_df, time_column="ts")
    assert t.num_rows == 0
    assert "g" in t.dictionaries


# ------------------------------------------------------------ HBM budget

def test_hbm_budget_lru_eviction():
    df = _frame(6000, 7)
    eng = Engine(EngineConfig(hbm_budget_bytes=1))  # evict everything else
    eng.register_table("t", df, time_column="ts", block_rows=1024)
    eng.sql("SELECT city, sum(qty) AS s FROM t GROUP BY city")
    eng.sql("SELECT status, sum(price) AS p FROM t GROUP BY status")
    led = eng.runner._hbm_ledger
    assert led.evictions > 0
    # correctness survives eviction: re-run the first query
    got = eng.sql("SELECT city, sum(qty) AS s FROM t "
                  "GROUP BY city ORDER BY city")
    exp = df.groupby("city", as_index=False).agg(s=("qty", "sum"))
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)
    assert eng.history[-1]["hbm_evictions"] > 0


def test_hbm_budget_pins_working_set():
    """Within one query, the env build must not evict its own columns."""
    df = _frame(4000, 8)
    eng = Engine(EngineConfig(hbm_budget_bytes=1))
    eng.register_table("t", df, time_column="ts", block_rows=1024)
    got = eng.sql("SELECT city, status, sum(qty) AS s, sum(price) AS p, "
                  "max(wide) AS w FROM t GROUP BY city, status "
                  "ORDER BY city, status")
    exp = (df.groupby(["city", "status"], as_index=False)
           .agg(s=("qty", "sum"), p=("price", "sum"), w=("wide", "max")))
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


def test_unbudgeted_ledger_keeps_all():
    df = _frame(3000, 9)
    eng = Engine()
    eng.register_table("t", df, time_column="ts", block_rows=1024)
    eng.sql("SELECT city, sum(qty) AS s FROM t GROUP BY city")
    assert eng.runner._hbm_ledger.evictions == 0


def test_all_null_string_batch_streams(tmp_path):
    """A parquet file whose string column is entirely null in a batch
    reads via read_dictionary as an EMPTY dictionary — must ingest as
    all-null codes, not crash (round-3 dictionary fast path)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from tpu_olap.segments.ingest import ingest_parquet_stream
    n = 600
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(np.arange(n), unit="min"),
        "s": pd.array([None] * n, dtype="string"),
        "v": np.arange(n, dtype=np.int64),
    })
    p = str(tmp_path / "nulls.parquet")
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), p,
                   row_group_size=128)
    seg = ingest_parquet_stream("t", [p], "ts", block_rows=256)
    assert seg.num_rows == n
    assert seg.dictionaries["s"].cardinality == 0
    assert all((s.columns["s"][:s.meta.n_valid] == 0).all()
               for s in seg.segments)
