"""Server behavior under concurrent mixed load (VERDICT r4 weak #6):
the BI-connectivity layer's job (SURVEY.md §3.1 ThriftServer role) is N
clients at once, so beyond cache SAFETY (test_cache_safety.py) CI must
pin BEHAVIOR: with device-path, fallback, and planner-only statements
interleaved across threads, every class keeps making progress — the
shared device lock must not starve any class, and no request may error.
The full banked artifact (p50/p99 per class, throughput) comes from
tools/bench_concurrency.py -> BENCH_CONCURRENCY.json; this is the
regression gate."""

import json
import threading
import time
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer
from tpu_olap.executor import EngineConfig

CLASSES = {
    "grouped": "SELECT g, sum(v) AS s, count(*) AS n FROM t "
               "GROUP BY g ORDER BY g",
    "ungrouped": "SELECT sum(v) AS s, count(*) AS n FROM t WHERE v < 500",
    "fallback": "SELECT g, v, row_number() OVER "
                "(PARTITION BY g ORDER BY v DESC) AS r FROM t "
                "WHERE v > 990",
    "statement": "EXPLAIN DRUID REWRITE SELECT g, sum(v) AS s FROM t "
                 "GROUP BY g",
}


@pytest.fixture(scope="module")
def served_engine():
    rng = np.random.default_rng(11)
    rows = 20_000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, rows), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(32)], rows),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    eng = Engine(EngineConfig(query_deadline_s=30.0))
    eng.register_table("t", df, time_column="ts", block_rows=1 << 12)
    srv = QueryServer(eng).start()
    # warm every class once: timed samples are the BI steady state
    for sql in CLASSES.values():
        eng.sql(sql)
    yield eng, srv
    srv.stop()


def test_no_class_starves_under_mixed_load(served_engine):
    eng, srv = served_engine
    results: list = []
    stop = threading.Event()

    def client(sql, label):
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                req = urllib.request.Request(
                    srv.url + "/sql",
                    data=json.dumps({"query": sql}).encode(),
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=60) as r:
                    json.loads(r.read())
                ok = True
            except Exception:  # noqa: BLE001 — counted, not raised
                ok = False
            results.append((label, time.perf_counter() - t0, ok))

    labels = list(CLASSES)
    threads = [threading.Thread(target=client,
                                args=(CLASSES[lb], lb), daemon=True)
               for lb in labels for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(2.5)
    stop.set()
    for t in threads:
        t.join(timeout=90)

    by_class = {lb: [r for r in results if r[0] == lb] for lb in labels}
    starved = [lb for lb, rs in by_class.items() if not rs]
    assert not starved, f"classes made no progress: {starved}"
    errs = [(lb, sum(1 for _, _, ok in rs if not ok))
            for lb, rs in by_class.items()]
    assert all(n == 0 for _, n in errs), f"request errors: {errs}"
    # the device lock serialized device dispatches without deadlock:
    # grouped+ungrouped rode the device path (history counts them)
    assert len(eng.history) >= len(by_class["grouped"])


def test_pipelined_16_thread_mixed_class_parity():
    """ISSUE 10: with pipelined execution (pipeline_depth >= 2, the
    default) 16 threads of mixed classes — device GROUP BY, device
    global agg, pandas fallback, planner-only statement — hammer one
    engine directly. Every response must be frame-identical to the
    single-threaded reference: the enqueue-only lock scope must not
    let stage-2 completions cross-contaminate plans, caches, records,
    or results."""
    rng = np.random.default_rng(31)
    rows = 20_000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, rows), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(32)], rows),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    eng = Engine(EngineConfig(pipeline_depth=2))
    eng.register_table("t", df, time_column="ts", block_rows=1 << 12)
    # deterministic-response classes only (EXPLAIN output includes no
    # frame to compare, so the statement class checks shape instead)
    ref = {lb: eng.sql(sql) for lb, sql in CLASSES.items()}
    h0 = len(eng.runner.history)

    errs: list = []
    stop = threading.Event()

    def client(label):
        sql = CLASSES[label]
        while not stop.is_set():
            try:
                out = eng.sql(sql)
                if label == "statement":
                    if list(out.columns) != list(ref[label].columns):
                        errs.append((label, "columns drifted"))
                elif not out.equals(ref[label]):
                    errs.append((label, "frame mismatch"))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errs.append((label, repr(e)))

    labels = list(CLASSES)
    threads = [threading.Thread(target=client, args=(labels[i % 4],),
                                daemon=True) for i in range(16)]
    for t in threads:
        t.start()
    time.sleep(2.5)
    stop.set()
    for t in threads:
        t.join(timeout=90)
    assert not errs, errs[:5]
    # the device classes really rode the pipelined path
    piped = [m for m in eng.runner.history[h0:] if m.get("pipelined")]
    assert piped, "no pipelined records under mixed load"
    # all in-flight accounting drained
    snap = eng.runner.admission.snapshot()
    assert snap["pipeline_inflight"] == 0
    assert eng.runner._hbm_ledger.inflight_bytes == 0


def test_coalescing_window_batches_concurrent_queries():
    """batch_window_ms > 0: concurrent execute() callers ride ONE
    shared-scan dispatch (executor.batch.Coalescer) — identical
    in-flight queries scan once, distinct compatible ones fuse — and
    every caller still gets exactly its own sequential-path result."""
    rng = np.random.default_rng(23)
    rows = 20_000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, rows), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(16)], rows),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
    })
    eng = Engine(EngineConfig(batch_window_ms=40.0))
    eng.register_table("t", df, time_column="ts", block_rows=1 << 12)
    sqls = {
        "a": "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g",
        "b": "SELECT sum(v) AS s, count(*) AS n FROM t WHERE v < 500",
    }
    ref = {k: eng.sql(q) for k, q in sqls.items()}  # warm via coalescer
    h0 = len(eng.history)

    out: dict = {}
    n_threads = 6
    barrier = threading.Barrier(n_threads)

    def client(i, key):
        barrier.wait()
        out[(i, key)] = eng.sql(sqls[key])

    threads = [threading.Thread(target=client,
                                args=(i, "a" if i % 2 else "b"))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert len(out) == n_threads
    for (i, key), frame in out.items():
        assert frame.equals(ref[key]), (i, key)
    # at least one multi-query batch formed inside the window, and its
    # shared pass carries the attribution fields
    hist = eng.history[h0:]
    batched = [m for m in hist if m.get("batch_size", 0) >= 2
               and not m.get("batch_dedup")]
    assert batched, "no coalesced batch formed inside the window"
    assert all("scan_ms_shared" in m and "agg_ms" in m for m in batched)
    # far fewer physical scans than logical queries
    scans = [m for m in hist if not m.get("batch_dedup")]
    assert len(scans) < n_threads
