"""Real-time ingest (ISSUE 13; docs/INGEST.md): durable delta
segments, WAL crash recovery, and backpressured compaction.

Covers the tentpole contracts:
- appended rows are queryable immediately alongside sealed segments
  with exact parity vs a one-shot registration of the same rows (device
  path, fallback path, and the lexicographic-bound fast path across an
  append-extended, temporarily-unsorted dictionary);
- every acknowledged append survives a crash: a fresh engine
  registering the same base replays the WAL to the exact acknowledged
  state (sha256-identical query results), a torn WAL tail truncates
  cleanly, and re-registering a LIVE table resets the log;
- a full delta sheds with 429 + Retry-After (never a silent drop) and
  compaction (sync + background) seals deltas into time-partitioned
  segments, re-sorting the dictionary, without losing racing appends;
- partial-survival: a delta-only append leaves sealed-segment tier-1
  cache partials servable (hit-rate > 0) and does NOT stale
  generation-current cubes — cube serves fold the delta remainder
  through the base path with zero stale serves;
- a seeded kill-and-recover chaos suite across the append/wal-write/
  wal-replay/compact fault sites (append ∥ query ∥ compact ∥ crash →
  replay → parity).
"""

import hashlib
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.resilience import FaultInjector
from tpu_olap.resilience.errors import IngestBackpressure, UserError

BLOCK = 512


def _df(n=2000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2022-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 45, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _cfg(**kw):
    kw.setdefault("ingest_auto_compact", False)
    kw.setdefault("cube_auto_refresh", False)
    return EngineConfig(**kw)


def _engine(data=None, **kw):
    eng = Engine(_cfg(**kw))
    eng.register_table("t", _df() if data is None else data,
                       time_column="ts", block_rows=BLOCK)
    return eng


def _rows_frame(rows):
    """Appended row dicts -> the frame a one-shot reference registers."""
    df = pd.DataFrame(rows)
    df["ts"] = pd.to_datetime(df["ts"], format="mixed")
    return df


def _reference(extra_rows, n=2000, seed=3):
    base = _df(n, seed)
    data = pd.concat([base, _rows_frame(extra_rows)],
                     ignore_index=True) if extra_rows else base
    ref = Engine()
    ref.register_table("t", data, time_column="ts", block_rows=BLOCK)
    return ref


PARITY_QUERIES = [
    "SELECT g, count(*) AS n, sum(v) AS s FROM t GROUP BY g ORDER BY g",
    "SELECT month(ts) AS mo, sum(v) AS s, min(v) AS lo, max(v) AS hi "
    "FROM t GROUP BY month(ts) ORDER BY mo",
    "SELECT count(*) AS n, sum(v) AS s FROM t WHERE v < 500",
    "SELECT g, sum(v) AS s FROM t "
    "WHERE ts >= TIMESTAMP '2022-04-01' GROUP BY g ORDER BY g",
]


def _digest(frame: pd.DataFrame) -> str:
    return hashlib.sha256(
        frame.to_csv(index=False).encode()).hexdigest()


def _assert_parity(eng, ref, label=""):
    for q in PARITY_QUERIES:
        a, b = eng.sql(q), ref.sql(q)
        assert _digest(a) == _digest(b), \
            f"{label}: {q}\n{a}\nvs\n{b}"


# ------------------------------------------------------------- appends

def test_append_visible_immediately_with_parity():
    eng = _engine()
    rows = [{"ts": "2022-04-20T01:02:03", "g": "g1", "v": 7},
            {"ts": "2022-05-02T00:00:00", "g": "g5", "v": 10}]
    out = eng.append("t", rows)
    assert out["rows"] == 2 and out["delta_rows"] == 2
    ts = eng.catalog.get("t").segments
    assert ts.delta_ids() and ts.sealed_generation < ts.generation
    _assert_parity(eng, _reference(rows), "append")


def test_append_new_dict_values_and_lex_bounds():
    """Unseen string values take tail codes (dictionary temporarily
    unsorted): lexicographic bound filters must stay exact via the
    predicate-table fallback, and GROUP BY ordering must stay
    value-ordered."""
    eng = _engine()
    rows = [{"ts": "2022-04-20", "g": "aardvark", "v": 1},
            {"ts": "2022-04-21", "g": "zzz", "v": 2},
            {"ts": "2022-04-22", "g": "g3", "v": 3}]
    eng.append("t", rows)
    assert not eng.catalog.get("t").segments.dictionaries["g"].is_sorted
    ref = _reference(rows)
    for q in ["SELECT count(*) AS n FROM t WHERE g >= 'g5' AND g < 'z'",
              "SELECT count(*) AS n FROM t WHERE g BETWEEN 'a' AND 'b'",
              "SELECT g, count(*) AS n FROM t WHERE g > 'g6' "
              "GROUP BY g ORDER BY g",
              "SELECT count(*) AS n FROM t WHERE g LIKE 'g%'"]:
        assert _digest(eng.sql(q)) == _digest(ref.sql(q)), q
    _assert_parity(eng, ref, "new-dict")


def test_append_validation_never_half_applied():
    eng = _engine()
    before = eng.catalog.get("t").segments
    with pytest.raises(UserError):
        eng.append("t", [{"ts": "2022-04-20", "nope": 1}])
    with pytest.raises(UserError):  # LONG column, junk value
        eng.append("t", [{"ts": "2022-04-20", "g": "g1", "v": "x"}])
    with pytest.raises(UserError):  # non-null time required
        eng.append("t", [{"g": "g1", "v": 1}])
    after = eng.catalog.get("t").segments
    assert after is before and after.delta_rows == 0
    # unaccelerated tables refuse legibly
    eng.register_table("plain", pd.DataFrame({"x": [1]}),
                       accelerate=False)
    with pytest.raises(UserError):
        eng.append("plain", [{"x": 2}])


def test_append_nulls_and_numeric_widening():
    eng = _engine()
    rows = [{"ts": "2022-04-20", "g": None, "v": None},
            {"ts": "2022-04-21", "g": "g1", "v": 1_000_000}]
    eng.append("t", rows)  # v widens past the sealed int16 range
    got = eng.sql("SELECT count(*) AS n, sum(v) AS s, "
                  "count(v) AS nv FROM t")
    assert int(got["n"][0]) == 2002
    assert int(got["nv"][0]) == 2001  # the NULL v doesn't count
    assert int(got["s"][0]) == 999008 + 1_000_000


def test_insert_into_sql_verb():
    eng = _engine()
    out = eng.sql("INSERT INTO t (ts, g, v) VALUES "
                  "(TIMESTAMP '2022-04-20 01:02:03', 'g1', 7), "
                  "('2022-05-02', 'it''s', NULL)")
    assert int(out["rows"][0]) == 2 and int(out["delta_rows"][0]) == 2
    got = eng.sql("SELECT count(*) AS n FROM t WHERE g = 'it''s'")
    assert int(got["n"][0]) == 1
    with pytest.raises(UserError):
        eng.sql("INSERT INTO t (ts, g) VALUES (1, 'a', 3)")


def test_fallback_path_sees_delta():
    eng = _engine()
    rows = [{"ts": "2022-04-20", "g": "g1", "v": 7}]
    eng.append("t", rows)
    # force the interpreter: fallback frames must include the delta
    from tpu_olap.planner.fallback import execute_fallback
    from tpu_olap.planner.sqlparse import parse_sql
    got = execute_fallback(
        parse_sql("SELECT count(*) AS n, sum(v) AS s FROM t"),
        eng.catalog, eng.config)
    assert int(got["n"][0]) == 2001
    assert int(got["s"][0]) == 999008 + 7


# ---------------------------------------------------- WAL / recovery

def test_wal_replay_restores_acknowledged_state(tmp_path):
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    acked = []
    for i in range(5):
        rows = [{"ts": f"2022-05-{10 + i:02d}", "g": f"w{i}",
                 "v": i * 100}]
        out = eng.append("t", rows)
        assert out["wal_seq"] == i + 1
        acked.extend(rows)
    digests = {q: _digest(eng.sql(q)) for q in PARITY_QUERIES}
    # crash: abandon the engine; a fresh process registers the same
    # base and the WAL replays to the exact acknowledged state
    rec = _engine(ingest_wal_dir=wal)
    assert rec.catalog.get("t").segments.delta_rows == 5
    for q in PARITY_QUERIES:
        assert _digest(rec.sql(q)) == digests[q], q
    ev = [e for e in rec.runner.events.snapshot()
          if e["event"] == "wal_replay"]
    assert ev and ev[0]["records"] == 5 and ev[0]["rows"] == 5
    # the replayed engine keeps appending with continuous seqs
    assert rec.append("t", acked[:1])["wal_seq"] == 6


def test_wal_torn_tail_truncates(tmp_path):
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    eng.append("t", [{"ts": "2022-05-10", "g": "w", "v": 1}])
    want = _digest(eng.sql(PARITY_QUERIES[0]))
    path = os.path.join(wal, "t.wal")
    good = os.path.getsize(path)
    with open(path, "ab") as f:
        f.write(b"\x40\x00\x00\x00torn-frame-garbage")
    rec = _engine(ingest_wal_dir=wal)
    assert _digest(rec.sql(PARITY_QUERIES[0])) == want
    assert os.path.getsize(path) == good  # tail cut off


def test_reregistering_live_table_resets_wal(tmp_path):
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    eng.append("t", [{"ts": "2022-05-10", "g": "w", "v": 1}])
    assert os.path.getsize(os.path.join(wal, "t.wal")) > 0
    # fresh data replaces the table IN-PROCESS: the logged appends
    # belonged to the old data — no replay, log truncated
    eng.register_table("t", _df(seed=9), time_column="ts",
                       block_rows=BLOCK)
    assert eng.catalog.get("t").segments.delta_rows == 0
    assert os.path.getsize(os.path.join(wal, "t.wal")) == 0


def test_drop_table_deletes_wal(tmp_path):
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    eng.append("t", [{"ts": "2022-05-10", "g": "w", "v": 1}])
    eng.drop_table("t")
    assert not os.path.exists(os.path.join(wal, "t.wal"))


# ------------------------------------------- backpressure / compaction

def test_backpressure_sheds_never_drops():
    eng = _engine(ingest_max_delta_rows=8)
    ok = eng.append("t", [{"ts": "2022-05-01", "g": "a", "v": 1}] * 8)
    assert ok["delta_rows"] == 8
    with pytest.raises(IngestBackpressure) as ei:
        eng.append("t", [{"ts": "2022-05-01", "g": "a", "v": 1}])
    assert ei.value.http_status == 429
    assert ei.value.retry_after_s > 0
    # shed means SHED: the rejected row is absent, the 8 accepted stay
    assert eng.catalog.get("t").segments.delta_rows == 8
    assert int(eng.sql("SELECT count(*) AS n FROM t")["n"][0]) == 2008
    # compaction drains the delta; the retried append then lands
    eng.compact_now("t")
    assert eng.append("t", [{"ts": "2022-05-01", "g": "a",
                             "v": 1}])["rows"] == 1


def test_compaction_seals_resorts_and_preserves_results():
    eng = _engine()
    rows = [{"ts": "2022-04-20", "g": "zzz", "v": 5},
            {"ts": "2022-02-01", "g": "aaa", "v": 6}]
    eng.append("t", rows)
    ref = _reference(rows)
    ts0 = eng.catalog.get("t").segments
    res = eng.compact_now("t")
    assert res["delta_rows_folded"] == 2
    ts1 = eng.catalog.get("t").segments
    assert ts1.delta_rows == 0 and ts1.sealed_count == len(ts1.segments)
    assert ts1.sealed_generation > ts0.sealed_generation
    assert ts1.dictionaries["g"].is_sorted  # tail re-sorted
    # sealed blocks are time-ordered again (id order tracks time_min)
    mins = [s.meta.time_min for s in ts1.segments]
    assert mins == sorted(mins)
    _assert_parity(eng, ref, "post-compact")
    # SQL spelling
    out = eng.sql("COMPACT DRUID TABLE t")
    assert out["status"][0] == "empty-delta"


def test_compaction_keeps_racing_appends():
    eng = _engine()
    stop = threading.Event()
    appended = []

    def writer():
        i = 0
        while not stop.is_set():
            rows = [{"ts": "2022-04-25", "g": f"r{i % 4}", "v": i}]
            eng.append("t", rows)
            appended.extend(rows)
            i += 1

    t = threading.Thread(target=writer)
    t.start()
    try:
        time.sleep(0.05)
        for _ in range(3):
            eng.compact_now("t")
    finally:
        stop.set()
        t.join()
    eng.compact_now("t")
    _assert_parity(eng, _reference(appended), "racing-appends")


def test_background_compactor_and_close_joins_threads():
    eng = _engine(ingest_auto_compact=True, ingest_compact_rows=4,
                  ingest_compact_interval_s=0.05)
    rows = [{"ts": "2022-04-25", "g": "bg", "v": 1}] * 6
    eng.append("t", rows)
    deadline = time.monotonic() + 10
    while eng.catalog.get("t").segments.delta_rows and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert eng.catalog.get("t").segments.delta_rows == 0
    _assert_parity(eng, _reference(rows), "bg-compact")
    # deterministic shutdown: the compactor/maintainer background
    # graphs are cancelled (and any in-progress pass joined)
    compactor = eng.ingest._compact_handle
    assert compactor is not None
    eng.close()
    assert eng.ingest._compact_handle is None
    assert compactor.cancelled and not compactor.running
    m = eng.cubes._handle
    assert m is None or (m.cancelled and not m.running)
    # the engine stays usable after close
    assert int(eng.sql("SELECT count(*) AS n FROM t")["n"][0]) == 2006


# ------------------------------------------------- partial survival

def test_delta_append_preserves_tier1_partials():
    eng = _engine(data=_df(4000), segment_cache_enabled=True,
                  result_cache_enabled=True)
    q = PARITY_QUERIES[0]
    eng.sql(q)                       # populate tier 1 + tier 2
    rec = eng.runner.history[-1]
    n_sealed_cached = rec["segments_computed"]
    eng.sql(q)                       # tier-2 hit
    assert eng.runner.history[-1].get("cache_tier") == "full"
    stats0 = dict(eng.runner.result_cache.stats["segment"])
    eng.append("t", [{"ts": "2022-05-01", "g": "g3", "v": 3}])
    out = eng.sql(q)                 # tier-2 stale; tier-1 survives
    rec = eng.runner.history[-1]
    stats1 = dict(eng.runner.result_cache.stats["segment"])
    assert rec.get("cache_tier") == "segment"
    assert rec["segments_cached"] > 0, "sealed partials were evicted"
    assert stats1["hit"] - stats0["hit"] == rec["segments_cached"]
    # only straddlers + the delta block recomputed, not the store
    assert rec["segments_computed"] < n_sealed_cached
    ref = _reference([{"ts": "2022-05-01", "g": "g3", "v": 3}],
                     n=4000)
    assert _digest(out) == _digest(ref.sql(q))


def test_delta_append_keeps_cube_current_zero_stale():
    eng = _engine(data=_df(4000), cube_serve_min_reduction=0.0)
    eng.sql("CREATE DRUID CUBE c1 ON t DIMENSIONS (g) "
            "GRANULARITY month AGGREGATES (sum(v), count(*))")
    q = PARITY_QUERIES[0]
    eng.sql(q)
    assert eng.runner.history[-1].get("cube") == "c1"
    rows = [{"ts": "2022-05-01", "g": "g3", "v": 3},
            {"ts": "2022-03-05", "g": "new_val", "v": 11}]
    eng.append("t", rows)
    cube = eng.cubes.get("c1")
    assert not cube.snapshot_row(eng)["stale"], \
        "delta-only append must not stale the cube"
    out = eng.sql(q)
    rec = eng.runner.history[-1]
    assert rec.get("cube") == "c1" and rec.get("delta_segments") == 1
    ref = _reference(rows, n=4000)
    assert _digest(out) == _digest(ref.sql(q))  # zero stale serves
    assert cube.refreshes == 0  # no full rebuild for the open bucket
    # compaction changes the SEALED set: now the cube is stale until
    # the maintainer/REFRESH rebuilds it — and never served meanwhile
    eng.compact_now("t")
    assert cube.snapshot_row(eng)["stale"]
    out = eng.sql(q)
    assert eng.runner.history[-1].get("cube") is None
    assert _digest(out) == _digest(ref.sql(q))
    eng.sql("REFRESH DRUID CUBES")
    out = eng.sql(q)
    assert eng.runner.history[-1].get("cube") == "c1"
    assert _digest(out) == _digest(ref.sql(q))


# -------------------------------------------------- surfaces / obs

def test_sys_segments_kind_watermark_and_debug_ingest():
    eng = _engine()
    eng.append("t", [{"ts": "2022-05-01", "g": "g1", "v": 1}])
    segs = eng.sql("SELECT * FROM sys.segments WHERE table = 't'")
    kinds = set(segs["kind"])
    assert kinds == {"sealed", "delta"}
    wm = eng.catalog.get("t").segments.watermark
    assert (segs["watermark"] == wm).all()
    delta = segs[segs["kind"] == "delta"]
    assert int(delta["rows"].sum()) == 1
    snap = eng.ingest.snapshot()
    ti = snap["tables"]["t"]
    assert ti["delta_rows"] == 1 and ti["watermark"] == wm
    assert ti["appended_rows"] == 1
    # metrics families
    text = eng.metrics.render()
    for fam in ("tpu_olap_ingest_rows_total",
                "tpu_olap_delta_rows"):
        assert fam in text, fam
    ev = [e for e in eng.runner.events.snapshot()
          if e["event"] == "ingest" and e.get("kind") == "append"]
    assert ev and ev[0]["rows"] == 1


def test_http_ingest_endpoints(tmp_path):
    import json
    import urllib.request

    from tpu_olap.api.server import QueryServer
    eng = _engine(ingest_wal_dir=str(tmp_path),
                  ingest_max_delta_rows=4)
    srv = QueryServer(eng).start()
    try:
        def post(path, payload):
            req = urllib.request.Request(
                srv.url + path, json.dumps(payload).encode(),
                {"Content-Type": "application/json"})
            return urllib.request.urlopen(req)

        r = post("/ingest", {"table": "t", "rows": [
            {"ts": "2022-05-01", "g": "g1", "v": 5}]})
        body = json.loads(r.read())
        assert r.status == 200 and body["rows"] == 1
        assert body["wal_seq"] == 1
        # visible through SQL over HTTP
        r = post("/sql", {"query": "SELECT count(*) AS n FROM t"})
        assert json.loads(r.read())["rows"][0]["n"] == 2001
        # backpressure: full delta -> 429 + Retry-After, body says why
        post("/ingest", {"table": "t", "rows": [
            {"ts": "2022-05-01", "g": "g1", "v": 5}] * 3})
        try:
            post("/ingest", {"table": "t", "rows": [
                {"ts": "2022-05-01", "g": "g1", "v": 5}]})
            raise AssertionError("expected 429")
        except urllib.error.HTTPError as e:
            assert e.code == 429
            assert int(e.headers["Retry-After"]) >= 1
            assert json.loads(e.read())["code"] == \
                "ingest_backpressure"
        with urllib.request.urlopen(srv.url + "/debug/ingest") as r:
            snap = json.loads(r.read())
        assert snap["tables"]["t"]["delta_rows"] == 4
        assert snap["tables"]["t"]["wal"]["bytes"] > 0
    finally:
        srv.stop()
    # Server.stop() called Engine.close(): background graphs cancelled
    h = eng.ingest._compact_handle
    assert h is None or (h.cancelled and not h.running)


# ------------------------------------------------------ chaos suite

CHAOS_SITES = ("append", "wal-write", "compact", "wal-replay")


def _chaos_round(seed, wal_dir, n_ops=40):
    """One kill-and-recover round: appends ∥ queries ∥ compactions
    under seeded faults at the ingest sites, then a simulated crash and
    WAL replay into a fresh engine. Returns (recovered, acked rows)."""
    eng = _engine(ingest_wal_dir=wal_dir)
    inj = FaultInjector(seed=seed, rate=0.2,
                        stages={"append", "wal-write", "compact"})
    eng.config.fault_injector = inj
    rng = np.random.default_rng(seed)
    acked = []
    q = PARITY_QUERIES[0]
    for i in range(n_ops):
        op = rng.integers(0, 10)
        if op < 6:
            rows = [{"ts": "2022-04-25", "g": f"c{int(rng.integers(4))}",
                     "v": int(rng.integers(100))}]
            try:
                acked_out = eng.append("t", rows)
                acked.extend(rows)
                assert acked_out["rows"] == 1
            except RuntimeError:
                pass  # injected before any state change
        elif op < 8:
            # queries stay exact mid-chaos (the delta is a snapshot)
            got = eng.sql(q)
            assert int(got["n"].sum()) == 2000 + len(acked)
        else:
            try:
                eng.compact_now("t")
            except RuntimeError:
                pass  # injected: delta intact, retried later
    eng.config.fault_injector = None
    # the live engine never lost an acknowledged row
    assert int(eng.sql(q)["n"].sum()) == 2000 + len(acked)
    # crash + recover (wal-replay faults: first attempt may die —
    # the table must come back base-only, and a retry replays fully)
    rec = Engine(_cfg(ingest_wal_dir=wal_dir))
    rinj = FaultInjector(seed=seed + 1, rate=0.3,
                         stages={"wal-replay"})
    rec.config.fault_injector = rinj
    try:
        rec.register_table("t", _df(), time_column="ts",
                           block_rows=BLOCK)
    except RuntimeError:
        assert int(rec.sql(q)["n"].sum()) == 2000  # cleanly base-only
        rec.config.fault_injector = None
        rec.register_table("t", _df(), time_column="ts",
                           block_rows=BLOCK)
    rec.config.fault_injector = None
    return rec, acked, inj


@pytest.mark.parametrize("seed", [7, 19])
def test_chaos_kill_and_recover_parity(seed, tmp_path):
    rec, acked, inj = _chaos_round(seed, str(tmp_path / f"w{seed}"))
    assert inj.faults > 0, "chaos never fired — the test proves nothing"
    ref = _reference(acked)
    _assert_parity(rec, ref, f"chaos seed {seed}")
    # recovery is idempotent across another crash + compaction
    rec.compact_now("t")
    _assert_parity(rec, ref, f"chaos seed {seed} post-compact")


def test_chaos_concurrent_append_query_compact(tmp_path):
    """append ∥ query ∥ compact on real threads with seeded faults;
    then crash → replay → sha256 parity vs a one-shot registration of
    base + acknowledged appends."""
    wal = str(tmp_path / "wc")
    eng = _engine(ingest_wal_dir=wal)
    inj = FaultInjector(seed=11, rate=0.1,
                        stages={"append", "wal-write", "compact"})
    eng.config.fault_injector = inj
    acked = []
    alock = threading.Lock()
    stop = threading.Event()
    errors = []

    def writer(wid):
        i = 0
        while not stop.is_set():
            rows = [{"ts": "2022-04-25", "g": f"w{wid}",
                     "v": wid * 1000 + i}]
            try:
                eng.append("t", rows)
                with alock:
                    acked.extend(rows)
            except RuntimeError:
                pass
            i += 1

    def reader():
        while not stop.is_set():
            try:
                got = eng.sql(PARITY_QUERIES[0])
                n = int(got["n"].sum())
                with alock:
                    lo = 2000  # acked grows monotonically
                if n < lo:
                    errors.append(f"lost rows: {n}")
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(2)] + [threading.Thread(target=reader)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            try:
                eng.compact_now("t")
            except RuntimeError:
                pass
            time.sleep(0.05)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors[:3]
    assert int(eng.sql(PARITY_QUERIES[0])["n"].sum()) \
        == 2000 + len(acked)
    eng.config.fault_injector = None
    eng.close()
    # crash + replay
    rec = _engine(ingest_wal_dir=wal)
    _assert_parity(rec, _reference(acked), "concurrent chaos")


# ------------------------------------------- durability edge hardening

def test_wal_failed_write_rolls_back_and_never_replays(tmp_path,
                                                       monkeypatch):
    """A write acknowledged to NOBODY must not survive into recovery:
    an fsync failure rolls the file back to the last acked frame and
    the failed batch's seq slot is never reused."""
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    eng.append("t", [{"ts": "2022-04-01", "g": "g1", "v": 1}])
    path = os.path.join(wal, "t.wal")
    size_acked = os.path.getsize(path)

    real_fsync = os.fsync
    boom = {"on": True}

    def flaky_fsync(fd):
        if boom["on"]:
            raise OSError(28, "No space left on device")
        return real_fsync(fd)

    monkeypatch.setattr(os, "fsync", flaky_fsync)
    with pytest.raises(OSError):
        eng.append("t", [{"ts": "2022-04-02", "g": "g2", "v": 123456}])
    boom["on"] = False
    # rolled back: no unacknowledged frame left behind
    assert os.path.getsize(path) == size_acked
    # the failed batch never reached the delta either
    assert int(eng.sql(
        "SELECT count(*) AS n FROM t WHERE v = 123456")["n"].iloc[0]) == 0
    # next append acks normally and recovery sees exactly the acks
    eng.append("t", [{"ts": "2022-04-03", "g": "g3", "v": 3}])
    eng.close()
    monkeypatch.undo()
    rec = _engine(ingest_wal_dir=wal)
    _assert_parity(rec, _reference(
        [{"ts": "2022-04-01", "g": "g1", "v": 1},
         {"ts": "2022-04-03", "g": "g3", "v": 3}]), "post-rollback")
    rec.close()


def test_wal_replay_stops_at_seq_regression(tmp_path):
    """Defense in depth: a frame whose seq does not advance (a rolled-
    back write that survived anyway) truncates replay like a torn
    tail — only the strictly-increasing acked prefix applies."""
    import json
    import struct
    import zlib

    from tpu_olap.segments.wal import replay_wal
    path = str(tmp_path / "t.wal")
    with open(path, "wb") as f:
        for seq, v in [(1, 10), (2, 20), (2, 99), (3, 30)]:
            payload = json.dumps(
                {"seq": seq,
                 "rows": [{"__time": 1648771200000, "g": "g1",
                           "v": v}]},
                separators=(",", ":")).encode()
            f.write(struct.pack("<II", len(payload),
                                zlib.crc32(payload)) + payload)
    records = replay_wal(path)
    assert [s for s, _ in records] == [1, 2]
    assert [r[0]["v"] for _, r in records] == [10, 20]


def test_register_after_close_resets_wal(tmp_path):
    """Engine.close() closes every WAL; re-registering the table
    afterwards must still reset the log instead of raising (the engine
    stays usable after close)."""
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    eng.append("t", [{"ts": "2022-04-01", "g": "g1", "v": 1}])
    eng.close()
    eng.register_table("t", _df(), time_column="ts", block_rows=BLOCK)
    # the logged append belonged to the replaced data: log is gone
    rec = _engine(ingest_wal_dir=wal)
    _assert_parity(rec, _reference([]), "post-close re-register")
    rec.close()
    eng.close()


def test_compact_skip_statuses_are_distinguishable():
    """COMPACT DRUID TABLE must not claim 'empty-delta' when the
    compaction was actually skipped (breaker open / already running)."""
    eng = _engine()
    eng.append("t", [{"ts": "2022-04-01", "g": "g1", "v": 1}])
    br = eng.runner.breaker
    for _ in range(int(eng.config.breaker_failure_threshold or 3)):
        br.record_failure()
    assert br.state == "open"
    res = eng.compact_now("t")
    assert res["status"] == "breaker-open"
    out = eng.sql("COMPACT DRUID TABLE t")
    assert out["status"].iloc[0] == "breaker-open"
    br.close()
    res = eng.compact_now("t")
    assert res["status"] == "compacted" and res["delta_rows_folded"] == 1
    assert eng.compact_now("t") is None  # genuinely empty now
    out = eng.sql("COMPACT DRUID TABLE t")
    assert out["status"].iloc[0] == "empty-delta"


def test_compaction_consolidates_fallback_frames():
    """Per-append fallback frames must not accumulate across
    compactions: sealed appends consolidate to one frame."""
    eng = _engine()
    for i in range(6):
        eng.append("t", [{"ts": "2022-04-01", "g": "g1", "v": i}])
    st = eng.ingest._state("t")
    assert len(st.frames) == 6
    eng.compact_now("t")
    assert len(st.frames) <= 1
    eng.append("t", [{"ts": "2022-04-02", "g": "g2", "v": 50}])
    eng.compact_now("t")
    assert len(st.frames) <= 1
    # every appended row still visible exactly once
    rows = [{"ts": "2022-04-01", "g": "g1", "v": i} for i in range(6)]
    rows.append({"ts": "2022-04-02", "g": "g2", "v": 50})
    _assert_parity(eng, _reference(rows), "consolidated frames")


def test_empty_append_returns_full_shape():
    eng = _engine()
    out = eng.append("t", [])
    assert {"table", "rows", "generation", "sealed_generation",
            "delta_rows", "watermark", "wal_seq"} <= set(out)
    assert out["rows"] == 0


def test_append_out_of_bounds_time_rejected_atomically(tmp_path):
    """The fallback frame is built BEFORE the WAL write: a timestamp
    the encoder accepts but pandas cannot represent must reject the
    whole batch with nothing applied — not ack a batch the
    interpreter path can never see."""
    wal = str(tmp_path)
    eng = _engine(ingest_wal_dir=wal)
    path = os.path.join(wal, "t.wal")
    size0 = os.path.getsize(path) if os.path.exists(path) else 0
    with pytest.raises(Exception):
        eng.append("t", [{"ts": 10**16, "g": "g1", "v": 123456}])
    assert (os.path.getsize(path) if os.path.exists(path)
            else 0) == size0
    assert eng.catalog.get("t").segments.delta_rows == 0
    assert int(eng.sql(
        "SELECT count(*) AS n FROM t WHERE v = 123456")["n"].iloc[0]) == 0
    eng.close()
    rec = _engine(ingest_wal_dir=wal)
    _assert_parity(rec, _reference([]), "oob-time reject")
    rec.close()
