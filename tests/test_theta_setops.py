"""Theta sketch set operations (INTERSECT / UNION / NOT post-aggs) — the
datasketches-extension capability that motivates theta over HLL
(SURVEY.md §3.3). Sketches below stay under their nominal k, so every
estimate is EXACT and compares against a pandas oracle with zero
tolerance."""

import json

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig


@pytest.fixture(scope="module")
def setup():
    rng = np.random.default_rng(5)
    n = 6000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 20, n), unit="s"),
        "user": rng.integers(0, 800, n).astype(np.int64),
        "action": rng.choice(["buy", "view", "share"], n),
        "device": rng.choice(["ios", "android"], n),
    })
    eng = Engine(EngineConfig())
    eng.register_table("events", df, time_column="ts")
    return eng, df


def _theta(name, filt=None):
    agg = {"type": "thetaSketch", "name": name, "fieldName": "user",
           "size": 4096}
    if filt is None:
        return agg
    return {"type": "filtered", "name": name,
            "filter": {"type": "selector", "dimension": "action",
                       "value": filt},
            "aggregator": agg}


def _run(eng, post_aggs):
    spec = json.dumps({
        "queryType": "timeseries",
        "granularity": "all",
        "aggregations": [_theta("buyers", "buy"), _theta("viewers", "view"),
                         _theta("sharers", "share")],
        "postAggregations": post_aggs,
    })
    return eng.sql(f"ON DRUID DATASOURCE events EXECUTE QUERY '{spec}'")


def _setop(name, func, *fields):
    return {"type": "thetaSketchEstimate", "name": name,
            "field": {"type": "thetaSketchSetOp", "func": func,
                      "fields": [{"type": "fieldAccess", "fieldName": f}
                                 for f in fields]}}


def test_intersect(setup):
    eng, df = setup
    out = _run(eng, [_setop("both", "INTERSECT", "buyers", "viewers")])
    buyers = set(df[df.action == "buy"].user)
    viewers = set(df[df.action == "view"].user)
    assert int(out["both"][0]) == len(buyers & viewers)


def test_union(setup):
    eng, df = setup
    out = _run(eng, [_setop("any2", "UNION", "buyers", "sharers")])
    buyers = set(df[df.action == "buy"].user)
    sharers = set(df[df.action == "share"].user)
    assert int(out["any2"][0]) == len(buyers | sharers)


def test_not(setup):
    eng, df = setup
    out = _run(eng, [_setop("only_buy", "NOT", "buyers", "viewers")])
    buyers = set(df[df.action == "buy"].user)
    viewers = set(df[df.action == "view"].user)
    assert int(out["only_buy"][0]) == len(buyers - viewers)


def test_nested_and_three_way(setup):
    eng, df = setup
    nested = {"type": "thetaSketchEstimate", "name": "triple", "field": {
        "type": "thetaSketchSetOp", "func": "INTERSECT",
        "fields": [
            {"type": "fieldAccess", "fieldName": "buyers"},
            {"type": "thetaSketchSetOp", "func": "UNION",
             "fields": [{"type": "fieldAccess", "fieldName": "viewers"},
                        {"type": "fieldAccess", "fieldName": "sharers"}]},
        ]}}
    out = _run(eng, [nested])
    buyers = set(df[df.action == "buy"].user)
    viewers = set(df[df.action == "view"].user)
    sharers = set(df[df.action == "share"].user)
    assert int(out["triple"][0]) == len(buyers & (viewers | sharers))


def test_setop_in_groupby(setup):
    """Per-group set ops: one sketch pair per device value."""
    eng, df = setup
    spec = json.dumps({
        "queryType": "groupBy",
        "granularity": "all",
        "dimensions": ["device"],
        "aggregations": [_theta("buyers", "buy"), _theta("viewers", "view")],
        "postAggregations": [_setop("both", "INTERSECT",
                                    "buyers", "viewers")],
    })
    out = eng.sql(f"ON DRUID DATASOURCE events EXECUTE QUERY '{spec}'")
    for _, row in out.iterrows():
        sub = df[df.device == row["device"]]
        want = len(set(sub[sub.action == "buy"].user)
                   & set(sub[sub.action == "view"].user))
        assert int(row["both"]) == want


def test_setop_arithmetic(setup):
    """Set-op estimates compose with arithmetic post-aggs (overlap %)."""
    eng, df = setup
    post = [
        _setop("both", "INTERSECT", "buyers", "viewers"),
        _setop("any", "UNION", "buyers", "viewers"),
        {"type": "arithmetic", "name": "jaccard", "fn": "/",
         "fields": [{"type": "fieldAccess", "fieldName": "both"},
                    {"type": "fieldAccess", "fieldName": "any"}]},
    ]
    out = _run(eng, post)
    buyers = set(df[df.action == "buy"].user)
    viewers = set(df[df.action == "view"].user)
    want = len(buyers & viewers) / len(buyers | viewers)
    assert abs(float(out["jaccard"][0]) - want) < 1e-9


def test_json_round_trip():
    from tpu_olap.ir.postaggs import postagg_from_json
    d = {"type": "thetaSketchEstimate", "name": "e", "field": {
        "type": "thetaSketchSetOp", "func": "NOT", "name": "",
        "fields": [{"type": "fieldAccess", "fieldName": "a"},
                   {"type": "fieldAccess", "fieldName": "b"}]}}
    pa = postagg_from_json(d)
    assert pa.to_json()["field"]["func"] == "NOT"
    assert pa.inputs() == {"a", "b"}


def test_sql_theta_setops(setup):
    """SQL spellings: theta_sketch_intersect/union/not over FILTERed
    theta sketches rewrite to set-op post-aggs on the device path; the
    fallback computes exact sets, and under-capacity sketches make the
    device estimates exact too."""
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = setup
    buyers = set(df[df.action == "buy"].user)
    viewers = set(df[df.action == "view"].user)
    sharers = set(df[df.action == "share"].user)
    cases = [
        ("theta_sketch_estimate(theta_sketch_intersect("
         "theta_sketch(user) FILTER (WHERE action = 'buy'), "
         "theta_sketch(user) FILTER (WHERE action = 'view')))",
         len(buyers & viewers)),
        ("theta_sketch_union("
         "theta_sketch(user) FILTER (WHERE action = 'buy'), "
         "theta_sketch(user) FILTER (WHERE action = 'share'))",
         len(buyers | sharers)),
        ("theta_sketch_not("
         "theta_sketch(user) FILTER (WHERE action = 'buy'), "
         "theta_sketch(user) FILTER (WHERE action = 'view'))",
         len(buyers - viewers)),
        ("theta_sketch_intersect("
         "theta_sketch(user) FILTER (WHERE action = 'buy'), "
         "theta_sketch_union("
         "theta_sketch(user) FILTER (WHERE action = 'view'), "
         "theta_sketch(user) FILTER (WHERE action = 'share')))",
         len(buyers & (viewers | sharers))),
    ]
    for expr, want in cases:
        sql = f"SELECT {expr} AS x FROM events"
        dev = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        assert int(dev["x"][0]) == want, (expr, dev["x"][0], want)
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert int(fb["x"][0]) == want


def test_sql_theta_setop_grouped(setup):
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = setup
    sql = ("SELECT device, theta_sketch_intersect("
           "theta_sketch(user) FILTER (WHERE action = 'buy'), "
           "theta_sketch(user) FILTER (WHERE action = 'view')) AS b "
           "FROM events GROUP BY device ORDER BY device")
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    for (_, r1), (_, r2) in zip(dev.iterrows(), fb.iterrows()):
        sub = df[df.device == r1["device"]]
        want = len(set(sub[sub.action == "buy"].user)
                   & set(sub[sub.action == "view"].user))
        assert int(r1["b"]) == want and int(r2["b"]) == want


def test_sql_theta_setop_bad_arg_falls_back(setup):
    """A non-theta argument rejects the rewrite; the fallback then raises
    the same legible error."""
    import pytest as _p

    from tpu_olap.planner.fallback import FallbackError
    eng, _ = setup
    with _p.raises(FallbackError, match="theta_sketch"):
        eng.sql("SELECT theta_sketch_intersect(sum(user), "
                "theta_sketch(user)) AS x FROM events")
    assert not eng.last_plan.rewritten


def test_sql_theta_setop_multichip():
    """Set ops over raw sketch tables merged across an 8-device mesh:
    the unpacked raw-table path composes with the theta_merge
    collective; sparse sets keep the oracle discriminating."""
    import numpy as np

    from tpu_olap.executor import EngineConfig
    rng = np.random.default_rng(3)
    n = 20_000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-05-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "user": rng.integers(0, 30_000, n),
        "act": rng.choice(["b", "v"], n),
        "dev": rng.choice(["x", "y"], n),
    })
    eng = Engine(EngineConfig(num_shards=8,
                              fallback_on_device_failure=False))
    eng.register_table("ev", df, time_column="ts", block_rows=512)
    got = eng.sql(
        "SELECT dev, theta_sketch_intersect("
        "theta_sketch(user) FILTER (WHERE act = 'b'), "
        "theta_sketch(user) FILTER (WHERE act = 'v')) AS both_u "
        "FROM ev GROUP BY dev ORDER BY dev")
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    for _, r in got.iterrows():
        sub = df[df.dev == r["dev"]]
        want = len(set(sub[sub.act == "b"].user)
                   & set(sub[sub.act == "v"].user))
        assert int(r["both_u"]) == want


def test_sketch_state_budget_routes_wide_groups_to_sparse():
    """A grouped sketch query whose [groups x radix] state exceeds
    dense_sketch_state_budget must take the sparse path (clamped sketch
    width) instead of allocating the dense state (observed: >100 GB at
    K ~ 1M before the budget existed). Results stay parity-exact here
    because per-group cardinality is far below the clamped width."""
    from tpu_olap.executor import EngineConfig
    from tpu_olap.executor.lowering import lower
    rng = np.random.default_rng(7)
    n = 6000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 5, n), unit="s"),
        "a": rng.integers(0, 300, n).astype(np.int64),
        "b": rng.integers(0, 300, n).astype(np.int64),
        "u": rng.integers(0, 50, n).astype(np.int64),
    })
    eng = Engine(EngineConfig())
    eng.register_table("wide_t", df, time_column="ts")
    q = ("SELECT a, b, theta_sketch_estimate(theta_sketch(u)) AS d "
         "FROM wide_t GROUP BY a, b ORDER BY a, b")
    plan = eng.planner.plan(q)
    phys = lower(plan.query, plan.entry.segments, eng.config)
    # 300*300 groups x 2^14 sketch width = 1.47e9 state elements >> 2^28
    assert phys.sparse, (phys.total_groups, phys.sparse)
    got = eng.sql(q)
    exp = (df.groupby(["a", "b"]).u.nunique()
           .reset_index(name="d").sort_values(["a", "b"]))
    assert [int(x) for x in got["d"]] == [int(x) for x in exp["d"]]
