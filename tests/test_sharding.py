"""Multi-chip tests on the 8-virtual-device CPU mesh (SURVEY.md §5
implication #4): sharded execution must agree exactly with single-device
and with pandas."""

import jax
import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.utils import timeutil as tu

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def build(num_shards=None):
    rng = np.random.default_rng(3)
    n = 20_000
    t0 = tu.date_to_millis(1993, 1, 1)
    df = pd.DataFrame({
        "ts": pd.to_datetime(t0 + rng.integers(0, 2 * 365 * 86_400_000, n),
                             unit="ms"),
        "brand": rng.choice([f"B{i:02d}" for i in range(30)], n),
        "region": rng.choice(["ASIA", "EUROPE", "AMERICA"], n),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(0, 100, n), 2),
        "uid": rng.integers(0, 3000, n).astype(np.int64),
    })
    eng = Engine(EngineConfig(num_shards=num_shards))
    eng.register_table("f", df, time_column="ts", block_rows=1 << 11)
    return eng, df


QUERIES = [
    "SELECT sum(qty) AS s, count() AS n FROM f",
    """SELECT brand, sum(qty * price) AS rev FROM f
       WHERE region = 'ASIA' GROUP BY brand""",
    """SELECT region, min(price) AS mn, max(qty) AS mx, avg(price) AS av
       FROM f GROUP BY region""",
    """SELECT year(ts) AS yr, count() AS n FROM f GROUP BY year(ts)""",
    """SELECT brand, sum(qty) AS s FROM f GROUP BY brand
       ORDER BY s DESC LIMIT 5""",
    """SELECT count() AS n FROM f WHERE ts >= '1993-06-01'
       AND ts < '1994-02-01'""",
]


@pytest.mark.parametrize("idx", range(len(QUERIES)))
def test_sharded_matches_single(idx):
    sql = QUERIES[idx]
    e1, _ = build(num_shards=None)
    e8, _ = build(num_shards=8)
    a = e1.sql(sql)
    b = e8.sql(sql)
    assert e8.last_plan.rewritten, e8.last_plan.fallback_reason
    assert e8.runner.history[-1]["num_shards"] == 8
    pd.testing.assert_frame_equal(a, b)


def test_sharded_theta_matches_single():
    from tpu_olap.ir import (ThetaSketchAggregation, TimeseriesQuerySpec,
                             GroupByQuerySpec, DefaultDimensionSpec)
    q = GroupByQuerySpec(
        data_source="f", dimensions=(DefaultDimensionSpec("region"),),
        aggregations=(ThetaSketchAggregation("u", "uid", 1 << 12),))
    e1, df = build(num_shards=None)
    e8, _ = build(num_shards=8)
    r1 = e1.execute_ir(q)
    r8 = e8.execute_ir(q)
    assert r1.rows == r8.rows
    truth = df.groupby("region").uid.nunique()
    for r in r8.rows:
        want = truth[r["region"]]
        assert abs(r["u"] - want) / want < 0.1, (r, want)


def test_sharded_hll_and_scan():
    e8, df = build(num_shards=8)
    out = e8.sql("SELECT count(DISTINCT uid) AS u FROM f")
    want = df.uid.nunique()
    assert abs(out.u[0] - want) / want < 0.1
    scan = e8.sql("SELECT brand, qty FROM f WHERE qty = 49 LIMIT 12")
    truth = df.sort_values("ts", kind="stable")
    truth = truth[truth.qty == 49]
    assert scan.qty.tolist() == truth.qty.head(12).tolist()
    assert scan.brand.tolist() == truth.brand.head(12).tolist()


def test_sharded_pruning_still_correct():
    e8, df = build(num_shards=8)
    out = e8.sql("SELECT count() AS n FROM f WHERE year(ts) = 1994")
    years = pd.to_datetime(df.ts).dt.year
    assert out.n[0] == int((years == 1994).sum())
    # the last DEVICE record: a fallback-served environment (device
    # failure) records the fallback execution after the device attempt
    m = [h for h in e8.runner.history if "segments_total" in h][-1]
    assert m["segments_scanned"] < m["segments_total"]


# ---------------------------------------------------------------------------
# jit + NamedSharding rebuild (ISSUE 15): interleaved placement, per-chip
# windows, broker merge, cache shards, sys.devices, incremental re-place


def test_interleaved_placement_perms():
    """placement(): chip-major placed order, logical i on chip i mod D,
    and the two permutations are inverses."""
    from tpu_olap.executor.sharding import chip_of, placement
    to_place, to_logical = placement(16, 8)
    per_chip = 2
    for i in range(16):
        assert to_logical[to_place[i]] == i
        assert to_place[i] // per_chip == i % 8 == chip_of(i, 8)


def _month_build(num_shards=None, **cfg):
    rng = np.random.default_rng(11)
    n = 60_000
    df = pd.DataFrame({
        "ts": pd.to_datetime("1993-01-01")
        + pd.to_timedelta(rng.integers(0, 730, n), unit="D"),
        "g": rng.choice([f"g{i}" for i in range(16)], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    eng = Engine(EngineConfig(num_shards=num_shards, **cfg))
    eng.register_table("m", df, time_column="ts", block_rows=512,
                       time_partition="month")
    return eng, df


WINDOW_SQL = ("SELECT g, sum(v) AS s FROM m "
              "WHERE ts >= '1993-03-01' AND ts < '1993-06-01' "
              "GROUP BY g ORDER BY g")


def test_per_chip_window_prunes_working_set():
    """Interleaved placement turns a contiguous time range into a LOCAL
    window on every chip: the record carries segments_window_per_chip
    well under each chip's resident share, and results stay exact."""
    e1, _ = _month_build()
    e8, _ = _month_build(num_shards=8)
    a, b = e1.sql(WINDOW_SQL), e8.sql(WINDOW_SQL)
    pd.testing.assert_frame_equal(a, b)
    m = e8.runner.history[-1]
    n_seg = len(e8.catalog.get("m").segments.segments)
    per_chip = -(-n_seg // 8)
    w = m["segments_window_per_chip"]
    assert w is not None and 0 < w < per_chip, (w, per_chip)
    assert m["num_shards"] == 8
    assert m["cost"]["strategy"] in ("historicals", "broker")


def test_mesh_tier1_cache_shards_merge_at_broker():
    """Per-(chip, segment) tier-1 entries under a mesh: the first run
    populates per-segment partials from the sharded dispatch, the
    repeat serves them via the host broker fold, and sys.devices
    reports the per-chip cache-shard census."""
    e8, _ = _month_build(num_shards=8, segment_cache_enabled=True)
    a = e8.sql(WINDOW_SQL)
    m1 = e8.runner.history[-1]
    assert m1.get("segment_cache") is None  # tier served, not bypassed
    b = e8.sql(WINDOW_SQL)
    m2 = e8.runner.history[-1]
    pd.testing.assert_frame_equal(a, b)
    assert m2["cache_hit"] and m2["cache_tier"] == "segment"
    assert m2["segments_cached"] > 0 and m2["segments_computed"] == 0
    dev = e8.sql("SELECT sum(cache_shard_entries) AS n, count(*) AS d "
                 "FROM sys.devices")
    assert int(dev.d[0]) == 8
    assert int(dev.n[0]) == m2["segments_cached"]
    # parity against the single-device tier-1 path
    e1, _ = _month_build(segment_cache_enabled=True)
    e1.sql(WINDOW_SQL)
    pd.testing.assert_frame_equal(e1.sql(WINDOW_SQL), b)


def test_sys_devices_census():
    e8, _ = build(num_shards=8)
    e8.sql(QUERIES[0])
    out = e8.sql("SELECT * FROM sys.devices")
    assert len(out) == 8
    n_seg = len(e8.catalog.get("f").segments.segments)
    assert int(out.segments.sum()) == n_seg
    assert (out.chips == 8).all()
    assert int(out.dispatches.sum()) > 0


def test_incremental_replace_on_append():
    """A delta append re-places ONLY the touched segments' rows: the
    swapped-in dataset rebases resident stacks device-side instead of
    re-uploading every column, and mesh results stay exact."""
    e8, _ = _month_build(num_shards=8)
    e1, _ = _month_build()
    base = e8.sql(WINDOW_SQL)
    row = {"ts": "1994-12-30T00:00:00", "g": "g1", "v": 7}
    e8.append("m", [row])
    e1.append("m", [row])
    got = e8.sql("SELECT count() AS n FROM m")
    assert int(got.n[0]) == 60_001
    ds = e8.runner._datasets["m"]
    assert ds.rebased_cols > 0
    # uploaded rows bounded by the delta-touched segments, not the table
    n_seg = len(e8.catalog.get("m").segments.segments)
    assert ds.rebase_rows_uploaded < n_seg * 512 // 2
    pd.testing.assert_frame_equal(e1.sql(WINDOW_SQL), e8.sql(WINDOW_SQL))
    pd.testing.assert_frame_equal(base, e8.sql(WINDOW_SQL))


def test_compaction_keeps_untouched_cache_shards():
    """Partition-aligned incremental compaction shares untouched sealed
    segments by object, and tier-1 keys ride the segment uid — so only
    the delta-touched partition's entries invalidate (under a mesh:
    only the affected chip's cache shard)."""
    e8, _ = _month_build(num_shards=8, segment_cache_enabled=True,
                         ingest_auto_compact=False)
    e8.sql(WINDOW_SQL)          # populate per-segment entries
    warm = e8.sql(WINDOW_SQL)
    assert e8.runner.history[-1]["cache_hit"]
    # append OUTSIDE the queried window, then compact: the queried
    # months' sealed segments are untouched partitions
    e8.append("m", [{"ts": "1994-12-30T00:00:00", "g": "g1", "v": 7}])
    res = e8.compact_now("m")
    assert res.get("mode") == "incremental", res
    again = e8.sql(WINDOW_SQL)
    m = e8.runner.history[-1]
    pd.testing.assert_frame_equal(warm, again)
    assert m["cache_hit"], m.get("segment_cache")
    assert m["segments_cached"] > 0 and m["segments_computed"] == 0, m
