"""Multi-chip tests on the 8-virtual-device CPU mesh (SURVEY.md §5
implication #4): sharded execution must agree exactly with single-device
and with pandas."""

import jax
import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.utils import timeutil as tu

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices")


def build(num_shards=None):
    rng = np.random.default_rng(3)
    n = 20_000
    t0 = tu.date_to_millis(1993, 1, 1)
    df = pd.DataFrame({
        "ts": pd.to_datetime(t0 + rng.integers(0, 2 * 365 * 86_400_000, n),
                             unit="ms"),
        "brand": rng.choice([f"B{i:02d}" for i in range(30)], n),
        "region": rng.choice(["ASIA", "EUROPE", "AMERICA"], n),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(0, 100, n), 2),
        "uid": rng.integers(0, 3000, n).astype(np.int64),
    })
    eng = Engine(EngineConfig(num_shards=num_shards))
    eng.register_table("f", df, time_column="ts", block_rows=1 << 11)
    return eng, df


QUERIES = [
    "SELECT sum(qty) AS s, count() AS n FROM f",
    """SELECT brand, sum(qty * price) AS rev FROM f
       WHERE region = 'ASIA' GROUP BY brand""",
    """SELECT region, min(price) AS mn, max(qty) AS mx, avg(price) AS av
       FROM f GROUP BY region""",
    """SELECT year(ts) AS yr, count() AS n FROM f GROUP BY year(ts)""",
    """SELECT brand, sum(qty) AS s FROM f GROUP BY brand
       ORDER BY s DESC LIMIT 5""",
    """SELECT count() AS n FROM f WHERE ts >= '1993-06-01'
       AND ts < '1994-02-01'""",
]


@pytest.mark.parametrize("idx", range(len(QUERIES)))
def test_sharded_matches_single(idx):
    sql = QUERIES[idx]
    e1, _ = build(num_shards=None)
    e8, _ = build(num_shards=8)
    a = e1.sql(sql)
    b = e8.sql(sql)
    assert e8.last_plan.rewritten, e8.last_plan.fallback_reason
    assert e8.runner.history[-1]["num_shards"] == 8
    pd.testing.assert_frame_equal(a, b)


def test_sharded_theta_matches_single():
    from tpu_olap.ir import (ThetaSketchAggregation, TimeseriesQuerySpec,
                             GroupByQuerySpec, DefaultDimensionSpec)
    q = GroupByQuerySpec(
        data_source="f", dimensions=(DefaultDimensionSpec("region"),),
        aggregations=(ThetaSketchAggregation("u", "uid", 1 << 12),))
    e1, df = build(num_shards=None)
    e8, _ = build(num_shards=8)
    r1 = e1.execute_ir(q)
    r8 = e8.execute_ir(q)
    assert r1.rows == r8.rows
    truth = df.groupby("region").uid.nunique()
    for r in r8.rows:
        want = truth[r["region"]]
        assert abs(r["u"] - want) / want < 0.1, (r, want)


def test_sharded_hll_and_scan():
    e8, df = build(num_shards=8)
    out = e8.sql("SELECT count(DISTINCT uid) AS u FROM f")
    want = df.uid.nunique()
    assert abs(out.u[0] - want) / want < 0.1
    scan = e8.sql("SELECT brand, qty FROM f WHERE qty = 49 LIMIT 12")
    truth = df.sort_values("ts", kind="stable")
    truth = truth[truth.qty == 49]
    assert scan.qty.tolist() == truth.qty.head(12).tolist()
    assert scan.brand.tolist() == truth.brand.head(12).tolist()


def test_sharded_pruning_still_correct():
    e8, df = build(num_shards=8)
    out = e8.sql("SELECT count() AS n FROM f WHERE year(ts) = 1994")
    years = pd.to_datetime(df.ts).dt.year
    assert out.n[0] == int((years == 1994).sum())
    # the last DEVICE record: a fallback-served environment (device
    # failure) records the fallback execution after the device attempt
    m = [h for h in e8.runner.history if "segments_total" in h][-1]
    assert m["segments_scanned"] < m["segments_total"]
