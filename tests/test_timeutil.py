import datetime as dt

import pytest

from tpu_olap.utils import timeutil as tu


def test_period_parse_and_millis():
    assert tu.period_millis("PT1H") == 3_600_000
    assert tu.period_millis("P1D") == 86_400_000
    assert tu.period_millis("P1W") == 7 * 86_400_000
    assert tu.period_is_uniform("PT15M")
    assert not tu.period_is_uniform("P1M")
    assert not tu.period_is_uniform("P1Y")
    with pytest.raises(ValueError):
        tu.period_millis("P1M")
    with pytest.raises(ValueError):
        tu.parse_period("bogus")


def test_iso_roundtrip():
    ms = tu.parse_iso_datetime("1993-05-17T12:34:56.789Z")
    assert tu.millis_to_iso(ms) == "1993-05-17T12:34:56.789Z"
    assert tu.parse_iso_datetime("1993-05-17") == tu.date_to_millis(1993, 5, 17)


def test_calendar_boundaries_month():
    t0 = tu.date_to_millis(1993, 1, 15)
    t1 = tu.date_to_millis(1993, 4, 2)
    bs = tu.calendar_boundaries("P1M", "UTC", t0, t1)
    # floors to Jan 1; covers through Apr, one boundary past t1
    assert bs[0] == tu.date_to_millis(1993, 1, 1)
    assert bs[1] == tu.date_to_millis(1993, 2, 1)
    assert bs[-1] > t1
    assert len(bs) == 5  # Jan Feb Mar Apr May


def test_calendar_boundaries_year_quarter_week():
    t0 = tu.date_to_millis(1992, 1, 1)
    t1 = tu.date_to_millis(1994, 12, 31)
    ys = tu.calendar_boundaries("P1Y", "UTC", t0, t1)
    assert ys[:3] == [tu.date_to_millis(1992), tu.date_to_millis(1993),
                      tu.date_to_millis(1994)]
    qs = tu.calendar_boundaries("P3M", "UTC", t0, tu.date_to_millis(1992, 12, 31))
    assert qs[1] == tu.date_to_millis(1992, 4, 1)
    # week floors to Monday: 1993-05-17 is a Monday
    ws = tu.calendar_boundaries("P1W", "UTC", tu.date_to_millis(1993, 5, 19),
                                tu.date_to_millis(1993, 5, 20))
    assert ws[0] == tu.date_to_millis(1993, 5, 17)


def test_calendar_boundaries_tz():
    # midnight in New York is 05:00 UTC (EST, Jan)
    t0 = tu.date_to_millis(1993, 1, 10)
    bs = tu.calendar_boundaries("P1D", "America/New_York", t0, t0)
    d = dt.datetime.fromtimestamp(bs[0] / 1000, tz=dt.timezone.utc)
    assert d.hour == 5
