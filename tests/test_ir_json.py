"""IR JSON round-trip golden tests (analog of the reference's spec-class
serialization coverage, SURVEY.md §3.3/§3.6)."""

import json

import pytest

from tpu_olap import ir
from tpu_olap.ir import (
    AllGranularity, AndFilter, ArithmeticPostAgg, BoundFilter,
    CardinalityAggregation, Col, ConstantPostAgg, CountAggregation,
    DefaultDimensionSpec, DurationGranularity, ExpressionFilter,
    ExtractionDimensionSpec, FieldAccessPostAgg, FilteredAggregation,
    GreaterThanHaving, GroupByQuerySpec, HyperUniqueAggregation,
    HyperUniqueCardinalityPostAgg, InFilter, Interval, LikeFilter, LimitSpec,
    Lit, MaxAggregation, MinAggregation, NotFilter, OrFilter,
    PeriodGranularity, RegexFilter, ScanQuerySpec, SearchQueryContains,
    SearchQuerySpec, SegmentMetadataQuerySpec, SelectorFilter,
    SumAggregation, ThetaSketchAggregation, TimeBoundaryQuerySpec,
    TimeFormatExtractionFn, TimeseriesQuerySpec, TopNQuerySpec,
    VirtualColumn, parse_expr,
)
from tpu_olap.ir.limit import OrderByColumnSpec
from tpu_olap.ir.having import AndHaving, LessThanHaving
from tpu_olap.ir.serde import query_from_json


def roundtrip(q):
    j = q.to_json()
    # must be plain-JSON serializable
    s = json.dumps(j)
    q2 = query_from_json(json.loads(s))
    assert q2 == q, f"\n{q}\n!=\n{q2}"
    return j


def test_timeseries_roundtrip():
    q = TimeseriesQuerySpec(
        data_source="lineorder",
        intervals=(Interval.of("1993-01-01", "1994-01-01"),),
        filter=AndFilter((
            BoundFilter("lo_discount", lower=1, upper=3, ordering="numeric"),
            BoundFilter("lo_quantity", upper=25, upper_strict=True,
                        ordering="numeric"),
        )),
        virtual_columns=(VirtualColumn("rev", parse_expr(
            "lo_extendedprice * lo_discount"), "long"),),
        granularity=AllGranularity(),
        aggregations=(SumAggregation("revenue", "rev", "long"),),
    )
    j = roundtrip(q)
    assert j["queryType"] == "timeseries"
    assert j["aggregations"][0]["type"] == "longSum"
    assert j["intervals"] == ["1993-01-01T00:00:00.000Z/1994-01-01T00:00:00.000Z"]


def test_groupby_roundtrip():
    q = GroupByQuerySpec(
        data_source="lineorder",
        intervals=(Interval.of("1992-01-01", "1999-01-01"),),
        dimensions=(
            DefaultDimensionSpec("d_year", "year"),
            ExtractionDimensionSpec("__time", TimeFormatExtractionFn("YYYY"),
                                    "ts_year"),
        ),
        granularity=PeriodGranularity("P1M", "America/New_York"),
        aggregations=(
            CountAggregation("cnt"),
            SumAggregation("rev", "lo_revenue", "long"),
            MinAggregation("mn", "lo_discount", "long"),
            MaxAggregation("mx", "lo_discount", "double"),
            FilteredAggregation(SelectorFilter("lo_shipmode", "AIR"),
                                SumAggregation("air_rev", "lo_revenue", "long")),
            CardinalityAggregation("uniq", ("lo_custkey",)),
            HyperUniqueAggregation("hu", "lo_partkey"),
            ThetaSketchAggregation("theta", "lo_suppkey", 4096),
        ),
        post_aggregations=(
            ArithmeticPostAgg("avg_rev", "/", (
                FieldAccessPostAgg("rev"), FieldAccessPostAgg("cnt"))),
            ArithmeticPostAgg("x2", "*", (
                FieldAccessPostAgg("rev"), ConstantPostAgg(2.0, "two"))),
            HyperUniqueCardinalityPostAgg("hu", "hu_card"),
        ),
        having=AndHaving((GreaterThanHaving("rev", 100.0),
                          LessThanHaving("cnt", 1e9))),
        limit_spec=LimitSpec(10, (OrderByColumnSpec("rev", "descending"),)),
    )
    j = roundtrip(q)
    assert j["queryType"] == "groupBy"
    assert j["granularity"] == {"type": "period", "period": "P1M",
                                "timeZone": "America/New_York"}
    assert j["limitSpec"]["limit"] == 10


def test_topn_roundtrip():
    q = TopNQuerySpec(
        data_source="lineorder",
        dimension=DefaultDimensionSpec("p_brand"),
        metric="revenue",
        threshold=10,
        aggregations=(SumAggregation("revenue", "lo_revenue", "long"),),
        filter=InFilter("p_category", ("MFGR#12", "MFGR#13")),
    )
    j = roundtrip(q)
    assert j["threshold"] == 10


def test_scan_select_search_meta_roundtrip():
    roundtrip(ScanQuerySpec("t", columns=("a", "b"), limit=100, order="descending"))
    roundtrip(SearchQuerySpec("t", search_dimensions=("c_name",),
                              query=SearchQueryContains("smith"), limit=5))
    roundtrip(SegmentMetadataQuerySpec("t", to_include=("a",)))
    roundtrip(TimeBoundaryQuerySpec("t", bound="maxTime"))


def test_filters_roundtrip():
    q = ScanQuerySpec(
        "t",
        filter=OrFilter((
            NotFilter(SelectorFilter("a", "x")),
            RegexFilter("b", "^foo.*"),
            LikeFilter("c", "%bar_"),
            ExpressionFilter(parse_expr("m1 + m2 > 10")),
        )),
    )
    roundtrip(q)


def test_granularity_simple_strings():
    from tpu_olap.ir.granularity import granularity_from_json
    assert granularity_from_json("all") == AllGranularity()
    g = granularity_from_json("hour")
    assert g == PeriodGranularity("PT1H")
    assert granularity_from_json({"type": "duration", "duration": 3600000}) \
        == DurationGranularity(3600000)


def test_druid_json_input_accepted():
    """A Druid-style query body (queryType, shorthand dims/granularity)."""
    d = {
        "queryType": "groupBy",
        "dataSource": "wikipedia",
        "granularity": "day",
        "dimensions": ["page"],
        "aggregations": [{"type": "longSum", "name": "edits",
                          "fieldName": "count"}],
        "intervals": ["2013-01-01T00:00:00.000Z/2013-01-08T00:00:00.000Z"],
        "filter": {"type": "selector", "dimension": "country", "value": "US"},
    }
    q = query_from_json(d)
    assert isinstance(q, GroupByQuerySpec)
    assert q.dimensions[0] == DefaultDimensionSpec("page")
    assert q.granularity == PeriodGranularity("P1D")
    assert q.filter == SelectorFilter("country", "US")


def test_expr_parser():
    e = parse_expr("a * (b + 2) - c / 4")
    assert e.columns() == {"a", "b", "c"}
    assert parse_expr("x") == Col("x")
    assert parse_expr("3.5") == Lit(3.5)
    with pytest.raises(ValueError):
        parse_expr("a +")


def test_interval_ops():
    iv = Interval.parse("1993-01-01T00:00:00Z/1994-01-01T00:00:00Z")
    assert iv.overlaps(iv.start, iv.start + 1)
    assert not iv.overlaps(iv.end, iv.end + 1)
    i2 = iv.intersect(Interval.of("1993-06-01", "1995-01-01"))
    assert i2 is not None and i2.end == iv.end


def test_unknown_type_raises():
    with pytest.raises(ValueError, match="unknown filter"):
        ir.from_json("filter", {"type": "bogus"})
