"""Kernel golden tests vs numpy/pandas oracle (SURVEY.md §5 implication #2).

Each kernel runs on both the numpy path and the jitted jax path; results
must agree with each other and with a pandas oracle.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from tpu_olap.ir import (AndFilter, BoundFilter, CountAggregation,
                         ExpressionFilter, InFilter, LikeFilter, NotFilter,
                         OrFilter, RegexFilter, SelectorFilter,
                         SumAggregation, MinAggregation, MaxAggregation,
                         CardinalityAggregation, ThetaSketchAggregation,
                         FilteredAggregation, PeriodGranularity, parse_expr)
from tpu_olap.kernels import (ConstPool, compile_aggregations, compile_filter,
                              compile_granularity, group_reduce,
                              hll_estimate, top_k_groups)
from tpu_olap.kernels.groupby import build_group_key, merge_partials
from tpu_olap.kernels.theta import theta_estimate, theta_merge
from tpu_olap.kernels.timebucket import compile_time_format
from tpu_olap.segments import ingest_pandas, TIME_COLUMN
from tpu_olap.utils import timeutil as tu

jax.config.update("jax_enable_x64", True)


def make_table(n=4000, seed=7):
    rng = np.random.default_rng(seed)
    t0 = tu.date_to_millis(1993, 1, 1)
    df = pd.DataFrame({
        "ts": t0 + rng.integers(0, 365 * 86_400_000, n),
        "city": rng.choice(["amsterdam", "berlin", "chicago", "denver", None],
                           n, p=[0.3, 0.3, 0.2, 0.15, 0.05]),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(0, 100, n), 2),
        "uid": rng.integers(0, 500, n).astype(np.int64),
    })
    ts = ingest_pandas("t", df, time_column="ts", block_rows=1 << 12)
    # ingest time-sorts rows; align the oracle frame the same way
    df = df.sort_values("ts", kind="stable").reset_index(drop=True)
    return df, ts


def flat_env(ts, xp):
    s = ts.segments[0]
    conv = (lambda a: a) if xp is np else jnp.asarray
    return {
        "cols": {c: conv(v) for c, v in s.columns.items()},
        "nulls": {c: conv(v) for c, v in s.null_masks.items()},
    }, conv(np.arange(s.block_rows) < s.meta.n_valid)


DF, TS = make_table()


def run_filter(spec, xp):
    pool = ConstPool()
    fn = compile_filter(spec, TS, pool,
                        virtual_exprs={"rev": parse_expr("qty * price")})
    env, valid = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    mask = fn(env, consts) & valid
    return np.asarray(mask)[:TS.segments[0].meta.n_valid]


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
class TestFilters:
    def test_selector(self, xp):
        got = run_filter(SelectorFilter("city", "berlin"), xp)
        assert (got == (DF.city == "berlin").to_numpy()).all()

    def test_selector_null(self, xp):
        got = run_filter(SelectorFilter("city", None), xp)
        assert (got == DF.city.isna().to_numpy()).all()

    def test_selector_numeric(self, xp):
        got = run_filter(SelectorFilter("qty", 7), xp)
        assert (got == (DF.qty == 7).to_numpy()).all()

    def test_bound_numeric(self, xp):
        got = run_filter(
            BoundFilter("price", lower=20, upper=60, upper_strict=True,
                        ordering="numeric"), xp)
        assert (got == ((DF.price >= 20) & (DF.price < 60)).to_numpy()).all()

    def test_bound_lexicographic(self, xp):
        got = run_filter(BoundFilter("city", lower="b", upper="chicago"), xp)
        want = ((DF.city >= "b") & (DF.city <= "chicago")).fillna(False)
        assert (got == want.to_numpy()).all()

    def test_in_string(self, xp):
        got = run_filter(InFilter("city", ("denver", "berlin")), xp)
        assert (got == DF.city.isin(["denver", "berlin"]).to_numpy()).all()

    def test_in_numeric(self, xp):
        got = run_filter(InFilter("qty", (1, 5, 7)), xp)
        assert (got == DF.qty.isin([1, 5, 7]).to_numpy()).all()

    def test_regex_like(self, xp):
        got = run_filter(RegexFilter("city", "^.e"), xp)
        want = DF.city.str.match(".e").fillna(False)
        assert (got == want.to_numpy()).all()
        got = run_filter(LikeFilter("city", "%er%"), xp)
        want = DF.city.str.contains("er").fillna(False)
        assert (got == want.to_numpy()).all()

    def test_logical(self, xp):
        spec = OrFilter((
            AndFilter((SelectorFilter("city", "berlin"),
                       BoundFilter("qty", lower=25, ordering="numeric"))),
            NotFilter(BoundFilter("price", lower=1, ordering="numeric")),
        ))
        got = run_filter(spec, xp)
        want = (((DF.city == "berlin") & (DF.qty >= 25))
                | ~(DF.price >= 1)).to_numpy()
        assert (got == want).all()

    def test_expression_virtual(self, xp):
        got = run_filter(ExpressionFilter(parse_expr("rev > 2000")), xp)
        want = (DF.qty * DF.price > 2000).to_numpy()
        assert (got == want).all()


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_group_reduce_matches_pandas(xp):
    pool = ConstPool()
    aggs = (
        CountAggregation("cnt"),
        SumAggregation("q_sum", "qty", "long"),
        SumAggregation("p_sum", "price", "double"),
        MinAggregation("p_min", "price", "double"),
        MaxAggregation("q_max", "qty", "long"),
        FilteredAggregation(SelectorFilter("city", "berlin"),
                            SumAggregation("b_sum", "qty", "long")),
    )
    plans = compile_aggregations(aggs, TS, pool)
    env, valid = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    codes = env["cols"]["city"]
    K = TS.dictionaries["city"].size + 1
    key, total = build_group_key([codes], [K], xp)
    out = group_reduce(key, valid, env, plans, total, consts)
    out = {k: np.asarray(v) for k, v in out.items()}

    g = DF.assign(city=DF.city.fillna("\0null")).groupby("city")
    for city, sub in g:
        cid = 0 if city == "\0null" else TS.dictionaries["city"].id_of(city)
        assert out["_rows"][cid] == len(sub)
        assert out["cnt"][cid] == len(sub)
        assert out["q_sum"][cid] == sub.qty.sum()
        assert np.isclose(out["p_sum"][cid], sub.price.sum())
        assert np.isclose(out["p_min"][cid], sub.price.min())
        assert out["q_max"][cid] == sub.qty.max()
        want_b = sub.qty[sub.city == "berlin"].sum()
        assert out["b_sum"][cid] == want_b


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_group_reduce_merge_partials_equals_whole(xp):
    pool = ConstPool()
    plans = compile_aggregations(
        (SumAggregation("s", "qty", "long"), CountAggregation("c"),
         MinAggregation("m", "price", "double")), TS, pool)
    env, valid = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    codes = env["cols"]["city"]
    K = TS.dictionaries["city"].size + 1
    key, total = build_group_key([codes], [K], xp)
    n = TS.segments[0].meta.n_valid
    half = (np.arange(TS.segments[0].block_rows) < n // 2)
    half = half if xp is np else jnp.asarray(half)
    m1 = valid & half
    m2 = valid & ~half
    p1 = group_reduce(key, m1, env, plans, total, consts)
    p2 = group_reduce(key, m2, env, plans, total, consts)
    whole = group_reduce(key, valid, env, plans, total, consts)
    merged = merge_partials(p1, p2, plans)
    for k in whole:
        assert np.allclose(np.asarray(merged[k]), np.asarray(whole[k])), k


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_hll_cardinality(xp):
    pool = ConstPool()
    plans = compile_aggregations(
        (CardinalityAggregation("u", ("uid",)),), TS, pool)
    env, valid = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    key, total = build_group_key([env["cols"]["city"]],
                                 [TS.dictionaries["city"].size + 1], xp)
    out = group_reduce(key, valid, env, plans, total, consts)
    est = hll_estimate(np.asarray(out["u"]))
    # "\0null", not a bare "\0": modern pandas drops a lone NUL in
    # fillna (the sentinel came back '' and indexed cid -1)
    truth = DF.assign(
        city=DF.city.fillna("\0null")).groupby("city").uid.nunique()
    for city, want in truth.items():
        cid = 0 if city == "\0null" \
            else TS.dictionaries["city"].id_of(city)
        assert abs(est[cid] - want) / max(want, 1) < 0.12, (city, est[cid], want)


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_theta_exact_when_small(xp):
    pool = ConstPool()
    plans = compile_aggregations(
        (ThetaSketchAggregation("t", "uid", 1024),), TS, pool)
    env, valid = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    key, total = build_group_key([env["cols"]["city"]],
                                 [TS.dictionaries["city"].size + 1], xp)
    out = group_reduce(key, valid, env, plans, total, consts)
    est = theta_estimate(np.asarray(out["t"]))
    truth = DF.assign(
        city=DF.city.fillna("\0null")).groupby("city").uid.nunique()
    for city, want in truth.items():
        cid = 0 if city == "\0null" \
            else TS.dictionaries["city"].id_of(city)
        # distinct counts < k=1024, so exact
        assert est[cid] == want, (city, est[cid], want)


def test_theta_merge_matches_union():
    rng = np.random.default_rng(3)
    from tpu_olap.kernels.hashing import hash32_int
    from tpu_olap.kernels.theta import theta_update
    a_vals = rng.integers(0, 300, 2000).astype(np.int32)
    b_vals = rng.integers(200, 600, 2000).astype(np.int32)
    key = np.zeros(2000, np.int32)
    valid = np.ones(2000, bool)
    k = 256
    ta = theta_update(hash32_int(a_vals, np), valid, key, 1, k, np)
    tb = theta_update(hash32_int(b_vals, np), valid, key, 1, k, np)
    merged = theta_merge(ta, tb, np)
    est = theta_estimate(merged)[0]
    truth = len(set(a_vals.tolist()) | set(b_vals.tolist()))
    assert abs(est - truth) / truth < 0.15, (est, truth)


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_granularity_buckets(xp):
    pool = ConstPool()
    t0, t1 = TS.time_boundary
    plan = compile_granularity(PeriodGranularity("P1M"), t0, t1, pool)
    assert plan.n_buckets == 12
    env, valid = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    ids = np.asarray(plan.ids(env["cols"][TIME_COLUMN], consts))
    n = TS.segments[0].meta.n_valid
    want = pd.to_datetime(DF.ts.to_numpy(), unit="ms").month - 1
    assert (ids[:n] == want.to_numpy()).all()


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_time_format_extraction(xp):
    pool = ConstPool()
    t0, t1 = TS.time_boundary
    plan, remap_name, values = compile_time_format("YYYY", "UTC", t0, t1, pool)
    assert values == ["1993"]
    plan2, remap2, values2 = compile_time_format("%m", "UTC", t0, t1, pool)
    assert len(values2) == 12
    env, _ = flat_env(TS, xp)
    consts = pool.consts if xp is np else {k: jnp.asarray(v)
                                           for k, v in pool.consts.items()}
    fine = np.asarray(plan2.ids(env["cols"][TIME_COLUMN], consts))
    group = np.asarray(consts[remap2])[fine]
    n = TS.segments[0].meta.n_valid
    months = pd.to_datetime(DF.ts.to_numpy(), unit="ms").month
    want = [values2.index(f"{m:02d}") for m in months]
    assert (group[:n] == np.asarray(want)).all()


@pytest.mark.parametrize("xp", [np, jnp], ids=["numpy", "jax"])
def test_top_k(xp):
    metric = np.array([5.0, 1.0, 9.0, 7.0, 3.0])
    present = np.array([True, True, True, False, True])
    m = metric if xp is np else jnp.asarray(metric)
    p = present if xp is np else jnp.asarray(present)
    idx, valid = top_k_groups(m, p, 3, False, xp)
    assert np.asarray(idx).tolist() == [2, 0, 4]
    idx, valid = top_k_groups(m, p, 3, True, xp)
    assert np.asarray(idx).tolist() == [1, 4, 0]
    idx, valid = top_k_groups(m, p, 5, False, xp)
    assert np.asarray(valid).sum() == 4  # absent group never 'valid'


def test_jitted_group_reduce_compiles_once():
    pool = ConstPool()
    plans = compile_aggregations((SumAggregation("s", "qty", "long"),), TS,
                                 pool)
    env, valid = flat_env(TS, jnp)
    consts = {k: jnp.asarray(v) for k, v in pool.consts.items()}

    calls = {"n": 0}

    def f(env, valid, consts):
        calls["n"] += 1
        key, total = build_group_key([env["cols"]["city"]],
                                     [TS.dictionaries["city"].size + 1], jnp)
        return group_reduce(key, valid, env, plans, total, consts)

    jf = jax.jit(f)
    out1 = jf(env, valid, consts)
    # second call with different consts: no retrace
    consts2 = dict(consts)
    out2 = jf(env, valid, consts2)
    assert calls["n"] == 1
    assert np.allclose(np.asarray(out1["s"]), np.asarray(out2["s"]))
