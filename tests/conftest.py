"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors SURVEY.md §5's implication #4: distribution is tested without TPU
hardware via XLA's host-platform device-count flag. Must run before jax
initializes its backends, hence the env mutation at import time.
"""

import os

# Force the CPU backend with 8 virtual devices so multi-chip paths run
# without hardware. The sandbox's sitecustomize imports jax at interpreter
# startup with JAX_PLATFORMS=axon already snapshotted, so mutating the env
# var here is too late — jax.config.update still works as long as no
# backend has been initialized yet.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
