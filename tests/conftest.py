"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Mirrors SURVEY.md §5's implication #4: distribution is tested without TPU
hardware via XLA's host-platform device-count flag. Must run before jax
initializes its backends, hence the env mutation at import time.
"""

# Force the CPU backend with 8 virtual devices so multi-chip paths run
# without hardware (see tpu_olap.utils.platform for why env vars alone
# are not enough in this sandbox).
from tpu_olap.utils.platform import force_cpu_devices  # noqa: E402

force_cpu_devices(8)  # raises if a backend beat us to initialization

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; register the marker so the soak
    # variants (e.g. tests/test_chaos_recovery.py) deselect cleanly
    # without an unknown-marker warning
    config.addinivalue_line(
        "markers", "slow: out-of-tier-1 soak tests (deselected by "
        "-m 'not slow')")


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)
