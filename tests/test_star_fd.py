"""FD-driven star-schema join subsumption (SURVEY.md §3.2 JoinTransform,
§3.4 StarSchema): snowflake dim⋈dim chain collapse, FunctionalDependency-
implied links, join-order independence, and negative (non-subsumed) cases.
The fixture is an SSB-flavored nation→region chain (VERDICT r1 #5)."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.catalog.star import (FunctionalDependency, StarDimension,
                                   StarSchema)

_REGIONS = {"ams": ("NETHERLANDS", 1), "ber": ("GERMANY", 1),
            "nyc": ("UNITED STATES", 2), "rio": ("BRAZIL", 2),
            "osa": ("JAPAN", 3)}
_REGION_NAMES = {1: "EUROPE", 2: "AMERICA", 3: "ASIA"}


def _fixture(with_fd: bool):
    rng = np.random.default_rng(11)
    n = 4000
    city = rng.choice(list(_REGIONS), n)
    nation = np.array([_REGIONS[c][0] for c in city], object)
    region = np.array([_REGION_NAMES[_REGIONS[c][1]] for c in city], object)
    fact = pd.DataFrame({
        "ts": pd.to_datetime("2023-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 20, n), unit="s"),
        "c_city": city,
        "c_nation": nation,
        "c_region": region,
        "amount": rng.integers(1, 900, n).astype(np.int64),
    })
    nation_t = pd.DataFrame({
        "n_name": [v[0] for v in _REGIONS.values()],
        "n_regionkey": [v[1] for v in _REGIONS.values()],
    }).drop_duplicates()
    region_t = pd.DataFrame({
        "r_regionkey": list(_REGION_NAMES),
        "r_name": list(_REGION_NAMES.values()),
    })
    fds = (FunctionalDependency("c_city", "c_nation"),
           FunctionalDependency("c_nation", "c_region"))
    if with_fd:
        # the fact column c_nation functionally determines the (absent)
        # nation surrogate key — this is what licenses joining region
        # without materializing the nation table in the query
        fds += (FunctionalDependency("c_nation", "n_regionkey"),)
    star = StarSchema(
        fact="fact",
        dimensions=(
            StarDimension("nation", fact_key="c_nation", dim_key="n_name",
                          column_map={"n_name": "c_nation"}),
            StarDimension("region", fact_key="n_regionkey",
                          dim_key="r_regionkey",
                          column_map={"r_name": "c_region"}),
        ),
        functional_dependencies=fds)
    eng = Engine()
    eng.register_table("fact", fact, time_column="ts", star_schema=star)
    eng.register_table("nation", nation_t, accelerate=False)
    eng.register_table("region", region_t, accelerate=False)
    return eng, fact


CHAIN_SQL = ("SELECT r_name, sum(amount) AS s FROM fact "
             "JOIN nation ON c_nation = n_name "
             "JOIN region ON n_regionkey = r_regionkey "
             "GROUP BY r_name ORDER BY r_name")


def _expected(fact):
    return (fact.groupby("c_region", as_index=False)
            .agg(s=("amount", "sum"))
            .rename(columns={"c_region": "r_name"})
            .sort_values("r_name").reset_index(drop=True))


def test_snowflake_chain_collapses():
    eng, fact = _fixture(with_fd=False)
    got = eng.sql(CHAIN_SQL)
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    pd.testing.assert_frame_equal(got, _expected(fact), check_dtype=False)


def test_snowflake_chain_parity_vs_fallback():
    """The pandas fallback executes the same chain with real merges —
    results must match the collapsed device plan exactly."""
    eng, fact = _fixture(with_fd=False)
    got = eng.sql(CHAIN_SQL)
    from tpu_olap.planner.fallback import execute_fallback
    ref = execute_fallback(eng.planner.plan(CHAIN_SQL).stmt, eng.catalog,
                           eng.config)
    pd.testing.assert_frame_equal(
        got.reset_index(drop=True),
        ref.sort_values("r_name").reset_index(drop=True), check_dtype=False)


def test_chain_join_order_independent():
    """Region listed before nation still collapses (the reference walks
    the whole join tree, not a left-to-right list)."""
    eng, fact = _fixture(with_fd=False)
    sql = ("SELECT r_name, sum(amount) AS s FROM fact "
           "JOIN region ON n_regionkey = r_regionkey "
           "JOIN nation ON c_nation = n_name "
           "GROUP BY r_name ORDER BY r_name")
    got = eng.sql(sql)
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    pd.testing.assert_frame_equal(got, _expected(fact), check_dtype=False)


def test_fd_implied_link_without_intermediate_table():
    """With FD c_nation → n_regionkey declared, region joins WITHOUT the
    nation table in the query: the link column is implied, not
    materialized. This query is planner-only territory — the pandas
    fallback cannot execute it (no n_regionkey column anywhere in the
    FROM) — exactly the reference's FD payoff."""
    eng, fact = _fixture(with_fd=True)
    sql = ("SELECT r_name, sum(amount) AS s FROM fact "
           "JOIN region ON n_regionkey = r_regionkey "
           "GROUP BY r_name ORDER BY r_name")
    got = eng.sql(sql)
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    pd.testing.assert_frame_equal(got, _expected(fact), check_dtype=False)


def test_unsubsumed_chain_falls_back():
    """No FD, no nation join: the region link is underivable; the plan
    must NOT rewrite (negative test for join subsumption)."""
    eng, _ = _fixture(with_fd=False)
    sql = ("SELECT r_name, sum(amount) AS s FROM fact "
           "JOIN region ON n_regionkey = r_regionkey "
           "GROUP BY r_name ORDER BY r_name")
    plan = eng.planner.plan(sql)
    assert not plan.rewritten
    assert "not subsumed" in plan.fallback_reason


def test_non_fk_join_condition_falls_back():
    eng, _ = _fixture(with_fd=False)
    sql = ("SELECT r_name, sum(amount) AS s FROM fact "
           "JOIN region ON c_city = r_name GROUP BY r_name")
    plan = eng.planner.plan(sql)
    assert not plan.rewritten
    assert "no FK join condition" in plan.fallback_reason


def test_ssb_nation_region_chain_variant():
    """SSB-variant acceptance (VERDICT r1 #5 'done' condition): the bench
    fixture's supplier chain s_city → s_nation → s_region expressed as
    normalized snowflake tables rewrites onto the denormalized fact."""
    from tpu_olap.bench.ssb import generate_tables, denormalize, TIME_COL
    tables = generate_tables(8000, seed=3)
    denorm = denormalize(tables)
    sup = tables["supplier"]
    nation_t = (sup[["s_nation"]].drop_duplicates()
                .rename(columns={"s_nation": "sn_name"}))
    nation_t["sn_regionkey"] = pd.factorize(
        sup.drop_duplicates("s_nation")["s_region"])[0]
    region_map = (sup[["s_nation", "s_region"]].drop_duplicates("s_nation"))
    key_of = dict(zip(nation_t.sn_name, nation_t.sn_regionkey))
    region_t = pd.DataFrame({
        "sr_key": [key_of[n] for n in region_map.s_nation],
        "sr_name": list(region_map.s_region),
    }).drop_duplicates("sr_key")
    star = StarSchema(
        fact="lineorder",
        dimensions=(
            StarDimension("nation", fact_key="s_nation", dim_key="sn_name",
                          column_map={"sn_name": "s_nation"}),
            StarDimension("region", fact_key="sn_regionkey",
                          dim_key="sr_key",
                          column_map={"sr_name": "s_region"}),
        ))
    eng = Engine()
    eng.register_table("lineorder", denorm, time_column=TIME_COL,
                       star_schema=star)
    eng.register_table("nation", nation_t, accelerate=False)
    eng.register_table("region", region_t, accelerate=False)
    got = eng.sql(
        "SELECT sr_name, sum(lo_revenue) AS rev FROM lineorder "
        "JOIN nation ON s_nation = sn_name "
        "JOIN region ON sn_regionkey = sr_key "
        "GROUP BY sr_name ORDER BY sr_name")
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    exp = (denorm.groupby("s_region", as_index=False)
           .agg(rev=("lo_revenue", "sum"))
           .rename(columns={"s_region": "sr_name"})
           .sort_values("sr_name").reset_index(drop=True))
    pd.testing.assert_frame_equal(got, exp, check_dtype=False)


# --- filter-constrained dimension domains (round 3) ---------------------

def _restrict_fixture():
    from tpu_olap.bench.parity import check_query  # noqa: F401
    rng = np.random.default_rng(7)
    n = 5000
    cities = [f"c{i}" for i in range(12)]
    zone_of = {c: ("west" if i < 4 else "east" if i < 8 else "mid")
               for i, c in enumerate(cities)}
    city = rng.choice(cities, n)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "city": city,
        "zone": np.array([zone_of[c] for c in city], object),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    eng = Engine()
    eng.register_table("f", df, time_column="ts", star_schema=StarSchema(
        fact="f", dimensions=(),
        functional_dependencies=(FunctionalDependency("city", "zone"),)))
    return eng


def test_direct_filter_restricts_dim_domain():
    """A literal filter on the grouped dim itself shrinks its dense id
    space to |set|+1 (the Q3.3/Q3.4 shape) with identical results."""
    from tpu_olap.bench.parity import check_query
    from tpu_olap.executor.lowering import lower
    eng = _restrict_fixture()
    sql = ("SELECT city, sum(v) AS s FROM f "
           "WHERE city IN ('c1', 'c3') GROUP BY city ORDER BY city")
    plan = eng.planner.plan(sql)
    assert plan.rewritten, plan.fallback_reason
    phys = lower(plan.query, plan.entry.segments, eng.config)
    assert phys.total_groups == 3  # null slot + 2 allowed values
    check_query(eng, sql)


def test_fd_filter_restricts_determinant_domain():
    """A filter on the FD *dependent* (zone) shrinks the grouped
    *determinant* (city) to the codes observed with allowed dependents,
    verified against the data."""
    from tpu_olap.bench.parity import check_query
    from tpu_olap.executor.lowering import lower
    eng = _restrict_fixture()
    sql = ("SELECT city, sum(v) AS s FROM f "
           "WHERE zone = 'west' GROUP BY city ORDER BY city")
    plan = eng.planner.plan(sql)
    assert plan.rewritten, plan.fallback_reason
    phys = lower(plan.query, plan.entry.segments, eng.config)
    assert phys.total_groups == 5  # null slot + the 4 'west' cities
    check_query(eng, sql)


def test_fd_violation_disables_restriction():
    """Data violating the declared FD must disable the remap (map is
    None) — correctness never rests on the declaration."""
    from tpu_olap.bench.parity import check_query
    n = 1000
    rng = np.random.default_rng(3)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(np.arange(n), unit="min"),
        "city": rng.choice(["a", "b", "c"], n),
        "zone": rng.choice(["x", "y"], n),  # NOT functionally dependent
        "v": np.ones(n, np.int64),
    })
    eng = Engine()
    eng.register_table("f", df, time_column="ts", star_schema=StarSchema(
        fact="f", dimensions=(),
        functional_dependencies=(FunctionalDependency("city", "zone"),)))
    assert eng.catalog.get("f").segments.fd_code_map("city", "zone") is None
    sql = ("SELECT city, sum(v) AS s FROM f WHERE zone = 'x' "
           "GROUP BY city ORDER BY city")
    check_query(eng, sql)
