"""Randomized query-parity fuzzing: seeded random SQL over a random table,
device path vs pandas fallback (SURVEY.md §5 implication #3 generalized —
the fixed suites pin known shapes; this sweeps the combination space of
dims x filters x aggs x granularity x having x order/limit).

Deterministic: every case derives from a seed, so a failure prints its
seed and query for exact replay.
"""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import ParityError, assert_frame_parity, run_both
from tpu_olap.executor import EngineConfig

N_CASES = 200

_CITY_REGION = {f"city{i}": ("west" if i < 5 else "east") for i in range(9)}


def _make_table(rng, n):
    cities = rng.choice([f"city{i}" for i in range(9)], n)
    frame = pd.DataFrame({
        "ts": pd.to_datetime("2019-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 400, n), unit="s"),
        "cat": rng.choice(["alpha", "beta", "gamma", "delta", None], n,
                          p=[0.3, 0.3, 0.2, 0.15, 0.05]),
        "城市": cities,
        "region": np.array([_CITY_REGION[c] for c in cities], object),
        "small": rng.integers(0, 7, n).astype(np.int64),
        "qty": rng.integers(-50, 200, n).astype(np.int64),
        "price": np.round(rng.random(n) * 1000, 3),
    })
    # a second city-vocabulary column for columnComparison shapes,
    # derived WITHOUT consuming rng draws (keeps every other column's
    # per-seed values stable across grammar generations); the roll
    # guarantees frequent matches and mismatches, the shift skews the
    # vocabulary so cross-dictionary translation sees absent values
    frame["peer"] = np.where(
        frame["small"] >= 3, np.roll(cities, 7),
        np.array([f"city{(int(c[4:]) + 2) % 11}" for c in cities], object))
    if rng.random() < 0.5:
        frame.loc[rng.random(n) < 0.04, "qty"] = np.nan
        frame["qty"] = frame["qty"].astype("Int64")
    return frame


def _city_dim():
    return pd.DataFrame({
        "d_city": list(_CITY_REGION),
        "d_region": list(_CITY_REGION.values()),
    })


def _star():
    from tpu_olap.catalog.star import StarDimension, StarSchema
    return StarSchema(
        fact="t",
        dimensions=(StarDimension(
            "citydim", fact_key="城市", dim_key="d_city",
            column_map={"d_city": "城市", "d_region": "region"}),))


_DIMS = ["cat", "城市", "small", "region", "peer"]
_AGGS = [
    ("sum(qty)", "sq"), ("sum(price)", "sp"), ("count(*)", "n"),
    ("min(price)", "mp"), ("max(qty)", "xq"), ("avg(price)", "ap"),
    ("sum(qty * small)", "svs"), ("sum(price + qty)", "spq"),
    ("count(qty > 25)", "cge"),  # null comparison -> null -> not counted
    ("sum(CASE WHEN qty > 25 THEN qty ELSE 0 END)", "scw"),
    ("sum(CAST(price AS INT))", "sci"),
    ("max(CAST(qty AS DOUBLE))", "xcd"),
    # standard-SQL FILTER aggregates (round 3)
    ("sum(qty) FILTER (WHERE region = 'west')", "sfw"),
    ("count(*) FILTER (WHERE price > 500.5)", "cfp"),
    ("avg(price) FILTER (WHERE small IN (1, 2))", "afs"),
    # theta sketches + set ops (round 4; KMV is EXACT under capacity, and
    # every column here has cardinality << the sketch k, so device
    # estimates equal the fallback's exact distinct counts)
    ("theta_sketch_estimate(theta_sketch(城市))", "tse"),
    ("theta_sketch_estimate(theta_sketch_intersect("
     "theta_sketch(城市) FILTER (WHERE region = 'west'), "
     "theta_sketch(城市) FILTER (WHERE qty > 25)))", "tsi"),
    ("theta_sketch_union("
     "theta_sketch(cat) FILTER (WHERE small < 4), "
     "theta_sketch(cat) FILTER (WHERE price > 300.25))", "tsu"),
    ("theta_sketch_not("
     "theta_sketch(城市) FILTER (WHERE small >= 2), "
     "theta_sketch(城市) FILTER (WHERE small < 2))", "tsn"),
]
_FILTERS = [
    "qty > 25", "qty BETWEEN -10 AND 80", "price < 500.5",
    "cat = 'alpha'", "cat IN ('beta', 'gamma')", "cat IS NOT NULL",
    "城市 LIKE 'city1%'", "NOT (small = 3)",
    "small IN (1, 2, 5) OR qty < 0", "cat IS NULL",
    "substr(城市, 5, 1) = '3'",
    "(ts >= '2019-05-01' AND ts < '2019-08-01') "
    "OR (ts >= '2019-11-01' AND ts < '2020-01-15')",
    # extraction filters (round 3 features, fuzz-weighted in round 4):
    # case-fold selector/IN, substring IN, and extraction bound ranges
    "upper(cat) = 'ALPHA'",
    "upper(cat) IN ('ALPHA', 'BETA')",
    "substr(城市, 5, 1) IN ('1', '3', '8')",
    "substr(城市, 5, 1) >= '2' AND substr(城市, 5, 1) < '6'",
    "lower(region) = 'west'",
    # columnComparison shapes (round 4): row-vs-row equality across
    # string dims (cross-dictionary translation incl. absent values)
    # and numeric columns, plus the NOT composition where NULLs match
    "城市 = peer",
    "城市 <> peer",
    "城市 = peer AND qty > 25",
    "NOT (城市 = peer) OR cat = 'alpha'",
    "small = qty",
    "small <> qty",
    # round-4 second window: tuple IN (parse-time OR-of-AND expansion)
    # and TIMESTAMP/INTERVAL literal folding — both rewrite to the
    # device path, so the parity harness genuinely covers them.
    # (Correlated-EXISTS shapes are fallback-only — a subquery never
    # rides the device path — so they add no parity coverage here; the
    # margins tests oracle them instead.)
    "(cat, region) IN (('alpha', 'west'), ('beta', 'east'))",
    "(region, small) IN (('west', 1), ('east', 3), ('west', 5))",
    "ts < TIMESTAMP '2019-09-01' - INTERVAL '15' DAY",
    "ts >= DATE '2019-03-01' + INTERVAL 1 MONTH",
]
_TIME_EXPRS = [None, "year(ts)", "month(ts)", "quarter(ts)",
               "date_trunc('day', ts)"]
_EXTRACT_DIMS = ["substr(城市, 1, 5)", "regexp_extract(cat, '^(a|b)')",
                 # integer-expression dims (virtual numeric, round 3)
                 "small + 1", "small * 3 - 2"]


def _alias_key(g, dims):
    """A group expression's referenceable name: plain dims by their own
    name, the (single) extract dim by its SELECT alias `xd`, the time
    expression by `tg` — shared by the alias-GROUP-BY and ORDER-BY
    emitters so they cannot drift."""
    if g in dims:
        return g
    return "xd" if g in _EXTRACT_DIMS else "tg"


def _gen_query(rng):
    n_dims = int(rng.integers(0, 3))
    dims = list(rng.choice(_DIMS, size=n_dims, replace=False))
    join = rng.random() < 0.25
    if join and "region" in dims:
        # reach region through the star join instead of the fact column
        dims[dims.index("region")] = "d_region"
    texpr = _TIME_EXPRS[rng.integers(0, len(_TIME_EXPRS))]
    aggs = [_AGGS[i] for i in
            rng.choice(len(_AGGS), size=rng.integers(1, 4), replace=False)]

    select = list(dims)
    group = list(dims)
    if rng.random() < 0.15:
        ex = _EXTRACT_DIMS[rng.integers(0, len(_EXTRACT_DIMS))]
        select.append(f"{ex} AS xd")
        group.append(ex)
    if texpr is not None and rng.random() < 0.6:
        select.append(f"{texpr} AS tg")
        group.append(texpr)

    from_clause = " FROM t"
    if join:
        from_clause = " FROM t JOIN citydim ON 城市 = d_city"

    if not aggs or (group and rng.random() < 0.1):
        pass
    if group and not select:
        select = list(group)
    distinct = rng.random() < 0.1 and group
    if distinct:
        sql = "SELECT DISTINCT " + ", ".join(group) + from_clause
        group = []
    else:
        select += [f"{e} AS {a}" for e, a in aggs]
        sql = "SELECT " + ", ".join(select) + from_clause
    n_filters = int(rng.integers(0, 3))
    if n_filters:
        fs = list(rng.choice(_FILTERS, size=n_filters, replace=False))
        sql += " WHERE " + " AND ".join(f"({f})" for f in fs)
    # ordinals: group keys occupy select positions 1..len(group) (the
    # select list was built dims-first in the same order), so GROUP BY /
    # ORDER BY may legally reference them by position
    use_ordinals = bool(group) and not distinct and rng.random() < 0.2
    if group:
        if use_ordinals:
            sql += " GROUP BY " + ", ".join(
                str(i + 1) for i in range(len(group)))
        elif rng.random() < 0.25:
            # output-alias references (round-4 second window): the
            # extract/time group keys may be named by their SELECT alias
            sql += " GROUP BY " + ", ".join(
                _alias_key(g, dims) for g in group)
        else:
            sql += " GROUP BY " + ", ".join(group)
        if rng.random() < 0.3:
            sql += f" HAVING {aggs[0][1]} > 0"
    if rng.random() < 0.5 and group:
        # order by EVERY group key so LIMIT selects a unique row set —
        # ties under a partial ORDER BY may legally differ between paths
        keys = [_alias_key(g, dims) for g in group]
        if use_ordinals and rng.random() < 0.5:
            keys = [str(i + 1) for i in range(len(group))]
        direction = "DESC" if rng.random() < 0.5 else "ASC"
        sql += " ORDER BY " + ", ".join(f"{k} {direction}" for k in keys)
        if rng.random() < 0.5:
            sql += f" LIMIT {int(rng.integers(1, 30))}"
            if rng.random() < 0.4:
                sql += f" OFFSET {int(rng.integers(0, 10))}"
    if rng.random() < 0.08:
        # CTE wrap: exercises WITH-inlining + the derived-table fallback
        sql = f"WITH q AS ({sql}) SELECT * FROM q"
    return sql


def test_having_null_aggregate_parity():
    """HAVING over a NULL aggregate (sum of an all-NA group). Device path
    surfaces the NULL as NaN, where every comparison is False and NOT
    flips it to True; the fallback must collapse pd.NA identically
    (VERDICT round-2 weak #1, fuzz seed 102)."""
    frame = pd.DataFrame({
        "ts": pd.to_datetime("2019-03-01") + pd.to_timedelta(
            np.arange(6), unit="h"),
        "cat": ["a", "a", "b", "b", "c", "c"],
        "qty": pd.array([1, 2, None, None, -3, None], dtype="Int64"),
    })
    eng = Engine()
    eng.register_table("t", frame, time_column="ts")
    for having in ("sum(qty) > 0", "sum(qty) < 0", "sum(qty) = 0",
                   "NOT (sum(qty) > 0)",
                   "sum(qty) > 0 OR count(*) > 99",
                   "sum(qty) > 0 AND count(*) > 0"):
        sql = (f"SELECT cat, sum(qty) AS s FROM t GROUP BY cat "
               f"HAVING {having}")
        device, fb, _ = run_both(eng, sql)
        assert_frame_parity(device, fb, ordered=False,
                            label=f"having={having!r}")


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_parity(seed):
    rng = np.random.default_rng(1000 + seed)
    frame = _make_table(rng, int(rng.integers(500, 6000)))
    pallas = "force" if seed % 3 == 0 else "never"
    shards = 8 if seed % 5 == 0 else None
    eng = Engine(EngineConfig(use_pallas=pallas, num_shards=shards))
    eng.register_table("t", frame, time_column="ts",
                       block_rows=int(2 ** rng.integers(8, 11)),
                       star_schema=_star())
    eng.register_table("citydim", _city_dim(), accelerate=False)
    sql = _gen_query(rng)
    try:
        device, fb, plan = run_both(eng, sql)
    except ParityError:
        # planner chose fallback for this shape — legal, not a parity bug,
        # but record why so systematic regressions surface in the log
        print(f"seed {seed}: fallback: {eng.last_plan.fallback_reason}")
        return
    # ORDER BY with LIMIT can legally tie-break differently; compare as
    # unordered sets unless the query is unambiguous
    ordered = False
    try:
        assert_frame_parity(device, fb, ordered=ordered,
                            label=f"seed={seed} sql={sql!r}")
    except ParityError:
        print(f"FUZZ FAILURE seed={seed}\nSQL: {sql}")
        raise
