"""Randomized query-parity fuzzing: seeded random SQL over a random table,
device path vs pandas fallback (SURVEY.md §5 implication #3 generalized —
the fixed suites pin known shapes; this sweeps the combination space of
dims x filters x aggs x granularity x having x order/limit).

Deterministic: every case derives from a seed, so a failure prints its
seed and query for exact replay.
"""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import ParityError, assert_frame_parity, run_both
from tpu_olap.executor import EngineConfig

N_CASES = 40


def _make_table(rng, n):
    frame = pd.DataFrame({
        "ts": pd.to_datetime("2019-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 400, n), unit="s"),
        "cat": rng.choice(["alpha", "beta", "gamma", "delta", None], n,
                          p=[0.3, 0.3, 0.2, 0.15, 0.05]),
        "城市": rng.choice([f"city{i}" for i in range(9)], n),
        "small": rng.integers(0, 7, n).astype(np.int64),
        "qty": rng.integers(-50, 200, n).astype(np.int64),
        "price": np.round(rng.random(n) * 1000, 3),
    })
    if rng.random() < 0.5:
        frame.loc[rng.random(n) < 0.04, "qty"] = np.nan
        frame["qty"] = frame["qty"].astype("Int64")
    return frame


_DIMS = ["cat", "城市", "small"]
_AGGS = [
    ("sum(qty)", "sq"), ("sum(price)", "sp"), ("count(*)", "n"),
    ("min(price)", "mp"), ("max(qty)", "xq"), ("avg(price)", "ap"),
    ("sum(qty * small)", "svs"), ("sum(price + qty)", "spq"),
    ("count(qty > 25)", "cge"),  # null comparison -> null -> not counted
]
_FILTERS = [
    "qty > 25", "qty BETWEEN -10 AND 80", "price < 500.5",
    "cat = 'alpha'", "cat IN ('beta', 'gamma')", "cat IS NOT NULL",
    "城市 LIKE 'city1%'", "NOT (small = 3)",
    "small IN (1, 2, 5) OR qty < 0", "cat IS NULL",
]
_TIME_EXPRS = [None, "year(ts)", "month(ts)", "date_trunc('day', ts)"]


def _gen_query(rng):
    n_dims = int(rng.integers(0, 3))
    dims = list(rng.choice(_DIMS, size=n_dims, replace=False))
    texpr = _TIME_EXPRS[rng.integers(0, len(_TIME_EXPRS))]
    aggs = [_AGGS[i] for i in
            rng.choice(len(_AGGS), size=rng.integers(1, 4), replace=False)]

    select = list(dims)
    group = list(dims)
    if texpr is not None and rng.random() < 0.6:
        select.append(f"{texpr} AS tg")
        group.append(texpr)
    select += [f"{e} AS {a}" for e, a in aggs]

    sql = "SELECT " + ", ".join(select) + " FROM t"
    n_filters = int(rng.integers(0, 3))
    if n_filters:
        fs = list(rng.choice(_FILTERS, size=n_filters, replace=False))
        sql += " WHERE " + " AND ".join(f"({f})" for f in fs)
    if group:
        sql += " GROUP BY " + ", ".join(group)
        if rng.random() < 0.3:
            sql += f" HAVING {aggs[0][1]} > 0"
    if rng.random() < 0.5 and group:
        # order by EVERY group key so LIMIT selects a unique row set —
        # ties under a partial ORDER BY may legally differ between paths
        keys = [g if g in dims else "tg" for g in group]
        direction = "DESC" if rng.random() < 0.5 else "ASC"
        sql += " ORDER BY " + ", ".join(f"{k} {direction}" for k in keys)
        if rng.random() < 0.5:
            sql += f" LIMIT {int(rng.integers(1, 30))}"
    return sql


@pytest.mark.parametrize("seed", range(N_CASES))
def test_fuzz_parity(seed):
    rng = np.random.default_rng(1000 + seed)
    frame = _make_table(rng, int(rng.integers(500, 6000)))
    pallas = "force" if seed % 3 == 0 else "never"
    shards = 8 if seed % 5 == 0 else None
    eng = Engine(EngineConfig(use_pallas=pallas, num_shards=shards))
    eng.register_table("t", frame, time_column="ts",
                       block_rows=int(2 ** rng.integers(8, 11)))
    sql = _gen_query(rng)
    try:
        device, fb, plan = run_both(eng, sql)
    except ParityError:
        # planner chose fallback for this shape — legal, not a parity bug,
        # but record why so systematic regressions surface in the log
        print(f"seed {seed}: fallback: {eng.last_plan.fallback_reason}")
        return
    # ORDER BY with LIMIT can legally tie-break differently; compare as
    # unordered sets unless the query is unambiguous
    ordered = False
    try:
        assert_frame_parity(device, fb, ordered=ordered,
                            label=f"seed={seed} sql={sql!r}")
    except ParityError:
        print(f"FUZZ FAILURE seed={seed}\nSQL: {sql}")
        raise
