"""Executor end-to-end tests: every query type vs a pandas oracle, on both
the numpy platform and the jitted jax path (SURVEY.md §5 implication #3 —
the TPU-vs-fallback parity idea, here jax vs numpy vs pandas)."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap.executor import EngineConfig, QueryRunner
from tpu_olap.ir import (AndFilter, ArithmeticPostAgg, BoundFilter,
                         CardinalityAggregation, CountAggregation,
                         DefaultDimensionSpec, ExtractionDimensionSpec,
                         FieldAccessPostAgg, GreaterThanHaving,
                         GroupByQuerySpec, InFilter, Interval, LimitSpec,
                         PeriodGranularity, ScanQuerySpec,
                         SearchQueryContains, SearchQuerySpec,
                         SegmentMetadataQuerySpec, SelectorFilter,
                         SelectQuerySpec, SubstringExtractionFn,
                         SumAggregation, TimeBoundaryQuerySpec,
                         TimeFormatExtractionFn, TimeseriesQuerySpec,
                         TopNQuerySpec, VirtualColumn, parse_expr)
from tpu_olap.ir.limit import OrderByColumnSpec
from tpu_olap.segments import ingest_pandas
from tpu_olap.utils import timeutil as tu


def make():
    rng = np.random.default_rng(11)
    n = 5000
    t0 = tu.date_to_millis(1993, 1, 1)
    df = pd.DataFrame({
        "ts": t0 + rng.integers(0, 2 * 365 * 86_400_000, n),  # 1993-1994
        "city": rng.choice(["amsterdam", "berlin", "chicago", None], n,
                           p=[0.4, 0.3, 0.25, 0.05]),
        "kind": rng.choice(["aa", "ab", "bb"], n),
        "year_col": rng.integers(1993, 1996, n).astype(np.int64),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": np.round(rng.uniform(0, 100, n), 2),
        "uid": rng.integers(0, 800, n).astype(np.int64),
    })
    table = ingest_pandas("t", df, time_column="ts", block_rows=1 << 10)
    df = df.sort_values("ts", kind="stable").reset_index(drop=True)
    return df, table


DF, TABLE = make()


@pytest.fixture(scope="module", params=["cpu", "device"])
def runner(request):
    return QueryRunner(EngineConfig(platform=request.param))


def test_timeseries_all(runner):
    q = TimeseriesQuerySpec(
        data_source="t",
        filter=SelectorFilter("city", "berlin"),
        aggregations=(CountAggregation("n"),
                      SumAggregation("q", "qty", "long")),
        post_aggregations=(ArithmeticPostAgg(
            "avg_q", "/", (FieldAccessPostAgg("q"), FieldAccessPostAgg("n"))),),
    )
    res = runner.execute(q, TABLE)
    sub = DF[DF.city == "berlin"]
    assert len(res.rows) == 1
    assert res.rows[0]["n"] == len(sub)
    assert res.rows[0]["q"] == sub.qty.sum()
    assert np.isclose(res.rows[0]["avg_q"], sub.qty.mean())


def test_timeseries_monthly_with_interval(runner):
    iv = Interval.of("1993-03-01", "1993-06-01")
    q = TimeseriesQuerySpec(
        data_source="t", intervals=(iv,),
        granularity=PeriodGranularity("P1M"),
        aggregations=(CountAggregation("n"),),
    )
    res = runner.execute(q, TABLE)
    assert [r["timestamp"][:7] for r in res.rows] == \
        ["1993-03", "1993-04", "1993-05"]
    ms = DF.ts[(DF.ts >= iv.start) & (DF.ts < iv.end)]
    month = pd.to_datetime(ms.to_numpy(), unit="ms").month
    for r, m in zip(res.rows, [3, 4, 5]):
        assert r["n"] == (month == m).sum()
    # pruning happened
    assert res.metrics["segments_scanned"] < res.metrics["segments_total"]


def test_groupby_two_dims_having_limit(runner):
    q = GroupByQuerySpec(
        data_source="t",
        dimensions=(DefaultDimensionSpec("city"),
                    DefaultDimensionSpec("year_col", "yr")),
        aggregations=(SumAggregation("q", "qty", "long"),
                      CountAggregation("n")),
        having=GreaterThanHaving("n", 50),
        limit_spec=LimitSpec(5, (OrderByColumnSpec("q", "descending"),)),
    )
    res = runner.execute(q, TABLE)
    truth = (DF.assign(city=DF.city.fillna("~null"))
             .groupby(["city", "year_col"])
             .agg(q=("qty", "sum"), n=("qty", "count")).reset_index())
    truth = truth[truth.n > 50].sort_values("q", ascending=False).head(5)
    assert len(res.rows) == len(truth)
    for r, (_, t) in zip(res.rows, truth.iterrows()):
        want_city = None if t.city == "~null" else t.city
        assert r["city"] == want_city
        assert r["yr"] == t.year_col
        assert r["q"] == t.q


def test_groupby_time_extraction_dim(runner):
    q = GroupByQuerySpec(
        data_source="t",
        dimensions=(
            ExtractionDimensionSpec("__time", TimeFormatExtractionFn("YYYY"),
                                    "yr"),
            ExtractionDimensionSpec("kind", SubstringExtractionFn(0, 1),
                                    "k1"),
        ),
        aggregations=(CountAggregation("n"),),
    )
    res = runner.execute(q, TABLE)
    years = pd.to_datetime(DF.ts.to_numpy(), unit="ms").year.astype(str)
    truth = (DF.assign(yr=years, k1=DF.kind.str[0])
             .groupby(["yr", "k1"]).size())
    assert len(res.rows) == len(truth)
    for r in res.rows:
        assert r["n"] == truth[(r["yr"], r["k1"])]


def test_groupby_monthly_granularity(runner):
    q = GroupByQuerySpec(
        data_source="t",
        intervals=(Interval.of("1993-01-01", "1993-04-01"),),
        dimensions=(DefaultDimensionSpec("city"),),
        granularity=PeriodGranularity("P1M"),
        aggregations=(CountAggregation("n"),),
    )
    res = runner.execute(q, TABLE)
    sub = DF[DF.ts < tu.date_to_millis(1993, 4, 1)]
    month = pd.to_datetime(sub.ts.to_numpy(), unit="ms").month
    truth = (sub.assign(m=month, city=sub.city.fillna("~"))
             .groupby(["m", "city"]).size())
    assert len(res.rows) == len(truth)
    # natural order: timestamp then dim
    stamps = [r["timestamp"] for r in res.rows]
    assert stamps == sorted(stamps)
    for r in res.rows:
        m = int(r["timestamp"][5:7])
        c = r["city"] if r["city"] is not None else "~"
        assert r["n"] == truth[(m, c)]


def test_topn(runner):
    q = TopNQuerySpec(
        data_source="t",
        dimension=DefaultDimensionSpec("city"),
        metric="q", threshold=2,
        aggregations=(SumAggregation("q", "qty", "long"),),
    )
    res = runner.execute(q, TABLE)
    truth = (DF.assign(city=DF.city.fillna("~"))
             .groupby("city").qty.sum().sort_values(ascending=False))
    got = [(r["city"] or "~", r["q"]) for r in res.rows]
    assert got == list(truth.items())[:2]
    # bottom-N
    q2 = TopNQuerySpec(
        data_source="t", dimension=DefaultDimensionSpec("city"),
        metric="q", threshold=2, inverted=True,
        aggregations=(SumAggregation("q", "qty", "long"),),
    )
    res2 = runner.execute(q2, TABLE)
    got2 = [(r["city"] or "~", r["q"]) for r in res2.rows]
    assert got2 == list(truth.items())[::-1][:2]


def test_cardinality_hll(runner):
    q = TimeseriesQuerySpec(
        data_source="t",
        aggregations=(CardinalityAggregation("u", ("uid",)),),
    )
    res = runner.execute(q, TABLE)
    want = DF.uid.nunique()
    assert abs(res.rows[0]["u"] - want) / want < 0.1


def test_scan_with_filter_and_limit(runner):
    q = ScanQuerySpec(
        data_source="t",
        filter=AndFilter((SelectorFilter("city", "chicago"),
                          BoundFilter("qty", lower=45, ordering="numeric"))),
        columns=("city", "qty", "price"),
        limit=10,
    )
    res = runner.execute(q, TABLE)
    sub = DF[(DF.city == "chicago") & (DF.qty >= 45)]
    assert len(res.rows) == min(10, len(sub))
    for r, (_, t) in zip(res.rows, sub.iterrows()):
        assert r["city"] == "chicago" and r["qty"] == t.qty
    # offset continues where limit stopped
    q2 = ScanQuerySpec(data_source="t", filter=q.filter,
                       columns=("qty",), offset=10, limit=5)
    res2 = runner.execute(q2, TABLE)
    assert [r["qty"] for r in res2.rows] == sub.qty.iloc[10:15].tolist()


def test_scan_descending(runner):
    q = ScanQuerySpec(data_source="t", columns=("qty",), limit=5,
                      order="descending")
    res = runner.execute(q, TABLE)
    assert [r["qty"] for r in res.rows] == DF.qty.iloc[::-1].head(5).tolist()


def test_select_paging(runner):
    q = SelectQuerySpec(data_source="t",
                        filter=SelectorFilter("kind", "aa"),
                        dimensions=("city", "kind"), metrics=("qty",),
                        page_size=7)
    res = runner.execute(q, TABLE)
    sub = DF[DF.kind == "aa"]
    assert len(res.rows) == 7
    pid = res.druid[0]["result"]["pagingIdentifiers"]["offset"]
    assert pid == 7
    q2 = SelectQuerySpec(data_source="t", filter=q.filter,
                         dimensions=("city", "kind"), metrics=("qty",),
                         page_size=7, paging_offset=pid)
    res2 = runner.execute(q2, TABLE)
    assert [r["qty"] for r in res2.rows] == sub.qty.iloc[7:14].tolist()


def test_search(runner):
    q = SearchQuerySpec(
        data_source="t", search_dimensions=("city", "kind"),
        query=SearchQueryContains("am"), limit=10,
    )
    res = runner.execute(q, TABLE)
    vals = {(h["dimension"], h["value"]) for h in res.rows}
    assert ("city", "amsterdam") in vals
    assert all("am" in h["value"] for h in res.rows)
    counts = {h["value"]: h["count"] for h in res.rows}
    assert counts["amsterdam"] == (DF.city == "amsterdam").sum()


def test_time_boundary(runner):
    res = runner.execute(TimeBoundaryQuerySpec(data_source="t"), TABLE)
    t0, t1 = TABLE.time_boundary
    assert res.rows[0]["minTime"] == tu.millis_to_iso(t0)
    assert res.rows[0]["maxTime"] == tu.millis_to_iso(t1)


def test_segment_metadata(runner):
    res = runner.execute(SegmentMetadataQuerySpec(data_source="t"), TABLE)
    rec = res.rows[0]
    assert rec["numRows"] == len(DF)
    assert rec["columns"]["city"]["cardinality"] == 3


def test_virtual_column_and_filtered_sum(runner):
    q = TimeseriesQuerySpec(
        data_source="t",
        virtual_columns=(VirtualColumn("rev", parse_expr("qty * price")),),
        filter=InFilter("city", ("berlin", "chicago")),
        aggregations=(SumAggregation("r", "rev", "double"),),
    )
    res = runner.execute(q, TABLE)
    sub = DF[DF.city.isin(["berlin", "chicago"])]
    assert np.isclose(res.rows[0]["r"], (sub.qty * sub.price).sum())


def test_empty_interval(runner):
    q = TimeseriesQuerySpec(
        data_source="t",
        intervals=(Interval.of("2050-01-01", "2051-01-01"),),
        aggregations=(CountAggregation("n"),),
    )
    res = runner.execute(q, TABLE)
    assert res.rows == []


def test_compile_cache_hits_across_literals():
    r = QueryRunner(EngineConfig(platform="device"))

    def q(val):
        return TimeseriesQuerySpec(
            data_source="t", filter=SelectorFilter("city", val),
            aggregations=(SumAggregation("q", "qty", "long"),))
    res1 = r.execute(q("berlin"), TABLE)
    res2 = r.execute(q("chicago"), TABLE)
    assert res1.metrics["jit_cache_hit"] is False
    assert res2.metrics["jit_cache_hit"] is True
    assert res2.rows[0]["q"] == DF.qty[DF.city == "chicago"].sum()
    # execute-only time on a cache hit should be far below compile time
    assert res2.metrics["execute_ms"] < res1.metrics["execute_ms"]


def test_history_records(runner):
    before = len(runner.history)
    runner.execute(TimeBoundaryQuerySpec(data_source="t"), TABLE)
    assert len(runner.history) == before + 1
    rec = runner.history[-1]
    assert rec["query_type"] == "timeBoundary"
    assert "total_ms" in rec


def test_search_padded_shard_mask():
    """Search with num_shards not dividing the segment count: the
    dispatch mask is padded past the segment stack and the count path
    must slice it, never mis-map (5000 rows / 1024 block_rows = 5
    segments, padded to 8 shards)."""
    r8 = QueryRunner(EngineConfig(platform="device", num_shards=8))
    q = SearchQuerySpec(
        data_source="t", search_dimensions=("city",),
        query=SearchQueryContains("am"),
    )
    res = r8.execute(q, TABLE)
    counts = {h["value"]: h["count"] for h in res.rows}
    assert counts["amsterdam"] == (DF.city == "amsterdam").sum()
