"""Timezone-aware granularity EXECUTION tests (SURVEY.md §5: "date-time
function tests (granularity/extraction correctness incl. timezone)").

test_timeutil pins boundary math; these run full queries through the
engine across DST transitions and compare against pandas tz-aware
truncation — the semantics Druid defines for period granularities with a
time zone (local calendar buckets, offset changes at DST).
"""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.ir.aggregations import CountAggregation, SumAggregation
from tpu_olap.ir.granularity import PeriodGranularity
from tpu_olap.ir.query import TimeseriesQuerySpec

NY = "America/New_York"


@pytest.fixture(scope="module")
def engine():
    rng = np.random.default_rng(17)
    # one row every 20 minutes across the 2021 US spring-forward (Mar 14)
    # and fall-back (Nov 7) transitions
    ts = pd.date_range("2021-03-12", "2021-03-17", freq="20min",
                       tz="UTC").tz_localize(None)
    ts = ts.append(pd.date_range("2021-11-05", "2021-11-10", freq="20min",
                                 tz="UTC").tz_localize(None))
    df = pd.DataFrame({
        "ts": ts,
        "v": rng.integers(1, 100, len(ts)).astype(np.int64),
    })
    eng = Engine(EngineConfig())
    eng.register_table("e", df, time_column="ts", block_rows=256)
    eng._test_frame = df
    return eng


def _run_timeseries(eng, period, tz):
    q = TimeseriesQuerySpec(
        data_source="e",
        granularity=PeriodGranularity(period, tz),
        aggregations=(CountAggregation("n"), SumAggregation("s", "v")),
    )
    res = eng.execute_ir(q)
    return res.rows


@pytest.mark.parametrize("tz", ["UTC", NY])
def test_day_buckets_across_dst(engine, tz):
    rows = _run_timeseries(engine, "P1D", tz)
    df = engine._test_frame
    loc = df.set_index("ts").tz_localize("UTC").tz_convert(tz)
    exp = loc.groupby(loc.index.normalize()).agg(
        n=("v", "size"), s=("v", "sum"))
    got = {r["timestamp"]: (r["n"], r["s"]) for r in rows if r["n"] > 0}
    assert len(got) == len(exp)
    for ts_local, row in exp.iterrows():
        iso = ts_local.tz_convert("UTC").tz_localize(None) \
            .isoformat(timespec="milliseconds") + "Z"
        assert got[iso] == (row.n, row.s), (tz, iso)


def test_dst_spring_forward_day_is_23_hours(engine):
    """The Mar 14 2021 NY bucket spans 23 real hours; hour buckets inside
    it must still partition the rows exactly."""
    day_rows = _run_timeseries(engine, "P1D", NY)
    hour_rows = _run_timeseries(engine, "PT1H", NY)
    # locate the spring-forward local day: starts 2021-03-14T05:00Z
    target = "2021-03-14T05:00:00.000Z"
    day = next(r for r in day_rows if r["timestamp"] == target)
    nxt = "2021-03-15T04:00:00.000Z"  # next local midnight is EDT (UTC-4)
    in_day = [r for r in hour_rows if target <= r["timestamp"] < nxt]
    assert sum(r["n"] for r in in_day) == day["n"]
    assert sum(r["s"] for r in in_day) == day["s"]
    assert len([r for r in in_day if r["n"] > 0]) == 23  # 23-hour day


def test_fall_back_day_is_25_hours(engine):
    day_rows = _run_timeseries(engine, "P1D", NY)
    target = "2021-11-07T04:00:00.000Z"  # local midnight EDT (UTC-4)
    nxt = "2021-11-08T05:00:00.000Z"     # next local midnight EST (UTC-5)
    hour_rows = _run_timeseries(engine, "PT1H", NY)
    day = next(r for r in day_rows if r["timestamp"] == target)
    in_day = [r for r in hour_rows if target <= r["timestamp"] < nxt]
    assert sum(r["n"] for r in in_day) == day["n"]
    assert len([r for r in in_day if r["n"] > 0]) == 25  # 25-hour day


def test_sql_date_trunc_tz_parity_utc(engine):
    """SQL surface: date_trunc over the DST data stays on the device path
    and matches the pandas fallback exactly."""
    from tpu_olap.bench.parity import check_query
    check_query(engine, "SELECT date_trunc('day', ts) AS d, count(*) AS n, "
                        "sum(v) AS s FROM e GROUP BY date_trunc('day', ts)")


def test_month_granularity_tz(engine):
    rows = _run_timeseries(engine, "P1M", NY)
    df = engine._test_frame
    loc = df.set_index("ts").tz_localize("UTC").tz_convert(NY)
    exp = loc.groupby([loc.index.year, loc.index.month]).agg(s=("v", "sum"))
    present = [r for r in rows if r["n"] > 0]
    assert len(present) == len(exp)
    assert sorted(r["s"] for r in present) == sorted(int(x) for x in exp.s)
