"""SQL margins the reference served via full Spark SQL (SURVEY.md §3.1):
RIGHT/FULL OUTER joins and equality-correlated subqueries (the TPC-H
correlation class), both executing on the fallback path with pandas
oracles."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine


@pytest.fixture()
def eng():
    e = Engine()
    rng = np.random.default_rng(17)
    n = 500
    fact = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 60, n), unit="s"),
        "k": rng.integers(0, 12, n),
        "grp": rng.choice(["a", "b", "c"], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    dim = pd.DataFrame({
        # keys 8..15: overlaps fact on 8..11, 12..15 unmatched on the
        # right; fact keys 0..7 unmatched on the left
        "dk": np.arange(8, 16),
        "dname": [f"d{i}" for i in range(8, 16)],
    })
    e.register_table("fact", fact, time_column="ts")
    e.register_table("dim", dim)
    return e, fact, dim


def test_subquery_inlining_runs_inner_on_device(eng):
    """Uncorrelated subquery inlining (round 4): the inner aggregate
    executes through the engine — on the DEVICE path for an accelerated
    table — and the outer query pushes down with the result inlined
    (the reference's split: Spark ran the subquery, the rewritten outer
    query hit Druid; SURVEY.md §3.1)."""
    e, fact, dim = eng
    n0 = len(e.history)
    got = e.sql("SELECT grp, sum(v) AS s FROM fact "
                "WHERE v > (SELECT avg(v) FROM fact) "
                "GROUP BY grp ORDER BY grp")
    assert e.last_plan.rewritten
    # two device dispatches: the inner avg and the outer groupBy
    assert len(e.history) == n0 + 2
    mean = fact.v.sum() / len(fact)
    expect = fact[fact.v > mean].groupby("grp").v.sum().sort_index()
    assert list(got["grp"]) == list(expect.index)
    assert [int(x) for x in got["s"]] == [int(x) for x in expect.values]


def test_right_join(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT dim.dname AS dname, count(fact.v) AS n
                   FROM fact RIGHT JOIN dim ON fact.k = dim.dk
                   GROUP BY dim.dname ORDER BY dname""")
    m = fact.merge(dim, left_on="k", right_on="dk", how="right")
    exp = m.groupby("dname", as_index=False).agg(n=("v", "count")) \
        .sort_values("dname").reset_index(drop=True)
    assert got["dname"].tolist() == exp["dname"].tolist()
    assert got["n"].tolist() == exp["n"].tolist()
    # unmatched dim rows (dk 12..15) must be present with count 0
    assert {"d12", "d13", "d14", "d15"} <= set(got["dname"])


def test_full_outer_join(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT k, dname FROM fact FULL OUTER JOIN dim
                   ON fact.k = dim.dk WHERE v > 1000 OR v IS NULL
                   ORDER BY dname""")
    # v > 1000 never true: only unmatched right rows (v NULL) survive
    assert got["dname"].tolist() == ["d12", "d13", "d14", "d15"]
    assert got["k"].isna().all()


def test_full_outer_counts(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT count(*) AS total FROM fact
                   FULL JOIN dim ON fact.k = dim.dk""")
    m = fact.merge(dim, left_on="k", right_on="dk", how="outer")
    assert int(got["total"].iloc[0]) == len(m)


def test_left_join_extra_on_conjunct_preserves_unmatched(eng):
    """ON a=b AND extra must not re-filter unmatched left rows (the SQL
    outer-join contract; a naive post-merge filter drops them)."""
    e, fact, dim = eng
    got = e.sql("""SELECT count(*) AS n,
                          count(dim.dname) AS matched
                   FROM fact LEFT JOIN dim
                   ON fact.k = dim.dk AND dim.dk > 9""")
    m = fact.merge(dim, left_on="k", right_on="dk", how="inner")
    m = m[m["dk"] > 9]
    assert int(got["n"].iloc[0]) == len(fact) - fact["k"].isin(
        m["dk"].unique()).sum() + len(m)
    assert int(got["matched"].iloc[0]) == len(m)


def test_correlated_scalar_avg(eng):
    """TPC-H Q17 shape: compare each row against its group's average."""
    e, fact, _ = eng
    got = e.sql("""SELECT count(*) AS n FROM fact
                   WHERE v > (SELECT avg(f2.v) FROM fact f2
                              WHERE f2.k = fact.k)""")
    avg = fact.groupby("k")["v"].mean()
    exp = int((fact["v"] > fact["k"].map(avg)).sum())
    assert int(got["n"].iloc[0]) == exp


def test_correlated_scalar_in_projection(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT dk, (SELECT max(fact.v) FROM fact
                               WHERE fact.k = dim.dk) AS mx
                   FROM dim ORDER BY dk""")
    mx = fact.groupby("k")["v"].max()
    exp = [mx.get(k) for k in sorted(dim["dk"])]
    for g, x in zip(got["mx"], exp):
        if x is None or (isinstance(x, float) and np.isnan(x)):
            assert pd.isna(g)
        else:
            assert g == x


def test_correlated_scalar_empty_group_null_and_count_zero(eng):
    e, fact, dim = eng
    # dk 12..15 match no fact rows: max -> NULL, count -> 0
    got = e.sql("""SELECT dk,
                     (SELECT max(v) FROM fact WHERE fact.k = dim.dk) AS mx,
                     (SELECT count(*) FROM fact WHERE fact.k = dim.dk) AS c
                   FROM dim WHERE dk >= 12 ORDER BY dk""")
    assert got["mx"].isna().all()
    assert got["c"].tolist() == [0, 0, 0, 0]


def test_correlated_exists_and_not_exists(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT count(*) AS n FROM dim
                   WHERE EXISTS (SELECT 1 FROM fact
                                 WHERE fact.k = dim.dk AND fact.v > 50)""")
    keys = set(fact.loc[fact["v"] > 50, "k"])
    exp = int(dim["dk"].isin(keys).sum())
    assert int(got["n"].iloc[0]) == exp

    got2 = e.sql("""SELECT count(*) AS n FROM dim
                    WHERE NOT EXISTS (SELECT 1 FROM fact
                                      WHERE fact.k = dim.dk)""")
    exp2 = int((~dim["dk"].isin(set(fact["k"]))).sum())
    assert int(got2["n"].iloc[0]) == exp2


def test_correlated_in(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT count(*) AS n FROM fact
                   WHERE grp IN (SELECT f2.grp FROM fact f2
                                 WHERE f2.k = fact.k AND f2.v >= 90)""")
    hi = fact[fact["v"] >= 90]
    pairs = set(zip(hi["k"], hi["grp"]))
    exp = int(sum((k, g) in pairs
                  for k, g in zip(fact["k"], fact["grp"])))
    assert int(got["n"].iloc[0]) == exp


def test_correlated_multi_key(eng):
    e, fact, _ = eng
    got = e.sql("""SELECT count(*) AS n FROM fact
                   WHERE v >= (SELECT max(f2.v) FROM fact f2
                               WHERE f2.k = fact.k AND f2.grp = fact.grp)""")
    mx = fact.groupby(["k", "grp"])["v"].transform("max")
    exp = int((fact["v"] >= mx).sum())
    assert int(got["n"].iloc[0]) == exp


def test_exists_over_ungrouped_aggregate_is_always_true(eng):
    """SQL: an ungrouped aggregate subquery yields one row even over
    zero input rows, so EXISTS over it is true for every outer row."""
    e, _, dim = eng
    got = e.sql("""SELECT count(*) AS n FROM dim
                   WHERE EXISTS (SELECT max(v) FROM fact
                                 WHERE fact.k = dim.dk)""")
    assert int(got["n"].iloc[0]) == len(dim)


def test_aliased_self_join_rejected_not_wrong(eng):
    """Qualifier-stripping cannot disambiguate an aliased multi-table
    scope (a.v vs b.v over the same table) — it must reject, never
    silently read the wrong frame."""
    e, _, _ = eng
    with pytest.raises(Exception, match="alias"):
        e.sql("""SELECT a.v AS av, b.v AS bv FROM fact a
                 JOIN fact b ON a.k = b.k LIMIT 5""")


def test_cross_join_and_using(eng):
    e, fact, dim = eng
    got = e.sql("SELECT count(*) AS n FROM fact CROSS JOIN dim")
    assert int(got["n"].iloc[0]) == len(fact) * len(dim)
    e.register_table("dim2", pd.DataFrame(
        {"k": [1, 2, 3], "tag": ["a", "b", "c"]}), accelerate=False)
    got = e.sql("SELECT tag, count(*) AS n FROM fact "
                "JOIN dim2 USING (k) GROUP BY tag ORDER BY tag")
    exp = fact.merge(pd.DataFrame({"k": [1, 2, 3],
                                   "tag": ["a", "b", "c"]}), on="k") \
        .groupby("tag", as_index=False).size()
    assert got["n"].tolist() == exp["size"].tolist()


def test_multi_column_using(eng):
    """USING (a, b) must join on BOTH columns, not the first plus a
    tautology."""
    e, _, _ = eng
    left = pd.DataFrame({"a": [1, 1, 2], "b": [10, 20, 30],
                         "x": ["p", "q", "r"]})
    right = pd.DataFrame({"a": [1, 1, 2], "b": [10, 99, 30],
                          "y": ["s", "t", "u"]})
    e.register_table("ml", left, accelerate=False)
    e.register_table("mr", right, accelerate=False)
    got = e.sql("SELECT x, y FROM ml JOIN mr USING (a, b) ORDER BY x")
    assert got["x"].tolist() == ["p", "r"]
    assert got["y"].tolist() == ["s", "u"]


def test_scalar_functions_and_concat_operator(eng):
    e, fact, _ = eng
    got = e.sql("SELECT coalesce(NULLIF(grp, 'a'), 'zz') AS g2, "
                "length(grp) AS ln, replace(grp, 'b', 'B') AS r, "
                "grp || '!' AS bang, EXTRACT(YEAR FROM ts) AS y "
                "FROM fact LIMIT 3")
    assert set(got.columns) == {"g2", "ln", "r", "bang", "y"}
    assert (got["ln"] == 1).all()
    assert got["bang"].str.endswith("!").all()
    assert (got["y"] == 2024).all()
    assert not (got["g2"] == "a").any()  # 'a' nullified then coalesced


def test_nulls_first_last_honored(eng):
    e, _, _ = eng
    df = pd.DataFrame({"x": [3, None, 1, None, 2],
                       "tag": list("abcde")})
    e.register_table("nt", df, accelerate=False)
    last = e.sql("SELECT tag FROM nt ORDER BY x ASC NULLS LAST")
    assert last["tag"].tolist() == ["c", "e", "a", "b", "d"]
    first = e.sql("SELECT tag FROM nt ORDER BY x DESC NULLS FIRST")
    assert first["tag"].tolist() == ["b", "d", "a", "e", "c"]
    # the device path declines the explicit spelling (fallback serves it)
    got = e.sql("SELECT grp, count(*) AS n FROM fact GROUP BY grp "
                "ORDER BY n DESC NULLS LAST LIMIT 2")
    assert not e.last_plan.rewritten
    # a spelling on one key must not flip the placement of another,
    # unspelled key (both x-nulls stay LAST, per this path's default)
    df2 = pd.DataFrame({"x": [3, None, 1, None, 2],
                        "y": [1, 2, 3, 4, 5], "tag": list("abcde")})
    e.register_table("nt2", df2, accelerate=False)
    got = e.sql("SELECT tag FROM nt2 ORDER BY x ASC, y ASC NULLS LAST")
    assert got["tag"].tolist() == ["c", "e", "a", "b", "d"]


def test_rollup(eng):
    e, fact, _ = eng
    got = e.sql("SELECT grp, k, sum(v) AS s FROM fact "
                "GROUP BY ROLLUP(grp, k) ORDER BY grp, k")
    detail = fact.groupby(["grp", "k"])["v"].sum()
    per_grp = fact.groupby("grp")["v"].sum()
    total = fact["v"].sum()
    assert len(got) == len(detail) + len(per_grp) + 1
    grand = got[got["grp"].isna() & got["k"].isna()]
    assert len(grand) == 1 and int(grand["s"].iloc[0]) == int(total)
    sub = got[got["grp"].notna() & got["k"].isna()]
    assert {(r.grp, int(r.s)) for r in sub.itertuples()} \
        == {(g, int(v)) for g, v in per_grp.items()}


def test_cube_and_grouping_sets(eng):
    e, fact, _ = eng
    cube = e.sql("SELECT grp, k, count(*) AS n FROM fact "
                 "GROUP BY CUBE(grp, k)")
    n_detail = fact.groupby(["grp", "k"]).ngroups
    n_grp = fact["grp"].nunique()
    n_k = fact["k"].nunique()
    assert len(cube) == n_detail + n_grp + n_k + 1
    gs = e.sql("SELECT grp, k, count(*) AS n FROM fact "
               "GROUP BY GROUPING SETS ((grp), (k), ())")
    assert len(gs) == n_grp + n_k + 1
    # HAVING filters within each set
    hv = e.sql("SELECT grp, count(*) AS n FROM fact "
               "GROUP BY GROUPING SETS ((grp), ()) HAVING count(*) > 0")
    assert len(hv) == n_grp + 1
    # GROUPING() distinguishes rollup NULLs from data NULLs
    gm = e.sql("SELECT grp, GROUPING(grp) AS gg, count(*) AS n FROM fact "
               "GROUP BY ROLLUP(grp) ORDER BY gg, grp")
    assert gm["gg"].tolist() == [0] * n_grp + [1]
    assert gm[gm["gg"] == 1]["grp"].isna().all()
    # ordinals resolve inside the construct
    ro = e.sql("SELECT grp, k, sum(v) AS s FROM fact "
               "GROUP BY ROLLUP(1, 2)")
    assert len(ro) == fact.groupby(["grp", "k"]).ngroups \
        + fact["grp"].nunique() + 1
    # a plain column literally named 'cube' still groups normally
    e.register_table("t3", pd.DataFrame({"cube": ["x", "y", "x"],
                                         "v": [1, 2, 3]}),
                     accelerate=False)
    pc = e.sql("SELECT cube, sum(v) AS s FROM t3 GROUP BY cube "
               "ORDER BY cube")
    assert pc["cube"].tolist() == ["x", "y"]
    assert pc["s"].tolist() == [4, 2]


def test_lag_lead_window(eng):
    e, _, _ = eng
    df = pd.DataFrame({"p": ["a", "a", "a", "b", "b"],
                       "o": [1, 2, 3, 1, 2],
                       "v": [10, 20, 30, 40, 50]})
    e.register_table("w", df, accelerate=False)
    got = e.sql("SELECT p, o, lag(v) OVER (PARTITION BY p ORDER BY o) "
                "AS prev, lead(v, 1, -1) OVER (PARTITION BY p ORDER BY o)"
                " AS nxt FROM w ORDER BY p, o")
    exp_prev = df.sort_values(["p", "o"]).groupby("p")["v"].shift(1)
    assert [None if pd.isna(x) else x for x in got["prev"]] \
        == [None if pd.isna(x) else int(x) for x in exp_prev]
    # lead default -1 fills the partition tail, not data nulls
    assert got["nxt"].tolist() == [20, 30, -1, 50, -1]
    # offset 0 is the identity, not offset 1
    z = e.sql("SELECT lag(v, 0) OVER (PARTITION BY p ORDER BY o) AS z "
              "FROM w ORDER BY p, o")
    assert z["z"].tolist() == [10, 20, 30, 40, 50]


def test_non_equality_correlated_scalar_nested_loop(eng):
    """Comparison-correlated scalar aggregate: beyond the magic-set
    rewrite, served by the bounded nested loop (round 5)."""
    e, fact, _ = eng
    got = e.sql("""SELECT count(*) AS n FROM fact
                   WHERE v > (SELECT avg(f2.v) FROM fact f2
                              WHERE f2.k > fact.k)""")
    def avg_above(k):
        c = fact[fact["k"] > k]["v"]
        return None if c.empty else c.sum() / len(c)
    exp = sum(1 for r in fact.itertuples()
              if avg_above(r.k) is not None and r.v > avg_above(r.k))
    assert int(got["n"].iloc[0]) == exp


def test_derived_table_in_join(eng):
    """JOIN (SELECT ...) alias — the reference handed these to full
    Spark SQL (SURVEY.md §3.1); here the derived frame executes once
    and joins like a dimension table, on the fallback path."""
    e, fact, dim = eng
    got = e.sql("""SELECT grp, sum(v * c) AS s FROM fact
                   JOIN (SELECT k AS jk, count(*) AS c FROM fact
                         GROUP BY k) q
                   ON k = jk GROUP BY grp ORDER BY grp""")
    assert not e.last_plan.rewritten
    cnt = fact.groupby("k").size().rename("c").reset_index()
    j = fact.merge(cnt, on="k")
    exp = (j.v * j.c).groupby(j.grp).sum().sort_index()
    assert list(got["grp"]) == list(exp.index)
    assert [int(x) for x in got["s"]] == [int(x) for x in exp.values]


def test_cte_in_join_position(eng):
    """A CTE referenced in JOIN position inlines like the FROM position
    (previously a legible rejection)."""
    e, fact, dim = eng
    got = e.sql("""WITH q AS (SELECT k AS jk, sum(v) AS tot FROM fact
                              GROUP BY k)
                   SELECT dname, tot FROM dim
                   JOIN q ON dk = jk ORDER BY dname""")
    tot = fact.groupby("k").v.sum()
    exp = dim[dim.dk.isin(tot.index)].sort_values("dname")
    assert list(got["dname"]) == list(exp["dname"])
    assert [int(x) for x in got["tot"]] == \
        [int(tot[k]) for k in exp["dk"]]


def test_tpch_q15_comma_join_cte(eng):
    """TPC-H Q15's actual spelling: a comma join of an aggregating CTE
    plus a scalar subquery over the same CTE."""
    e, fact, dim = eng
    got = e.sql("""WITH rev AS (SELECT k AS sk, sum(v) AS total
                                FROM fact GROUP BY k)
                   SELECT dname, total FROM dim, rev
                   WHERE dk = sk AND total = (SELECT max(total) FROM rev)""")
    tot = fact[fact.k.isin(dim.dk)].groupby("k").v.sum()
    best = tot.idxmax()
    assert got["dname"].tolist() == \
        dim[dim.dk == best]["dname"].tolist()
    assert [int(x) for x in got["total"]] == [int(tot.max())]


def test_left_join_derived_preserves_unmatched(eng):
    e, fact, dim = eng
    got = e.sql("""SELECT dname, c FROM dim
                   LEFT JOIN (SELECT k AS jk, count(*) AS c FROM fact
                              GROUP BY k) q
                   ON dk = jk ORDER BY dname""")
    cnt = fact.groupby("k").size()
    exp = [int(cnt.get(k, 0)) or None for k in dim.sort_values("dname").dk]
    assert [None if pd.isna(x) else int(x) for x in got["c"]] == exp


def test_derived_join_ambiguous_columns_rejected(eng):
    """A derived join whose output reuses a base-table column name is
    ambiguous after qualifier stripping — reject, never mis-resolve."""
    e, _, _ = eng
    with pytest.raises(Exception, match="alias|disambiguate"):
        e.sql("""SELECT q.v FROM fact
                 JOIN (SELECT k, max(v) AS v FROM fact GROUP BY k) q
                 ON fact.k = q.k""")


def test_correlated_derived_join_rejected_not_wrong(eng):
    """A non-LATERAL derived table cannot see the outer row (standard
    SQL); an outer-table qualifier inside the body must reject, never
    silently strip onto a same-named inner column (code-review repro:
    fact also has the outer column's name)."""
    e, fact, dim = eng
    e.register_table("dim2", pd.DataFrame(
        {"dk": [1, 2], "v": [50, 60]}), accelerate=False)
    with pytest.raises(Exception, match="correlated|not supported"):
        e.sql("""SELECT dname FROM dim
                 JOIN (SELECT k, count(*) AS c FROM fact
                       WHERE v < dim.v GROUP BY k) q
                 ON dk = k""")


def test_correlated_from_derived_rejected_not_wrong(eng):
    """Same contract for FROM-position derived tables."""
    e, _, _ = eng
    with pytest.raises(Exception, match="correlated|not supported"):
        e.sql("""SELECT c FROM (SELECT count(*) AS c FROM fact
                                WHERE fact.v < dim.dk) q""")


def test_from_derived_join_ambiguous_columns_rejected(eng):
    """FROM-position derived table joined against a table that reuses
    one of its output names: same ambiguity class as the JOIN-position
    twin — reject, never mis-resolve (code-review repro)."""
    e, _, _ = eng
    e.register_table("vdim", pd.DataFrame(
        {"dk": [1, 2], "v": [100, 200]}), accelerate=False)
    with pytest.raises(Exception, match="alias|disambiguate"):
        e.sql("""SELECT vdim.v AS dv
                 FROM (SELECT k, sum(v) AS v FROM fact GROUP BY k) q
                 JOIN vdim ON k = dk""")


def test_sum_avg_distinct(eng):
    """SUM(DISTINCT)/AVG(DISTINCT) on the fallback path; MIN/MAX
    DISTINCT are no-ops; other DISTINCT aggs reject legibly."""
    e, fact, _ = eng
    got = e.sql("SELECT grp, sum(DISTINCT v) AS sd, avg(DISTINCT v) AS ad,"
                " min(DISTINCT v) AS mn FROM fact GROUP BY grp ORDER BY grp")
    assert not e.last_plan.rewritten
    exp = fact.groupby("grp").v.agg(
        sd=lambda s: s.dropna().drop_duplicates().sum(),
        ad=lambda s: s.dropna().drop_duplicates().mean(),
        mn="min").sort_index()
    assert [int(x) for x in got["sd"]] == [int(x) for x in exp["sd"]]
    assert [round(float(x), 9) for x in got["ad"]] == \
        [round(float(x), 9) for x in exp["ad"]]
    assert [int(x) for x in got["mn"]] == [int(x) for x in exp["mn"]]
    # global (ungrouped) spelling
    g = e.sql("SELECT sum(DISTINCT v) AS sd FROM fact")
    assert int(g["sd"].iloc[0]) == int(fact.v.drop_duplicates().sum())
    with pytest.raises(Exception, match="DISTINCT"):
        e.sql("SELECT theta_sketch(DISTINCT v) FROM fact")


def test_output_alias_in_group_and_order(eng):
    """Output-alias references in GROUP BY / ORDER BY (Spark/MySQL
    semantics): alias resolves unless it shadows a source column, and
    the resolved form may take the device path."""
    e, fact, _ = eng
    got = e.sql("SELECT v % 10 AS b, count(*) AS n FROM fact "
                "GROUP BY b ORDER BY b")
    exp = (fact.assign(b=fact.v % 10).groupby("b").size()
           .sort_index())
    assert [int(x) for x in got["n"]] == [int(x) for x in exp]
    # ORDER BY an expression over an alias
    got2 = e.sql("SELECT grp, count(*) AS n FROM fact GROUP BY grp "
                 "ORDER BY n % 7, grp")
    exp2 = fact.groupby("grp").size().reset_index(name="n")
    exp2 = exp2.sort_values(["n", "grp"],
                            key=lambda s: s % 7 if s.name == "n" else s)
    assert list(got2["grp"]) == list(exp2["grp"])
    # a source column wins over a same-named alias
    got3 = e.sql("SELECT sum(v) AS v, grp FROM fact GROUP BY grp "
                 "ORDER BY grp")
    assert [int(x) for x in got3["v"]] == \
        [int(x) for x in fact.groupby("grp").v.sum().sort_index()]


def test_tuple_in(eng):
    e, fact, _ = eng
    got = e.sql("SELECT count(*) AS n FROM fact "
                "WHERE (grp, k) IN (('a', 3), ('b', 5))")
    assert e.last_plan.rewritten
    exp = (((fact.grp == "a") & (fact.k == 3))
           | ((fact.grp == "b") & (fact.k == 5))).sum()
    assert int(got["n"].iloc[0]) == int(exp)
    with pytest.raises(Exception, match="arity"):
        e.sql("SELECT count(*) FROM fact "
              "WHERE (grp, k) IN (('a', 1, 2))")


def test_timestamp_interval_literals(eng):
    e, fact, _ = eng
    got = e.sql("SELECT count(*) AS n FROM fact "
                "WHERE ts >= TIMESTAMP '2024-02-01' - INTERVAL '7' DAY")
    exp = (fact.ts >= pd.Timestamp("2024-01-25")).sum()
    assert int(got["n"].iloc[0]) == int(exp)
    got2 = e.sql("SELECT count(*) AS n FROM fact "
                 "WHERE ts < DATE '2024-01-01' + INTERVAL 1 MONTH")
    exp2 = (fact.ts < pd.Timestamp("2024-02-01")).sum()
    assert int(got2["n"].iloc[0]) == int(exp2)


def test_window_over_grouped_query(eng):
    """Window functions evaluate AFTER grouping (rewritten to the
    derived-table form): rank over per-group aggregates."""
    e, fact, _ = eng
    got = e.sql("SELECT grp, k, rank() OVER (PARTITION BY grp "
                "ORDER BY sum(v) DESC) AS r FROM fact "
                "GROUP BY grp, k ORDER BY grp, r, k")
    g = fact.groupby(["grp", "k"]).v.sum().reset_index()
    g["r"] = g.groupby("grp").v.rank(method="min", ascending=False)
    g = g.sort_values(["grp", "r", "k"])
    assert [int(x) for x in got["r"]] == [int(x) for x in g["r"]]
    # running total over the grouped rows
    got2 = e.sql("SELECT grp, sum(v) AS s, sum(sum(v)) OVER "
                 "(ORDER BY grp) AS rt FROM fact GROUP BY grp "
                 "ORDER BY grp")
    exp2 = fact.groupby("grp").v.sum().sort_index().cumsum()
    assert [int(x) for x in got2["rt"]] == [int(x) for x in exp2]


def test_rows_frame_windows(eng):
    e, fact, _ = eng
    df = pd.DataFrame({"ts": pd.to_datetime("2021-01-01")
                       + pd.to_timedelta(range(8), unit="D"),
                       "v": [3, 1, 4, 1, 5, 9, 2, 6]})
    e.register_table("fr", df, time_column="ts")
    got = e.sql("SELECT ts, sum(v) OVER (ORDER BY ts ROWS BETWEEN 2 "
                "PRECEDING AND CURRENT ROW) AS rs, min(v) OVER (ORDER "
                "BY ts ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS mn "
                "FROM fr ORDER BY ts")
    exp_rs = df.v.rolling(3, min_periods=1).sum()
    exp_mn = df.v.rolling(3, min_periods=1, center=True).min()
    assert [float(x) for x in got["rs"]] == [float(x) for x in exp_rs]
    assert [float(x) for x in got["mn"]] == [float(x) for x in exp_mn]
    with pytest.raises(Exception, match="RANGE"):
        e.sql("SELECT sum(v) OVER (ORDER BY ts RANGE BETWEEN 1 "
              "PRECEDING AND CURRENT ROW) FROM fr")


def test_comparison_correlated_exists(eng):
    """Non-equality correlated EXISTS via the per-group min/max
    reduction: EXISTS(... inner OP outer AND eq-keys) <=> the group
    extreme satisfies OP."""
    e, fact, _ = eng
    mx = fact.groupby("grp").v.transform("max")
    got = e.sql(
        "SELECT count(*) AS n FROM fact f1 WHERE EXISTS "
        "(SELECT 1 FROM fact f2 WHERE f2.v > f1.v AND f2.grp = f1.grp)")
    assert int(got["n"].iloc[0]) == int((fact.v < mx).sum())
    got2 = e.sql(
        "SELECT count(*) AS n FROM fact f1 WHERE NOT EXISTS "
        "(SELECT 1 FROM fact f2 WHERE f2.v > f1.v AND f2.grp = f1.grp)")
    assert int(got2["n"].iloc[0]) == int((fact.v == mx).sum())
    # no equality key: global extreme
    got3 = e.sql(
        "SELECT count(*) AS n FROM fact f1 WHERE EXISTS "
        "(SELECT 1 FROM fact f2 WHERE f2.v > f1.v)")
    assert int(got3["n"].iloc[0]) == int((fact.v < fact.v.max()).sum())
    # two comparison conjuncts cannot be witnessed by min/max — the
    # bounded nested loop serves them instead (round 5, VERDICT r4 #2)
    got4 = e.sql("SELECT count(*) AS n FROM fact f1 WHERE EXISTS "
                 "(SELECT 1 FROM fact f2 WHERE f2.v > f1.v AND "
                 "f2.k < f1.k)")
    exp4 = sum(1 for r in fact.itertuples()
               if ((fact.v > r.v) & (fact.k < r.k)).any())
    assert int(got4["n"].iloc[0]) == exp4


def test_window_over_groups_nested_scopes(eng):
    """The grouped-window rewrite applies inside CTEs, derived tables,
    and UNION parts, not just at top level."""
    e, fact, _ = eng
    top = e.sql("SELECT grp, rank() OVER (ORDER BY sum(v) DESC) AS r "
                "FROM fact GROUP BY grp ORDER BY r, grp")
    cte = e.sql("WITH x AS (SELECT grp, rank() OVER (ORDER BY sum(v) "
                "DESC) AS r FROM fact GROUP BY grp) "
                "SELECT * FROM x ORDER BY r, grp")
    der = e.sql("SELECT * FROM (SELECT grp, rank() OVER (ORDER BY "
                "sum(v) DESC) AS r FROM fact GROUP BY grp) d "
                "ORDER BY r, grp")
    assert list(cte["r"]) == list(top["r"])
    assert list(der["r"]) == list(top["r"])
    # unaliased projections keep human-readable headers
    h = e.sql("SELECT grp, sum(v), rank() OVER (ORDER BY sum(v)) AS r "
              "FROM fact GROUP BY grp")
    assert list(h.columns) == ["grp", "sum(v)", "r"]


def test_interval_commuted_and_rejections(eng):
    e, fact, _ = eng
    a = e.sql("SELECT count(*) AS n FROM fact "
              "WHERE ts < TIMESTAMP '2024-02-01' + INTERVAL '1' DAY")
    b = e.sql("SELECT count(*) AS n FROM fact "
              "WHERE ts < INTERVAL '1' DAY + TIMESTAMP '2024-02-01'")
    assert int(a["n"].iloc[0]) == int(b["n"].iloc[0])
    with pytest.raises(Exception, match="INTERVAL"):
        e.sql("SELECT INTERVAL '1' DAY FROM fact")
    with pytest.raises(Exception, match="integer"):
        e.sql("SELECT sum(v) OVER (ORDER BY ts ROWS 1.5 PRECEDING) "
              "FROM fact")
    with pytest.raises(Exception, match="frame"):
        e.sql("SELECT sum(v) OVER (ORDER BY ts ROWS BETWEEN CURRENT "
              "ROW AND UNBOUNDED PRECEDING) FROM fact")


def _norm(df):
    """Order- and dtype-insensitive frame normalization for union parity:
    grouping-set unions only promise row MULTISET equality (+ ORDER BY
    where spelled), and NULL key columns are object-typed on the union
    path vs whatever pandas inferred on the fallback path."""
    out = df.astype(object).where(df.notna(), None)
    return sorted(map(tuple, out.to_numpy().tolist()),
                  key=lambda t: tuple(str(x) for x in t))


GSET_QUERIES = [
    "SELECT grp, k, sum(v) AS s, count(*) AS n FROM fact "
    "GROUP BY ROLLUP(grp, k)",
    "SELECT grp, k, sum(v) AS s FROM fact GROUP BY CUBE(grp, k)",
    "SELECT grp, k, count(*) AS n FROM fact "
    "GROUP BY GROUPING SETS ((grp), (k), ())",
    "SELECT grp, GROUPING(grp) AS gg, sum(v) AS s FROM fact "
    "GROUP BY ROLLUP(grp) ORDER BY gg, grp",
    "SELECT grp, k, sum(v) AS s FROM fact "
    "GROUP BY GROUPING SETS ((grp, k), ()) ORDER BY s DESC LIMIT 7",
]


@pytest.mark.parametrize("sql", GSET_QUERIES)
def test_grouping_sets_device_union_parity(eng, sql):
    """VERDICT r4 missing #4: GROUPING SETS/ROLLUP/CUBE execute as a
    union of per-set GROUP BY dispatches on the DEVICE path, with exact
    multiset parity vs the whole-statement fallback."""
    e, fact, dim = eng
    got = e.sql(sql)
    plan = e.last_plan
    legs = getattr(plan, "grouping_legs", None)
    assert legs, "grouping-sets union path did not engage"
    assert all(lp.rewritten for lp in legs), \
        [lp.fallback_reason for lp in legs]
    # whole-statement fallback oracle on an unaccelerated twin
    e2 = Engine()
    e2.register_table("fact", fact, time_column="ts", accelerate=False)
    want = e2.sql(sql)
    assert list(got.columns) == list(want.columns)
    assert _norm(got) == _norm(want)
    if "ORDER BY" in sql and "LIMIT" not in sql:
        # spelled ordering must hold exactly, not just as a multiset
        key = got.columns[got.columns.get_loc("gg")] \
            if "gg" in got.columns else None
        if key is not None:
            assert got["gg"].tolist() == want["gg"].tolist()


def test_grouping_sets_pure_dimension_projection(eng):
    """A set whose projections all fold to constants (the () leg of a
    GROUPING()-only SELECT) must still contribute its rows — one per
    group of that set — via the hidden count probe."""
    e, fact, _ = eng
    sql = ("SELECT grp, GROUPING(grp) AS gg FROM fact "
           "GROUP BY ROLLUP(grp) ORDER BY gg, grp")
    got = e.sql(sql)
    assert getattr(e.last_plan, "grouping_legs", None)
    e2 = Engine()
    e2.register_table("fact", fact, time_column="ts", accelerate=False)
    want = e2.sql(sql)
    assert getattr(e2.last_plan, "grouping_legs", None) is None, \
        "oracle must not take the union path"
    assert _norm(got) == _norm(want)
    assert len(got) == fact["grp"].nunique() + 1
    # per-group multiplicity: a (k) set with constant projections emits
    # one row per k group
    sql2 = ("SELECT grp, GROUPING(grp) AS gg FROM fact "
            "GROUP BY GROUPING SETS ((grp), (k))")
    got2 = e.sql(sql2)
    want2 = e2.sql(sql2)
    assert _norm(got2) == _norm(want2)
    assert len(got2) == fact["grp"].nunique() + fact["k"].nunique()


def test_grouping_sets_union_leg_fallback_still_correct(eng):
    """Legs the device path cannot serve (e.g. the grand-total () leg
    with HAVING: a K=1 aggregate with HAVING is a known device decline)
    fall back alone; the union stays correct and the grouped legs still
    ride the device path."""
    e, fact, _ = eng
    for sql, min_dev in (
        ("SELECT grp, count(*) AS n FROM fact "
         "GROUP BY GROUPING SETS ((grp), ()) HAVING count(*) > 0", 1),
        ("SELECT grp, k, sum(v) AS s FROM fact "
         "GROUP BY ROLLUP(grp, k) HAVING count(*) > 5", 2),
    ):
        got = e.sql(sql)
        legs = getattr(e.last_plan, "grouping_legs", None)
        assert legs, "union path did not engage"
        assert sum(1 for lp in legs if lp.rewritten) >= min_dev, \
            [lp.fallback_reason for lp in legs]
        e2 = Engine()
        e2.register_table("fact", fact, time_column="ts",
                          accelerate=False)
        want = e2.sql(sql)
        assert _norm(got) == _norm(want)


def test_nested_loop_multi_comparison_exists(eng):
    """Two comparison conjuncts must hold on the same inner row — the
    min/max reduction cannot witness that, so the bounded nested loop
    serves it (VERDICT r4 missing #2)."""
    e, fact, dim = eng
    got = e.sql(
        "SELECT count(*) AS n FROM fact WHERE EXISTS "
        "(SELECT 1 FROM dim WHERE dim.dk >= fact.k "
        "AND dim.dk <= fact.k + 2)")
    want = int((fact["k"] >= 6).sum())  # dk in 8..15, k in 0..11
    assert int(got["n"].iloc[0]) == want


def test_nested_loop_scalar_order_by_limit(eng):
    """Correlated scalar subquery with ORDER BY/LIMIT (closest-match
    lookup) — rejected by the magic-set shape guard, nested loop runs."""
    e, fact, dim = eng
    got = e.sql(
        "SELECT k, (SELECT d.dname FROM dim d WHERE d.dk <= fact.k "
        "ORDER BY d.dk DESC LIMIT 1) AS nm FROM fact")
    def oracle(k):
        c = dim[dim["dk"] <= k]
        return None if c.empty else \
            c.sort_values("dk").iloc[-1]["dname"]
    # row order is the engine's (time-sorted scan); check per-row
    assert len(got) == len(fact)
    for r in got.itertuples():
        assert (None if pd.isna(r.nm) else r.nm) == oracle(r.k), r


def test_nested_loop_scalar_outer_ref_in_projection(eng):
    """Outer reference in the subquery SELECT list: decorrelation only
    handles WHERE equality refs; the nested loop substitutes anywhere."""
    e, fact, dim = eng
    got = e.sql(
        "SELECT k, (SELECT max(d.dk) - fact.k FROM dim d "
        "WHERE d.dk > fact.k) AS gap FROM fact")
    def oracle(k):
        c = dim[dim["dk"] > k]
        return None if c.empty else int(c["dk"].max()) - k
    assert len(got) == len(fact)
    for r in got.itertuples():
        assert (None if pd.isna(r.gap) else int(r.gap)) == oracle(r.k), r


def test_nested_loop_in_comparison_correlation(eng):
    """Comparison-correlated IN subquery (allow_cmp is False for IN in
    the magic-set rewrite) runs on the nested loop."""
    e, fact, dim = eng
    got = e.sql(
        "SELECT count(*) AS n FROM fact WHERE k IN "
        "(SELECT d.dk - 8 FROM dim d WHERE d.dk < fact.v)")
    def hit(row):
        c = dim[dim["dk"] < row.v]
        return row.k in set(c["dk"] - 8)
    want = sum(1 for r in fact.itertuples() if hit(r))
    assert int(got["n"].iloc[0]) == want


def test_nested_loop_cap_is_legible(eng):
    """Past corr_nested_loop_cap the refusal names the knob."""
    from tpu_olap.executor import EngineConfig
    e2 = Engine(EngineConfig(corr_nested_loop_cap=3))
    _, fact, dim = eng
    e2.register_table("fact", fact, time_column="ts")
    e2.register_table("dim", dim)
    with pytest.raises(Exception, match="corr_nested_loop_cap"):
        e2.sql("SELECT count(*) AS n FROM fact WHERE EXISTS "
               "(SELECT 1 FROM dim WHERE dim.dk >= fact.k "
               "AND dim.dk <= fact.k + 2)")
