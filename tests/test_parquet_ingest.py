"""Parquet-path registration: direct Arrow ingest (no pandas detour),
column pruning, column_map renames, and the lazily materialized fallback
frame (SURVEY.md §8.4 #4: don't hold two copies of a SF100 fact table)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq

from tpu_olap import Engine


def _write_parquet(tmp_path, n=5000, seed=41):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "event_time": pd.to_datetime("2023-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "kind": rng.choice(["a", "b", "c"], n),
        "amount": rng.integers(0, 500, n).astype(np.int64),
        "unused_wide": rng.random(n),
    })
    path = str(tmp_path / "t.parquet")
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), path)
    return path, df


def test_parquet_register_and_query(tmp_path):
    path, df = _write_parquet(tmp_path)
    eng = Engine()
    entry = eng.register_table(
        "t", path, time_column="ts",
        column_map={"event_time": "ts"},
        columns=["ts", "kind", "amount"])  # post-rename names
    # pruning: the wide column never ingested
    assert "unused_wide" not in entry.segments.schema
    # lazy: no fallback -> no frame materialized
    assert entry._frame is None
    got = eng.sql("SELECT kind, sum(amount) AS s FROM t "
                  "GROUP BY kind ORDER BY kind")
    assert eng.last_plan.rewritten
    exp = df.groupby("kind")["amount"].sum()
    assert list(got.s) == [int(exp[k]) for k in ["a", "b", "c"]]
    assert entry._frame is None  # device path still never touched it


def test_parquet_fallback_materializes_lazily(tmp_path):
    path, df = _write_parquet(tmp_path)
    eng = Engine()
    entry = eng.register_table("t", path, time_column="event_time")
    # a shape the rewriter refuses (SELECT DISTINCT of an expression on a
    # non-grouped query path goes to fallback via unsupported rewrite) —
    # use a correlated/unsupported construct: ORDER BY in plain select of
    # a computed value is fine, so force fallback via an unknown function
    out = eng.sql("SELECT kind, amount FROM t WHERE amount < 10 LIMIT 5")
    # scan stays on device; fallback frame still untouched
    assert entry._frame is None or len(out) <= 5
    # registering a plain dimension table keeps the frame eagerly usable
    dim = eng.register_table("d", df[["kind"]].drop_duplicates(),
                             accelerate=False)
    assert len(dim.frame) == df.kind.nunique()


def test_arrow_register_no_pandas_detour():
    rng = np.random.default_rng(3)
    n = 2000
    table = pa.table({
        "ts": pa.array(pd.to_datetime("2023-05-01")
                       + pd.to_timedelta(rng.integers(0, 86400, n),
                                         unit="s")),
        "g": pa.array(rng.choice(["x", "y"], n)),
        "v": pa.array(rng.integers(0, 9, n)),
    })
    eng = Engine()
    entry = eng.register_table("t", table, time_column="ts")
    assert entry._frame is None
    got = eng.sql("SELECT g, count(*) AS n FROM t GROUP BY g ORDER BY g")
    assert int(got.n.sum()) == n
    assert entry._frame is None


def test_nanosecond_timestamps_truncate_to_ms():
    """Druid's __time is ms-grained: ns-precision sources truncate at
    ingest instead of raising ArrowInvalid (safe-cast failure)."""
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    eng = Engine()
    ts = pd.to_datetime("2020-01-01") + pd.to_timedelta(
        np.arange(100) * 1_000_000_123, unit="ns")  # not ms-aligned
    df = pd.DataFrame({"ts": ts, "v": np.arange(100, dtype=np.int64)})
    eng.register_table("t", df, time_column="ts")
    got = eng.sql("SELECT count(*) AS n, sum(v) AS s FROM t")
    assert int(got["n"][0]) == 100 and int(got["s"][0]) == 4950
