"""Device-facing observability (ISSUE 8): the Chrome-trace exporter +
/debug/profile, the structured event log + /debug/events, memory/compile
accounting in /metrics, the SLO burn rate, and tools/bench_compare.py.
"""

import json
import os
import subprocess
import sys
import threading
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer
from tpu_olap.executor import EngineConfig
from tpu_olap.obs.events import EventLog
from tpu_olap.obs.profile import chrome_trace
from tpu_olap.obs.slo import SloTracker

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _df(n=6000, seed=11):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2023-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 90, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(12)], n),
        "h": rng.choice([f"h{i}" for i in range(7)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _engine(**kw):
    eng = Engine(EngineConfig(**kw))
    eng.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    return eng


GROUP_SQL = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
GROUP2_SQL = "SELECT h, sum(v) AS s2 FROM t GROUP BY h ORDER BY h"
AGG_SQL = "SELECT sum(v) AS s, count(*) AS n FROM t"


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.status, r.read().decode()


def _get_code(url):
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def _post(url, code_only=False):
    req = urllib.request.Request(url, data=b"", method="POST")
    try:
        with urllib.request.urlopen(req, timeout=60) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


# ------------------------------------------------------- span positions


def test_span_start_ms_stamped_and_contained():
    """Satellite: spans carry start_ms (offset from the trace root) so
    timelines are layout-able; children sit inside their parents."""
    eng = _engine()
    eng.sql(GROUP_SQL)
    trace = eng.tracer.last
    assert trace.start_ms == 0.0
    seen = 0
    for _, s in trace.walk():
        if s.start_ms is None or s.duration_ms is None:
            continue
        end = s.start_ms + s.duration_ms
        for c in s.children:
            if c.start_ms is None or c.duration_ms is None:
                continue
            seen += 1
            assert c.start_ms >= s.start_ms - 0.001
            assert c.start_ms + c.duration_ms <= end + 0.5
    assert seen >= 4  # parse/plan/execute/dispatch at least
    j = trace.to_json()
    assert j["start_ms"] == 0.0
    assert j["children"][0]["start_ms"] >= 0.0


# ------------------------------------------------------- chrome export


def _x_events(doc):
    return [e for e in doc["traceEvents"] if e.get("ph") == "X"]


def test_chrome_trace_schema_and_roundtrip():
    """Tentpole acceptance: every complete event has ts/dur/pid/tid/
    name, the JSON round-trips, and per-trace events sit inside their
    root's interval (the Perfetto layout contract)."""
    eng = _engine()
    eng.sql(GROUP_SQL)
    eng.sql(AGG_SQL)
    doc = json.loads(json.dumps(
        chrome_trace(eng.tracer.recent_traces())))
    assert doc["traceEvents"][0]["args"]["name"] == "tpu_olap"
    xs = _x_events(doc)
    assert len(xs) >= 10
    by_tid = {}
    for e in xs:
        for k in ("name", "ts", "dur", "pid", "tid"):
            assert k in e, f"event missing {k}: {e}"
        assert e["dur"] >= 0 and e["ts"] > 0
        by_tid.setdefault(e["tid"], []).append(e)
    assert len(by_tid) == 2  # one tid per query
    for tid, evs in by_tid.items():
        root = next(e for e in evs if e["name"] in ("sql", "sql_batch"))
        assert root["args"]["query_id"].startswith("q")
        lo, hi = root["ts"], root["ts"] + root["dur"]
        for e in evs:
            assert e["ts"] >= lo - 1.0          # µs tolerance
            assert e["ts"] + e["dur"] <= hi + 500.0
    # thread_name metadata names each query row
    metas = [e for e in doc["traceEvents"]
             if e.get("ph") == "M" and e["name"] == "thread_name"]
    assert len(metas) == 2
    assert all(m["args"]["name"].startswith("query q") for m in metas)


def test_chrome_trace_batch_legs_share_shared_scan_tid():
    eng = _engine()
    eng.sql_batch([GROUP_SQL, GROUP2_SQL])
    trace = eng.tracer.last
    assert trace.name == "sql_batch"
    doc = chrome_trace([trace])
    xs = _x_events(doc)
    shared = [e for e in xs if e["name"] == "shared-scan"]
    legs = [e for e in xs if e["name"] == "leg"]
    assert shared and len(legs) == 2
    tid = shared[0]["tid"]
    for leg in legs:
        assert leg["tid"] == tid
        # and the leg sits inside the shared-scan interval
        assert leg["ts"] >= shared[0]["ts"] - 1.0
        assert leg["ts"] + leg["dur"] \
            <= shared[0]["ts"] + shared[0]["dur"] + 500.0


def test_debug_profile_endpoints():
    """GET /debug/profile serves Chrome-trace JSON; POST runs (or
    legibly degrades) a jax.profiler capture; params are validated."""
    eng = _engine()
    eng.sql(GROUP_SQL)
    eng.sql(AGG_SQL)
    srv = QueryServer(eng).start()
    try:
        _, body = _get(srv.url + "/debug/profile")
        doc = json.loads(body)
        assert _x_events(doc)
        _, body1 = _get(srv.url + "/debug/profile?n=1")
        assert len(_x_events(json.loads(body1))) < len(_x_events(doc))
        code, _ = _get_code(srv.url + "/debug/profile?n=oops")
        assert code == 400
        # on-demand capture: ok on backends with a working profiler,
        # a structured degrade elsewhere — never a 500
        code, out = _post(srv.url + "/debug/profile?ms=20")
        assert code == 200 and "ok" in out
        if out["ok"]:
            assert os.path.isdir(out["trace_dir"])
        else:
            assert out["reason"]
        code, _ = _post(srv.url + "/debug/profile?ms=nope")
        assert code == 400
    finally:
        srv.stop()


# ----------------------------------------------------------- event log


def test_events_contract_every_path():
    """One structured event per query on every serving path — dense,
    sparse, fallback, batch leg (incl. dedup fan-out), and shed."""
    eng = _engine(max_inflight_dispatches=1, admission_queue_limit=0)
    eng.register_table("dim", pd.DataFrame({"k": [1, 2]}),
                       accelerate=False)

    def q_events():
        return [e for e in eng.runner.events.snapshot()
                if e["event"] == "query"]

    n0 = len(q_events())
    eng.sql(GROUP_SQL)                    # dense
    assert len(q_events()) == n0 + 1
    assert q_events()[0]["path"] == "dense"
    eng.sql("SELECT k FROM dim")          # fallback
    assert q_events()[0]["path"] == "fallback"
    outs = eng.sql_batch([GROUP_SQL, GROUP2_SQL, GROUP_SQL])
    assert len(outs) == 3
    batch_evs = [e for e in q_events() if e["path"] == "batch"]
    assert len(batch_evs) == 3            # 2 legs + 1 dedup fan-out
    assert len({e["query_id"] for e in batch_evs}) == 3

    sp = Engine(EngineConfig(dense_group_budget=4))
    sp.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    sp.sql("SELECT g, h, sum(v) AS s FROM t GROUP BY g, h")
    sparse_evs = [e for e in sp.runner.events.snapshot()
                  if e["event"] == "query"]
    assert sparse_evs and sparse_evs[0]["path"] == "sparse"

    # shed: occupy the single slot from another thread; queue_limit=0
    # sheds the next arrival — which never reaches record(), so the
    # shed event is its entry in the log
    from tpu_olap.resilience.errors import QueryShed
    entered, release = threading.Event(), threading.Event()

    def hold():
        with eng.runner.admission.slot():
            entered.set()
            release.wait(10)

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    assert entered.wait(5)
    try:
        with pytest.raises(QueryShed):
            eng.sql(GROUP_SQL)
    finally:
        release.set()
        t.join(timeout=10)
    sheds = [e for e in eng.runner.events.snapshot()
             if e["event"] == "shed"]
    assert sheds and sheds[0]["reason"] == "queue_full"
    assert sheds[0]["query_id"].startswith("q")
    # every event serializes (the ring's contract)
    json.dumps(eng.runner.events.snapshot())


def test_compensated_device_failure_single_slo_event():
    """A device failure the engine answers via fallback is ONE logical
    query: one `query` event + one SLO observation (the fallback's),
    plus a visible `query_error` for the failed device leg — never a
    bad+good double count."""
    calls = {"n": 0}

    def inj(stage, attempt):
        calls["n"] += 1
        if calls["n"] <= 10:
            raise RuntimeError("injected device fault")

    eng = _engine(dispatch_retries=1, fault_injector=inj)
    out = eng.sql(GROUP_SQL)  # retries exhaust -> fallback answers
    assert len(out) == 12
    evs = eng.runner.events.snapshot()
    assert [e["event"] for e in evs if e["event"] == "query"] == ["query"]
    assert [e for e in evs if e["event"] == "query_error"]
    snap = eng.runner.slo.snapshot()
    assert snap["window_events"] == 1  # the served response only


def test_event_ring_bounded_and_ingest_cache_events():
    eng = _engine(event_log_limit=5)
    ingests = [e for e in eng.runner.events.snapshot()
               if e["event"] == "ingest"]
    assert ingests and ingests[0]["table"] == "t"
    assert ingests[0]["rows"] == len(_df()) and ingests[0]["accelerated"]
    eng.sql("CLEAR DRUID CACHE t")
    clears = [e for e in eng.runner.events.snapshot()
              if e["event"] == "cache_clear"]
    assert clears and clears[0]["table"] == "t"
    for _ in range(12):
        eng.sql(AGG_SQL)
    assert len(eng.runner.events.snapshot()) == 5  # ring bounded


def test_event_log_file_sink(tmp_path):
    path = str(tmp_path / "events.jsonl")
    eng = Engine(EngineConfig(event_log_path=path))
    eng.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    eng.sql(AGG_SQL)
    eng.sql(AGG_SQL)
    assert eng.runner.events.flush(10.0)  # sink writes are async
    lines = [json.loads(ln) for ln in
             open(path).read().strip().splitlines()]
    assert [e["event"] for e in lines][:1] == ["ingest"]
    assert sum(1 for e in lines if e["event"] == "query") == 2
    assert all("ts" in e and "seq" in e for e in lines)
    seqs = [e["seq"] for e in lines]
    assert seqs == sorted(seqs)


def test_event_log_never_raises():
    log = EventLog(limit=4, path="/nonexistent-dir/e.jsonl")
    class Weird:
        def __repr__(self):
            return "w" * 1000
    rec = log.emit("x", exc=RuntimeError("boom"), obj=Weird(),
                   arr=np.int64(3), f=float("nan"))
    assert rec["arr"] == 3 and rec["f"] is None
    assert len(rec["obj"]) <= 300
    json.dumps(log.snapshot())
    # the unwritable sink failed in the background, counted not raised
    log.flush(10.0)
    assert log.sink_errors >= 1
    log.close()


def test_debug_events_endpoint_param_guard():
    eng = _engine(event_log_limit=64)
    for _ in range(4):
        eng.sql(AGG_SQL)
    srv = QueryServer(eng).start()
    try:
        _, body = _get(srv.url + "/debug/events")
        doc = json.loads(body)
        assert doc["limit"] == 64
        evs = doc["events"]
        assert evs[0]["event"] == "query"  # newest first
        _, body = _get(srv.url + "/debug/events?n=2")
        assert len(json.loads(body)["events"]) == 2
        # cap at ring size: a huge n is clamped, not honored
        _, body = _get(srv.url + "/debug/events?n=999999")
        assert len(json.loads(body)["events"]) <= 64
        for bad in ("?n=abc", "?n=-3", "?n=1.5"):
            code, body = _get_code(srv.url + "/debug/events" + bad)
            assert code == 400, bad
            assert json.loads(body)["code"] == "user_error"
        # same guard on /debug/queries (satellite)
        code, _ = _get_code(srv.url + "/debug/queries?limit=zzz")
        assert code == 400
        _, body = _get(srv.url + "/debug/queries?n=1")
        assert len(json.loads(body)["recent"]) == 1
    finally:
        srv.stop()


# ------------------------------------------- memory/compile accounting


def test_memory_and_compile_metrics_exposed():
    """Acceptance: after a mixed workload /metrics exposes non-zero
    live-bytes, cache-entry, recompile, and SLO burn-rate series."""
    eng = _engine(slo_latency_ms=0.0)  # everything is "bad": burn > 0
    eng.sql(GROUP_SQL)
    eng.sql(GROUP_SQL)
    eng.sql_batch([GROUP_SQL, GROUP2_SQL])
    srv = QueryServer(eng).start()
    try:
        _, text = _get(srv.url + "/metrics")
    finally:
        srv.stop()

    def value(line_prefix):
        hits = [ln for ln in text.splitlines()
                if ln.startswith(line_prefix)]
        assert hits, f"{line_prefix} missing from /metrics"
        return float(hits[0].rsplit(" ", 1)[1])

    assert value('tpu_olap_device_bytes{table="t"}') > 0
    assert value('tpu_olap_cache_entries{cache="jit"}') >= 1
    assert value('tpu_olap_cache_entries{cache="plan"}') >= 1
    assert value('tpu_olap_cache_entries{cache="arg"}') >= 1
    recompiles = sum(
        float(ln.rsplit(" ", 1)[1]) for ln in text.splitlines()
        if ln.startswith("tpu_olap_recompiles_total"))
    assert recompiles >= 1
    assert value("tpu_olap_compile_ms_total") > 0
    assert value("tpu_olap_slo_burn_rate") > 0
    assert value('tpu_olap_slo_events_total{outcome="bad"}') >= 4
    # per-query attribution landed in the record schema
    cold = [h for h in eng.history if h.get("recompiles")]
    assert cold and all(h.get("compile_ms", 0) > 0 for h in cold)
    warm = [h for h in eng.history
            if h.get("jit_cache_hit") and not h.get("recompiles")]
    assert warm and all("compile_ms" not in h for h in warm)


def test_device_bytes_track_clear_and_status():
    eng = _engine()
    eng.sql(GROUP_SQL)
    by_table = eng.runner.device_bytes_by_table()
    assert by_table.get("t", 0) > 0
    srv = QueryServer(eng).start()
    try:
        _, body = _get(srv.url + "/status")
        st = json.loads(body)
        assert st["device_bytes"]["t"] > 0
        assert st["slo"]["latency_objective_ms"] == 500.0
        assert "burn_rate" in st["slo"]
    finally:
        srv.stop()
    eng.clear_cache()
    assert eng.runner.device_bytes_by_table() == {}
    eng.runner.refresh_resource_gauges()
    assert eng.runner._m_device_bytes.value(table="t") == 0.0


# ----------------------------------------------------------------- SLO


def test_slo_tracker_burn_rate_math():
    slo = SloTracker(latency_ms=10.0, target=0.9, window_s=60.0)
    slo.observe(5.0)
    slo.observe(50.0)
    # bad fraction 1/2 over a 0.1 error budget -> burn 5.0
    assert abs(slo.burn_rate() - 5.0) < 1e-9
    assert slo.good_total == 1 and slo.bad_total == 1
    slo.observe(1.0, failed=True)  # fast but failed: still bad
    assert slo.bad_total == 2
    snap = slo.snapshot()
    assert snap["window_events"] == 3 and snap["window_bad"] == 2


# ------------------------------------------------------- bench_compare


def _write_bench(path, p50s):
    with open(path, "w") as f:
        json.dump({"metric": "ssb_13q_p50_max_ms", "value": 1,
                   "detail": {"per_query_p50_ms": p50s}}, f)


def test_bench_compare_gate(tmp_path):
    a = str(tmp_path / "a.json")
    b = str(tmp_path / "b.json")
    tool = os.path.join(REPO, "tools", "bench_compare.py")
    _write_bench(a, {"q1": 100.0, "q2": 50.0})
    _write_bench(b, {"q1": 104.0, "q2": 52.0})
    ok = subprocess.run([sys.executable, tool, a, b],
                        capture_output=True, text=True, timeout=60)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert "q1" in ok.stdout and "ok" in ok.stdout

    _write_bench(b, {"q1": 130.0, "q2": 52.0})
    bad = subprocess.run([sys.executable, tool, a, b],
                         capture_output=True, text=True, timeout=60)
    assert bad.returncode == 1
    assert "REGRESSED" in bad.stdout and "q1" in bad.stderr

    # tighter threshold flips the verdict the other way
    loose = subprocess.run(
        [sys.executable, tool, a, b, "--threshold", "0.5"],
        capture_output=True, text=True, timeout=60)
    assert loose.returncode == 0

    # malformed artifact: usage error, not a crash or a false pass
    with open(b, "w") as f:
        json.dump({"nope": 1}, f)
    err = subprocess.run([sys.executable, tool, a, b],
                         capture_output=True, text=True, timeout=60)
    assert err.returncode == 2
