"""Durable segment store (ISSUE 14; docs/DURABILITY.md): checkpointed
sealed segments, WAL truncation, and verified crash recovery.

Covers the tentpole contracts:
- a checkpoint spills the sealed scope as CRC-framed columnar chunks
  plus an atomically-swapped manifest; the layout is canonical, so an
  unchanged sealed set re-checkpoints as a byte-identical noop and
  incremental compaction's untouched segments reuse their chunk files;
- the WAL truncates lag-one (only through the OLDEST retained
  manifest's watermark), so recovery after a checkpoint replays only
  the tail — O(tail), not O(total appends) — and a single corrupt
  newest checkpoint still finds the covering WAL frames on disk;
- the recovery ladder steps over corrupt/missing chunks and torn
  manifests (newest verifiable manifest wins, then the previous, then
  base + WAL) — corruption is detected and surfaced, never served;
- a REAL SIGKILL at each new fault site (spill-write, manifest-swap,
  wal-truncate, store-load) recovers to sha256 parity with a
  never-crashed oracle: zero wrong answers, zero acknowledged-row
  loss;
- recovery edge cases: manifest pointing at a deleted chunk, a
  checkpoint racing concurrent appends, a double crash during recovery
  itself, and close -> reopen -> checkpoint idempotency.

Satellites asserted here too: incremental compaction rewrites only the
delta-touched calendar partitions, the vectorized encode_rows keeps
the original per-row semantics (code order, nulls, atomic rejection),
and backpressure Retry-After derives from the measured compactor
drain rate.
"""

import hashlib
import os
import signal
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.resilience import FaultInjector
from tpu_olap.resilience.errors import IngestBackpressure, UserError
from tpu_olap.segments.store import (SegmentStore, encode_segment,
                                     StoreCorrupt)
from tpu_olap.segments.wal import replay_wal, wal_path

BLOCK = 512


def _df(n=2000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2022-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 45, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _cfg(tmp, **kw):
    kw.setdefault("ingest_wal_dir", os.path.join(str(tmp), "wal"))
    kw.setdefault("ingest_store_dir", os.path.join(str(tmp), "store"))
    kw.setdefault("ingest_auto_compact", False)
    kw.setdefault("cube_auto_refresh", False)
    return EngineConfig(**kw)


def _mk(tmp, data=None, **kw):
    eng = Engine(_cfg(tmp, **kw))
    eng.register_table("t", _df() if data is None else data,
                       time_column="ts", block_rows=BLOCK,
                       time_partition="month")
    return eng


def _batch(i, rows=3):
    return [{"ts": f"2022-04-{(i % 27) + 1:02d}T00:00:{j:02d}",
             "g": f"g{(i + j) % 8}", "v": i * 100 + j}
            for j in range(rows)]


def _reference(extra_rows):
    data = _df()
    if extra_rows:
        ext = pd.DataFrame(extra_rows)
        ext["ts"] = pd.to_datetime(ext["ts"], format="mixed")
        data = pd.concat([data, ext], ignore_index=True)
    ref = Engine()
    ref.register_table("t", data, time_column="ts", block_rows=BLOCK,
                       time_partition="month")
    return ref


PARITY_QUERIES = [
    "SELECT g, count(*) AS n, sum(v) AS s FROM t GROUP BY g ORDER BY g",
    "SELECT month(ts) AS mo, sum(v) AS s, min(v) AS lo, max(v) AS hi "
    "FROM t GROUP BY month(ts) ORDER BY mo",
    "SELECT count(*) AS n, sum(v) AS s FROM t WHERE v < 500",
]


def _digest(frame: pd.DataFrame) -> str:
    return hashlib.sha256(
        frame.to_csv(index=False).encode()).hexdigest()


def _assert_parity(eng, ref, label=""):
    for q in PARITY_QUERIES:
        a, b = eng.sql(q), ref.sql(q)
        assert _digest(a) == _digest(b), \
            f"{label}: {q}\n{a}\nvs\n{b}"


def _store_files(tmp):
    d = os.path.join(str(tmp), "store", "t")
    return sorted(os.listdir(d)), d


def _manifest_refs(tmp, which=-1):
    """Chunk files referenced by one retained manifest (newest = -1)."""
    import json
    names, d = _store_files(tmp)
    manifests = [n for n in names if n.startswith("manifest-")]
    with open(os.path.join(d, manifests[which]), "rb") as f:
        payload = json.load(f)["payload"]
    refs = {e["file"] for e in payload["segments"]}
    refs.add(payload["dictionary"]["file"])
    return refs, payload


# -------------------------------------------------- checkpoint basics

def test_checkpoint_spill_noop_and_canonical_respill(tmp_path):
    eng = _mk(tmp_path)
    for i in range(4):
        eng.append("t", _batch(i))
    res = eng.checkpoint_now("t")
    assert res["status"] == "checkpointed" and res["checkpoint_id"] == 1
    assert res["files_written"] > 0
    # canonical layout: re-encoding an unchanged segment is
    # byte-identical, so an unchanged sealed set re-checkpoints as a
    # pure noop (no files written, no new manifest)
    seg = eng.catalog.get("t").segments.segments[0]
    assert encode_segment(seg) == encode_segment(seg)
    res2 = eng.checkpoint_now("t")
    assert res2["status"] == "noop" and res2["files_written"] == 0
    # the store directory holds content-addressed chunks + 1 manifest
    names, _ = _store_files(tmp_path)
    assert any(n.startswith("seg-") for n in names)
    assert any(n.startswith("dict-") for n in names)
    assert sum(n.startswith("manifest-") for n in names) == 1
    eng.close()


def test_checkpoint_truncates_wal_lag_one(tmp_path):
    eng = _mk(tmp_path)
    wal = wal_path(eng.config.ingest_wal_dir, "t")
    for i in range(4):
        eng.append("t", _batch(i))
    r1 = eng.checkpoint_now("t")
    # first checkpoint: only one manifest retained -> nothing may be
    # truncated yet (the lag-one guarantee needs a previous rung)
    assert r1["status"] == "checkpointed"
    assert r1["wal_frames_truncated"] == 0
    assert len(replay_wal(wal)) == 4
    for i in range(4, 6):
        eng.append("t", _batch(i))
    r2 = eng.checkpoint_now("t")
    assert r2["status"] == "checkpointed"
    # second checkpoint truncates exactly the frames the FIRST (now
    # oldest retained) manifest covers
    assert r2["wal_frames_truncated"] == 4
    kept = replay_wal(wal)
    assert [s for s, _ in kept] == [5, 6]
    # acknowledged seq counters never rewind
    st = eng.ingest._state("t")
    assert st.acked_seq == 6
    _assert_parity(eng, _reference(
        [r for i in range(6) for r in _batch(i)]), "post-truncate")
    eng.close()


def test_recovery_replays_only_tail(tmp_path):
    eng = _mk(tmp_path)
    for i in range(6):
        eng.append("t", _batch(i))
    eng.checkpoint_now("t")
    tail = [_batch(i) for i in range(6, 8)]
    for b in tail:
        eng.append("t", b)
    eng.close()
    rec = _mk(tmp_path)
    ev = [e for e in rec.runner.events.snapshot()
          if e["event"] == "wal_replay"]
    loads = [e for e in rec.runner.events.snapshot()
             if e["event"] == "store_load"]
    assert loads and loads[0]["wal_seq"] == 6
    # O(tail): only the 2 post-checkpoint frames replayed, not all 8
    assert ev and ev[0]["records"] == 2
    _assert_parity(rec, _reference(
        [r for i in range(8) for r in _batch(i)]), "tail-only")
    # recovered acked seq continues the original sequence
    assert rec.ingest._state("t").acked_seq == 8
    rec.close()


def test_checkpoint_on_compact_auto_hook(tmp_path):
    eng = _mk(tmp_path)  # ingest_store_checkpoint_on_compact defaults on
    for i in range(3):
        eng.append("t", _batch(i))
    res = eng.compact_now("t")
    assert res["status"] == "compacted"
    assert res["checkpoint"]["status"] == "checkpointed"
    st = eng.ingest._state("t")
    assert st.checkpoints == 1 and st.sealed_through_seq == 3
    eng.close()


def test_no_store_dir_disables_checkpointing(tmp_path):
    eng = _mk(tmp_path, ingest_store_dir=None)
    eng.append("t", _batch(0))
    res = eng.checkpoint_now("t")
    assert res["status"] == "no-store"
    out = eng.sql("CHECKPOINT DRUID TABLE t")
    assert out["status"][0] == "no-store"
    eng.close()


# ---------------------------------------------------- recovery ladder

def _build_two_checkpoints(tmp_path):
    """acked batches 0..7: 0-3 in ck1, 4-5 in ck2, 6-7 WAL tail."""
    eng = _mk(tmp_path)
    for i in range(4):
        eng.append("t", _batch(i))
    eng.checkpoint_now("t")
    for i in range(4, 6):
        eng.append("t", _batch(i))
    eng.checkpoint_now("t")
    for i in range(6, 8):
        eng.append("t", _batch(i))
    eng.close()
    return [r for i in range(8) for r in _batch(i)]


def test_corrupt_newest_manifest_falls_back_one_rung(tmp_path):
    acked = _build_two_checkpoints(tmp_path)
    names, d = _store_files(tmp_path)
    newest = [n for n in names if n.startswith("manifest-")][-1]
    with open(os.path.join(d, newest), "r+b") as f:
        f.seek(40)
        b = f.read(1)
        f.seek(40)
        f.write(bytes([b[0] ^ 0xFF]))
    rec = _mk(tmp_path)
    loads = [e for e in rec.runner.events.snapshot()
             if e["event"] == "store_load"]
    falls = [e for e in rec.runner.events.snapshot()
             if e["event"] == "store_fallback"]
    assert falls and falls[0]["manifest"] == newest
    # the previous manifest won; the lag-one WAL tail covers the rest
    assert loads and loads[0]["wal_seq"] == 4
    _assert_parity(rec, _reference(acked), "ladder rung 2")
    rec.close()


def test_manifest_pointing_at_deleted_chunk(tmp_path):
    acked = _build_two_checkpoints(tmp_path)
    refs2, _ = _manifest_refs(tmp_path, -1)
    refs1, _ = _manifest_refs(tmp_path, 0)
    only_newest = sorted(refs2 - refs1)
    assert only_newest, "checkpoint 2 wrote no fresh chunk"
    _, d = _store_files(tmp_path)
    os.unlink(os.path.join(d, only_newest[0]))
    rec = _mk(tmp_path)
    falls = [e for e in rec.runner.events.snapshot()
             if e["event"] == "store_fallback"]
    assert falls and "missing chunk" in falls[0]["reason"]
    _assert_parity(rec, _reference(acked), "deleted chunk")
    rec.close()


def test_bitflip_corruption_campaign(tmp_path):
    """Flip one byte in every recoverable spill file, one at a time:
    both manifests, every chunk not shared by all retained rungs.
    Each flip must be DETECTED (fallback event, never a crash) and
    recovery must reach sha256 parity with the never-crashed oracle.
    A chunk shared by every retained manifest is the single durable
    copy of pre-checkpoint rows (the WAL below the oldest watermark is
    truncated) — flipping it exercises the ladder floor instead:
    detected, surfaced, and the registration REFUSED (a coverage gap
    between the surviving WAL and what any rung covers must never
    silently serve a table missing acknowledged rows)."""
    acked = _build_two_checkpoints(tmp_path)
    ref = _reference(acked)
    refs2, _ = _manifest_refs(tmp_path, -1)
    refs1, _ = _manifest_refs(tmp_path, 0)
    names, d = _store_files(tmp_path)
    manifests = [n for n in names if n.startswith("manifest-")]
    recoverable = manifests + sorted(refs1 ^ refs2)
    flipped = 0
    for fname in recoverable:
        path = os.path.join(d, fname)
        with open(path, "rb") as f:
            orig = f.read()
        pos = len(orig) // 2
        with open(path, "wb") as f:
            f.write(orig[:pos] + bytes([orig[pos] ^ 0x55])
                    + orig[pos + 1:])
        rec = _mk(tmp_path)
        _assert_parity(rec, ref, f"bit-flip {fname}")
        rec.close()
        with open(path, "wb") as f:
            f.write(orig)
        flipped += 1
    assert flipped >= 3, "campaign too small to prove anything"
    # ladder floor: a chunk shared by ALL retained manifests is a
    # single copy — both rungs fail, and because the WAL below the
    # oldest watermark is truncated there is a coverage gap the
    # recovery must REFUSE to paper over
    shared = sorted(refs1 & refs2)
    assert shared, "no shared chunk — dedup across checkpoints broke"
    path = os.path.join(d, shared[0])
    with open(path, "rb") as f:
        orig = f.read()
    with open(path, "wb") as f:
        f.write(orig[:64] + bytes([orig[64] ^ 0x55]) + orig[65:])
    rec = Engine(_cfg(tmp_path))
    with pytest.raises(RuntimeError, match="recovery .* refused"):
        rec.register_table("t", _df(), time_column="ts",
                           block_rows=BLOCK, time_partition="month")
    falls = [e for e in rec.runner.events.snapshot()
             if e["event"] == "store_fallback"]
    assert len(falls) >= 2  # both rungs detected the corruption
    rec.close()
    # restoring the chunk makes the same registration recover fully
    with open(path, "wb") as f:
        f.write(orig)
    rec = _mk(tmp_path)
    _assert_parity(rec, ref, "restored shared chunk")
    rec.close()


def test_all_manifests_corrupt_before_truncation_full_replay(tmp_path):
    """With a single checkpoint nothing was truncated yet, so losing
    EVERY manifest still recovers fully from base + the whole WAL."""
    eng = _mk(tmp_path)
    for i in range(4):
        eng.append("t", _batch(i))
    eng.checkpoint_now("t")
    eng.close()
    names, d = _store_files(tmp_path)
    for n in names:
        if n.startswith("manifest-"):
            with open(os.path.join(d, n), "ab") as f:
                f.truncate(10)  # torn manifest
    rec = _mk(tmp_path)
    ev = [e for e in rec.runner.events.snapshot()
          if e["event"] == "wal_replay"]
    assert ev and ev[0]["records"] == 4
    _assert_parity(rec, _reference(
        [r for i in range(4) for r in _batch(i)]), "base+full WAL")
    rec.close()


def test_store_unit_load_ladder_reports_fallbacks(tmp_path):
    """SegmentStore.load in isolation: corrupt newest -> previous wins
    with the rung recorded; all corrupt -> LoadedCheckpoint with
    segments None (base-only), never an exception."""
    acked = _build_two_checkpoints(tmp_path)
    del acked
    store = SegmentStore(os.path.join(str(tmp_path), "store"))
    loaded = store.load("t")
    assert loaded.segments is not None and not loaded.fallbacks
    names, d = _store_files(tmp_path)
    for n in names:
        if n.startswith("seg-") or n.startswith("dict-"):
            with open(os.path.join(d, n), "r+b") as f:
                f.seek(8)
                f.write(b"\x00\x00\x00\x00")
    loaded = store.load("t")
    assert loaded.segments is None and len(loaded.fallbacks) == 2
    assert store.load("missing") is None
    with pytest.raises(StoreCorrupt):
        store._read_manifest(os.path.join(d, "manifest-absent.json"))


# ------------------------------------------------ SIGKILL chaos suite

KILL_SITES = ("spill-write", "manifest-swap", "wal-truncate",
              "store-load")


class _KillAt:
    """Fault injector that dies for real — no unwind, no atexit."""

    def __init__(self, stage):
        self.stages = {stage}

    def __call__(self, stage, attempt):
        os.kill(os.getpid(), signal.SIGKILL)


@pytest.mark.parametrize("site", KILL_SITES)
def test_sigkill_at_fault_site_recovers_to_parity(site, tmp_path):
    """Fork a child that SIGKILLs itself exactly at the fault site
    mid-checkpoint (or mid-recovery for store-load), then recover in
    the parent and assert sha256 parity with a never-crashed oracle.
    The child runs platform="cpu" (pure numpy) so the forked process
    never touches the parent's jax runtime."""
    pid = os.fork()
    if pid == 0:
        try:
            eng = _mk(tmp_path, platform="cpu",
                      ingest_wal_fsync="always")
            for i in range(3):
                eng.append("t", _batch(i))
            eng.checkpoint_now("t")
            for i in range(3, 6):
                eng.append("t", _batch(i))
            if site == "store-load":
                # recovery-side site: crash while LOADING the store —
                # a second in-child engine over the same dirs
                eng2 = Engine(_cfg(tmp_path, platform="cpu"))
                eng2.config.fault_injector = _KillAt(site)
                eng2.register_table("t", _df(), time_column="ts",
                                    block_rows=BLOCK,
                                    time_partition="month")
            else:
                eng.config.fault_injector = _KillAt(site)
                eng.checkpoint_now("t")
        except BaseException:
            pass
        os._exit(86)  # the fault never fired
    _, status = os.waitpid(pid, 0)
    assert os.WIFSIGNALED(status) \
        and os.WTERMSIG(status) == signal.SIGKILL, \
        f"child exited {status} without hitting {site}"
    rec = _mk(tmp_path)
    # every acknowledged append survived: 3 checkpointed + 3 tail
    assert rec.ingest._state("t").acked_seq == 6
    _assert_parity(rec, _reference(
        [r for i in range(6) for r in _batch(i)]), f"SIGKILL {site}")
    rec.close()


def test_seeded_inprocess_chaos_all_store_sites(tmp_path):
    """Seeded RuntimeError chaos at every store site interleaved with
    appends/checkpoints (the in-process spelling the PR 13 suite
    established); the abandoned-state files must always recover."""
    eng = _mk(tmp_path)
    inj = FaultInjector(seed=23, rate=0.35,
                        stages={"spill-write", "manifest-swap",
                                "wal-truncate", "compact"})
    eng.config.fault_injector = inj
    rng = np.random.default_rng(23)
    acked = []
    for i in range(24):
        rows = _batch(i)
        try:
            eng.append("t", rows)
            acked.extend(rows)
        except RuntimeError:
            pass
        if rng.random() < 0.4:
            try:
                res = eng.checkpoint_now("t")
                assert res["status"] in ("checkpointed", "noop",
                                         "busy", "error", "compacted",
                                         "breaker-open")
            except RuntimeError:
                pass  # injected mid-spill: previous manifest stands
    assert inj.faults > 0, "chaos never fired"
    eng.config.fault_injector = None
    eng.close()
    rec = _mk(tmp_path)
    _assert_parity(rec, _reference(acked), "in-process chaos")
    rec.close()


# -------------------------------------------------- recovery edge cases

def test_double_crash_during_recovery(tmp_path):
    """Crash while recovering (store-load), then crash again while
    replaying the tail (wal-replay): each retry starts the ladder
    clean and the third attempt recovers fully."""
    acked = _build_two_checkpoints(tmp_path)
    rec = Engine(_cfg(tmp_path))
    rec.config.fault_injector = FaultInjector(
        seed=1, rate=1.0, stages={"store-load"})
    with pytest.raises(RuntimeError):
        rec.register_table("t", _df(), time_column="ts",
                           block_rows=BLOCK, time_partition="month")
    rec.config.fault_injector = FaultInjector(
        seed=2, rate=1.0, stages={"wal-replay"})
    with pytest.raises(RuntimeError):
        rec.register_table("t", _df(), time_column="ts",
                           block_rows=BLOCK, time_partition="month")
    rec.config.fault_injector = None
    rec.register_table("t", _df(), time_column="ts",
                       block_rows=BLOCK, time_partition="month")
    _assert_parity(rec, _reference(acked), "double crash")
    rec.close()


def test_checkpoint_racing_concurrent_appends(tmp_path):
    """Appends on a real thread while checkpoints run: the watermark
    only ever covers rows actually in the sealed scope, nothing acked
    is lost, and a cold-start recovery reaches parity."""
    eng = _mk(tmp_path)
    acked = []
    alock = threading.Lock()
    stop = threading.Event()

    def writer():
        i = 100
        while not stop.is_set():
            rows = _batch(i)
            eng.append("t", rows)
            with alock:
                acked.extend(rows)
            i += 1

    th = threading.Thread(target=writer)
    th.start()
    try:
        for _ in range(5):
            res = eng.checkpoint_now("t")
            assert res["status"] in ("checkpointed", "noop", "busy")
            st = eng.ingest._state("t")
            assert st.sealed_through_seq <= st.acked_seq
            time.sleep(0.02)
    finally:
        stop.set()
        th.join()
    with alock:
        n_acked = len(acked)
    got = int(eng.sql("SELECT count(*) AS n FROM t")["n"][0])
    assert got == 2000 + n_acked
    eng.close()
    rec = _mk(tmp_path)
    _assert_parity(rec, _reference(acked), "racing appends")
    rec.close()


def test_noop_checkpoint_still_truncates_wal(tmp_path):
    """Crash in the wal-truncate window (manifest swapped, log not yet
    rewritten): the next checkpoint of the unchanged sealed set is a
    noop, but it must still truncate the covered prefix — otherwise
    the frames persist forever."""
    eng = _mk(tmp_path)
    wal = wal_path(eng.config.ingest_wal_dir, "t")
    for i in range(3):
        eng.append("t", _batch(i))
    eng.checkpoint_now("t")
    for i in range(3, 5):
        eng.append("t", _batch(i))
    eng.config.fault_injector = FaultInjector(
        seed=3, rate=1.0, stages={"wal-truncate"})
    with pytest.raises(RuntimeError):
        eng.checkpoint_now("t")  # manifest advanced, truncation died
    eng.config.fault_injector = None
    assert len(replay_wal(wal)) == 5  # covered prefix still on disk
    res = eng.checkpoint_now("t")
    assert res["status"] == "noop"
    assert res["wal_frames_truncated"] == 3
    assert [s for s, _ in replay_wal(wal)] == [4, 5]
    _assert_parity(eng, _reference(
        [r for i in range(5) for r in _batch(i)]), "noop truncate")
    eng.close()


def test_stale_checkpoint_after_replacement_is_discarded(tmp_path):
    """A checkpoint commit that loses the race with a re-registration
    must not survive it: a manifest of the REPLACED data with the old
    high watermark would make the next recovery silently drop every
    newly acknowledged row. Simulates the race's late half by driving
    _checkpoint_sealed with the displaced state object."""
    eng = _mk(tmp_path)
    eng.append("t", _batch(0))
    old_entry = eng.catalog.get("t")
    old_st = eng.ingest._state("t")
    # the replacement lands mid-checkpoint (before the commit check)
    eng.register_table("t", _df(seed=9), time_column="ts",
                       block_rows=BLOCK, time_partition="month")
    res = eng.ingest._checkpoint_sealed("t", old_entry, old_st)
    assert res["status"] == "stale"
    assert not os.path.isdir(os.path.join(str(tmp_path), "store", "t"))
    # new appends recover normally — nothing resurrected, nothing lost
    eng.append("t", _batch(1))
    eng.close()
    rec = Engine(_cfg(tmp_path))
    rec.register_table("t", _df(seed=9), time_column="ts",
                       block_rows=BLOCK, time_partition="month")
    n = int(rec.sql("SELECT count(*) AS n FROM t")["n"][0])
    assert n == 2000 + 3  # base(seed 9) + the post-replacement batch
    rec.close()


def test_close_reopen_checkpoint_idempotent(tmp_path):
    eng = _mk(tmp_path)
    for i in range(3):
        eng.append("t", _batch(i))
    r1 = eng.checkpoint_now("t")
    assert r1["status"] == "checkpointed"
    eng.close()
    rec = _mk(tmp_path)
    # nothing changed across the restart: the sealed scope re-spills
    # byte-identically and the manifest does not advance
    r2 = rec.checkpoint_now("t")
    assert r2["status"] == "noop" and r2["files_written"] == 0
    rec.close()
    rec2 = _mk(tmp_path)
    _assert_parity(rec2, _reference(
        [r for i in range(3) for r in _batch(i)]), "reopen x2")
    rec2.close()


def test_reregistering_live_table_drops_store(tmp_path):
    eng = _mk(tmp_path)
    eng.append("t", _batch(0))
    eng.checkpoint_now("t")
    _, d = _store_files(tmp_path)
    assert os.path.isdir(d)
    # replacing a LIVE table: its checkpoints covered the old data
    eng.register_table("t", _df(seed=9), time_column="ts",
                       block_rows=BLOCK, time_partition="month")
    assert not os.path.isdir(d)
    n = int(eng.sql("SELECT count(*) AS n FROM t")["n"][0])
    assert n == 2000  # no resurrected appends
    eng.close()


def test_drop_table_deletes_store(tmp_path):
    eng = _mk(tmp_path)
    eng.append("t", _batch(0))
    eng.checkpoint_now("t")
    _, d = _store_files(tmp_path)
    eng.drop_table("t")
    assert not os.path.isdir(d)
    eng.close()


# ------------------------------------------- incremental compaction

def test_incremental_compaction_rewrites_only_touched(tmp_path):
    """Base spans months 3-4/2022; appends land in April only. The
    compactor must reuse March's sealed segments (mode=incremental)
    and the next checkpoint must reuse their spilled chunks."""
    eng = _mk(tmp_path)
    eng.checkpoint_now("t")  # spill the pristine base
    before = eng.catalog.get("t").segments
    march = [s for s in before.segments
             if pd.Timestamp(s.meta.time_min, unit="ms").month == 3]
    assert march, "base has no March partition"
    for i in range(3):
        eng.append("t", _batch(i))  # April timestamps only
    res = eng.compact_now("t")
    assert res["mode"] == "incremental"
    assert res["segments_reused"] >= len(march)
    # the reused segments' chunk files were NOT rewritten
    ck = res["checkpoint"]
    assert ck["status"] == "checkpointed"
    assert ck["chunks_reused"] >= len(march)
    _assert_parity(eng, _reference(
        [r for i in range(3) for r in _batch(i)]), "incremental")
    eng.close()


def test_unsorted_dictionary_forces_full_compaction(tmp_path):
    eng = _mk(tmp_path)
    # an unseen value tail-extends the dictionary -> unsorted ->
    # incremental ineligible (stored codes would need a re-sort)
    eng.append("t", [{"ts": "2022-04-02T00:00:00", "g": "aaa_new",
                      "v": 5}])
    assert not eng.catalog.get("t").segments.dictionaries["g"].is_sorted
    res = eng.compact_now("t")
    assert res["mode"] == "full"
    assert eng.catalog.get("t").segments.dictionaries["g"].is_sorted
    _assert_parity(eng, _reference(
        [{"ts": "2022-04-02T00:00:00", "g": "aaa_new", "v": 5}]),
        "full fallback")
    eng.close()


# -------------------------------------------- vectorized encode_rows

def test_vectorized_encode_rows_semantics():
    """The numpy batch encoder keeps the per-row loop's observable
    contract: unseen values coded in first-appearance order, None
    folds to SQL NULL (NaN-in-LONG still rejects, like int(nan)
    always did), and a bad value rejects the batch whole."""
    eng = Engine(EngineConfig(ingest_auto_compact=False,
                              cube_auto_refresh=False))
    eng.register_table("t", _df(), time_column="ts", block_rows=BLOCK)
    rows = [
        {"ts": "2022-04-01T00:00:00", "g": "zz", "v": 1},
        {"ts": "2022-04-01T00:00:01", "g": "aa", "v": None},
        {"ts": "2022-04-01T00:00:02", "g": "zz", "v": None},
        {"ts": "2022-04-01T00:00:03", "g": None, "v": 4},
        {"ts": "2022-04-01T00:00:04", "g": "mm", "v": 5},
    ]
    eng.append("t", rows)
    d = eng.catalog.get("t").segments.dictionaries["g"]
    # first-appearance tail order — the exact codes the original
    # per-row sequence assigned (WAL replay block-identity)
    assert list(d.values[-3:]) == ["zz", "aa", "mm"]
    got = eng.sql("SELECT count(*) AS n, count(v) AS nv, sum(v) AS s "
                  "FROM t WHERE g IN ('zz', 'aa', 'mm')")
    assert int(got["n"][0]) == 4 and int(got["nv"][0]) == 2
    assert int(got["s"][0]) == 6
    before = eng.catalog.get("t").segments.delta_rows
    with pytest.raises(UserError, match="LONG"):
        eng.append("t", [
            {"ts": "2022-04-01T00:00:00", "g": "x", "v": 1},
            {"ts": "2022-04-01T00:00:01", "g": "x", "v": "junk"}])
    assert eng.catalog.get("t").segments.delta_rows == before


def test_vectorized_encode_rows_throughput_floor():
    """The batch encoder must beat the old ~13k rows/s per-row loop by
    a wide margin; assert a conservative floor so a regression back to
    per-row Python work fails loudly."""
    eng = Engine(EngineConfig(ingest_auto_compact=False,
                              cube_auto_refresh=False,
                              ingest_max_delta_rows=1 << 22))
    eng.register_table("t", _df(), time_column="ts", block_rows=BLOCK)
    n = 50_000
    rng = np.random.default_rng(0)
    base_ms = int(pd.Timestamp("2022-04-01").value // 10 ** 6)
    rows = [{"ts": base_ms + int(x), "g": f"g{int(c)}", "v": int(v)}
            for x, c, v in zip(rng.integers(0, 10 ** 9, n),
                               rng.integers(0, 8, n),
                               rng.integers(0, 1000, n))]
    t0 = time.perf_counter()
    eng.append("t", rows)
    rps = n / (time.perf_counter() - t0)
    assert rps > 40_000, f"encode_rows regressed to {rps:,.0f} rows/s"


# ------------------------------------ drain-rate-derived Retry-After

def test_retry_after_derives_from_measured_drain_rate(tmp_path):
    eng = _mk(tmp_path, ingest_max_delta_rows=64,
              ingest_store_checkpoint_on_compact=False)
    st = eng.ingest._state("t")
    assert st.drain_rps is None
    # before any compaction: the fixed config constant
    for i in range(21):
        eng.append("t", _batch(i))  # 63 rows
    with pytest.raises(IngestBackpressure) as e1:
        eng.append("t", _batch(99))
    assert e1.value.retry_after_s \
        == pytest.approx(eng.config.ingest_retry_after_s)
    eng.compact_now("t")  # observes the drain rate
    assert st.drain_rps and st.drain_rps > 0
    for i in range(21):
        eng.append("t", _batch(i))
    with pytest.raises(IngestBackpressure) as e2:
        eng.append("t", _batch(99))
    need = 63 + 3 - 64
    lo, hi = eng.ingest._RETRY_AFTER_BOUNDS
    expect = min(hi, max(lo, need / st.drain_rps))
    assert e2.value.retry_after_s == pytest.approx(expect)
    snap = eng.ingest.snapshot()["tables"]["t"]
    assert snap["drain_rows_per_s"] == round(st.drain_rps, 1)
    eng.close()


# ------------------------------------------------ surfaces & contract

def test_sys_checkpoints_and_debug_surfaces(tmp_path):
    eng = _mk(tmp_path)
    for i in range(3):
        eng.append("t", _batch(i))
    out = eng.sql("CHECKPOINT DRUID TABLE t")
    assert out["status"][0] == "checkpointed"
    rows = eng.sql("SELECT * FROM sys.checkpoints")
    assert list(rows["table"]) == ["t"]
    r = rows.iloc[0]
    assert int(r["checkpoint_id"]) == 1
    assert int(r["wal_watermark"]) == 3
    assert int(r["acked_seq"]) == 3
    assert int(r["checkpoints"]) == 1
    assert r["last_status"] == "checkpointed"
    snap = eng.ingest.snapshot()
    assert snap["store"]["dir"] == eng.config.ingest_store_dir
    tstore = snap["tables"]["t"]["store"]
    assert tstore["checkpoints"] == 1
    assert tstore["sealed_through_seq"] == 3
    # metrics registered and counting
    text = eng.runner.metrics.render()
    assert "checkpoints_total" in text
    assert "store_bytes" in text
    eng.close()
