"""Pipelined query execution (ISSUE 10): the enqueue-only dispatch
lock. Stage 1 (enqueue, under dispatch_lock) fires the async device
program and pins the result buffers in the HbmLedger; stage 2
(complete, lock-free) transfers, finalizes, and assembles on the
caller's thread. These tests pin the stage split's contracts: ledger
pinning vs eviction, deadline expiry during a stage-2 transfer,
breaker trips between enqueue and complete, result-cache population
from a stage-2 completion, the pipeline-occupancy bound, and the new
observability surface (dispatch_lock_wait_ms, pipelined flag)."""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.executor.dataset import HbmLedger
from tpu_olap.resilience import QueryShed
from tpu_olap.resilience.admission import AdmissionController


def _df(n=4096, seed=9):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "g": rng.choice(["x", "y", "z"], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


SQL = "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g"


def _register(eng, **kw):
    eng.register_table("t", _df(), time_column="ts", block_rows=512,
                       **kw)


def _reference():
    ref = Engine(EngineConfig(pipeline_depth=0))
    _register(ref)
    return ref.sql(SQL)


# ------------------------------------------------------------- basics


def test_pipelined_is_default_and_matches_serialized():
    eng = Engine()
    assert eng.config.pipeline_depth == 4
    _register(eng)
    got = eng.sql(SQL)
    rec = eng.runner.history[-1]
    assert rec["pipelined"] is True
    assert "lock_wait_ms" in rec
    pd.testing.assert_frame_equal(got, _reference())
    # the new metric series exist and saw traffic
    text = eng.metrics.render()
    assert "tpu_olap_dispatch_lock_wait_ms_count" in text
    assert "tpu_olap_pipeline_inflight" in text
    assert "tpu_olap_inflight_transfers" in text
    hist = eng.metrics.histogram("dispatch_lock_wait_ms")
    assert hist.series and next(iter(hist.series.values())).n >= 1


def test_serialized_mode_still_works():
    eng = Engine(EngineConfig(pipeline_depth=0))
    _register(eng)
    got = eng.sql(SQL)
    rec = eng.runner.history[-1]
    assert rec["pipelined"] is False
    pd.testing.assert_frame_equal(got, _reference())


# ------------------------------------------ ledger in-flight pinning


def test_ledger_pin_inflight_counts_and_never_evicts():
    """The eviction-vs-pinned-inflight-result race: a pinned in-flight
    result's bytes count toward the budget (a concurrent env build must
    evict resident COLUMNS to make room) but the pin itself is never
    evictable — the transfer is about to read it."""
    led = HbmLedger(budget_bytes=1000)
    evicted = []
    led.add(("t", "col", "a"), 400, lambda: evicted.append("a"))
    led.add(("t", "col", "b"), 400, lambda: evicted.append("b"))
    assert led.bytes_in_use == 800 and not evicted
    led.pin_inflight(("__inflight__", 1), 500)
    assert led.bytes_in_use == 1300
    assert led.inflight_bytes == 500
    # a new column add must evict the resident columns (LRU first),
    # NEVER the in-flight pin
    led.add(("t", "col", "c"), 400, lambda: evicted.append("c"))
    assert "a" in evicted
    assert led.inflight_bytes == 500  # pin survived
    led.unpin_inflight(("__inflight__", 1))
    assert led.inflight_bytes == 0
    # unpin released exactly the pinned bytes
    assert led.bytes_in_use == sum(
        n for n, _ in led._entries.values())


def test_concurrent_queries_under_tight_budget_stay_correct():
    """Engine-level race: pipelined queries against a 1-byte HBM budget
    force constant eviction while results are in flight — every thread
    still gets the exact answer."""
    eng = Engine(EngineConfig(hbm_budget_bytes=1, pipeline_depth=2))
    _register(eng)
    want = _reference()
    errs = []

    def worker():
        try:
            for _ in range(3):
                pd.testing.assert_frame_equal(eng.sql(SQL), want)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
    assert eng.runner._hbm_ledger.inflight_bytes == 0  # all unpinned


# --------------------------------- deadline during a stage-2 transfer


class _StallTransfer:
    """Injector that stalls the host-transfer site once."""

    stages = {"host-transfer"}

    def __init__(self, stall_s):
        self.stall_s = stall_s
        self.armed = False
        self.fired = 0

    def __call__(self, stage, attempt):
        if self.armed:
            self.fired += 1
            self.armed = False
            time.sleep(self.stall_s)


def test_deadline_expiry_during_stage2_transfer():
    """A transfer that hangs AFTER the lock was released must still
    trip the watchdog: deadline -> wedge -> fallback answers -> the
    reprobe clears the wedge and the device path serves again."""
    inj = _StallTransfer(stall_s=2.0)
    eng = Engine(EngineConfig(dispatch_retries=0, fault_injector=inj))
    _register(eng)
    want = _reference()
    eng.sql(SQL)  # warm compile outside the deadline regime
    eng.config.query_deadline_s = 0.4
    inj.armed = True
    t0 = time.perf_counter()
    got = eng.sql(SQL)  # transfer stalls -> deadline -> fallback
    assert inj.fired == 1
    assert time.perf_counter() - t0 < 10
    assert "QueryDeadlineExceeded" in eng.last_plan.fallback_reason
    assert any(h.get("deadline_exceeded") for h in eng.runner.history)
    pd.testing.assert_frame_equal(got, want)
    # recovery: reprobe clears the wedge, device path again
    eng.config.query_deadline_s = 30.0
    got2 = eng.sql(SQL)
    assert eng.last_plan.fallback_reason is None
    assert not eng.runner._wedged
    pd.testing.assert_frame_equal(got2, want)
    time.sleep(1.8)  # let the abandoned transfer thread drain


# ------------------------------- breaker trip between enqueue and complete


def test_breaker_trips_on_stage2_failure():
    """A transfer failure between enqueue and complete is a terminal
    device failure: it counts toward the breaker, and once open the
    engine serves degraded (path=fallback_breaker) without dispatch."""

    class FailTransfer:
        stages = {"host-transfer"}

        def __call__(self, stage, attempt):
            raise RuntimeError("injected transfer loss")

    eng = Engine(EngineConfig(dispatch_retries=0,
                              breaker_failure_threshold=2,
                              breaker_open_cooldown_s=30.0,
                              fault_injector=FailTransfer()))
    _register(eng)
    try:
        want = _reference()
        for _ in range(2):  # two terminal stage-2 failures trip it
            pd.testing.assert_frame_equal(eng.sql(SQL), want)
        assert eng.runner.breaker.state == "open"
        got = eng.sql(SQL)
        rec = eng.runner.history[-1]
        assert rec["path"] == "fallback_breaker"
        pd.testing.assert_frame_equal(got, want)
    finally:
        eng.runner.breaker.close()


# ------------------------------------- result cache from a stage-2 completion


def test_result_cache_populates_from_stage2_completion():
    eng = Engine(EngineConfig(result_cache_enabled=True,
                              pipeline_depth=2))
    _register(eng)
    want = _reference()
    pd.testing.assert_frame_equal(eng.sql(SQL), want)
    assert eng.runner.history[-1]["pipelined"] is True
    pd.testing.assert_frame_equal(eng.sql(SQL), want)
    rec = eng.runner.history[-1]
    assert rec["path"] == "cache" and rec["cache_tier"] == "full"


# ----------------------------------------------- pipeline occupancy bound


def test_pipeline_slot_bounds_inflight():
    ac = AdmissionController(max_inflight=8, queue_limit=8,
                             pipeline_depth=1)
    entered, release = threading.Event(), threading.Event()

    def hold():
        with ac.pipeline_slot():
            entered.set()
            release.wait(timeout=30)

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    assert entered.wait(5)
    assert ac.snapshot()["pipeline_inflight"] == 1
    # a second acquirer with an exhausted budget sheds instead of
    # queueing forever
    with pytest.raises(QueryShed) as ei:
        with ac.pipeline_slot(budget_s=0.05):
            pass
    assert ei.value.reason == "pipeline_stall"
    release.set()
    t.join(timeout=10)
    assert ac.snapshot()["pipeline_inflight"] == 0
    with ac.pipeline_slot():  # reusable after release
        assert ac.snapshot()["pipeline_inflight"] == 1
    # re-entrant per thread, like slot()
    with ac.pipeline_slot():
        with ac.pipeline_slot():
            assert ac.snapshot()["pipeline_inflight"] == 1


def test_pipeline_slot_disabled_is_noop():
    ac = AdmissionController(max_inflight=8, queue_limit=8,
                             pipeline_depth=0)
    with ac.pipeline_slot():
        assert ac.snapshot()["pipeline_inflight"] == 0


def test_reset_pipeline_reclaims_stranded_slots():
    """A deadline-abandoned dispatch thread strands its pipeline slot;
    wedge recovery calls reset_pipeline so device capacity comes back.
    The stranded holder's eventual release clamps at zero instead of
    going negative."""
    ac = AdmissionController(max_inflight=8, queue_limit=8,
                             pipeline_depth=1)
    entered, release = threading.Event(), threading.Event()

    def stranded():
        with ac.pipeline_slot():
            entered.set()
            release.wait(timeout=30)

    t = threading.Thread(target=stranded, daemon=True)
    t.start()
    assert entered.wait(5)
    # capacity gone: a budgeted waiter sheds
    with pytest.raises(QueryShed):
        with ac.pipeline_slot(budget_s=0.05):
            pass
    ac.reset_pipeline()  # wedge recovery reclaims the slot
    with ac.pipeline_slot(budget_s=0.05):
        assert ac.snapshot()["pipeline_inflight"] == 1
    release.set()  # the stranded holder finally drains: clamp, not -1
    t.join(timeout=10)
    assert ac.snapshot()["pipeline_inflight"] == 0


def test_recovery_survives_stranded_dispatch_lock():
    """An abandoned stage-1 thread holding dispatch_lock must not hang
    recovery forever: _recover_after_probe bounds its acquire in
    pipelined mode, reports failure, and succeeds once the lock
    drains."""
    eng = Engine(EngineConfig(pipeline_depth=2))
    _register(eng)
    eng.sql(SQL)
    release = threading.Event()
    held = threading.Event()

    def strand():
        eng.runner.dispatch_lock.acquire()
        held.set()
        release.wait(timeout=30)
        eng.runner.dispatch_lock.release()

    t = threading.Thread(target=strand, daemon=True)
    t.start()
    assert held.wait(5)
    t0 = time.perf_counter()
    assert eng.runner._recover_after_probe(lock_timeout_s=1.0) is False
    assert time.perf_counter() - t0 < 5  # bounded, not forever
    assert eng.runner.history[-1].get("device_probe_lock_stranded")
    release.set()
    t.join(timeout=10)
    assert eng.runner._recover_after_probe(lock_timeout_s=1.0) is True


def test_sparse_path_leaves_no_inflight_pins():
    """The sparse dispatch pins its enqueued output like every other
    device path and unpins on success AND on the over-budget raise."""
    sql = ("SELECT g, v, sum(v) AS s FROM t GROUP BY g, v "
           "ORDER BY g, v")
    eng = Engine(EngineConfig(dense_group_budget=4, pipeline_depth=2))
    _register(eng)
    got = eng.sql(sql)
    assert eng.runner.history[-1].get("sparse")
    assert eng.runner._hbm_ledger.inflight_bytes == 0
    ref = Engine(EngineConfig(pipeline_depth=0))
    _register(ref)
    pd.testing.assert_frame_equal(got, ref.sql(sql))
    # overflow path: a sparse budget too small for the present groups
    # raises (engine serves via fallback) — and still unpins
    sp = Engine(EngineConfig(dense_group_budget=1,
                             sparse_group_budget=1, pipeline_depth=2))
    _register(sp)
    out = sp.sql(sql)
    assert len(out) > 3
    assert sp.runner.history[-1]["query_type"] == "fallback"
    assert sp.runner._hbm_ledger.inflight_bytes == 0


# ------------------------------------ stage-graph scheduler (ISSUE 16)


FOREGROUND = ("plan", "enqueue", "transfer", "finalize", "assemble")


@pytest.mark.parametrize("site", [f"stage-{s}" for s in FOREGROUND])
def test_fault_at_each_stage_boundary_still_answers(site):
    """Every stage boundary carries a fault-injection site; a fault at
    any of them must never surface — the engine retries or falls back
    and the answer stays frame-identical, then heals."""
    from tpu_olap.resilience import FaultInjector
    eng = Engine(EngineConfig(breaker_failure_threshold=100))
    _register(eng)
    want = _reference()
    eng.sql(SQL)  # warm before arming
    inj = FaultInjector(stages={site}, fail_calls=(1,))
    eng.config.fault_injector = inj
    try:
        pd.testing.assert_frame_equal(eng.sql(SQL), want)
    finally:
        eng.config.fault_injector = None
    assert inj.faults == 1, f"{site} never fired"
    # healed: next query rides the device path, no stranded slots
    pd.testing.assert_frame_equal(eng.sql(SQL), want)
    snap = eng.runner.stages.snapshot()["pools"]
    assert all(p["active"] == 0 for p in snap.values()), snap


def test_breaker_trips_between_enqueue_and_transfer_stage():
    """A fault at the transfer *stage boundary* (after enqueue released
    the lock, before the host copy) is a terminal device failure just
    like a mid-transfer loss: two of them open the breaker and the
    engine serves degraded."""

    class FailBoundary:
        stages = {"stage-transfer"}

        def __call__(self, stage, attempt):
            raise RuntimeError("injected loss at the transfer boundary")

    eng = Engine(EngineConfig(dispatch_retries=0,
                              breaker_failure_threshold=2,
                              breaker_open_cooldown_s=30.0,
                              fault_injector=FailBoundary()))
    _register(eng)
    try:
        want = _reference()
        for _ in range(2):
            pd.testing.assert_frame_equal(eng.sql(SQL), want)
        assert eng.runner.breaker.state == "open"
        got = eng.sql(SQL)
        assert eng.runner.history[-1]["path"] == "fallback_breaker"
        pd.testing.assert_frame_equal(got, want)
    finally:
        eng.runner.breaker.close()
        eng.config.fault_injector = None


def test_deadline_expiry_at_transfer_stage_boundary():
    """_StallTransfer again, but stalling at the stage-transfer site:
    the stage section sits inside the deadline watchdog, so a stall at
    the boundary trips the deadline exactly like a mid-copy hang."""
    inj = _StallTransfer(stall_s=2.0)
    inj.stages = {"stage-transfer"}
    eng = Engine(EngineConfig(dispatch_retries=0, fault_injector=inj))
    _register(eng)
    want = _reference()
    eng.sql(SQL)  # warm compile outside the deadline regime
    eng.config.query_deadline_s = 0.4
    inj.armed = True
    got = eng.sql(SQL)
    assert inj.fired == 1
    assert any(h.get("deadline_exceeded") for h in eng.runner.history)
    pd.testing.assert_frame_equal(got, want)
    eng.config.query_deadline_s = 30.0
    pd.testing.assert_frame_equal(eng.sql(SQL), want)
    assert not eng.runner._wedged
    time.sleep(1.8)  # let the abandoned transfer thread drain


def test_stage_pool_bounds_and_reclaims_stranded_slots():
    """StagePool unit contract: slots bound concurrency, a budgeted
    waiter raises the deadline error when none frees, and
    reclaim_stranded frees abandoned slots (the late release no-ops)."""
    from tpu_olap.executor.runner import QueryDeadlineExceeded
    from tpu_olap.executor.stages import StageScheduler
    sched = StageScheduler(EngineConfig())
    pool = sched.pools["enqueue"]  # width 1: one chip program queue
    assert pool.max_workers == 1
    entered, release = threading.Event(), threading.Event()

    def strand():
        with pool.section():
            entered.set()
            release.wait(timeout=30)

    t = threading.Thread(target=strand, daemon=True)
    t.start()
    assert entered.wait(5)
    with pytest.raises(QueryDeadlineExceeded):
        with pool.section(budget_s=0.05):
            pass  # pragma: no cover
    time.sleep(0.25)
    assert sched.reclaim_stranded(0.2) >= 1
    with pool.section(budget_s=5.0) as waited_ms:
        assert waited_ms >= 0.0  # slot reclaimed, section admitted
    release.set()
    t.join(timeout=10)
    # the stranded holder's own release was a no-op: no double-free
    tot = pool.totals()
    assert tot["active"] == 0 and tot["stranded"] >= 1
    sched.stop()


def test_stage_section_is_reentrant_per_thread():
    """A thread already inside a stage section re-enters for free —
    chained work (checkpoint after compact) must not deadlock on its
    own slot or double-count occupancy."""
    from tpu_olap.executor.stages import StageScheduler
    sched = StageScheduler(EngineConfig())
    pool = sched.pools["enqueue"]  # width 1
    with pool.section():
        with pool.section():  # would deadlock if not re-entrant
            assert pool.totals()["active"] == 1
    assert pool.totals()["active"] == 0
    sched.stop()


def test_scheduler_background_graph_runs_wakes_and_rearms():
    """register_periodic drives a background graph off the one ticker:
    it runs on interval, wake() runs it now, cancel() stops it — and
    after stop() the scheduler re-arms so a later registration still
    runs (the engine stays usable after close)."""
    from tpu_olap.executor.stages import StageScheduler
    sched = StageScheduler(EngineConfig())
    runs = []
    h = sched.register_periodic("probe", lambda: 30.0,
                                lambda: runs.append(1))
    h.wake()
    deadline = time.monotonic() + 10
    while not runs and time.monotonic() < deadline:
        time.sleep(0.02)
    assert runs and h.runs >= 1
    sched.stop()
    assert h.cancelled
    # re-arm: a fresh registration after stop still ticks
    runs2 = []
    h2 = sched.register_periodic("probe2", lambda: 0.05,
                                 lambda: runs2.append(1))
    assert not h2.cancelled
    deadline = time.monotonic() + 10
    while not runs2 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert runs2
    sched.stop()


def test_background_graph_fault_is_recorded_and_retried():
    """A fault inside a background graph body (stage-background site)
    is caught by the launcher — errors are counted on the handle and
    the next wake retries the body successfully."""
    from tpu_olap.resilience import FaultInjector
    from tpu_olap.resilience.faults import maybe_inject
    cfg = EngineConfig(
        fault_injector=FaultInjector(stages={"stage-background"},
                                     fail_calls=(1,)))
    from tpu_olap.executor.stages import StageScheduler
    sched = StageScheduler(cfg, inject=lambda s: maybe_inject(cfg, s))
    runs = []
    h = sched.register_periodic("flaky", lambda: 30.0,
                                lambda: runs.append(1))
    h.wake()  # first run: injected fault before the body
    deadline = time.monotonic() + 10
    while h.errors < 1 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert h.errors == 1 and not runs
    assert "injected fault" in (h.last_error or "")
    h.wake()  # retry succeeds
    deadline = time.monotonic() + 10
    while not runs and time.monotonic() < deadline:
        time.sleep(0.02)
    assert runs
    sched.stop()


def test_mixed_class_16_thread_sha_parity_at_depth4():
    """16 threads in the bench's 6/6/2/2 grouped/ungrouped/fallback/
    statement mix at the new default depth 4: every response hashes
    identical to its single-threaded reference, every foreground stage
    saw traffic, and no stage slot leaks."""
    import hashlib
    eng = Engine(EngineConfig(pipeline_depth=4))
    _register(eng)
    qs = {
        "grouped": SQL,
        "ungrouped": "SELECT sum(v) AS s, count(*) AS n FROM t "
                     "WHERE v < 50",
        "fallback": "SELECT g, v, row_number() OVER "
                    "(PARTITION BY g ORDER BY v DESC, ts) AS r "
                    "FROM t WHERE v > 90",
        "statement": "EXPLAIN DRUID REWRITE SELECT g, sum(v) AS s "
                     "FROM t GROUP BY g",
    }

    def sha(df):
        return hashlib.sha256(
            df.to_csv(index=False).encode()).hexdigest()

    ref = {k: sha(eng.sql(q)) for k, q in qs.items()
           if k != "statement"}
    errs = []
    mix = ["grouped"] * 6 + ["ungrouped"] * 6 + \
          ["fallback"] * 2 + ["statement"] * 2

    def worker(label):
        try:
            for _ in range(3):
                out = eng.sql(qs[label])
                if label != "statement":
                    got = sha(out)
                    assert got == ref[label], (label, got)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append((label, repr(e)))

    threads = [threading.Thread(target=worker, args=(lb,))
               for lb in mix]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    assert not errs, errs
    snap = eng.runner.stages.snapshot()["pools"]
    for s in FOREGROUND:
        assert snap[s]["submitted"] > 0, (s, snap[s])
        assert snap[s]["active"] == 0, (s, snap[s])
    assert snap["enqueue"]["max_workers"] == 1
