"""Pipelined query execution (ISSUE 10): the enqueue-only dispatch
lock. Stage 1 (enqueue, under dispatch_lock) fires the async device
program and pins the result buffers in the HbmLedger; stage 2
(complete, lock-free) transfers, finalizes, and assembles on the
caller's thread. These tests pin the stage split's contracts: ledger
pinning vs eviction, deadline expiry during a stage-2 transfer,
breaker trips between enqueue and complete, result-cache population
from a stage-2 completion, the pipeline-occupancy bound, and the new
observability surface (dispatch_lock_wait_ms, pipelined flag)."""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.executor.dataset import HbmLedger
from tpu_olap.resilience import QueryShed
from tpu_olap.resilience.admission import AdmissionController


def _df(n=4096, seed=9):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "g": rng.choice(["x", "y", "z"], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


SQL = "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g"


def _register(eng, **kw):
    eng.register_table("t", _df(), time_column="ts", block_rows=512,
                       **kw)


def _reference():
    ref = Engine(EngineConfig(pipeline_depth=0))
    _register(ref)
    return ref.sql(SQL)


# ------------------------------------------------------------- basics


def test_pipelined_is_default_and_matches_serialized():
    eng = Engine()
    assert eng.config.pipeline_depth == 2
    _register(eng)
    got = eng.sql(SQL)
    rec = eng.runner.history[-1]
    assert rec["pipelined"] is True
    assert "lock_wait_ms" in rec
    pd.testing.assert_frame_equal(got, _reference())
    # the new metric series exist and saw traffic
    text = eng.metrics.render()
    assert "tpu_olap_dispatch_lock_wait_ms_count" in text
    assert "tpu_olap_pipeline_inflight" in text
    assert "tpu_olap_inflight_transfers" in text
    hist = eng.metrics.histogram("dispatch_lock_wait_ms")
    assert hist.series and next(iter(hist.series.values())).n >= 1


def test_serialized_mode_still_works():
    eng = Engine(EngineConfig(pipeline_depth=0))
    _register(eng)
    got = eng.sql(SQL)
    rec = eng.runner.history[-1]
    assert rec["pipelined"] is False
    pd.testing.assert_frame_equal(got, _reference())


# ------------------------------------------ ledger in-flight pinning


def test_ledger_pin_inflight_counts_and_never_evicts():
    """The eviction-vs-pinned-inflight-result race: a pinned in-flight
    result's bytes count toward the budget (a concurrent env build must
    evict resident COLUMNS to make room) but the pin itself is never
    evictable — the transfer is about to read it."""
    led = HbmLedger(budget_bytes=1000)
    evicted = []
    led.add(("t", "col", "a"), 400, lambda: evicted.append("a"))
    led.add(("t", "col", "b"), 400, lambda: evicted.append("b"))
    assert led.bytes_in_use == 800 and not evicted
    led.pin_inflight(("__inflight__", 1), 500)
    assert led.bytes_in_use == 1300
    assert led.inflight_bytes == 500
    # a new column add must evict the resident columns (LRU first),
    # NEVER the in-flight pin
    led.add(("t", "col", "c"), 400, lambda: evicted.append("c"))
    assert "a" in evicted
    assert led.inflight_bytes == 500  # pin survived
    led.unpin_inflight(("__inflight__", 1))
    assert led.inflight_bytes == 0
    # unpin released exactly the pinned bytes
    assert led.bytes_in_use == sum(
        n for n, _ in led._entries.values())


def test_concurrent_queries_under_tight_budget_stay_correct():
    """Engine-level race: pipelined queries against a 1-byte HBM budget
    force constant eviction while results are in flight — every thread
    still gets the exact answer."""
    eng = Engine(EngineConfig(hbm_budget_bytes=1, pipeline_depth=2))
    _register(eng)
    want = _reference()
    errs = []

    def worker():
        try:
            for _ in range(3):
                pd.testing.assert_frame_equal(eng.sql(SQL), want)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errs.append(e)

    threads = [threading.Thread(target=worker) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
    assert eng.runner._hbm_ledger.inflight_bytes == 0  # all unpinned


# --------------------------------- deadline during a stage-2 transfer


class _StallTransfer:
    """Injector that stalls the host-transfer site once."""

    stages = {"host-transfer"}

    def __init__(self, stall_s):
        self.stall_s = stall_s
        self.armed = False
        self.fired = 0

    def __call__(self, stage, attempt):
        if self.armed:
            self.fired += 1
            self.armed = False
            time.sleep(self.stall_s)


def test_deadline_expiry_during_stage2_transfer():
    """A transfer that hangs AFTER the lock was released must still
    trip the watchdog: deadline -> wedge -> fallback answers -> the
    reprobe clears the wedge and the device path serves again."""
    inj = _StallTransfer(stall_s=2.0)
    eng = Engine(EngineConfig(dispatch_retries=0, fault_injector=inj))
    _register(eng)
    want = _reference()
    eng.sql(SQL)  # warm compile outside the deadline regime
    eng.config.query_deadline_s = 0.4
    inj.armed = True
    t0 = time.perf_counter()
    got = eng.sql(SQL)  # transfer stalls -> deadline -> fallback
    assert inj.fired == 1
    assert time.perf_counter() - t0 < 10
    assert "QueryDeadlineExceeded" in eng.last_plan.fallback_reason
    assert any(h.get("deadline_exceeded") for h in eng.runner.history)
    pd.testing.assert_frame_equal(got, want)
    # recovery: reprobe clears the wedge, device path again
    eng.config.query_deadline_s = 30.0
    got2 = eng.sql(SQL)
    assert eng.last_plan.fallback_reason is None
    assert not eng.runner._wedged
    pd.testing.assert_frame_equal(got2, want)
    time.sleep(1.8)  # let the abandoned transfer thread drain


# ------------------------------- breaker trip between enqueue and complete


def test_breaker_trips_on_stage2_failure():
    """A transfer failure between enqueue and complete is a terminal
    device failure: it counts toward the breaker, and once open the
    engine serves degraded (path=fallback_breaker) without dispatch."""

    class FailTransfer:
        stages = {"host-transfer"}

        def __call__(self, stage, attempt):
            raise RuntimeError("injected transfer loss")

    eng = Engine(EngineConfig(dispatch_retries=0,
                              breaker_failure_threshold=2,
                              breaker_open_cooldown_s=30.0,
                              fault_injector=FailTransfer()))
    _register(eng)
    try:
        want = _reference()
        for _ in range(2):  # two terminal stage-2 failures trip it
            pd.testing.assert_frame_equal(eng.sql(SQL), want)
        assert eng.runner.breaker.state == "open"
        got = eng.sql(SQL)
        rec = eng.runner.history[-1]
        assert rec["path"] == "fallback_breaker"
        pd.testing.assert_frame_equal(got, want)
    finally:
        eng.runner.breaker.close()


# ------------------------------------- result cache from a stage-2 completion


def test_result_cache_populates_from_stage2_completion():
    eng = Engine(EngineConfig(result_cache_enabled=True,
                              pipeline_depth=2))
    _register(eng)
    want = _reference()
    pd.testing.assert_frame_equal(eng.sql(SQL), want)
    assert eng.runner.history[-1]["pipelined"] is True
    pd.testing.assert_frame_equal(eng.sql(SQL), want)
    rec = eng.runner.history[-1]
    assert rec["path"] == "cache" and rec["cache_tier"] == "full"


# ----------------------------------------------- pipeline occupancy bound


def test_pipeline_slot_bounds_inflight():
    ac = AdmissionController(max_inflight=8, queue_limit=8,
                             pipeline_depth=1)
    entered, release = threading.Event(), threading.Event()

    def hold():
        with ac.pipeline_slot():
            entered.set()
            release.wait(timeout=30)

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    assert entered.wait(5)
    assert ac.snapshot()["pipeline_inflight"] == 1
    # a second acquirer with an exhausted budget sheds instead of
    # queueing forever
    with pytest.raises(QueryShed) as ei:
        with ac.pipeline_slot(budget_s=0.05):
            pass
    assert ei.value.reason == "pipeline_stall"
    release.set()
    t.join(timeout=10)
    assert ac.snapshot()["pipeline_inflight"] == 0
    with ac.pipeline_slot():  # reusable after release
        assert ac.snapshot()["pipeline_inflight"] == 1
    # re-entrant per thread, like slot()
    with ac.pipeline_slot():
        with ac.pipeline_slot():
            assert ac.snapshot()["pipeline_inflight"] == 1


def test_pipeline_slot_disabled_is_noop():
    ac = AdmissionController(max_inflight=8, queue_limit=8,
                             pipeline_depth=0)
    with ac.pipeline_slot():
        assert ac.snapshot()["pipeline_inflight"] == 0


def test_reset_pipeline_reclaims_stranded_slots():
    """A deadline-abandoned dispatch thread strands its pipeline slot;
    wedge recovery calls reset_pipeline so device capacity comes back.
    The stranded holder's eventual release clamps at zero instead of
    going negative."""
    ac = AdmissionController(max_inflight=8, queue_limit=8,
                             pipeline_depth=1)
    entered, release = threading.Event(), threading.Event()

    def stranded():
        with ac.pipeline_slot():
            entered.set()
            release.wait(timeout=30)

    t = threading.Thread(target=stranded, daemon=True)
    t.start()
    assert entered.wait(5)
    # capacity gone: a budgeted waiter sheds
    with pytest.raises(QueryShed):
        with ac.pipeline_slot(budget_s=0.05):
            pass
    ac.reset_pipeline()  # wedge recovery reclaims the slot
    with ac.pipeline_slot(budget_s=0.05):
        assert ac.snapshot()["pipeline_inflight"] == 1
    release.set()  # the stranded holder finally drains: clamp, not -1
    t.join(timeout=10)
    assert ac.snapshot()["pipeline_inflight"] == 0


def test_recovery_survives_stranded_dispatch_lock():
    """An abandoned stage-1 thread holding dispatch_lock must not hang
    recovery forever: _recover_after_probe bounds its acquire in
    pipelined mode, reports failure, and succeeds once the lock
    drains."""
    eng = Engine(EngineConfig(pipeline_depth=2))
    _register(eng)
    eng.sql(SQL)
    release = threading.Event()
    held = threading.Event()

    def strand():
        eng.runner.dispatch_lock.acquire()
        held.set()
        release.wait(timeout=30)
        eng.runner.dispatch_lock.release()

    t = threading.Thread(target=strand, daemon=True)
    t.start()
    assert held.wait(5)
    t0 = time.perf_counter()
    assert eng.runner._recover_after_probe(lock_timeout_s=1.0) is False
    assert time.perf_counter() - t0 < 5  # bounded, not forever
    assert eng.runner.history[-1].get("device_probe_lock_stranded")
    release.set()
    t.join(timeout=10)
    assert eng.runner._recover_after_probe(lock_timeout_s=1.0) is True


def test_sparse_path_leaves_no_inflight_pins():
    """The sparse dispatch pins its enqueued output like every other
    device path and unpins on success AND on the over-budget raise."""
    sql = ("SELECT g, v, sum(v) AS s FROM t GROUP BY g, v "
           "ORDER BY g, v")
    eng = Engine(EngineConfig(dense_group_budget=4, pipeline_depth=2))
    _register(eng)
    got = eng.sql(sql)
    assert eng.runner.history[-1].get("sparse")
    assert eng.runner._hbm_ledger.inflight_bytes == 0
    ref = Engine(EngineConfig(pipeline_depth=0))
    _register(ref)
    pd.testing.assert_frame_equal(got, ref.sql(sql))
    # overflow path: a sparse budget too small for the present groups
    # raises (engine serves via fallback) — and still unpins
    sp = Engine(EngineConfig(dense_group_budget=1,
                             sparse_group_budget=1, pipeline_depth=2))
    _register(sp)
    out = sp.sql(sql)
    assert len(out) > 3
    assert sp.runner.history[-1]["query_type"] == "fallback"
    assert sp.runner._hbm_ledger.inflight_bytes == 0
