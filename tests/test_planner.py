"""Plan-level tests (SURVEY.md §5: 'sql -> expected query IR, no device
needed') + Engine-level parity between the device path and the pandas
fallback on identical data."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.ir.query import (GroupByQuerySpec, ScanQuerySpec,
                               TimeseriesQuerySpec, TopNQuerySpec)
from tpu_olap.utils import timeutil as tu


def build_engine(platform="device"):
    rng = np.random.default_rng(23)
    n = 6000
    t0 = tu.date_to_millis(1993, 1, 1)
    lineorder = pd.DataFrame({
        "lo_orderdate": rng.integers(0, 2000, n) + 19930000,  # date FK
        "ts": pd.to_datetime(
            t0 + rng.integers(0, 3 * 365 * 86_400_000, n), unit="ms"),
        "lo_discount": rng.integers(0, 11, n).astype(np.int64),
        "lo_quantity": rng.integers(1, 51, n).astype(np.int64),
        "lo_extendedprice": rng.integers(100, 10_000, n).astype(np.int64),
        "lo_revenue": rng.integers(100, 100_000, n).astype(np.int64),
        "lo_supplycost": rng.integers(10, 1000, n).astype(np.int64),
        "p_brand": rng.choice([f"MFGR#{i:02d}" for i in range(12)], n),
        "p_category": rng.choice(["MFGR#12", "MFGR#13", "MFGR#14"], n),
        "s_region": rng.choice(["AMERICA", "ASIA", "EUROPE"], n),
        "c_nation": rng.choice(["US", "CN", "DE", "FR"], n),
    })
    # denormalized d_year must agree with the dimension row it joins to
    lineorder["d_year"] = (1993
                           + (lineorder.lo_orderdate - 19930000) % 3
                           ).astype(np.int64)
    date_dim = pd.DataFrame({
        "d_datekey": np.arange(19930000, 19935000),
        "d_year2": 1993 + (np.arange(5000) % 3),
    })
    eng = Engine(EngineConfig(platform=platform))
    eng.register_table(
        "lineorder", lineorder, time_column="ts",
        star_schema={
            "fact": "lineorder",
            "dimensions": [{"table": "date_dim", "factKey": "lo_orderdate",
                            "dimKey": "d_datekey",
                            "columnMap": {"d_year2": "d_year"}}],
        })
    eng.register_table("date_dim", date_dim, accelerate=False)
    return eng, lineorder, date_dim


ENG, LO, DD = build_engine()


# ---------------------------------------------------------- plan assertions

def test_q11_star_join_rewrites_to_timeseries():
    sql = """SELECT sum(lo_extendedprice * lo_discount) AS revenue
             FROM lineorder, date_dim
             WHERE lo_orderdate = d_datekey AND d_year2 = 1993
               AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25"""
    plan = ENG.planner.plan(sql)
    assert plan.rewritten, plan.fallback_reason
    q = plan.query
    assert isinstance(q, TimeseriesQuerySpec)
    assert q.data_source == "lineorder"
    assert len(q.virtual_columns) == 1
    assert q.aggregations[0].to_json()["type"] == "longSum"
    # d_year2 remapped onto the denormalized fact column d_year
    assert "d_year" in q.filter.columns()


def test_year_filter_becomes_interval():
    sql = "SELECT count() AS n FROM lineorder WHERE year(ts) = 1993"
    plan = ENG.planner.plan(sql)
    assert plan.rewritten
    (iv,) = plan.query.intervals
    assert iv.start == tu.date_to_millis(1993)
    assert iv.end == tu.date_to_millis(1994)
    assert plan.query.filter is None


def test_time_literal_bounds_become_interval():
    sql = ("SELECT count() AS n FROM lineorder "
           "WHERE ts >= '1993-06-01' AND ts < '1993-09-01'")
    plan = ENG.planner.plan(sql)
    assert plan.rewritten
    (iv,) = plan.query.intervals
    assert iv.start == tu.date_to_millis(1993, 6, 1)
    assert iv.end == tu.date_to_millis(1993, 9, 1)


def test_groupby_with_year_extraction():
    sql = """SELECT d_year, year(ts) AS yr, sum(lo_revenue) AS rev
             FROM lineorder GROUP BY d_year, year(ts)"""
    plan = ENG.planner.plan(sql)
    assert plan.rewritten
    q = plan.query
    assert isinstance(q, GroupByQuerySpec)
    assert q.dimensions[0].to_json()["type"] == "default"
    assert q.dimensions[1].to_json()["extractionFn"]["format"] == "YYYY"
    assert plan.outputs[1].cast == "int"


def test_date_trunc_becomes_granularity():
    sql = """SELECT date_trunc('month', ts) AS m, count() AS n
             FROM lineorder GROUP BY date_trunc('month', ts)"""
    plan = ENG.planner.plan(sql)
    assert plan.rewritten
    q = plan.query
    assert isinstance(q, TimeseriesQuerySpec)
    assert q.granularity.to_json()["period"] == "P1M"
    assert plan.outputs[0].source == "timestamp"


def test_avg_becomes_postagg():
    plan = ENG.planner.plan(
        "SELECT avg(lo_quantity) AS aq FROM lineorder")
    assert plan.rewritten
    q = plan.query
    assert q.post_aggregations[0].to_json()["fn"] == "quotient"
    assert {a.to_json()["type"] for a in q.aggregations} == \
        {"longSum", "count"}


def test_count_distinct_becomes_cardinality():
    plan = ENG.planner.plan(
        "SELECT count(DISTINCT p_brand) AS u FROM lineorder")
    assert plan.rewritten
    assert plan.query.aggregations[0].to_json()["type"] == "cardinality"
    # and falls back when disallowed
    eng2 = Engine(EngineConfig(platform="cpu", allow_count_distinct=False))
    eng2.catalog = ENG.catalog
    from tpu_olap.planner import DruidPlanner
    eng2.planner = DruidPlanner(eng2.catalog, eng2.config)
    plan2 = eng2.planner.plan(
        "SELECT count(DISTINCT p_brand) AS u FROM lineorder")
    assert not plan2.rewritten


def test_topn_selection_and_threshold():
    sql = """SELECT p_brand, sum(lo_revenue) AS rev FROM lineorder
             GROUP BY p_brand ORDER BY rev DESC LIMIT 5"""
    plan = ENG.planner.plan(sql)
    assert isinstance(plan.query, TopNQuerySpec)
    assert plan.query.threshold == 5 and not plan.query.inverted
    # ascending -> bottom-N (inverted)
    plan2 = ENG.planner.plan(sql.replace("DESC", "ASC"))
    assert isinstance(plan2.query, TopNQuerySpec) and plan2.query.inverted
    # multi-dim group: stays groupBy
    sql3 = """SELECT p_brand, d_year, sum(lo_revenue) AS rev FROM lineorder
              GROUP BY p_brand, d_year ORDER BY rev DESC LIMIT 5"""
    plan3 = ENG.planner.plan(sql3)
    assert isinstance(plan3.query, GroupByQuerySpec)


def test_scan_plan():
    plan = ENG.planner.plan(
        "SELECT p_brand, lo_revenue FROM lineorder "
        "WHERE s_region = 'ASIA' LIMIT 7")
    assert isinstance(plan.query, ScanQuerySpec)
    assert plan.query.limit == 7


def test_fallbacks():
    # left join is not collapsible
    plan = ENG.planner.plan(
        "SELECT count() AS n FROM lineorder LEFT JOIN date_dim "
        "ON lo_orderdate = d_datekey")
    assert not plan.rewritten and "left" in plan.fallback_reason
    # join with no star edge
    plan = ENG.planner.plan(
        "SELECT count() AS n FROM lineorder, date_dim "
        "WHERE d_year = d_year2")
    assert not plan.rewritten
    # query on a non-accelerated table
    plan = ENG.planner.plan("SELECT count() AS n FROM date_dim")
    assert not plan.rewritten and "not" in plan.fallback_reason


def test_explain_shapes():
    exp = ENG.explain("SELECT count() AS n FROM lineorder")
    assert exp["rewritten"] and exp["query"]["queryType"] == "timeseries"
    exp2 = ENG.explain("SELECT count() AS n FROM date_dim")
    assert not exp2["rewritten"] and "reason" in exp2


# ------------------------------------------------------------ parity: device
# path vs pandas fallback on identical SQL (SURVEY.md §5 implication #3)

PARITY_QUERIES = [
    """SELECT sum(lo_extendedprice * lo_discount) AS revenue
       FROM lineorder, date_dim
       WHERE lo_orderdate = d_datekey AND d_year2 = 1993
         AND lo_discount BETWEEN 1 AND 3 AND lo_quantity < 25""",
    """SELECT d_year, sum(lo_revenue) AS rev, count() AS n
       FROM lineorder WHERE s_region = 'ASIA' GROUP BY d_year""",
    """SELECT p_brand, sum(lo_revenue) AS rev FROM lineorder
       WHERE p_category = 'MFGR#12' GROUP BY p_brand
       ORDER BY rev DESC LIMIT 4""",
    """SELECT year(ts) AS yr, avg(lo_quantity) AS aq
       FROM lineorder GROUP BY year(ts)""",
    """SELECT date_trunc('month', ts) AS m, count() AS n FROM lineorder
       WHERE year(ts) = 1994 GROUP BY date_trunc('month', ts)""",
    """SELECT c_nation, d_year, sum(lo_revenue - lo_supplycost) AS profit
       FROM lineorder GROUP BY c_nation, d_year
       HAVING sum(lo_revenue - lo_supplycost) > 100000""",
    """SELECT s_region, min(lo_revenue) AS mn, max(lo_revenue) AS mx
       FROM lineorder GROUP BY s_region""",
    """SELECT p_brand FROM lineorder WHERE lo_quantity = 50
       AND p_category = 'MFGR#13' LIMIT 6""",
    """SELECT DISTINCT s_region FROM lineorder""",
    """SELECT count() AS n FROM lineorder WHERE p_brand LIKE 'MFGR#0%'""",
    """SELECT count() AS n FROM lineorder
       WHERE c_nation IN ('US', 'DE') AND NOT (lo_discount = 0)""",
]


@pytest.mark.parametrize("idx", range(len(PARITY_QUERIES)))
def test_parity_device_vs_fallback(idx):
    sql = PARITY_QUERIES[idx]
    dev = ENG.sql(sql)
    assert ENG.last_plan.rewritten, ENG.last_plan.fallback_reason
    from tpu_olap.planner.fallback import execute_fallback
    fb = execute_fallback(ENG.last_plan.stmt, ENG.catalog, ENG.config)
    fb.columns = list(dev.columns)[:len(fb.columns)]
    a = dev.sort_values(list(dev.columns)).reset_index(drop=True)
    b = fb.sort_values(list(fb.columns)).reset_index(drop=True)
    assert len(a) == len(b), (sql, len(a), len(b))
    for col in a.columns:
        av, bv = a[col].to_numpy(), b[col].to_numpy()
        if av.dtype.kind in "fc" or bv.dtype.kind in "fc":
            assert np.allclose(av.astype(float), bv.astype(float),
                               rtol=1e-9, equal_nan=True), (sql, col)
        else:
            assert (av == bv).all(), (sql, col, av[:5], bv[:5])


# --- ExprUtil simplification (SURVEY.md §3.2; round 3) -------------------

def test_simplify_constant_folding():
    from tpu_olap.ir.expr import BinOp, Col, FuncCall, Lit
    from tpu_olap.planner.exprutil import simplify
    assert simplify(BinOp("+", Lit(2), Lit(3))) == Lit(5)
    assert simplify(BinOp("*", Lit(4), Lit(2.5))) == Lit(10.0)
    assert simplify(BinOp("<", Lit(1), Lit(2))) == Lit(True)
    assert simplify(BinOp("+", Col("x"), Lit(0))) == Col("x")
    assert simplify(BinOp("*", Lit(1), Col("x"))) == Col("x")
    # x*0 must NOT fold (NULL*0 is NULL)
    z = simplify(BinOp("*", Col("x"), Lit(0)))
    assert isinstance(z, BinOp)
    # NULL arithmetic propagates
    assert simplify(BinOp("+", Lit(None), Lit(3))) == Lit(None)
    # NOT NOT x -> x; casts of literals fold
    assert simplify(FuncCall("not", (FuncCall("not", (Col("b"),)),))) \
        == Col("b")
    assert simplify(FuncCall("cast_long", (Lit(3.9),))) == Lit(3)
    assert simplify(FuncCall("cast_double", (Lit("1.5"),))) == Lit(1.5)
    # boolean identities prune branches
    t = BinOp("&&", BinOp(">", Lit(2), Lit(1)), Col("p"))
    assert simplify(t) == Col("p")
    f = BinOp("||", Col("p"), BinOp(">", Lit(1), Lit(2)))
    assert simplify(f) == Col("p")


def test_simplified_where_enables_rewrite():
    """A tautological conjunct (1 < 2) would previously force fallback
    as an unsupported literal predicate; simplification prunes it."""
    plan = ENG.planner.plan(
        "SELECT p_brand, sum(lo_revenue) AS s FROM lineorder "
        "WHERE 1 < 2 AND lo_quantity > 0 GROUP BY p_brand")
    assert plan.rewritten, plan.fallback_reason


def test_simplify_review_regressions():
    from tpu_olap.ir.expr import BinOp, Col, Lit
    from tpu_olap.planner.exprutil import simplify
    # non-numeric '/' literals must not crash planning
    assert isinstance(simplify(BinOp("/", Lit("a"), Lit(2))), BinOp)
    # float/bool identity elements must NOT fold (dtype coercion)
    assert isinstance(simplify(BinOp("+", Col("q"), Lit(0.0))), BinOp)
    assert isinstance(simplify(BinOp("*", Col("q"), Lit(1.0))), BinOp)
    assert isinstance(simplify(BinOp("*", Col("q"), Lit(True))), BinOp)
    # standalone tautological WHERE is dropped -> still rewrites
    plan = ENG.planner.plan(
        "SELECT p_brand, sum(lo_revenue) AS s FROM lineorder "
        "WHERE 1 < 2 GROUP BY p_brand")
    assert plan.rewritten, plan.fallback_reason
    assert plan.stmt.where is None


def test_group_by_integer_expression_rewrites():
    """GROUP BY <integer expr> lowers as a virtual numeric dimension
    (histogram bucketing) with numeric ORDER BY semantics."""
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.bench.parity import assert_frame_parity
    from tpu_olap.executor import EngineConfig
    from tpu_olap.planner.fallback import execute_fallback
    rng = np.random.default_rng(4)
    n = 4000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-02-01"),
        "g": rng.choice(["a", "b"], n),
        "v": rng.integers(0, 120, n).astype(np.int64),
    })
    eng = Engine(EngineConfig(fallback_on_device_failure=False))
    eng.register_table("t", df, time_column="ts")
    for sql in (
        "SELECT v + 1 AS w, count(*) AS n FROM t GROUP BY v + 1 "
        "ORDER BY w LIMIT 7",
        "SELECT g, v - 60 AS c, sum(v) AS s FROM t GROUP BY g, v - 60 "
        "ORDER BY g, c LIMIT 9",
    ):
        dev = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert_frame_parity(dev, fb, ordered=True)
    # float-typed expressions reject into the fallback, still answered
    r = eng.sql("SELECT v / 10 AS d, count(*) AS n FROM t GROUP BY v / 10")
    assert not eng.last_plan.rewritten
    assert len(r) > 0


def test_group_by_modulo_and_modulo_sum():
    """Floored-modulo expressions are integer-bounded ([0, m-1] for a
    positive constant modulus) and ride the device path both as a
    grouping dimension and as a Pallas-eligible sum input."""
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.bench.parity import assert_frame_parity
    from tpu_olap.executor import EngineConfig
    from tpu_olap.planner.fallback import execute_fallback
    rng = np.random.default_rng(6)
    n = 3000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-03-01"),
        "g": rng.choice(["a", "b"], n),
        "v": rng.integers(-80, 200, n).astype(np.int64),  # negatives too
    })
    eng = Engine(EngineConfig(fallback_on_device_failure=False))
    eng.register_table("t", df, time_column="ts")
    for sql in (
        "SELECT v % 7 AS m, count(*) AS n FROM t GROUP BY v % 7 "
        "ORDER BY m",
        "SELECT g, sum(v % 10) AS s FROM t GROUP BY g ORDER BY g",
    ):
        dev = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert_frame_parity(dev, fb, ordered=True)


def test_virtual_numeric_dim_with_nulls():
    """Null inputs to an expression dimension land in the null group on
    BOTH paths (device slot 0 -> None label; pandas NA group)."""
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.bench.parity import assert_frame_parity
    from tpu_olap.executor import EngineConfig
    from tpu_olap.planner.fallback import execute_fallback
    rng = np.random.default_rng(8)
    n = 2000
    v = rng.integers(0, 40, n).astype(np.float64)
    v[rng.random(n) < 0.1] = np.nan
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-04-01"),
        "v": pd.array(v, dtype="Int64"),
    })
    eng = Engine(EngineConfig(fallback_on_device_failure=False))
    eng.register_table("t", df, time_column="ts")
    for sql in (
        "SELECT v + 1 AS w, count(*) AS n FROM t GROUP BY v + 1 "
        "ORDER BY w",
        "SELECT v % 7 AS m, count(*) AS n FROM t GROUP BY v % 7 "
        "ORDER BY m",
    ):
        dev = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert_frame_parity(dev, fb, ordered=True)


def test_having_over_time_bucket_group():
    """GROUP BY date_trunc(...) HAVING ... must not lower to a
    timeseries query (which has no having clause — the filter would be
    silently dropped; fuzz seed 1300)."""
    import numpy as np
    import pandas as pd

    from tpu_olap import Engine
    from tpu_olap.bench.parity import assert_frame_parity
    from tpu_olap.planner.fallback import execute_fallback
    rng = np.random.default_rng(9)
    n = 3000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 40, n), unit="s"),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    eng = Engine()
    eng.register_table("t", df, time_column="ts")
    # the filtered sum is 0 for most days, so a dropped HAVING is visible
    sql = ("SELECT date_trunc('day', ts) AS d, "
           "sum(v) FILTER (WHERE v > 98) AS hi FROM t "
           "GROUP BY date_trunc('day', ts) HAVING hi > 0")
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten
    assert eng.planner.plan(sql).query.query_type == "groupBy"
    assert (dev["hi"] > 0).all()
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    assert_frame_parity(dev, fb)
