"""Shared-scan batch executor (executor.batch) + PR-4 satellite fixes.

Parity contract: every query in a batch returns EXACTLY what the
sequential path returns for it — the fused pass reads each segment
window once, but per-leg masks add only exact zeros, so results stay
bitwise identical on the jit platform (the numpy platform's chunked
merge may reorder float addition; see docs/BATCH_EXECUTION.md).
"""

import threading
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig


@pytest.fixture(scope="module")
def frame():
    rng = np.random.default_rng(19)
    rows = 30_000
    return pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 120, rows), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(16)], rows),
        "h": rng.choice(["a", "b", "c"], rows),
        "v": rng.integers(0, 1000, rows).astype(np.int64),
        "w": rng.normal(size=rows),
    })


@pytest.fixture(scope="module")
def eng(frame):
    e = Engine()
    e.register_table("t", frame, time_column="ts", block_rows=1 << 12)
    return e


# a mixed dashboard: grouped/ungrouped, HAVING, ORDER/LIMIT (topN
# shape), post-aggs (avg), time bucketing, interval filters, duplicates
BATCH = [
    "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g",
    "SELECT h, avg(w) AS m, max(v) AS mx FROM t WHERE v > 500 "
    "GROUP BY h ORDER BY h",
    "SELECT sum(v) AS s, count(*) AS n FROM t WHERE h = 'a'",
    "SELECT g, sum(v) AS s FROM t GROUP BY g HAVING sum(v) > 100000 "
    "ORDER BY s DESC LIMIT 3",
    "SELECT month(ts) AS m, sum(v) AS s FROM t GROUP BY month(ts) "
    "ORDER BY m",
    "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY s DESC LIMIT 5",
    "SELECT sum(v) AS s FROM t "
    "WHERE ts < TIMESTAMP '2024-02-01 00:00:00'",
    "SELECT g, count(*) AS n FROM t "
    "WHERE ts >= TIMESTAMP '2030-01-01 00:00:00' GROUP BY g",
    # duplicates: one physical scan must serve every copy
    "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g",
    "SELECT sum(v) AS s, count(*) AS n FROM t WHERE h = 'a'",
]


def test_batch_parity_bitwise(eng):
    seq = [eng.sql(q) for q in BATCH]          # warm + oracle
    bat = eng.sql_batch(BATCH)
    for i, (a, b) in enumerate(zip(seq, bat)):
        assert a.equals(b), f"batch leg {i} diverged from sequential"


def test_batch_metrics_shared_scan_counted_once(eng):
    h0 = len(eng.history)
    eng.sql_batch(BATCH)
    hist = eng.history[h0:]
    # dedup fan-out records are annotated COPIES of the leg's metrics —
    # the physical pass is only the non-dedup records
    fused = [m for m in hist if m.get("batch_legs", 0) >= 2
             and not m.get("batch_dedup")]
    assert fused, "no fused multi-leg dispatch was recorded"
    by_id = {}
    for m in fused:
        by_id.setdefault(m["batch_id"], []).append(m)
    for recs in by_id.values():
        # scan_ms_shared is the ONE shared pass: identical on every leg
        # of the batch (count it once per batch_id); agg_ms is the
        # per-leg share and never exceeds the shared wall
        shared = {m["scan_ms_shared"] for m in recs}
        assert len(shared) == 1
        assert all(m["agg_ms"] > 0 for m in recs)
        assert sum(m["agg_ms"] for m in recs) <= recs[0][
            "scan_ms_shared"] * 1.01
        assert len(recs) == recs[0]["batch_legs"]


def test_batch_dedupe_one_scan_many_queries(eng):
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
    ref = eng.sql(sql)
    h0 = len(eng.history)
    out = eng.sql_batch([sql] * 4)
    assert all(f.equals(ref) for f in out)
    hist = eng.history[h0:]
    scans = [m for m in hist if m.get("batch_legs") == 1
             and m.get("batch_size") == 4 and not m.get("batch_dedup")]
    dups = [m for m in hist if m.get("batch_dedup")]
    assert len(scans) == 1, "identical queries must share ONE scan"
    assert len(dups) == 3
    assert scans[0]["scan_ms_shared"] >= 0
    assert scans[0]["agg_ms"] >= 0


def test_batch_mixed_with_unfusable_legs(eng):
    # a raw scan (mask-kind plan) rides the same submission but runs
    # through the single-query path; agg legs still fuse around it
    mixed = [
        "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g",
        "SELECT g, v FROM t WHERE v > 995 LIMIT 7",
        "SELECT h, count(*) AS n FROM t GROUP BY h ORDER BY h",
    ]
    seq = [eng.sql(q) for q in mixed]
    bat = eng.sql_batch(mixed)
    for a, b in zip(seq, bat):
        assert a.equals(b)


def test_runner_execute_batch_boxes_failures_per_leg(eng):
    from tpu_olap.ir.aggregations import SumAggregation
    from tpu_olap.ir.dimensions import DefaultDimensionSpec
    from tpu_olap.ir.query import GroupByQuerySpec
    from tpu_olap.kernels.groupby import UnsupportedAggregation

    table = eng.catalog.get("t").segments
    good = GroupByQuerySpec(
        data_source="t", intervals=(),
        dimensions=(DefaultDimensionSpec("g"),),
        aggregations=(SumAggregation("s", "v"),))
    bad = GroupByQuerySpec(
        data_source="t", intervals=(),
        dimensions=(DefaultDimensionSpec("g"),),
        aggregations=(SumAggregation("s", "no_such_col"),))
    boxed = eng.runner._execute_batch_boxed([good, bad, good], table)
    assert isinstance(boxed[1], UnsupportedAggregation)
    assert boxed[0].rows == boxed[2].rows and boxed[0].rows
    with pytest.raises(UnsupportedAggregation):
        eng.runner.execute_batch([good, bad], table)


def test_compile_predicates_shared_env(eng):
    """Kernel-level multi-predicate evaluation: N filters compiled
    against ONE ConstPool evaluate over one shared column env."""
    from tpu_olap.ir.filters import BoundFilter, SelectorFilter
    from tpu_olap.kernels.filtereval import (ConstPool, compile_predicates,
                                             eval_predicates)

    table = eng.catalog.get("t").segments
    pool = ConstPool()
    fns = compile_predicates(
        [SelectorFilter("g", "g1"),
         BoundFilter("v", lower="500", ordering="numeric"),
         None],
        table, pool)
    seg = table.segments[0]
    env = {"cols": {"g": seg.columns["g"], "v": seg.columns["v"]},
           "nulls": {}}
    masks = eval_predicates(fns, env, pool.consts)
    n = seg.meta.n_valid
    g_vals = table.dictionaries["g"].decode(seg.columns["g"][:n])
    assert masks[0][:n].sum() == (g_vals == "g1").sum()
    assert masks[1][:n].sum() == (seg.columns["v"][:n] >= 500).sum()
    assert masks[2] is None


def test_group_reduce_batch_matches_single_legs(rng):
    from tpu_olap.kernels.groupby import (AggPlan, group_reduce,
                                          group_reduce_batch)
    n = 4096
    env = {"cols": {"x": rng.integers(0, 100, n).astype(np.int64)},
           "nulls": {}}
    legs = []
    for k in (4, 7):
        key = rng.integers(0, k, n).astype(np.int32)
        mask = rng.random(n) < 0.8
        plans = [AggPlan("s", "sum", ("x",), np.int64)]
        legs.append((key, mask, env, plans, k))
    batch = group_reduce_batch(legs, [{}, {}])
    for leg, got in zip(legs, batch):
        key, mask, e, plans, k = leg
        one = group_reduce(key, mask, e, plans, k, {})
        for name in one:
            np.testing.assert_array_equal(one[name], got[name])


def test_batch_numpy_platform_attribution_and_parity(frame):
    """The numpy platform's chunked shared scan fans chunks over
    threads, so raw per-leg CPU times can sum past the shared wall —
    attribution must rescale so sum(agg_ms) <= scan_ms_shared (the
    documented invariant) — and integer aggregates must stay exact
    under the chunk-merge reordering."""
    eng = Engine(EngineConfig(platform="cpu", batch_cpu_threads=4,
                              batch_chunk_segments=2))
    eng.register_table("t", frame, time_column="ts", block_rows=1 << 12)
    sqls = [
        "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g "
        "ORDER BY g",
        "SELECT h, count(*) AS n FROM t GROUP BY h ORDER BY h",
        "SELECT sum(v) AS s, count(*) AS n FROM t WHERE h = 'a'",
    ]
    seq = [eng.sql(q) for q in sqls]
    h0 = len(eng.history)
    bat = eng.sql_batch(sqls)
    for a, b in zip(seq, bat):
        assert a.equals(b)
    fused = [m for m in eng.history[h0:] if m.get("batch_legs", 0) >= 2]
    assert fused, "no fused dispatch on the numpy platform"
    assert sum(m["agg_ms"] for m in fused) \
        <= fused[0]["scan_ms_shared"] * 1.01


def test_sql_batch_propagates_interrupt_instead_of_retrying(eng,
                                                            monkeypatch):
    """run_batch boxes BaseException per leg so the Coalescer can fan
    failures out to their own callers — but sql_batch must NOT treat a
    boxed KeyboardInterrupt/SystemExit as a retryable device failure:
    a cancel mid-dispatch aborts the submission, it does not silently
    re-run every leg through the single-query path (double work)."""
    single_runs = []
    monkeypatch.setattr(
        eng.runner, "_execute_batch_boxed",
        lambda queries, table, query_ids=None:
        [KeyboardInterrupt()] * len(queries))
    real = eng._execute_plan
    monkeypatch.setattr(
        eng, "_execute_plan",
        lambda plan: single_runs.append(plan) or real(plan))
    with pytest.raises(KeyboardInterrupt):
        eng.sql_batch([BATCH[0], BATCH[1]])
    assert not single_runs, "interrupt was retried on the single path"


def test_coalesced_path_honors_query_deadline(frame):
    """query_deadline_s must bound the coalesced/batch path exactly like
    the single-query path: a hung dispatch raises QueryDeadlineExceeded
    to the caller within ~the deadline (not never), the engine falls
    back to pandas ('never an error'), and the wedged device is
    reprobed — not trusted — on the next dispatch."""
    eng = Engine(EngineConfig(batch_window_ms=10.0))
    eng.register_table("t", frame, time_column="ts",
                       block_rows=1 << 12)
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
    want = eng.sql(sql)  # warm (compile) BEFORE arming the deadline

    armed = {"hang": True}

    def injector(stage, attempt):
        if stage == "dispatch" and armed.pop("hang", False):
            time.sleep(30)

    eng.config.query_deadline_s = 1.0
    eng.config.fault_injector = injector
    t0 = time.perf_counter()
    got = eng.sql(sql)   # deadline fires -> pandas fallback
    dt = time.perf_counter() - t0
    assert dt < 15, "coalesced caller hung past the deadline"
    assert got["g"].tolist() == want["g"].tolist()
    assert got["s"].tolist() == want["s"].tolist()
    assert any(m.get("deadline_exceeded") for m in eng.runner.history)
    # device recovers: the reprobe clears the wedge and the same query
    # rides the device path again
    again = eng.sql(sql)
    assert again.equals(want)


def test_coalescer_leader_interrupt_does_not_strand_followers():
    """An async exception in the leader (KeyboardInterrupt mid-window)
    must still reset the collecting flag, drain the queue, and wake
    every follower — otherwise the coalescer wedges for the process
    lifetime (every later agg query enqueues behind a dead leader)."""
    from tpu_olap.executor.batch import Coalescer

    class StubRunner:
        dispatch_lock = threading.RLock()

    co = Coalescer(StubRunner(), 0.25)
    real_sleep = time.sleep
    out = {}

    def boom(s):
        if s == 0.25:        # the leader's window sleep
            real_sleep(0.1)  # let the follower enqueue first
            raise KeyboardInterrupt
        real_sleep(s)

    def leader():
        try:
            co.submit("q1", "t")
        except BaseException as e:  # noqa: BLE001 — inspected below
            out["leader"] = e

    def follower():
        try:
            out["follower"] = co.submit("q2", "t")
        except BaseException as e:  # noqa: BLE001 — inspected below
            out["follower"] = e

    time.sleep = boom
    try:
        tl = threading.Thread(target=leader)
        tl.start()
        real_sleep(0.02)
        tf = threading.Thread(target=follower)
        tf.start()
        tl.join(timeout=10)
        tf.join(timeout=10)
    finally:
        time.sleep = real_sleep
    assert not tf.is_alive(), "follower stranded by the dead leader"
    assert isinstance(out["leader"], KeyboardInterrupt)
    assert isinstance(out["follower"], RuntimeError)
    # the coalescer is reusable: the next caller becomes a fresh leader
    assert co._collecting is False and co._queue == []


# ------------------------------------------------------ satellite fixes


def test_fallback_parallel_timeout_default_and_scale():
    from tpu_olap.planner.fallback import _parallel_timeout_s
    cfg = EngineConfig()
    # ADVICE r5: a deadlocked fork pool must trigger the sequential
    # retry interactively, not after 15 minutes
    assert cfg.fallback_parallel_timeout_s == 45.0

    class E:
        parquet_rows = 0
    e = E()
    assert _parallel_timeout_s(cfg, e) == 45.0
    e.parquet_rows = 200_000_000
    assert _parallel_timeout_s(cfg, e) == 45.0
    e.parquet_rows = 2_000_000_000   # scan-size scaling kicks in
    assert _parallel_timeout_s(cfg, e) == pytest.approx(450.0)
    assert _parallel_timeout_s(cfg, None) == 45.0


def test_worker_pair_cap_divided_across_pool():
    # the per-worker caps must SUM to the configured cap: with the full
    # cap per worker, in-flight distinct pairs could transiently reach
    # workers x pair_cap before the parent-side merge re-checks
    from tpu_olap.planner import fallback as fb
    src = open(fb.__file__).read()
    assert "pair_cap // workers" in src
    import ast
    tree = ast.parse(src)
    fn = next(n for n in ast.walk(tree) if isinstance(n, ast.FunctionDef)
              and n.name == "_parallel_chunk_partials")
    assert "pair_cap // workers" in ast.get_source_segment(src, fn), \
        "the division must happen where the fork ctx is built"


def test_bool_object_columns_survive_null_normalization():
    from tpu_olap.planner.fallback import _coerce_nullable_numeric
    df = pd.DataFrame({
        "flag": pd.Series([True, None, False], dtype=object),
        "npflag": pd.Series([np.bool_(True), None, np.bool_(False)],
                            dtype=object),
        "m": pd.Series([1, None, 3], dtype=object),
    })
    out = _coerce_nullable_numeric(df)
    # nullable numeric -> float64 + NaN (the device-frame contract) ...
    assert out["m"].dtype == np.float64
    assert np.isnan(out["m"].iloc[1])
    # ... but nullable BOOLEAN stays boolean (bool is an int subclass;
    # it must not silently coerce to 1.0/0.0)
    assert out["flag"].dtype == object
    assert out["flag"].iloc[0] is True and out["flag"].iloc[2] is False
    assert out["npflag"].dtype == object


def test_grouping_sets_union_absent_keys_are_nan(frame, eng):
    sql = ("SELECT g, h, sum(v) AS s FROM t GROUP BY ROLLUP(g, h) "
           "ORDER BY g, h")
    got = eng.sql(sql)
    plan = eng.last_plan
    # the device union path served it (legs, not the whole-statement
    # fallback) — otherwise this test is not exercising the reattachment
    assert getattr(plan, "grouping_legs", None)
    assert plan.fallback_reason is None
    # absent group keys reattach as np.nan like the whole-statement
    # fallback, never as object None
    assert not any(v is None for v in got["g"])
    assert not any(v is None for v in got["h"])
    grand = got[got["g"].isna() & got["h"].isna()]
    assert len(grand) == 1
    assert int(grand["s"].iloc[0]) == int(frame["v"].sum())
    # oracle: identical statement through the pure pandas fallback
    e2 = Engine()
    e2.register_table("t", frame, time_column="ts", accelerate=False)
    want = e2.sql(sql)
    assert got["s"].tolist() == want["s"].tolist()
    assert [x if not pd.isna(x) else None for x in got["g"]] \
        == [x if not pd.isna(x) else None for x in want["g"]]
