"""SQL shapes outside the rewrite subset (UNION, derived tables,
subqueries) — VERDICT round-2 missing #4: the reference ran full Spark
SQL, so every parseable query had SOME execution path; these now parse
and execute on the fallback interpreter instead of raising SqlError."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine


def _df(n=3000, seed=17):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2023-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 90, n), unit="s"),
        "g": rng.choice(["a", "b", "c", "d"], n),
        "city": rng.choice([f"c{i}" for i in range(6)], n),
        "v": rng.integers(0, 500, n).astype(np.int64),
    })


def _engine():
    eng = Engine()
    df = _df()
    eng.register_table("t", df, time_column="ts")
    eng.register_table("dim", pd.DataFrame(
        {"d_city": [f"c{i}" for i in range(6)],
         "d_zone": ["west" if i < 3 else "east" for i in range(6)]}),
        accelerate=False)
    return eng, df


def test_union_all():
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(v) AS s FROM t WHERE g = 'a' GROUP BY g "
                  "UNION ALL "
                  "SELECT g, sum(v) AS s FROM t WHERE g = 'b' GROUP BY g "
                  "ORDER BY g")
    assert eng.last_plan.fallback_reason.startswith("UNION")
    assert list(got["g"]) == ["a", "b"]
    assert got["s"][0] == df[df.g == "a"].v.sum()
    assert got["s"][1] == df[df.g == "b"].v.sum()


def test_union_distinct_dedupes():
    eng, df = _engine()
    got = eng.sql("SELECT g FROM t UNION SELECT g FROM t ORDER BY g")
    assert list(got["g"]) == sorted(df.g.unique())


def test_union_limit_applies_to_whole():
    eng, _ = _engine()
    got = eng.sql("SELECT g FROM t UNION SELECT city FROM t "
                  "ORDER BY g LIMIT 3")
    assert len(got) == 3


def test_derived_table():
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(s) AS total FROM "
                  "(SELECT g, city, sum(v) AS s FROM t GROUP BY g, city) "
                  "sub GROUP BY g ORDER BY g")
    assert "derived table" in eng.last_plan.fallback_reason
    expect = df.groupby("g").v.sum()
    for _, row in got.iterrows():
        assert row["total"] == expect[row["g"]]


def test_in_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t WHERE city IN "
                  "(SELECT d_city FROM dim WHERE d_zone = 'west')")
    # round 4: uncorrelated IN subqueries inline and the outer query
    # pushes down (the reference's Spark-runs-the-subquery split)
    assert eng.last_plan.rewritten
    west = {f"c{i}" for i in range(3)}
    assert got["n"][0] == int(df.city.isin(west).sum())


def test_not_in_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t WHERE city NOT IN "
                  "(SELECT d_city FROM dim WHERE d_zone = 'west')")
    west = {f"c{i}" for i in range(3)}
    assert got["n"][0] == int((~df.city.isin(west)).sum())


def test_scalar_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(v) AS s FROM t "
                  "WHERE v > (SELECT avg(v) FROM t) GROUP BY g ORDER BY g")
    mean = df.v.sum() / len(df)
    sub = df[df.v > mean]
    expect = sub.groupby("g").v.sum()
    for _, row in got.iterrows():
        assert row["s"] == expect[row["g"]]


def test_subquery_free_queries_still_rewrite():
    eng, _ = _engine()
    eng.sql("SELECT g, sum(v) AS s FROM t GROUP BY g")
    assert eng.last_plan.rewritten


def test_explain_union_does_not_crash():
    eng, _ = _engine()
    out = eng.explain("SELECT g FROM t UNION ALL SELECT g FROM t")
    assert out["rewritten"] is False
    assert "UNION" in out["reason"]


# --- lookup extraction, SEARCH verb, paged select (VERDICT r2 missing #6)

def test_lookup_extraction_sql_both_paths():
    eng, df = _engine()
    eng.register_lookup("zone", {f"c{i}": ("west" if i < 3 else "east")
                                 for i in range(6)})
    sql = ("SELECT lookup(city, 'zone') AS z, sum(v) AS s FROM t "
           "GROUP BY lookup(city, 'zone') ORDER BY z")
    got = eng.sql(sql)
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    zmap = {f"c{i}": ("west" if i < 3 else "east") for i in range(6)}
    expect = df.assign(z=df.city.map(zmap)).groupby("z").v.sum()
    for _, row in got.iterrows():
        assert row["s"] == expect[row["z"]]
    # fallback path agrees
    from tpu_olap.planner.fallback import execute_fallback
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    pd.testing.assert_frame_equal(got, fb, check_dtype=False)


def test_lookup_missing_value_is_null():
    eng, df = _engine()
    eng.register_lookup("partial", {"c0": "zero"})
    got = eng.sql("SELECT lookup(city, 'partial') AS z, count(*) AS n "
                  "FROM t GROUP BY lookup(city, 'partial') ORDER BY z")
    assert eng.last_plan.rewritten
    zs = list(got["z"])
    assert "zero" in zs and len(zs) == 2
    assert any(pd.isna(z) for z in zs)  # unmapped values -> null group


def test_unknown_lookup_is_a_clear_error():
    """An unregistered lookup name is a USER error (Druid errors on it
    too) — it must surface legibly, not as a device crash."""
    import pytest as _pytest

    from tpu_olap.planner.fallback import FallbackError
    eng, _ = _engine()
    with _pytest.raises(FallbackError, match="unknown lookup"):
        eng.sql("SELECT lookup(city, 'nope') AS z FROM t LIMIT 1")
    assert not eng.last_plan.rewritten  # planner declined first


def test_search_verb():
    eng, df = _engine()
    got = eng.sql("SEARCH DRUID DATASOURCE t FOR 'c1' IN city, g LIMIT 10")
    assert list(got.columns) == ["dimension", "value", "count"]
    assert set(got["value"]) == {"c1"}
    assert int(got["count"][0]) == int((df.city == "c1").sum())


def test_select_page_api():
    eng, df = _engine()
    page1, off1 = eng.select_page("t", columns=("city",), page_size=7)
    assert len(page1) == 7 and off1 == 7
    page2, off2 = eng.select_page("t", columns=("city",), page_size=7,
                                  offset=off1)
    assert len(page2) == 7 and off2 == 14
    assert page1 != page2


def test_empty_scalar_subquery_matches_no_rows():
    """SQL NULL comparison semantics: an empty scalar subquery inlines
    as NULL and the comparison matches nothing (was a TypeError)."""
    eng, _ = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE v > (SELECT max(v) FROM t WHERE v > 99999)")
    assert got["n"][0] == 0


def test_in_subquery_packs_values():
    """Resolution packs IN-subquery values into ONE literal node."""
    from tpu_olap.ir.expr import FuncCall
    from tpu_olap.planner.fallback import _resolve_subqueries
    eng, df = _engine()
    stmt = eng.planner.plan(
        "SELECT count(*) AS n FROM t WHERE city IN "
        "(SELECT d_city FROM dim)").stmt
    resolved = _resolve_subqueries(stmt, eng.catalog, eng.config)
    calls = []

    def walk(e):
        if isinstance(e, FuncCall):
            calls.append(e.name)
            for a in e.args:
                walk(a)
    walk(resolved.where)
    assert "in_list_packed" in calls


# --- window functions (fallback-only; round 3) ---------------------------

def test_row_number_over_partition():
    eng, df = _engine()
    got = eng.sql("SELECT g, v, row_number() OVER "
                  "(PARTITION BY g ORDER BY v DESC, ts) AS rn FROM t")
    assert "window function" in eng.last_plan.fallback_reason
    # each partition's rn is a permutation of 1..n
    for gname, sub in got.groupby("g"):
        assert sorted(sub["rn"]) == list(range(1, len(sub) + 1))
    # and the max-v row in each partition has rn == 1
    for gname, sub in got.groupby("g"):
        assert sub.loc[sub["v"].idxmax(), "rn"] == 1


def test_rank_and_dense_rank():
    eng, df = _engine()
    got = eng.sql("SELECT g, v, rank() OVER (PARTITION BY g ORDER BY v) "
                  "AS r, dense_rank() OVER (PARTITION BY g ORDER BY v) "
                  "AS dr FROM t")
    for gname, sub in got.groupby("g"):
        vals = df[df.g == gname].v
        expect_r = vals.rank(method="min").astype(int)
        expect_dr = vals.rank(method="dense").astype(int)
        sub = sub.sort_values("v").reset_index(drop=True)
        assert list(sub["r"]) == sorted(expect_r)
        assert list(sub["dr"]) == sorted(expect_dr)


def test_window_aggregate_whole_partition():
    eng, df = _engine()
    got = eng.sql("SELECT g, v, sum(v) OVER (PARTITION BY g) AS gs, "
                  "avg(v) OVER (PARTITION BY g) AS ga FROM t")
    expect = df.groupby("g").v.agg(["sum", "mean"])
    for gname, sub in got.groupby("g"):
        assert (sub["gs"] == expect.loc[gname, "sum"]).all()
        assert np.allclose(sub["ga"], expect.loc[gname, "mean"])


def test_running_sum_window():
    eng, df = _engine()
    got = eng.sql("SELECT g, ts, v, sum(v) OVER "
                  "(PARTITION BY g ORDER BY ts) AS run FROM t")
    ref = df.sort_values("ts", kind="stable")
    ref = ref.assign(run=ref.groupby("g").v.cumsum())
    a = got.sort_values(["g", "ts"]).reset_index(drop=True)
    b = ref[["g", "ts", "v", "run"]].sort_values(["g", "ts"]) \
        .reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)


def test_window_over_derived_grouped():
    """Window over a grouped derived table — ranking group totals."""
    eng, df = _engine()
    got = eng.sql(
        "SELECT g, s, rank() OVER (ORDER BY s DESC) AS r FROM "
        "(SELECT g, sum(v) AS s FROM t GROUP BY g) sub ORDER BY r")
    totals = df.groupby("g").v.sum().sort_values(ascending=False)
    assert list(got["g"]) == list(totals.index)
    assert list(got["r"]) == [1, 2, 3, 4]


def test_window_null_partition_and_values():
    """NULL partition keys form their own partition; running aggregates
    skip NULL values (carry at NULL rows, NULL while the frame is
    empty); avg divides by the non-null count."""
    eng = Engine()
    df = pd.DataFrame({
        "ts": pd.to_datetime("2023-01-01") + pd.to_timedelta(
            np.arange(6), unit="h"),
        "g": ["a", "a", "a", None, None, "b"],
        "v": pd.array([1, None, 3, 5, None, None], dtype="Int64"),
    })
    eng.register_table("w", df, time_column="ts")
    got = eng.sql(
        "SELECT g, v, row_number() OVER (PARTITION BY g ORDER BY ts) "
        "AS rn, sum(v) OVER (PARTITION BY g ORDER BY ts) AS rs, "
        "avg(v) OVER (PARTITION BY g ORDER BY ts) AS ra FROM w")
    assert list(got["rn"]) == [1, 2, 3, 1, 2, 1]
    rs = list(got["rs"])
    assert rs[0] == 1 and rs[1] == 1 and rs[2] == 4  # carry at NULL
    assert rs[3] == 5 and rs[4] == 5
    assert pd.isna(rs[5])  # empty frame so far -> NULL
    ra = list(got["ra"])
    assert ra[0] == 1.0 and ra[1] == 1.0 and ra[2] == 2.0


def test_window_over_chunked_table_refuses_clearly(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq
    import pytest as _pytest

    from tpu_olap.executor import EngineConfig
    from tpu_olap.planner.fallback import FallbackError, execute_fallback
    df = _df(2000)
    p = str(tmp_path / "w.parquet")
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), p)
    eng = Engine(EngineConfig(fallback_chunk_rows=100))
    eng.register_table("t", p, time_column="ts")
    stmt = eng.planner.plan(
        "SELECT g, row_number() OVER (PARTITION BY g ORDER BY v) AS rn "
        "FROM t").stmt
    with _pytest.raises(FallbackError, match="whole partition"):
        execute_fallback(stmt, eng.catalog, eng.config)


def test_subquery_inside_window_spec():
    eng, df = _engine()
    got = eng.sql("SELECT g, v, rank() OVER "
                  "(ORDER BY v - (SELECT min(v) FROM t)) AS r FROM t")
    assert int(got.loc[got["v"].idxmin(), "r"]) == 1


def test_cte_basic():
    eng, df = _engine()
    got = eng.sql("WITH x AS (SELECT g, sum(v) AS s FROM t GROUP BY g) "
                  "SELECT g, s FROM x WHERE s > 0 ORDER BY g")
    want = df.groupby("g", as_index=False)["v"].sum() \
             .rename(columns={"v": "s"}).sort_values("g", ignore_index=True)
    pd.testing.assert_frame_equal(got, want)


def test_cte_chained():
    """A later CTE may reference an earlier one."""
    eng, df = _engine()
    got = eng.sql(
        "WITH base AS (SELECT g, v FROM t WHERE v >= 100), "
        "     agg AS (SELECT g, count(*) AS n FROM base GROUP BY g) "
        "SELECT g, n FROM agg ORDER BY g")
    want = (df[df.v >= 100].groupby("g", as_index=False).size()
            .rename(columns={"size": "n"}).sort_values("g",
                                                       ignore_index=True))
    want["n"] = want["n"].astype("int64")
    pd.testing.assert_frame_equal(got, want)


def test_cte_referenced_twice():
    eng, df = _engine()
    got = eng.sql(
        "WITH x AS (SELECT g, sum(v) AS s FROM t GROUP BY g) "
        "SELECT g, s FROM x WHERE s >= (SELECT max(s) FROM x) ORDER BY g")
    sums = df.groupby("g")["v"].sum()
    assert got["g"].tolist() == [sums.idxmax()]


def test_cte_in_join_executes():
    """A CTE in JOIN position inlines as a derived join (round 4;
    previously a legible rejection). Disjoint column names keep
    qualifier stripping sound."""
    eng, df = _engine()
    got = eng.sql("WITH x AS (SELECT g AS jg, count(*) AS c FROM t "
                  "GROUP BY g) "
                  "SELECT g, c FROM t JOIN x ON g = jg "
                  "GROUP BY g, c ORDER BY g")
    cnt = df.groupby("g").size()
    assert list(got["g"]) == sorted(cnt.index)
    assert [int(x) for x in got["c"]] == \
        [int(cnt[g]) for g in sorted(cnt.index)]


def test_group_by_ordinal():
    eng, df = _engine()
    got = eng.sql("SELECT g, count(*) AS n FROM t GROUP BY 1 ORDER BY g")
    want = eng.sql("SELECT g, count(*) AS n FROM t GROUP BY g ORDER BY g")
    pd.testing.assert_frame_equal(got, want)


def test_order_by_ordinal():
    """ORDER BY 2 sorts by the 2nd projection, not by the constant 2."""
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY 2")
    assert got["s"].is_monotonic_increasing
    got = eng.sql("SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY 2 DESC")
    assert got["s"].is_monotonic_decreasing


def test_ordinal_out_of_range():
    from tpu_olap.planner.sqlparse import SqlError
    eng, _ = _engine()
    with pytest.raises(SqlError, match="ordinal 7 out of range"):
        eng.sql("SELECT g FROM t ORDER BY 7")
    with pytest.raises(SqlError, match="cannot be resolved with SELECT"):
        eng.sql("SELECT * FROM t ORDER BY 1")


FILTER_QUERIES = [
    "SELECT g, sum(v) FILTER (WHERE city = 'c1') AS sx, count(*) AS n "
    "FROM t GROUP BY g ORDER BY g",
    "SELECT g, count(*) FILTER (WHERE v > 250) AS nh, sum(v) AS s "
    "FROM t GROUP BY g ORDER BY g",
    "SELECT g, avg(v) FILTER (WHERE city IN ('c1','c2')) AS af "
    "FROM t GROUP BY g ORDER BY g",
    "SELECT g, sum(v) FILTER (WHERE city = 'c0') AS sx, sum(v) AS s "
    "FROM t GROUP BY g ORDER BY g",
    "SELECT g, count(v) FILTER (WHERE city = 'c3') AS cv "
    "FROM t GROUP BY g ORDER BY g",
    "SELECT g, count_distinct(v) FILTER (WHERE city = 'c2') AS dx "
    "FROM t GROUP BY g ORDER BY g",
    "SELECT sum(v) FILTER (WHERE g = 'a') AS sa, "
    "sum(v) FILTER (WHERE g = 'b') AS sb FROM t",
]


@pytest.mark.parametrize("sql", FILTER_QUERIES)
def test_agg_filter_parity(sql):
    from tpu_olap.bench.parity import assert_frame_parity
    eng, df = _engine()
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    from tpu_olap.planner.fallback import execute_fallback
    ref = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                           eng.config)
    # count_distinct is approximate on the device path (HLL) and exact
    # on the fallback — the parity harness gets the standard tolerance
    assert_frame_parity(dev, ref, approx_cols=("dx",))


def test_agg_filter_oracle():
    """Absolute check against pandas (device and fallback are independent
    implementations, but pin the ground truth anyway)."""
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(v) FILTER (WHERE city = 'c1') AS sx "
                  "FROM t GROUP BY g ORDER BY g")
    want = (df[df.city == "c1"].groupby("g")["v"].sum()
            .reindex(sorted(df.g.unique())).fillna(0).astype("int64"))
    assert got["sx"].tolist() == want.tolist()


def test_agg_filter_chunked(tmp_path):
    """FILTER aggregates through the chunked (streamed) fallback."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from tpu_olap.executor import EngineConfig
    df = _df(4000)
    p = str(tmp_path / "f.parquet")
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), p)
    eng = Engine(EngineConfig(fallback_chunk_rows=100,
                              fallback_chunk_batch_rows=512))
    eng.register_table("t", p, time_column="ts", accelerate=False)
    sql = ("SELECT g, sum(v) FILTER (WHERE city = 'c1') AS sx, "
           "avg(v) FILTER (WHERE city = 'c2') AS ax, "
           "count(*) FILTER (WHERE v > 250) AS nh, "
           "count_distinct(v) FILTER (WHERE city = 'c0') AS dx "
           "FROM t GROUP BY g ORDER BY g")
    got = eng.sql(sql)
    c1 = df[df.city == "c1"].groupby("g")["v"].sum()
    gs = sorted(df.g.unique())
    assert got["g"].tolist() == gs
    want_sx = c1.reindex(gs).fillna(0).astype("int64").tolist()
    assert got["sx"].tolist() == want_sx
    want_ax = df[df.city == "c2"].groupby("g")["v"].mean().reindex(gs)
    for a, b in zip(got["ax"].tolist(), want_ax.tolist()):
        assert (pd.isna(a) and pd.isna(b)) or abs(a - b) < 1e-9
    want_dx = (df[df.city == "c0"].groupby("g")["v"].nunique()
               .reindex(gs).fillna(0).astype("int64").tolist())
    assert got["dx"].tolist() == want_dx


def test_filter_after_non_aggregate_rejected():
    from tpu_olap.planner.sqlparse import SqlError
    eng, _ = _engine()
    with pytest.raises(SqlError, match="FILTER only follows an aggregate"):
        eng.sql("SELECT substr(g, 1, 1) FILTER (WHERE v > 0) AS x FROM t")


def test_agg_filter_avg_empty_group_is_null():
    """avg(...) FILTER matching NO rows in a group is NULL on BOTH paths
    (SQL semantics; the device lowers to a true-division "quotient"
    post-agg instead of the x/0 -> 0 arithmetic rule)."""
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = _engine()
    sql = ("SELECT g, avg(v) FILTER (WHERE v < -1) AS a FROM t "
           "GROUP BY g ORDER BY g")
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten
    assert dev["a"].isna().all()
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    assert fb["a"].isna().all()


def test_intersect():
    eng, df = _engine()
    got = eng.sql("SELECT g FROM t WHERE v > 400 "
                  "INTERSECT SELECT g FROM t WHERE v < 100 ORDER BY g")
    hi = set(df[df.v > 400].g)
    lo = set(df[df.v < 100].g)
    assert got["g"].tolist() == sorted(hi & lo)
    assert "INTERSECT" in eng.last_plan.fallback_reason


def test_except():
    eng, df = _engine()
    got = eng.sql("SELECT city FROM t EXCEPT SELECT city FROM t "
                  "WHERE g = 'a' ORDER BY city")
    allc = set(df.city)
    witha = set(df[df.g == "a"].city)
    assert got["city"].tolist() == sorted(allc - witha)


def test_mixed_set_operators_need_parens():
    from tpu_olap.planner.sqlparse import SqlError
    eng, _ = _engine()
    with pytest.raises(SqlError, match="mixed set operators"):
        eng.sql("SELECT g FROM t UNION SELECT g FROM t "
                "INTERSECT SELECT g FROM t")


def test_exists_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE EXISTS (SELECT v FROM t WHERE v > 490)")
    assert got["n"][0] == (len(df) if (df.v > 490).any() else 0)
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE NOT EXISTS (SELECT v FROM t WHERE v > 9999)")
    assert got["n"][0] == len(df)
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE EXISTS (SELECT v FROM t WHERE v > 9999)")
    assert got["n"][0] == 0


def test_correlated_subquery_executes():
    """Equality-correlated subqueries decorrelate and execute (round-4
    margin close); they must NOT silently resolve the outer ref against
    the inner frame (qualifier stripping would otherwise turn `b.x = a.x`
    into `b.x = b.x` = always true)."""
    eng, df = _engine()
    eng.register_table("u", pd.DataFrame({"g": ["zz"], "v": [5]}),
                       accelerate=False)
    # no t.g value equals 'zz': EXISTS must be False for every row
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE EXISTS (SELECT 1 FROM u WHERE u.g = t.g)")
    assert got["n"][0] == 0
    # scalar max over an empty correlated group is NULL: v > NULL is False
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE v > (SELECT max(v) FROM u WHERE u.g = t.g)")
    assert got["n"][0] == 0
    # and a genuinely matching correlation agrees with pandas
    got = eng.sql("SELECT count(*) AS n FROM t WHERE v > "
                  "(SELECT avg(t2.v) FROM t t2 WHERE t2.g = t.g)")
    avg = df.groupby("g")["v"].mean()
    assert got["n"][0] == int((df["v"] > df["g"].map(avg)).sum())


def test_correlated_subquery_beyond_rewrite_nested_loop():
    """Correlation shapes outside the magic-set rewrite run the bounded
    nested loop (round 5, VERDICT r4 missing #2) — correct-but-slow, not
    an error; past corr_nested_loop_cap the refusal stays legible."""
    from tpu_olap.executor import EngineConfig
    from tpu_olap.planner.fallback import FallbackError
    eng, df = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE v > (SELECT avg(t2.v) FROM t t2 "
                  "WHERE t2.v < t.v)")

    def avg_below(v):
        c = df[df["v"] < v]["v"]
        return None if c.empty else c.sum() / len(c)

    exp = sum(1 for v in df["v"]
              if avg_below(v) is not None and v > avg_below(v))
    assert int(got["n"].iloc[0]) == exp

    # past the cap the refusal is still legible, never a wrong answer
    eng2 = Engine(EngineConfig(corr_nested_loop_cap=2))
    eng2.register_table("t", df, time_column="ts")
    with pytest.raises(FallbackError, match="corr_nested_loop_cap"):
        eng2.sql("SELECT count(*) AS n FROM t "
                 "WHERE v > (SELECT avg(t2.v) FROM t t2 "
                 "WHERE t2.v < t.v)")


def test_case_folding_extraction_dims():
    """upper()/lower() ride the device path as extraction dimensions and
    as extraction-fn selector filters (Druid's upper/lower extraction)."""
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = _engine()
    sql = ("SELECT upper(g) AS u, sum(v) AS s FROM t "
           "GROUP BY upper(g) ORDER BY u")
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    pd.testing.assert_frame_equal(dev, fb, check_dtype=False)
    want = df.assign(u=df.g.str.upper()).groupby("u")["v"].sum()
    assert dev["s"].tolist() == want.tolist()
    n = eng.sql("SELECT count(*) AS n FROM t WHERE upper(g) = 'A'")
    assert eng.last_plan.rewritten
    assert n["n"][0] == int((df.g == "a").sum())


def test_hour_minute_extractions():
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = _engine()
    for sql in (
        "SELECT hour(ts) AS h, count(*) AS n FROM t GROUP BY hour(ts) "
        "ORDER BY h",
        "SELECT minute(ts) AS m, count(*) AS n FROM t "
        "WHERE ts < '2023-01-03' GROUP BY minute(ts) ORDER BY m LIMIT 10",
    ):
        dev = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        pd.testing.assert_frame_equal(dev, fb, check_dtype=False)


def test_concat_and_trim_fallback():
    eng, df = _engine()
    got = eng.sql("SELECT concat(g, '/', city) AS gc, count(*) AS n "
                  "FROM t GROUP BY concat(g, '/', city) ORDER BY gc")
    assert not eng.last_plan.rewritten
    want = (df.g + "/" + df.city).value_counts().sort_index()
    assert got["gc"].tolist() == want.index.tolist()
    assert got["n"].tolist() == want.tolist()
    got = eng.sql("SELECT count(*) AS n FROM t WHERE trim(g) = 'a'")
    assert got["n"][0] == int((df.g == "a").sum())


def test_global_avg_over_zero_rows_is_null():
    """A global aggregate emits its one row even when no rows match;
    AVG of nothing is NULL on both paths (fuzz seed 664: the device's
    x/0 -> 0 arithmetic rule said 0.0)."""
    from tpu_olap.planner.fallback import execute_fallback
    eng, _ = _engine()
    sql = ("SELECT sum(v) AS s, avg(v) AS a FROM t "
           "WHERE g = 'a' AND g = 'b'")  # contradictory: zero rows
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten
    assert int(dev["s"][0]) == 0 and pd.isna(dev["a"][0])
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    assert pd.isna(fb["a"][0])


def test_extraction_in_filter_rewrites():
    """upper()/substr() IN (...) lowers to an OR of extraction selector
    filters on the device path (was fallback-only)."""
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = _engine()
    for sql, oracle in (
        ("SELECT count(*) AS n FROM t WHERE upper(g) IN ('A', 'B')",
         int(df.g.str.upper().isin(["A", "B"]).sum())),
        ("SELECT count(*) AS n FROM t WHERE substr(city, 1, 2) IN ('c0',"
         " 'c3')",
         int(df.city.str[:2].isin(["c0", "c3"]).sum())),
        ("SELECT count(*) AS n FROM t WHERE NOT (upper(g) IN ('A', 'Z'))",
         int((~df.g.str.upper().isin(["A", "Z"])).sum())),
    ):
        r = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        assert int(r["n"][0]) == oracle, sql
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert int(fb["n"][0]) == oracle


def test_extraction_in_filter_null_semantics():
    """NULL in an extraction IN list matches null rows identically on
    both paths (ex(null) is null; mirrors the plain-column in filter)."""
    from tpu_olap.planner.fallback import execute_fallback
    eng = Engine()
    rng = np.random.default_rng(0)
    n = 2000
    df2 = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01"),
        "g": rng.choice(["a", "B", "c", None], n),
        "v": rng.integers(0, 100, n),
    })
    eng.register_table("t2", df2, time_column="ts")
    for sql in (
        "SELECT count(*) AS n FROM t2 WHERE upper(g) IN ('A', NULL)",
        "SELECT count(*) AS n FROM t2 WHERE upper(g) IN ('A', 'C')",
    ):
        r = eng.sql(sql)
        assert eng.last_plan.rewritten
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert int(r["n"][0]) == int(fb["n"][0]), sql


def test_extraction_bound_filter_rewrites():
    """Range comparisons over extractions (substr/upper BETWEEN/</>)
    lower to bound filters with an extractionFn — one predicate table,
    lexicographic over the extracted strings."""
    from tpu_olap.planner.fallback import execute_fallback
    eng, df = _engine()
    for sql, oracle in (
        ("SELECT count(*) AS n FROM t WHERE substr(city, 1, 2) "
         "BETWEEN 'c1' AND 'c4'",
         int(df.city.str[:2].between("c1", "c4").sum())),
        ("SELECT count(*) AS n FROM t WHERE upper(g) >= 'C'",
         int((df.g.str.upper() >= "c".upper()).sum())),
        ("SELECT count(*) AS n FROM t WHERE substr(city, 2, 1) < '3'",
         int((df.city.str[1:2] < "3").sum())),
    ):
        r = eng.sql(sql)
        assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
        assert int(r["n"][0]) == oracle, sql
        fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                              eng.config)
        assert int(fb["n"][0]) == oracle


def test_derived_table_inner_rides_device_path():
    """Round 5 (soak r05: 100% of fuzz fallbacks were derived-table
    statements): a FROM/JOIN (SELECT ...) body that is device-rewritable
    executes through the statement executor — the scan-heavy inner
    aggregate rides the device path, the outer interpreter consumes the
    small materialized frame."""
    eng, df = _engine()
    n0 = len(eng.history)
    got = eng.sql("SELECT avg(s) AS a, count(*) AS n FROM "
                  "(SELECT g, sum(v) AS s FROM t WHERE v < 900 "
                  "GROUP BY g) d WHERE s > 0")
    assert len(eng.history) > n0, "inner did not dispatch to the device"
    sub = df[df.v < 900].groupby("g")["v"].sum()
    sub = sub[sub > 0]
    assert abs(float(got["a"].iloc[0]) - sub.mean()) < 1e-9
    assert int(got["n"].iloc[0]) == len(sub)

    n1 = len(eng.history)
    got2 = eng.sql(
        "SELECT g, sum(v) AS tv, max(ds) AS m FROM t "
        "JOIN (SELECT g AS dg, sum(v) AS ds FROM t GROUP BY g) d "
        "ON g = dg GROUP BY g ORDER BY g LIMIT 5")
    assert len(eng.history) > n1
    base = df.groupby("g")["v"].sum().reset_index()
    exp = base.assign(m=base.g.map(df.groupby("g")["v"].sum())) \
        .sort_values("g").head(5)
    assert list(got2["tv"]) == list(exp["v"])
    assert list(got2["m"]) == list(exp["m"])
