"""SQL shapes outside the rewrite subset (UNION, derived tables,
subqueries) — VERDICT round-2 missing #4: the reference ran full Spark
SQL, so every parseable query had SOME execution path; these now parse
and execute on the fallback interpreter instead of raising SqlError."""

import numpy as np
import pandas as pd

from tpu_olap import Engine


def _df(n=3000, seed=17):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2023-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 90, n), unit="s"),
        "g": rng.choice(["a", "b", "c", "d"], n),
        "city": rng.choice([f"c{i}" for i in range(6)], n),
        "v": rng.integers(0, 500, n).astype(np.int64),
    })


def _engine():
    eng = Engine()
    df = _df()
    eng.register_table("t", df, time_column="ts")
    eng.register_table("dim", pd.DataFrame(
        {"d_city": [f"c{i}" for i in range(6)],
         "d_zone": ["west" if i < 3 else "east" for i in range(6)]}),
        accelerate=False)
    return eng, df


def test_union_all():
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(v) AS s FROM t WHERE g = 'a' GROUP BY g "
                  "UNION ALL "
                  "SELECT g, sum(v) AS s FROM t WHERE g = 'b' GROUP BY g "
                  "ORDER BY g")
    assert eng.last_plan.fallback_reason.startswith("UNION")
    assert list(got["g"]) == ["a", "b"]
    assert got["s"][0] == df[df.g == "a"].v.sum()
    assert got["s"][1] == df[df.g == "b"].v.sum()


def test_union_distinct_dedupes():
    eng, df = _engine()
    got = eng.sql("SELECT g FROM t UNION SELECT g FROM t ORDER BY g")
    assert list(got["g"]) == sorted(df.g.unique())


def test_union_limit_applies_to_whole():
    eng, _ = _engine()
    got = eng.sql("SELECT g FROM t UNION SELECT city FROM t "
                  "ORDER BY g LIMIT 3")
    assert len(got) == 3


def test_derived_table():
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(s) AS total FROM "
                  "(SELECT g, city, sum(v) AS s FROM t GROUP BY g, city) "
                  "sub GROUP BY g ORDER BY g")
    assert "derived table" in eng.last_plan.fallback_reason
    expect = df.groupby("g").v.sum()
    for _, row in got.iterrows():
        assert row["total"] == expect[row["g"]]


def test_in_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t WHERE city IN "
                  "(SELECT d_city FROM dim WHERE d_zone = 'west')")
    assert "subquery" in eng.last_plan.fallback_reason
    west = {f"c{i}" for i in range(3)}
    assert got["n"][0] == int(df.city.isin(west).sum())


def test_not_in_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t WHERE city NOT IN "
                  "(SELECT d_city FROM dim WHERE d_zone = 'west')")
    west = {f"c{i}" for i in range(3)}
    assert got["n"][0] == int((~df.city.isin(west)).sum())


def test_scalar_subquery():
    eng, df = _engine()
    got = eng.sql("SELECT g, sum(v) AS s FROM t "
                  "WHERE v > (SELECT avg(v) FROM t) GROUP BY g ORDER BY g")
    mean = df.v.sum() / len(df)
    sub = df[df.v > mean]
    expect = sub.groupby("g").v.sum()
    for _, row in got.iterrows():
        assert row["s"] == expect[row["g"]]


def test_subquery_free_queries_still_rewrite():
    eng, _ = _engine()
    eng.sql("SELECT g, sum(v) AS s FROM t GROUP BY g")
    assert eng.last_plan.rewritten


def test_explain_union_does_not_crash():
    eng, _ = _engine()
    out = eng.explain("SELECT g FROM t UNION ALL SELECT g FROM t")
    assert out["rewritten"] is False
    assert "UNION" in out["reason"]


# --- lookup extraction, SEARCH verb, paged select (VERDICT r2 missing #6)

def test_lookup_extraction_sql_both_paths():
    eng, df = _engine()
    eng.register_lookup("zone", {f"c{i}": ("west" if i < 3 else "east")
                                 for i in range(6)})
    sql = ("SELECT lookup(city, 'zone') AS z, sum(v) AS s FROM t "
           "GROUP BY lookup(city, 'zone') ORDER BY z")
    got = eng.sql(sql)
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    zmap = {f"c{i}": ("west" if i < 3 else "east") for i in range(6)}
    expect = df.assign(z=df.city.map(zmap)).groupby("z").v.sum()
    for _, row in got.iterrows():
        assert row["s"] == expect[row["z"]]
    # fallback path agrees
    from tpu_olap.planner.fallback import execute_fallback
    fb = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                          eng.config)
    pd.testing.assert_frame_equal(got, fb, check_dtype=False)


def test_lookup_missing_value_is_null():
    eng, df = _engine()
    eng.register_lookup("partial", {"c0": "zero"})
    got = eng.sql("SELECT lookup(city, 'partial') AS z, count(*) AS n "
                  "FROM t GROUP BY lookup(city, 'partial') ORDER BY z")
    assert eng.last_plan.rewritten
    zs = list(got["z"])
    assert "zero" in zs and len(zs) == 2
    assert any(pd.isna(z) for z in zs)  # unmapped values -> null group


def test_unknown_lookup_is_a_clear_error():
    """An unregistered lookup name is a USER error (Druid errors on it
    too) — it must surface legibly, not as a device crash."""
    import pytest as _pytest

    from tpu_olap.planner.fallback import FallbackError
    eng, _ = _engine()
    with _pytest.raises(FallbackError, match="unknown lookup"):
        eng.sql("SELECT lookup(city, 'nope') AS z FROM t LIMIT 1")
    assert not eng.last_plan.rewritten  # planner declined first


def test_search_verb():
    eng, df = _engine()
    got = eng.sql("SEARCH DRUID DATASOURCE t FOR 'c1' IN city, g LIMIT 10")
    assert list(got.columns) == ["dimension", "value", "count"]
    assert set(got["value"]) == {"c1"}
    assert int(got["count"][0]) == int((df.city == "c1").sum())


def test_select_page_api():
    eng, df = _engine()
    page1, off1 = eng.select_page("t", columns=("city",), page_size=7)
    assert len(page1) == 7 and off1 == 7
    page2, off2 = eng.select_page("t", columns=("city",), page_size=7,
                                  offset=off1)
    assert len(page2) == 7 and off2 == 14
    assert page1 != page2


def test_empty_scalar_subquery_matches_no_rows():
    """SQL NULL comparison semantics: an empty scalar subquery inlines
    as NULL and the comparison matches nothing (was a TypeError)."""
    eng, _ = _engine()
    got = eng.sql("SELECT count(*) AS n FROM t "
                  "WHERE v > (SELECT max(v) FROM t WHERE v > 99999)")
    assert got["n"][0] == 0


def test_in_subquery_packs_values():
    """Resolution packs IN-subquery values into ONE literal node."""
    from tpu_olap.ir.expr import FuncCall
    from tpu_olap.planner.fallback import _resolve_subqueries
    eng, df = _engine()
    stmt = eng.planner.plan(
        "SELECT count(*) AS n FROM t WHERE city IN "
        "(SELECT d_city FROM dim)").stmt
    resolved = _resolve_subqueries(stmt, eng.catalog, eng.config)
    calls = []

    def walk(e):
        if isinstance(e, FuncCall):
            calls.append(e.name)
            for a in e.args:
                walk(a)
    walk(resolved.where)
    assert "in_list_packed" in calls
