"""Sort-based sparse group-by (kernels.sparse_groupby): high-cardinality
GROUP BY beyond the dense mixed-radix budget (SURVEY.md §8.4 hard part #1).

dense_group_budget is forced tiny so ordinary-size tables exercise the
sparse path; parity versus the pandas fallback is the oracle throughout.
"""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import check_query
from tpu_olap.executor import EngineConfig
from tpu_olap.executor.lowering import lower


def _df(n=6000, seed=23):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 100, n), unit="s"),
        "a": rng.choice([f"a{i}" for i in range(150)], n),
        "b": rng.choice([f"b{i}" for i in range(90)], n),
        "c": rng.choice(["x", "y", None], n),
        "v": rng.integers(-100, 1000, n).astype(np.int64),
        "w": np.round(rng.random(n) * 50, 4),
    })
    df.loc[rng.random(n) < 0.03, "v"] = np.nan
    df["v"] = df["v"].astype("Int64")
    return df


def _engine(**kw):
    cfg = EngineConfig(dense_group_budget=64, **kw)
    eng = Engine(cfg)
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    return eng


SQL = ("SELECT a, b, sum(v) AS sv, count(*) AS n, min(w) AS mw, "
       "max(v) AS xv FROM t GROUP BY a, b")


def test_sparse_plan_selected():
    eng = _engine()
    plan = eng.planner.plan(SQL)
    phys = lower(plan.query, plan.entry.segments, eng.config)
    assert phys.sparse
    assert phys.total_groups > 64


def test_sparse_parity():
    check_query(_engine(), SQL)


def test_sparse_parity_with_filter_and_having():
    check_query(_engine(),
                "SELECT a, b, sum(v) AS sv, count(*) AS n FROM t "
                "WHERE w < 40 AND c = 'x' GROUP BY a, b "
                "HAVING count(*) > 1")


def test_sparse_count_distinct():
    check_query(_engine(),
                "SELECT a, approx_count_distinct(b) AS d FROM t GROUP BY a",
                approx_cols=("d",))


def test_sparse_order_limit():
    check_query(_engine(),
                "SELECT a, b, sum(v) AS sv FROM t GROUP BY a, b "
                "ORDER BY sv DESC LIMIT 17")


def test_sparse_multichip_parity():
    check_query(_engine(num_shards=8), SQL)


def test_sparse_cap_adapts():
    eng = _engine(sparse_group_cap=64)
    res = eng.sql(SQL)
    h = eng.history[-1]
    assert h["sparse"] and h["result_groups"] > 64
    assert h["result_cap"] >= h["result_groups"]
    assert len(res) == h["result_groups"]


def test_sparse_budget_exceeded_falls_back():
    eng = _engine(sparse_group_budget=64)
    res = eng.sql(SQL)
    assert "sparse budget" in (eng.last_plan.fallback_reason or "")
    # fallback still answers correctly
    ref = _engine().sql(SQL)
    assert len(res) == len(ref)


def test_merge_propagates_local_overflow():
    """A chip whose LOCAL compact table overflowed dropped groups; the
    merged count must still exceed cap so the runner retries larger."""
    from tpu_olap.kernels.sparse_groupby import (merge_sparse,
                                                 sparse_group_reduce)
    from tpu_olap.kernels.groupby import AggPlan

    cap = 64
    plans = [AggPlan("n", "count", (), np.int64)]
    env = {"cols": {}, "nulls": {}}
    # chip A: 65 distinct keys -> local overflow drops one
    key_a = np.arange(65, dtype=np.int64)
    out_a = sparse_group_reduce(key_a, np.ones(65, bool), env, plans, cap,
                                {}, np)
    assert int(out_a["_count"]) == 65  # local overflow signalled
    # chip B: subset of A's surviving keys
    key_b = np.arange(32, dtype=np.int64)
    out_b = sparse_group_reduce(key_b, np.ones(32, bool), env, plans, cap,
                                {}, np)
    merged = merge_sparse([out_a, out_b], plans, cap, np)
    assert int(merged["_count"]) == 65  # NOT 64: retry must fire


def test_sparse_theta_rewrites():
    """Round 3: theta over a sparse group space executes on the device
    path (it used to be an UnsupportedAggregation fallback)."""
    eng = _engine()
    eng.sql("SELECT a, b, theta_sketch(c) AS d FROM t GROUP BY a, b")
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason


# --------------------------------------------------------------------------
# Hash-exchange multi-chip merge (SURVEY.md §3.5 last row, §8.4 #1)

def test_exchange_matches_gather():
    """Both multi-chip sparse merge strategies produce identical results
    (including HLL count-distinct and min/max with nulls)."""
    sql = ("SELECT a, b, sum(v) AS sv, count(*) AS n, min(w) AS mw, "
           "count(distinct c) AS dc FROM t GROUP BY a, b ORDER BY a, b")
    ex = _engine(num_shards=8, sparse_merge="exchange")
    ga = _engine(num_shards=8, sparse_merge="gather")
    got_x, got_g = ex.sql(sql), ga.sql(sql)
    assert ex.history[-1].get("sparse_merge") == "exchange"
    assert "sparse_merge" not in ga.history[-1]
    pd.testing.assert_frame_equal(got_x, got_g)


def test_exchange_parity_vs_fallback():
    check_query(_engine(num_shards=8, sparse_merge="exchange"), SQL)


def test_exchange_scales_past_per_chip_budget():
    """>= 1e6 present groups on 8 chips with a 2^17 per-chip budget:
    the gather strategy must refuse (cap is global there), the exchange
    strategy must answer — its capacity is D x budget (VERDICT r1 #6)."""
    n = 1_000_000  # one group per row (>= 1e6 present groups)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(np.arange(n) // 2000, unit="min"),
        "k": np.arange(n, dtype=np.int64),
        "v": np.ones(n, dtype=np.int64),
    })
    budget = 1 << 17

    def mk(merge):
        eng = Engine(EngineConfig(
            dense_group_budget=64, num_shards=8, sparse_merge=merge,
            sparse_group_budget=budget))
        eng.register_table("t", df, time_column="ts",
                           block_rows=1 << 14)
        return eng

    ex = mk("exchange")
    got = ex.sql("SELECT k, sum(v) AS s FROM t GROUP BY k LIMIT 7")
    h = ex.history[-1]
    assert h["sparse_merge"] == "exchange"
    assert h["result_groups"] == n  # every group present and counted
    assert len(got) == 7
    assert (got.s == 1).all()

    # exact parity on a filtered slice (1000 groups through the same
    # exchange kernel)
    sub = ex.sql("SELECT k, sum(v) AS s FROM t WHERE k < 1000 "
                 "GROUP BY k ORDER BY k")
    assert len(sub) == 1000
    assert (sub.s == 1).all()
    assert list(sub.k) == list(range(1000))

    # gather at the same budget refuses (falls back to pandas)
    ga = mk("gather")
    ga.sql("SELECT k, sum(v) AS s FROM t GROUP BY k LIMIT 7")
    assert "sparse budget" in (ga.last_plan.fallback_reason or "")


# Skewed-key workloads (VERDICT round-2 weak #8): keys chosen so the old
# hash-exchange would have routed every group to ONE owner chip — the
# worst case for a device-side exchange. The broker merge (per-chip
# compaction + host union, executor/sharding.py) has no owner chips, so
# these pin that skew cannot degrade capacity or correctness.

def _fib_owner(ids: np.ndarray, shards: int) -> np.ndarray:
    """Fibonacci multiplicative hash (the retired sharding._owner_of)
    — kept to CONSTRUCT maximally-skewed key sets."""
    h = ids.astype(np.int64) * np.int64(-7046029254386353131)
    h = (h >> np.int64(33)) & np.int64(0x7FFFFFFF)
    return (h % np.int64(shards)).astype(np.int32)


def _skewed_values(n_groups: int, shards: int = 8) -> np.ndarray:
    """Values for a single numeric dim whose sparse keys (value+1, with 0
    present as the min) all hash to owner(1)."""
    cand = np.arange(1, 400_000, dtype=np.int64)
    target = _fib_owner(np.array([1], np.int64), shards)[0]
    sel = cand[_fib_owner(cand, shards) == target][:n_groups] - 1
    assert sel.size == n_groups, "not enough same-owner candidates"
    assert sel[0] == 0  # value 0 present -> ids are exactly value+1
    return sel


def _skewed_engine(values, rows_per_group=3, **kw):
    vals = np.repeat(values, rows_per_group)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2022-01-01")
        + pd.to_timedelta(np.arange(len(vals)) % 9973, unit="s"),
        "k": vals,
        "v": np.ones(len(vals), dtype=np.int64),
    })
    eng = Engine(EngineConfig(dense_group_budget=64, num_shards=8,
                              sparse_merge="exchange", **kw))
    eng.register_table("t", df, time_column="ts", block_rows=512)
    return eng


SKEW_SQL = "SELECT k, sum(v) AS s, count(*) AS n FROM t GROUP BY k"


def test_exchange_skewed_single_owner_parity():
    """All keys would have landed on one hash owner: the broker's
    merged table must absorb the full group count — answers must still
    match the fallback exactly."""
    eng = _skewed_engine(_skewed_values(1500))
    check_query(eng, SKEW_SQL)
    m = eng.history[-1]
    assert m.get("sparse_merge") == "exchange"
    # the broker table sized to the full group count (not a per-owner
    # count/D estimate)
    assert m["result_cap_owner"] >= 1500


def test_exchange_skew_no_longer_overflows():
    """Hash skew was the old exchange's failure mode (every key owned by
    one chip overflowed that chip's owner table). The broker merge has
    no owner chips — the host union absorbs ANY key distribution — so
    the same shape now answers on the device path with exact parity."""
    eng = _skewed_engine(_skewed_values(1200), sparse_group_budget=512)
    check_query(eng, SKEW_SQL)
    m = eng.history[-1]
    assert m.get("sparse_merge") == "exchange"
    assert m["result_groups"] == 1200


def test_exchange_overflow_falls_back_cleanly():
    """Groups beyond the scaled capacity (local compaction past the
    per-chip budget, or the broker table past D x budget): retries
    exhaust and the engine answers via structural fallback, never an
    error (SURVEY.md §2 property 2)."""
    eng = _skewed_engine(_skewed_values(1200), sparse_group_budget=64)
    got = eng.sql(SKEW_SQL)
    assert eng.last_plan.fallback_reason is not None
    assert "sparse budget" in eng.last_plan.fallback_reason
    ref = _skewed_engine(_skewed_values(1200), sparse_group_budget=64)
    from tpu_olap.planner.fallback import execute_fallback
    expect = execute_fallback(ref.planner.plan(SKEW_SQL).stmt,
                              ref.catalog, ref.config)
    a = got.sort_values("k").reset_index(drop=True)
    b = expect.sort_values("k").reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)


def test_sparse_theta_parity():
    """theta_sketch over a sparse group space (round-3: previously an
    UnsupportedAggregation). Per-group distinct counts here stay under
    the clamped sketch width, so estimates are EXACT and the pandas
    fallback (exact nunique) is a zero-tolerance oracle."""
    eng = _engine()
    plan = eng.planner.plan(
        "SELECT a, b, theta_sketch(v) AS d FROM t GROUP BY a, b")
    phys = lower(plan.query, plan.entry.segments, eng.config)
    assert phys.sparse
    tk = [p.theta_k for p in phys.agg_plans if p.kind == "theta"]
    assert tk == [eng.config.sparse_theta_k_cap]
    check_query(eng,
                "SELECT a, b, theta_sketch(v) AS d, count(*) AS n FROM t "
                "GROUP BY a, b")


def test_sparse_theta_multichip_exchange():
    """theta tables ride the hash-exchange all_to_all merge: each owner
    unions the per-chip [cap, k] rows for its keys."""
    eng = _engine(num_shards=8, sparse_merge="exchange")
    check_query(eng,
                "SELECT a, theta_sketch(b) AS db, count(*) AS n FROM t "
                "GROUP BY a")


def test_sparse_theta_multichip_gather():
    eng = _engine(num_shards=8, sparse_merge="gather")
    check_query(eng,
                "SELECT a, theta_sketch(b) AS db FROM t GROUP BY a")
