"""Semantic result caching (executor.resultcache; docs/CACHING.md):
tier-2 full-result serving, tier-1 per-segment partial reuse across
moving windows (with bucket-layout rebase), the generational
invalidation contract (ingest bumps, CLEAR DRUID CACHE, DROP), byte-
budget LRU eviction, batch-executor tier sharing, and observability
(tier-labeled counters, /debug/cache)."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer
from tpu_olap.bench.parity import check_query
from tpu_olap.executor import EngineConfig

N_ROWS = 40_000


def _df(n=N_ROWS, seed=7, days=60):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2023-01-01")
        + pd.to_timedelta(np.sort(rng.integers(0, 86400 * days, n)),
                          unit="s"),
        "g": rng.choice([f"g{i}" for i in range(10)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _engine(df=None, **kw):
    cfg = dict(result_cache_enabled=True, segment_cache_enabled=True,
               segment_cache_min_rows=0)
    cfg.update(kw)
    eng = Engine(EngineConfig(**cfg))
    eng.register_table("t", df if df is not None else _df(),
                       time_column="ts", block_rows=1 << 11,
                       time_partition="day")
    return eng


GROUP_SQL = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
AGG_SQL = "SELECT sum(v) AS s, count(*) AS n FROM t"


def _win_sql(lo, hi):
    return ("SELECT g, sum(v) AS s FROM t WHERE "
            f"ts >= TIMESTAMP '{lo}' AND ts < TIMESTAMP '{hi}' "
            "GROUP BY g ORDER BY g")


# ------------------------------------------------------- tier 2 (full)


def test_full_cache_serves_repeat_with_real_cache_hit():
    eng = _engine()
    a = eng.sql(GROUP_SQL)
    first = dict(eng.history[-1])
    b = eng.sql(GROUP_SQL)
    hit = dict(eng.history[-1])
    assert a.equals(b)
    assert first["cache_hit"] is False
    assert hit["cache_hit"] is True
    assert hit["cache_tier"] == "full"
    assert hit["path"] == "cache"
    assert hit["rows_scanned"] == 0 and hit["segments_scanned"] == 0
    # tier-labeled counters are live in the registry (and /metrics)
    req = eng.metrics.counter("result_cache_requests_total")
    assert req.value(tier="full", result="hit") >= 1
    assert req.value(tier="full", result="miss") >= 1


def test_ingest_bumps_generation_and_invalidates_both_tiers():
    df = _df()
    eng = _engine(df)
    gen0 = eng.catalog.get("t").segments.generation
    a = eng.sql(GROUP_SQL)
    eng.sql(GROUP_SQL)  # tier-2 primed
    eng.sql(_win_sql("2023-01-01", "2023-02-01"))  # tier-1 primed
    # fresh ingest with DIFFERENT data: any stale entry would now give
    # a provably wrong answer
    eng.register_table("t", df.iloc[: N_ROWS // 2], time_column="ts",
                       block_rows=1 << 11, time_partition="day")
    gen1 = eng.catalog.get("t").segments.generation
    assert gen1 > gen0
    # tier 1 first (before anything repopulates entries under the new
    # generation): every lookup must miss
    w = eng.sql(_win_sql("2023-01-01", "2023-02-01"))
    rec = dict(eng.history[-1])
    assert not rec.get("segments_cached")  # tier 1 invalidated too
    check_query(eng, _win_sql("2023-01-01", "2023-02-01"),
                label="post-ingest-window")
    assert len(w) > 0
    b = eng.sql(GROUP_SQL)
    rec = dict(eng.history[-1])
    # the old generation's full result is never served (fresh gen-1
    # tier-1 entries stored by the window query above MAY serve — that
    # is the feature, and the frame + parity checks prove freshness)
    assert rec.get("cache_tier") != "full"
    assert not b.equals(a)
    check_query(eng, GROUP_SQL, label="post-ingest")
    # the eager purge dropped the stale bytes and logged the event
    snap = eng.runner.result_cache.snapshot()
    assert snap["full"]["entries"] <= 2  # only post-ingest entries
    assert any(e["event"] == "cache_invalidate"
               for e in eng.runner.events.snapshot())


def test_clear_druid_cache_clears_both_tiers():
    eng = _engine()
    eng.sql(GROUP_SQL)
    eng.sql(_win_sql("2023-01-01", "2023-02-01"))
    snap = eng.runner.result_cache.snapshot()
    assert snap["full"]["entries"] >= 1
    assert snap["segment"]["entries"] >= 1
    eng.sql("CLEAR DRUID CACHE t")
    snap = eng.runner.result_cache.snapshot()
    assert snap["full"]["entries"] == 0
    assert snap["segment"]["entries"] == 0
    eng.sql(GROUP_SQL)
    assert dict(eng.history[-1])["cache_hit"] is False
    # unscoped clear works too
    eng.sql("CLEAR DRUID CACHE")
    assert eng.runner.result_cache.snapshot()["full"]["entries"] == 0


def test_drop_table_purges_cache_entries():
    eng = _engine()
    eng.sql(GROUP_SQL)
    eng.drop_table("t")
    assert eng.runner.result_cache.snapshot()["full"]["entries"] == 0
    with pytest.raises(Exception):
        eng.sql(GROUP_SQL)  # table gone


def test_byte_budget_lru_eviction():
    eng = _engine(result_cache_max_bytes=20_000)
    # distinct queries -> distinct entries; tiny budget forces eviction
    for lo in range(1, 20):
        eng.sql(_win_sql(f"2023-01-{lo:02d}", "2023-02-01"))
    snap = eng.runner.result_cache.snapshot()
    assert snap["full"]["bytes"] <= 20_000
    assert snap["full"]["evict"] >= 1
    ev = eng.metrics.counter("result_cache_evictions_total")
    assert ev.value(tier="full") >= 1


# ---------------------------------------------------- tier 1 (segment)


def test_moving_window_recomputes_only_uncached_segments():
    eng = _engine(result_cache_enabled=False)  # isolate tier 1
    eng.sql(_win_sql("2023-01-01", "2023-02-01"))
    cold = dict(eng.history[-1])
    assert cold["cache_hit"] is False
    assert cold["segments_computed"] >= 28
    eng.sql(_win_sql("2023-01-08", "2023-02-15"))
    warm = dict(eng.history[-1])
    assert warm["cache_hit"] is True
    assert warm["cache_tier"] == "segment"
    assert warm["segments_cached"] >= 20   # Jan 8..Feb 1 reused
    assert warm["segments_computed"] <= 18  # only the new tail
    assert warm["rows_scanned"] < cold["rows_scanned"]
    check_query(eng, _win_sql("2023-01-08", "2023-02-15"),
                label="moving-window")
    # identical repeat: full tier-1 coverage, zero segments computed
    eng.sql(_win_sql("2023-01-08", "2023-02-15"))
    full = dict(eng.history[-1])
    assert full["segments_computed"] == 0
    assert full["rows_scanned"] == 0


def test_bucketed_layout_rebases_across_shifted_windows():
    eng = _engine(result_cache_enabled=False)
    sql1 = ("SELECT DATE_TRUNC('day', ts) AS d, sum(v) AS s, "
            "min(v) AS mn, max(v) AS mx FROM t WHERE "
            "ts < TIMESTAMP '2023-02-01' GROUP BY d ORDER BY d")
    sql2 = ("SELECT DATE_TRUNC('day', ts) AS d, sum(v) AS s, "
            "min(v) AS mn, max(v) AS mx FROM t WHERE "
            "ts >= TIMESTAMP '2023-01-05' AND "
            "ts < TIMESTAMP '2023-02-20' GROUP BY d ORDER BY d")
    eng.sql(sql1)
    eng.sql(sql2)
    rec = dict(eng.history[-1])
    # the shifted window's bucket grid differs, but cached per-segment
    # rows re-anchor by bucket start timestamp (resultcache._rebase)
    assert rec["cache_tier"] == "segment"
    assert rec["segments_cached"] >= 20
    check_query(eng, sql2, label="rebase")


def test_straddling_interval_segments_always_recompute():
    eng = _engine(result_cache_enabled=False)
    # mid-day boundaries: the edge segments' partials are interval-
    # dependent, so they must recompute (and never be stored)
    sql = ("SELECT g, sum(v) AS s FROM t WHERE "
           "ts >= TIMESTAMP '2023-01-03 12:00:00' AND "
           "ts < TIMESTAMP '2023-01-20 06:30:00' "
           "GROUP BY g ORDER BY g")
    eng.sql(sql)
    eng.sql(sql)
    rec = dict(eng.history[-1])
    assert rec.get("segments_computed", 0) >= 1  # the straddlers
    check_query(eng, sql, label="straddle")


def test_sketches_merge_exactly_through_segment_cache():
    eng = _engine(result_cache_enabled=False)
    sql1 = ("SELECT count(DISTINCT g) AS n, sum(v) AS s FROM t "
            "WHERE ts < TIMESTAMP '2023-02-01'")
    sql2 = ("SELECT count(DISTINCT g) AS n, sum(v) AS s FROM t "
            "WHERE ts >= TIMESTAMP '2023-01-10' AND "
            "ts < TIMESTAMP '2023-02-20'")
    eng.sql(sql1)
    eng.sql(sql2)
    rec = dict(eng.history[-1])
    assert rec.get("segments_cached", 0) >= 1
    check_query(eng, sql2, approx_cols=("n",), label="hll-merge")


def test_state_budget_bypass_falls_through_to_plain_path():
    eng = _engine(result_cache_enabled=False,
                  segment_cache_state_budget=1)
    out = eng.sql(GROUP_SQL)
    rec = dict(eng.history[-1])
    assert str(rec.get("segment_cache", "")).startswith("bypass")
    assert "segments_cached" not in rec
    assert len(out) == 10  # plain path served it
    req = eng.metrics.counter("result_cache_requests_total")
    assert req.value(tier="segment", result="bypass") >= 1


# ----------------------------------------------------- batch executor


def test_batch_legs_share_tiers_with_single_query_dispatch():
    eng = _engine()
    eng.sql(GROUP_SQL)  # single-query dispatch populates tier 2
    outs = eng.sql_batch([GROUP_SQL, AGG_SQL])
    assert outs[0].equals(eng.sql(GROUP_SQL))
    recs = list(eng.history)
    # the batch leg for GROUP_SQL served from the cache the single
    # path populated...
    assert any(r.get("cache_tier") == "full" for r in recs)
    # ...and the batch-computed AGG_SQL populated the tier the single
    # path now serves from
    eng.sql(AGG_SQL)
    assert dict(eng.history[-1])["cache_hit"] is True


# -------------------------------------------------------- LRU satellite


def test_runner_caches_are_lru_not_fifo():
    eng = _engine(result_cache_enabled=False,
                  segment_cache_enabled=False)
    r = eng.runner
    eng.sql(GROUP_SQL)
    eng.sql(AGG_SQL)
    k_group = next(iter(r._plan_cache))  # oldest = GROUP_SQL's plan
    eng.sql(GROUP_SQL)  # hit moves it to the end
    keys = list(r._plan_cache)
    assert keys[-1] == k_group, "plan-cache hit did not move-to-end"
    assert keys[0] != k_group


# ------------------------------------------------------- observability


def test_debug_cache_endpoint_and_metrics_exposition():
    eng = _engine()
    eng.sql(GROUP_SQL)
    eng.sql(GROUP_SQL)
    srv = QueryServer(eng).start()
    try:
        with urllib.request.urlopen(srv.url + "/debug/cache") as r:
            snap = json.loads(r.read())
        assert snap["enabled"] == {"full": True, "segment": True}
        assert snap["full"]["hit"] >= 1
        assert snap["generations"]["t"] >= 1
        with urllib.request.urlopen(srv.url + "/metrics") as r:
            text = r.read().decode()
        assert 'tpu_olap_result_cache_requests_total' \
               '{tier="full",result="hit"}' in text
        assert 'tpu_olap_result_cache_bytes{tier="full"}' in text
    finally:
        srv.stop()


def test_explain_analyze_shows_cache_decision():
    eng = _engine(result_cache_enabled=False)
    eng.sql(_win_sql("2023-01-01", "2023-02-01"))
    out = eng.sql("EXPLAIN ANALYZE "
                  + _win_sql("2023-01-05", "2023-02-10"))
    spans = {s.strip(): d for s, d in zip(out["span"], out["detail"])}
    assert "segment-cache" in spans
    d = json.loads(spans["segment-cache"])
    assert d["segments_cached"] >= 1
    assert "segments_computed" in d
