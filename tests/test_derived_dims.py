"""Precomputed dim id streams (remap/timeformat) are device-resident
derived columns, built once per content token and reused across queries
(the round-4 latency fix: a per-dispatch 6M-row 1-D gather costs ~60 ms
on a v5e; a resident stream costs one HBM read)."""

import numpy as np
import pandas as pd

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig


def _table(n=4000, seed=9):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 300, n), unit="s"),
        "city": rng.choice([f"c{i}" for i in range(20)], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


Q_REMAP = ("SELECT city, sum(v) AS s FROM t "
           "WHERE city IN ('c1', 'c2', 'c3') GROUP BY city ORDER BY city")
Q_TIMEFORMAT = ("SELECT year(ts) AS y, sum(v) AS s FROM t "
                "GROUP BY year(ts) ORDER BY y")


def _derived_store(eng):
    ds = eng.runner._datasets.get("t")
    return {} if ds is None else ds._derived


def test_derived_stream_cached_and_reused():
    eng = Engine()
    df = _table()
    eng.register_table("t", df, time_column="ts")
    eng.sql(Q_REMAP)
    store = _derived_store(eng)
    assert len(store) == 1  # the restricted-city remap stream
    token0 = next(iter(store))
    first = store[token0]
    eng.sql(Q_REMAP)
    assert store[token0] is first  # reused, not rebuilt
    # a different restriction is a different content token
    eng.sql(Q_REMAP.replace("'c3'", "'c4'"))
    assert len(store) == 2
    # timeformat dims cache too
    eng.sql(Q_TIMEFORMAT)
    assert len(store) == 3


def test_derived_stream_parity_and_eviction_rebuild():
    df = _table()
    eng = Engine()
    eng.register_table("t", df, time_column="ts")
    a = eng.sql(Q_REMAP)
    # oracle
    sub = df[df.city.isin(["c1", "c2", "c3"])]
    exp = sub.groupby("city", as_index=False).agg(s=("v", "sum")) \
        .sort_values("city").reset_index(drop=True)
    assert a["city"].tolist() == exp["city"].tolist()
    assert a["s"].tolist() == exp["s"].tolist()
    # evict everything; the stream must rebuild transparently
    eng.clear_cache()
    b = eng.sql(Q_REMAP)
    pd.testing.assert_frame_equal(a, b)
    assert len(_derived_store(eng)) == 1


def test_derived_stream_ledger_accounting():
    df = _table()
    eng = Engine(EngineConfig(hbm_budget_bytes=64 * 2**20))
    eng.register_table("t", df, time_column="ts")
    before = eng.runner._hbm_ledger.bytes_in_use
    eng.sql(Q_REMAP)
    after = eng.runner._hbm_ledger.bytes_in_use
    assert after > before  # derived stream is accounted, not free


def test_calendar_bucket_stream_cached_with_parity():
    """Calendar granularities (searchsorted over every row) cache their
    bucket-id stream as a derived column; uniform/all kinds do not."""
    eng = Engine()
    df = _table()
    eng.register_table("t", df, time_column="ts")
    q = ("SELECT date_trunc('month', ts) AS m, sum(v) AS s FROM t "
         "GROUP BY date_trunc('month', ts) ORDER BY m")
    got = eng.sql(q)
    store = _derived_store(eng)
    assert len(store) == 1  # the monthly boundary stream
    exp = df.assign(m=df.ts.dt.to_period("M").dt.start_time) \
        .groupby("m", as_index=False).agg(s=("v", "sum")).sort_values("m")
    assert [pd.Timestamp(x) for x in got["m"]] == exp["m"].tolist()
    assert got["s"].tolist() == exp["s"].tolist()
    # repeat run reuses, doesn't rebuild
    tok = next(iter(store))
    first = store[tok]
    eng.sql(q)
    assert store[tok] is first
    # an hourly (uniform) granularity caches its own id stream too
    # (round 5: uniform buckets ride a resident stream so timeseries
    # dispatches read [S,R] int32 ids instead of the int64 __time)
    eng.sql("SELECT date_trunc('hour', ts) AS h, count(*) AS n FROM t "
            "GROUP BY date_trunc('hour', ts) LIMIT 5")
    assert len(store) == 2
    assert any(t.startswith("u:") for t in store)


def test_pallas_auto_flop_budget_gates_large_k():
    """Under 'auto', a plan whose one-hot FLOP product exceeds the
    budget keeps the scatter kernel; 'force' ignores the budget."""
    from tpu_olap.executor.lowering import lower
    df = _table()
    q = "SELECT city, sum(v) AS s FROM t GROUP BY city"

    def plan_for(cfg):
        e = Engine(cfg)
        e.register_table("t", df, time_column="ts")
        p = e.planner.plan(q)
        return lower(p.query, p.entry.segments, e.config)

    tiny = plan_for(EngineConfig(use_pallas="force",
                                 pallas_auto_flop_budget=1.0))
    assert tiny.pallas_reason is None  # force ignores the budget

    # "auto" short-circuits off-TPU before the budget gate; fake the
    # backend so the gate itself is exercised (it returns before any
    # kernel build, so no Mosaic compile is attempted)
    import tpu_olap.executor.lowering as L
    orig = L._default_backend
    L._default_backend = lambda: "tpu"
    try:
        gated = plan_for(EngineConfig(use_pallas="auto",
                                      pallas_auto_flop_budget=1.0))
    finally:
        L._default_backend = orig
    assert gated.pallas_reason is not None
    assert "FLOP" in gated.pallas_reason


def test_pallas_tuning_file_supplies_auto_default(tmp_path, monkeypatch):
    """With EngineConfig.pallas_auto_flop_budget unset, the 'auto'
    policy reads the hardware-fitted default from
    planner/pallas_tuning.json (written by tools/fit_pallas_budget.py
    from the on-chip A/B). The shipped file is never touched: the
    reader's path is monkeypatched to a tmp copy."""
    import json
    import tpu_olap.executor.lowering as L
    from tpu_olap.executor.lowering import lower
    path = tmp_path / "pallas_tuning.json"
    monkeypatch.setattr(L, "_TUNING_PATH", str(path))
    df = _table()

    def plan_on_tpu(sql):
        L._tuning_cache = None  # drop the memo so the file is re-read
        e = Engine(EngineConfig(use_pallas="auto"))
        e.register_table("t", df, time_column="ts")
        p = e.planner.plan(sql)
        monkeypatch.setattr(L, "_default_backend", lambda: "tpu")
        try:
            return lower(p.query, p.entry.segments, e.config)
        finally:
            monkeypatch.undo()
            monkeypatch.setattr(L, "_TUNING_PATH", str(path))
            L._tuning_cache = None

    path.write_text(json.dumps({"auto_flop_budget": 1.0}))
    gated = plan_on_tpu("SELECT city, sum(v) AS s FROM t GROUP BY city")
    assert gated.pallas_reason is not None
    assert "FLOP" in gated.pallas_reason

    # hardware-fitted ungrouped policy: K==1 takes the generic fused
    # reduce when the A/B said the kernel loses there
    path.write_text(json.dumps({"auto_ungrouped_pallas": False}))
    phys2 = plan_on_tpu("SELECT sum(v) AS s FROM t")
    assert phys2.pallas_reason is not None
    assert "ungrouped" in phys2.pallas_reason
    L._tuning_cache = None


def test_derived_stream_under_mesh_parity():
    df = _table()
    plain = Engine()
    sharded = Engine(EngineConfig(num_shards=8))
    for e in (plain, sharded):
        e.register_table("t", df, time_column="ts", block_rows=256)
    pd.testing.assert_frame_equal(plain.sql(Q_REMAP), sharded.sql(Q_REMAP))
    pd.testing.assert_frame_equal(plain.sql(Q_TIMEFORMAT),
                                  sharded.sql(Q_TIMEFORMAT))
