"""Resilience layer (tpu_olap.resilience; docs/RESILIENCE.md):
admission control, device circuit breaker with degraded-mode serving,
the structured error taxonomy, generalized fault-injection sites, and
the HTTP contract (429 / 503+Retry-After / 504 / 200-after-heal) plus
health endpoints and graceful server drain."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer
from tpu_olap.executor import EngineConfig
from tpu_olap.executor.runner import QueryDeadlineExceeded
from tpu_olap.planner.fallback import FallbackError
from tpu_olap.resilience import (AdmissionController, BreakerOpen,
                                 CircuitBreaker, FaultInjector,
                                 InternalError, QueryError, QueryShed,
                                 UserError)


def _df(n=4096, seed=9):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "g": rng.choice(["x", "y", "z"], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


SQL = "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g"


def _register(eng, **kw):
    eng.register_table("t", _df(), time_column="ts", block_rows=512,
                       **kw)


def _wait_until(pred, timeout_s=10.0, every_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return True
        time.sleep(every_s)
    return pred()


# ------------------------------------------------------- error taxonomy


def test_error_taxonomy_contract():
    shed = QueryShed("full", reason="queue_full")
    assert shed.http_status == 429 and shed.retriable
    assert shed.to_json() == {"error": "full", "code": "shed",
                              "retriable": True}
    bo = BreakerOpen("open", retry_after_s=2.5)
    assert bo.http_status == 503 and bo.retriable
    assert bo.retry_after_s == 2.5
    # the pre-existing exceptions joined the taxonomy
    assert issubclass(QueryDeadlineExceeded, QueryError)
    assert QueryDeadlineExceeded.http_status == 504
    assert QueryDeadlineExceeded.retriable
    assert issubclass(FallbackError, QueryError)
    assert FallbackError.http_status == 400
    # double inheritance keeps legacy except-clauses working
    assert isinstance(UserError("x"), ValueError)
    assert isinstance(InternalError("x"), RuntimeError)


# ---------------------------------------------------- admission control


def _occupy(ac):
    """Hold one slot on a helper thread until the returned event set."""
    entered, release = threading.Event(), threading.Event()

    def hold():
        with ac.slot():
            entered.set()
            release.wait(timeout=30)

    t = threading.Thread(target=hold, daemon=True)
    t.start()
    assert entered.wait(5)
    return release, t


def test_admission_queue_full_sheds():
    ac = AdmissionController(max_inflight=1, queue_limit=0)
    release, t = _occupy(ac)
    try:
        with pytest.raises(QueryShed) as ei:
            with ac.slot():
                pass
        assert ei.value.reason == "queue_full"
        assert ei.value.http_status == 429
    finally:
        release.set()
        t.join(timeout=10)
    with ac.slot():  # the slot is reusable after release
        pass
    assert ac.snapshot()["inflight"] == 0


def test_admission_deadline_budget_sheds_at_the_door():
    ac = AdmissionController(max_inflight=1, queue_limit=8)
    release, t = _occupy(ac)
    try:
        # expected wait (EWMA-seeded ~50 ms) >> 1 µs budget: shed
        # immediately instead of queueing toward a certain timeout
        with pytest.raises(QueryShed) as ei:
            with ac.slot(budget_s=1e-6):
                pass
        assert ei.value.reason == "deadline_budget"
    finally:
        release.set()
        t.join(timeout=10)


def test_admission_waits_then_admits():
    ac = AdmissionController(max_inflight=1, queue_limit=8)
    release, t = _occupy(ac)
    threading.Timer(0.2, release.set).start()
    t0 = time.perf_counter()
    with ac.slot(budget_s=30.0):
        waited = time.perf_counter() - t0
    t.join(timeout=10)
    assert 0.05 < waited < 10.0  # queued until the holder released


def test_admission_reentrant_and_disabled():
    ac = AdmissionController(max_inflight=1, queue_limit=0)
    with ac.slot():
        with ac.slot():  # nested hold on one thread: free, no deadlock
            assert ac.snapshot()["inflight"] == 1
    off = AdmissionController(max_inflight=0, queue_limit=0)
    with off.slot():  # disabled: a no-op
        assert off.snapshot()["inflight"] == 0


# ----------------------------------------------------- circuit breaker


def test_breaker_trips_and_healer_closes():
    probe_ok = {"v": False}
    br = CircuitBreaker(failure_threshold=2, cooldown_s=0.1,
                        probe=lambda: probe_ok["v"])
    br.record_failure()
    assert br.state == "closed"
    br.record_failure()
    assert br.state == "open"
    with pytest.raises(BreakerOpen) as ei:
        br.check()
    assert ei.value.http_status == 503
    assert ei.value.retry_after_s >= 0
    time.sleep(0.4)  # healer probed (False) at least once: still open
    assert br.state in ("open", "half_open")
    probe_ok["v"] = True
    assert _wait_until(lambda: br.state == "closed", 5.0)
    br.check()  # closed: no raise


def test_breaker_success_resets_consecutive():
    br = CircuitBreaker(failure_threshold=2, cooldown_s=5.0)
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"  # never two CONSECUTIVE failures
    br.close()


def test_breaker_disabled():
    br = CircuitBreaker(failure_threshold=0, cooldown_s=1.0)
    for _ in range(10):
        br.record_failure()
    br.check()  # disabled: never raises
    assert br.state == "closed"


# ------------------------------------- breaker-open degraded serving


def test_breaker_open_serves_fallback_with_path():
    """Acceptance: breaker forced open via injected consecutive dispatch
    faults; a fallback-capable GROUP BY then returns frame-identical
    results to a healthy engine, recorded as path="fallback_breaker"."""

    def always_fail(stage, attempt):
        raise RuntimeError("injected device loss")

    eng = Engine(EngineConfig(dispatch_retries=0,
                              breaker_failure_threshold=2,
                              breaker_open_cooldown_s=30.0,
                              fault_injector=always_fail))
    _register(eng)
    try:
        for _ in range(2):  # two terminal failures trip the breaker
            eng.sql(SQL)    # served by the ordinary device-failure
            #                 fallback, so no error surfaces
        assert eng.runner.breaker.state == "open"

        got = eng.sql(SQL)  # breaker open: degraded-but-correct
        rec = eng.runner.history[-1]
        assert rec["path"] == "fallback_breaker"
        assert rec["query_type"] == "fallback"
        assert rec["fallback_reason"].startswith("breaker open")
        assert eng.last_plan.fallback_reason.startswith("breaker open")
        assert eng.runner._m_degraded.value() == 1
        # no dispatch was attempted: the device stayed untouched
        ref = Engine()
        ref.register_table("t", _df(), time_column="ts", block_rows=512)
        pd.testing.assert_frame_equal(got, ref.sql(SQL))
        # the rest are legibly refused when no fallback exists: the raw
        # IR passthrough has no interpreter equivalent
        with pytest.raises(BreakerOpen):
            eng.execute_ir({"queryType": "timeseries", "dataSource": "t",
                            "granularity": "all",
                            "aggregations": [{"type": "longSum",
                                              "name": "s",
                                              "fieldName": "v"}]})
    finally:
        eng.runner.breaker.close()  # stop the healer thread


def test_breaker_metrics_exported():
    eng = Engine(EngineConfig(breaker_failure_threshold=1,
                              breaker_open_cooldown_s=30.0,
                              dispatch_retries=0,
                              fault_injector=lambda s, a: (_ for _ in ())
                              .throw(RuntimeError("boom"))))
    _register(eng)
    try:
        eng.sql(SQL)  # one failure trips (threshold 1); fallback answers
        text = eng.metrics.render()
        assert "tpu_olap_breaker_state 2" in text
        assert 'tpu_olap_breaker_transitions_total{state="open"} 1' \
            in text
        assert "tpu_olap_admission_queue_depth 0" in text
    finally:
        eng.runner.breaker.close()


# ------------------------------------------- generalized fault sites


def test_host_transfer_fault_rides_dispatch_retry():
    inj = FaultInjector(stages={"host-transfer"}, fail_calls={1})
    eng = Engine(EngineConfig(dispatch_retries=1, fault_injector=inj))
    _register(eng)
    got = eng.sql(SQL)
    assert eng.runner.history[-1]["retries"] == 1
    assert inj.by_stage == {"host-transfer": 1}
    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=512)
    pd.testing.assert_frame_equal(got, ref.sql(SQL))


def test_ingest_fault_site_aborts_registration():
    inj = FaultInjector(stages={"ingest"}, fail_calls={1})
    eng = Engine(EngineConfig(fault_injector=inj))
    with pytest.raises(RuntimeError, match="injected fault"):
        _register(eng)
    assert "t" not in eng.catalog.names()  # nothing half-registered
    _register(eng)  # the retry (call 2) succeeds
    assert len(eng.sql(SQL)) == 3


def test_reprobe_fault_site_fails_probe():
    inj = FaultInjector(stages={"reprobe"}, rate=1.0)
    eng = Engine(EngineConfig(fault_injector=inj))
    assert eng.runner._probe_device(0.5) is False
    eng.config.fault_injector = None
    assert eng.runner._probe_device(10.0) is True


def test_batch_leg_fault_falls_back_per_query():
    inj = FaultInjector(stages={"batch-leg"}, fail_calls={1})
    eng = Engine(EngineConfig(fault_injector=inj))
    _register(eng)
    sqls = [SQL, "SELECT sum(v) AS s, count(*) AS n FROM t WHERE v < 50"]
    ref = [eng.sql(q) for q in sqls]  # warm, no faults (sites unarmed
    #                                   until the fused path runs legs)
    outs = eng.sql_batch(sqls)
    assert inj.by_stage.get("batch-leg") == 1
    for got, want in zip(outs, ref):
        pd.testing.assert_frame_equal(got, want)


def test_legacy_injector_fires_only_at_dispatch():
    seen = []

    def inj(stage, attempt):
        seen.append(stage)

    eng = Engine(EngineConfig(fault_injector=inj))
    _register(eng)
    eng.sql(SQL)
    assert seen and set(seen) == {"dispatch"}


# ------------------------------------------------------- HTTP surface


def _post(url, payload, timeout=30):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get_status(url, timeout=10):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_healthz_readyz():
    eng = Engine(EngineConfig(breaker_failure_threshold=2,
                              breaker_open_cooldown_s=30.0))
    _register(eng)
    srv = QueryServer(eng).start()
    try:
        code, body, _ = _get_status(srv.url + "/healthz")
        assert code == 200 and body["status"] == "ok"
        code, body, _ = _get_status(srv.url + "/readyz")
        assert code == 200 and body["ready"] is True
        # trip the breaker: readiness goes red, liveness stays green
        eng.runner.breaker.record_failure()
        eng.runner.breaker.record_failure()
        code, body, _ = _get_status(srv.url + "/readyz")
        assert code == 503 and body["ready"] is False
        assert body["breaker"] == "open"
        code, _, _ = _get_status(srv.url + "/healthz")
        assert code == 200
        eng.runner.breaker.close()
        code, body, _ = _get_status(srv.url + "/readyz")
        assert code == 200 and body["ready"] is True
        status = _get_status(srv.url + "/status")[1]
        assert status["resilience"]["breaker"] == "closed"
    finally:
        eng.runner.breaker.close()
        srv.stop()


class _ContractInjector:
    """Stateful injector for the HTTP contract test: one object, four
    modes, armed between steps from the test body."""

    stages = {"dispatch", "reprobe"}

    def __init__(self):
        self.mode = None
        self.release = threading.Event()

    def __call__(self, stage, attempt):
        if self.mode == "stall" and stage == "dispatch":
            self.release.wait(timeout=30)
        elif self.mode == "sleep" and stage == "dispatch":
            time.sleep(2.0)
        elif self.mode == "raise":
            raise RuntimeError(f"injected device loss at {stage}")


def test_http_contract_shed_breaker_deadline_heal():
    """Acceptance: the full HTTP resilience contract on a live server —
    429 on shed, 504 on deadline, 503 + Retry-After while the breaker
    is open, then 200 after the healer's half-open probe closes it."""
    inj = _ContractInjector()
    eng = Engine(EngineConfig(
        dispatch_retries=0, fallback_on_device_failure=False,
        max_inflight_dispatches=1, admission_queue_limit=0,
        breaker_failure_threshold=2, breaker_open_cooldown_s=0.5,
        fault_injector=inj))
    _register(eng)
    want = eng.sql(SQL)  # warm the compile cache before arming faults
    srv = QueryServer(eng).start()
    try:
        # --- 429: a stalled dispatch holds the only slot; queue_limit=0
        # sheds the next arrival immediately
        inj.mode = "stall"
        t = threading.Thread(target=_post, args=(
            srv.url + "/sql", {"query": SQL}), kwargs={"timeout": 60})
        t.start()
        assert _wait_until(
            lambda: eng.runner.admission.snapshot()["inflight"] == 1, 10)
        code, body, _ = _get_status(srv.url + "/status")  # not gated
        assert code == 200
        code, body, _ = _post_status(srv.url + "/sql", {"query": SQL})
        assert code == 429
        assert body["code"] == "shed" and body["retriable"] is True
        inj.release.set()
        t.join(timeout=60)
        inj.mode = None

        # --- 504: a wedged dispatch exceeds the deadline and no
        # fallback is available
        eng.config.query_deadline_s = 0.4
        inj.mode = "sleep"
        code, body, _ = _post_status(srv.url + "/sql", {"query": SQL})
        assert code == 504
        assert body["code"] == "deadline_exceeded"
        assert body["retriable"] is True

        # --- 503 + Retry-After: consecutive failures trip the breaker
        # (the deadline above already counted one); "raise" mode also
        # fails the reprobe so the healer cannot close it yet
        inj.mode = "raise"
        saw = []
        for _ in range(6):
            code, body, headers = _post_status(srv.url + "/sql",
                                               {"query": SQL})
            saw.append(code)
            if code == 503:
                break
        assert 503 in saw, saw
        assert body["code"] == "breaker_open"
        assert int(headers["Retry-After"]) >= 1
        code, _, _ = _get_status(srv.url + "/readyz")
        assert code == 503

        # --- 200 after heal: disarm the faults; the healer's half-open
        # probe closes the breaker within a cooldown cycle or two
        eng.config.query_deadline_s = None
        inj.mode = None
        assert _wait_until(
            lambda: _get_status(srv.url + "/readyz")[0] == 200, 20)
        out = _post(srv.url + "/sql", {"query": SQL})
        assert [r["g"] for r in out["rows"]] == list(want["g"])
    finally:
        inj.release.set()
        inj.mode = None
        eng.runner.breaker.close()
        srv.stop()
        time.sleep(0.1)  # let the abandoned sleep-dispatch thread drain


def _post_status(url, payload, timeout=30):
    try:
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def test_server_stop_drains_inflight_request():
    """QueryServer.stop() must let a mid-flight query finish (bounded)
    instead of severing its response at shutdown()."""
    inj = _ContractInjector()
    eng = Engine(EngineConfig(fault_injector=inj))
    _register(eng)
    eng.sql(SQL)  # warm
    srv = QueryServer(eng).start()
    out = {}

    def slow_post():
        try:
            out["resp"] = _post(srv.url + "/sql", {"query": SQL},
                                timeout=60)
        except Exception as e:  # noqa: BLE001 — inspected below
            out["err"] = e

    inj.mode = "stall"
    t = threading.Thread(target=slow_post)
    t.start()
    assert _wait_until(lambda: srv._inflight >= 1, 10)
    threading.Timer(0.4, inj.release.set).start()
    t0 = time.perf_counter()
    srv.stop(drain_timeout_s=15)
    stopped_in = time.perf_counter() - t0
    t.join(timeout=30)
    assert "err" not in out, out.get("err")
    assert [r["g"] for r in out["resp"]["rows"]] == ["x", "y", "z"]
    assert stopped_in < 12  # drained, not hung
