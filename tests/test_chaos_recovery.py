"""Seeded chaos recovery (ISSUE 7 satellite): random faults injected
across every generalized stage (dispatch, host-transfer, batch-leg,
reprobe, ingest) over a mixed query workload must never surface an
error or a wrong answer — every response stays frame-identical to a
clean engine (retry -> fallback -> breaker degraded serving, in that
order), and once the chaos stops the breaker heals closed.

The tier-1 variant runs ~50 queries; the @pytest.mark.slow soak runs a
higher count across more seeds (out of tier-1)."""

import random
import time

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import assert_frame_parity
from tpu_olap.executor import EngineConfig
from tpu_olap.resilience import FaultInjector


def _df(n=4096, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2022-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 45, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], n),
        "h": rng.choice(["a", "b"], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
        "w": rng.normal(50, 10, n),
    })


# mixed workload: dense GROUP BY, timeseries, topN-shaped, HAVING,
# filters, scan — every statement carries an ORDER BY (or the engine's
# deterministic time-sorted-prefix contract) so frames compare exactly
QUERIES = [
    "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g",
    "SELECT count(*) AS n, sum(v) AS s FROM t WHERE v < 500",
    "SELECT g, h, sum(v) AS s FROM t GROUP BY g, h ORDER BY g, h",
    "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY s DESC LIMIT 3",
    "SELECT g, max(w) AS m FROM t WHERE h = 'a' GROUP BY g "
    "HAVING sum(v) > 1000 ORDER BY g",
    "SELECT month(ts) AS mo, sum(v) AS s FROM t GROUP BY month(ts) "
    "ORDER BY mo",
    "SELECT min(v) AS lo, max(v) AS hi FROM t",
]
BATCH = [QUERIES[0], QUERIES[1], QUERIES[2]]


def _reference():
    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=512)
    return {q: ref.sql(q) for q in QUERIES}


def _run_chaos(n_queries: int, seed: int, rate: float = 0.25):
    want = _reference()
    eng = Engine(EngineConfig(dispatch_retries=1,
                              breaker_failure_threshold=2,
                              breaker_open_cooldown_s=0.2))
    # register BEFORE arming chaos (the ingest site would abort it);
    # ingest faults are exercised on scratch registrations below
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    inj = FaultInjector(seed=seed, rate=rate, stages=None)  # all sites
    eng.config.fault_injector = inj
    rng = random.Random(seed + 1)
    try:
        for i in range(n_queries):
            if i % 7 == 3:
                # batch submissions hit the per-batch-leg fault site;
                # a faulted leg re-runs per statement (retry/fallback)
                for got, q in zip(eng.sql_batch(BATCH), BATCH):
                    assert_frame_parity(got, want[q], ordered=True,
                                        label=q)
                continue
            if i % 10 == 5:
                # ingest faults abort registration legibly and leave
                # no half-registered table behind
                try:
                    eng.register_table(f"scratch{i}", _df(256),
                                       time_column="ts")
                except RuntimeError:
                    assert f"scratch{i}" not in eng.catalog.names()
                continue
            q = rng.choice(QUERIES)
            assert_frame_parity(eng.sql(q), want[q], ordered=True,
                                label=q)
    finally:
        eng.config.fault_injector = None
    assert inj.faults > 0, "chaos never fired — the test proves nothing"
    # chaos over: the healer closes the breaker (cooldown 0.2 s), and a
    # healthy query rides the device path again
    deadline = time.monotonic() + 10
    while eng.runner.breaker.state != "closed" and \
            time.monotonic() < deadline:
        time.sleep(0.05)
    assert eng.runner.breaker.state == "closed"
    assert_frame_parity(eng.sql(QUERIES[0]), want[QUERIES[0]],
                        ordered=True)
    assert eng.runner.history[-1]["query_type"] == "groupBy"
    return inj


def test_chaos_recovery_parity():
    inj = _run_chaos(n_queries=50, seed=7)
    # the sweep should have hit more than one stage to mean anything
    assert len(inj.by_stage) >= 2, inj.by_stage
    # stages=None opts into the per-stage-boundary sites too (ISSUE 16:
    # plan/enqueue/transfer/finalize/assemble) — the stage graph must
    # survive faults at its own seams, not just inside the legacy sites
    assert any(s.startswith("stage-") for s in inj.by_stage), \
        inj.by_stage


@pytest.mark.slow
@pytest.mark.parametrize("seed", [11, 23, 47])
def test_chaos_recovery_soak(seed):
    _run_chaos(n_queries=300, seed=seed, rate=0.3)
