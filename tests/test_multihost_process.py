"""The multi-host (DCN-shaped) path must execute with REAL multiple
processes, not just a single-process virtual mesh (SURVEY.md §3.6).

tools/multihost_check.py spawns 2 jax.distributed processes (4 virtual
CPU devices each), builds make_multihost_mesh over the 8 global devices,
shard_puts a segment-axis array from each host, and runs the engine's
merge shapes under `jax.jit` + `NamedSharding` — a replicated-output
reduce (GSPMD inserts the cross-host psum) and a sharded-output per-chip
partials reduce. This test drives it end-to-end and checks both workers
agreed on the global sum, and that a REAL engine GROUP BY (the mesh
dispatch forces the GSPMD "broker" strategy across processes) matches
the pandas oracle on each host.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_distributed_psum():
    env = dict(os.environ)
    env["MULTIHOST_PORT"] = "47353"  # keep clear of a concurrent CLI run
    # CI runs a reduced row count (the single-core host pays ~minutes at
    # the full 1M); the banked MULTIHOST_2PROC.json artifact is produced
    # by a separate full-size run (default MULTIHOST_ROWS = 1<<20)
    env.setdefault("MULTIHOST_ROWS", str(1 << 18))
    env.setdefault("MULTIHOST_OUT", "/tmp/MULTIHOST_CI.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_check.py")],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["ok"] is True
    with open(env["MULTIHOST_OUT"]) as f:
        art = json.load(f)
    assert art["ok"] is True
    assert len(art["workers"]) == 2
    if not art.get("compute_supported", True):
        # this jax build's CPU backend cannot compile cross-process
        # computations (newer builds can — CI runs the full path);
        # the distributed topology itself (2-process init, global
        # 8-device mesh, per-host shard materialization) was still
        # proven by each worker before it reported the capability gap
        for w in art["workers"]:
            assert w["devices"] == 8 and w["local_devices"] == 4
        return
    for w in art["workers"]:
        assert w["psum_total"] == w["expect"]
        # a REAL engine GROUP BY ran SPMD on both processes and matched
        # the pandas oracle on each
        assert w["engine_query_ok"] is True
