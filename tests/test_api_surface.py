"""L7 surface tests: statement verbs, counters, and the HTTP query server
(the ThriftServer-wrapper analog, SURVEY.md §3.1/§4.5)."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer


@pytest.fixture()
def engine():
    rng = np.random.default_rng(5)
    n = 5000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2021-06-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 60, n), unit="s"),
        "shop": rng.choice(["a", "b", "c"], n),
        "amount": rng.integers(1, 500, n).astype(np.int64),
    })
    eng = Engine()
    eng.register_table("sales", df, time_column="ts")
    return eng


def test_clear_cache_verb(engine):
    engine.sql("SELECT shop, sum(amount) AS s FROM sales GROUP BY shop")
    assert engine.runner._datasets
    out = engine.sql("CLEAR DRUID CACHE")
    assert out.status[0] == "cleared cache"
    assert not engine.runner._datasets
    out = engine.sql("CLEAR DRUID CACHE sales")
    assert "sales" in out.status[0]


def test_explain_rewrite_verb(engine):
    out = engine.sql(
        "EXPLAIN DRUID REWRITE SELECT shop, sum(amount) AS s "
        "FROM sales GROUP BY shop")
    text = "\n".join(out.plan)
    info = json.loads(text)
    assert info["rewritten"] is True
    assert info["query"]["queryType"] == "groupBy"


def test_passthrough_verb(engine):
    spec = json.dumps({
        "queryType": "timeseries",
        "granularity": "all",
        "aggregations": [{"type": "longSum", "name": "s",
                          "fieldName": "amount"}],
    })
    out = engine.sql(
        f"ON DRUID DATASOURCE sales EXECUTE QUERY '{spec}'")
    ref = engine.sql("SELECT sum(amount) AS s FROM sales")
    assert int(out.s[0]) == int(ref.s[0])


def test_counters(engine):
    engine.sql("SELECT shop, sum(amount) AS s FROM sales GROUP BY shop")
    engine.sql("SELECT sum(amount) AS s FROM sales")
    c = engine.counters()
    assert c["queries"] == 2
    assert c["rows_scanned"] > 0
    assert c["by_query_type"] == {"groupBy": 1, "timeseries": 1}


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def test_http_server(engine):
    srv = QueryServer(engine).start()
    try:
        out = _post(srv.url + "/sql", {
            "query": "SELECT shop, sum(amount) AS s FROM sales "
                     "GROUP BY shop ORDER BY shop"})
        assert out["columns"] == ["shop", "s"]
        assert [r["shop"] for r in out["rows"]] == ["a", "b", "c"]

        druid = _post(srv.url + "/druid/v2", {
            "queryType": "timeseries",
            "dataSource": "sales",
            "granularity": "all",
            "aggregations": [{"type": "longSum", "name": "s",
                              "fieldName": "amount"}]})
        assert druid[0]["result"]["s"] == sum(r["s"] for r in out["rows"])

        status = _get(srv.url + "/status")
        assert status["tables"]["sales"]["accelerated"] is True
        assert status["counters"]["queries"] >= 2

        meta = _get(srv.url + "/status/metadata/sales")
        assert meta["columns"]["amount"]["type"] == "LONG"

        # bad SQL -> 400 with an error body, server stays up
        try:
            _post(srv.url + "/sql", {"query": "SELEKT nope"})
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())
        out2 = _get(srv.url + "/status")
        assert out2["engine"] == "tpu_olap"
    finally:
        srv.stop()


def test_jsonable_pandas_nulls():
    from tpu_olap.api.server import _jsonable
    assert _jsonable(pd.NaT) is None
    assert _jsonable(pd.NA) is None
    assert _jsonable(float("nan")) is None
    assert _jsonable({"a": [pd.NaT, 1, "x"]}) == {"a": [None, 1, "x"]}
    assert _jsonable(np.float64("inf")) is None


def test_status_does_not_force_lazy_frame(engine, tmp_path):
    df = pd.DataFrame({"k": [1, 2], "v": ["x", "y"]})
    path = str(tmp_path / "dim.parquet")
    df.to_parquet(path)
    engine.register_table("dim", path, accelerate=False)
    srv = QueryServer(engine).start()
    try:
        status = _get(srv.url + "/status")
        assert status["tables"]["dim"]["numRows"] is None
        assert engine.catalog.get("dim")._frame is None  # not materialized
        engine.sql("SELECT k FROM dim")  # fallback loads it
        status = _get(srv.url + "/status")
        assert status["tables"]["dim"]["numRows"] == 2
    finally:
        srv.stop()


def test_concurrent_fallback_not_wedged_behind_device_query(engine):
    """A slow device dispatch must not block fallback queries or status
    pings (VERDICT r1 missing #6: one pathological query wedged the
    endpoint behind a global lock)."""
    import threading
    import time

    engine.register_table(
        "dim", pd.DataFrame({"k": [1, 2, 3]}), accelerate=False)
    release = threading.Event()

    def stall(stage, attempt):
        release.wait(timeout=20)

    engine.config.fault_injector = stall
    engine.clear_cache()  # force the next device query through dispatch
    srv = QueryServer(engine).start()
    try:
        t = threading.Thread(target=_post, args=(
            srv.url + "/sql",
            {"query": "SELECT sum(amount) AS s FROM sales"}))
        t.start()
        time.sleep(0.2)  # let the device query take the lock
        t0 = time.perf_counter()
        out = _post(srv.url + "/sql",
                    {"query": "SELECT k FROM dim ORDER BY k"})
        status = _get(srv.url + "/status")
        elapsed = time.perf_counter() - t0
        assert [r["k"] for r in out["rows"]] == [1, 2, 3]
        assert status["engine"] == "tpu_olap"
        assert elapsed < 5.0  # answered while the device query stalled
    finally:
        release.set()
        t.join(timeout=30)
        engine.config.fault_injector = None
        srv.stop()


def test_profiler_hook(tmp_path):
    rng = np.random.default_rng(3)
    df = pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400, 256), unit="s"),
        "v": rng.integers(0, 9, 256).astype(np.int64),
    })
    from tpu_olap.executor import EngineConfig
    eng = Engine(EngineConfig(profile_dir=str(tmp_path)))
    eng.register_table("t", df, time_column="ts")
    eng.sql("SELECT sum(v) AS s FROM t")
    rec = eng.history[-1]
    assert rec["profile_trace"].startswith(str(tmp_path))
    import os
    assert os.path.isdir(rec["profile_trace"])
