"""L7 surface tests: statement verbs, counters, and the HTTP query server
(the ThriftServer-wrapper analog, SURVEY.md §3.1/§4.5)."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer


@pytest.fixture()
def engine():
    rng = np.random.default_rng(5)
    n = 5000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2021-06-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 60, n), unit="s"),
        "shop": rng.choice(["a", "b", "c"], n),
        "amount": rng.integers(1, 500, n).astype(np.int64),
    })
    eng = Engine()
    eng.register_table("sales", df, time_column="ts")
    return eng


def test_clear_cache_verb(engine):
    engine.sql("SELECT shop, sum(amount) AS s FROM sales GROUP BY shop")
    assert engine.runner._datasets
    out = engine.sql("CLEAR DRUID CACHE")
    assert out.status[0] == "cleared cache"
    assert not engine.runner._datasets
    out = engine.sql("CLEAR DRUID CACHE sales")
    assert "sales" in out.status[0]


def test_explain_rewrite_verb(engine):
    out = engine.sql(
        "EXPLAIN DRUID REWRITE SELECT shop, sum(amount) AS s "
        "FROM sales GROUP BY shop")
    text = "\n".join(out.plan)
    info = json.loads(text)
    assert info["rewritten"] is True
    assert info["query"]["queryType"] == "groupBy"


def test_passthrough_verb(engine):
    spec = json.dumps({
        "queryType": "timeseries",
        "granularity": "all",
        "aggregations": [{"type": "longSum", "name": "s",
                          "fieldName": "amount"}],
    })
    out = engine.sql(
        f"ON DRUID DATASOURCE sales EXECUTE QUERY '{spec}'")
    ref = engine.sql("SELECT sum(amount) AS s FROM sales")
    assert int(out.s[0]) == int(ref.s[0])


def test_counters(engine):
    engine.sql("SELECT shop, sum(amount) AS s FROM sales GROUP BY shop")
    engine.sql("SELECT sum(amount) AS s FROM sales")
    c = engine.counters()
    assert c["queries"] == 2
    assert c["rows_scanned"] > 0
    assert c["by_query_type"] == {"groupBy": 1, "timeseries": 1}


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def test_http_server(engine):
    srv = QueryServer(engine).start()
    try:
        out = _post(srv.url + "/sql", {
            "query": "SELECT shop, sum(amount) AS s FROM sales "
                     "GROUP BY shop ORDER BY shop"})
        assert out["columns"] == ["shop", "s"]
        assert [r["shop"] for r in out["rows"]] == ["a", "b", "c"]

        druid = _post(srv.url + "/druid/v2", {
            "queryType": "timeseries",
            "dataSource": "sales",
            "granularity": "all",
            "aggregations": [{"type": "longSum", "name": "s",
                              "fieldName": "amount"}]})
        assert druid[0]["result"]["s"] == sum(r["s"] for r in out["rows"])

        status = _get(srv.url + "/status")
        assert status["tables"]["sales"]["accelerated"] is True
        assert status["counters"]["queries"] >= 2

        meta = _get(srv.url + "/status/metadata/sales")
        assert meta["columns"]["amount"]["type"] == "LONG"

        # bad SQL -> 400 with an error body, server stays up
        try:
            _post(srv.url + "/sql", {"query": "SELEKT nope"})
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())
        out2 = _get(srv.url + "/status")
        assert out2["engine"] == "tpu_olap"
    finally:
        srv.stop()
