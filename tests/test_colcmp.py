"""columnComparison filter: row-vs-row equality across columns
(SURVEY.md §3.3 filter family; the TPC-H Q5/Q7 shape).

Semantics under test (kernels/filtereval._colcmp_pair): a NULL operand
never matches at the leaf; NOT inversion makes NULL rows match `<>` —
exactly the pandas fallback's object-dtype behavior, so parity holds by
construction. String pairs translate codes across dictionaries via a
derived stream (one elementwise compare per dispatch, no gather).
"""

import json

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import assert_frame_parity, run_both
from tpu_olap.executor import EngineConfig
from tpu_olap.ir.filters import ColumnComparisonFilter, filter_from_json


def _frame(n=4000, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.integers(0, 5, n).astype(float)
    x[rng.random(n) < 0.1] = np.nan
    y = rng.integers(0, 5, n).astype(float)
    y[rng.random(n) < 0.1] = np.nan
    return pd.DataFrame({
        "ts": pd.to_datetime("2024-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        # overlapping-but-distinct vocabularies: "kiev" only on the left,
        # "bern" only on the right — exercises absent-value translation
        "city": rng.choice(["rome", "oslo", "lima", "kiev", None], n),
        "dest": rng.choice(["rome", "oslo", "lima", "bern", None], n),
        "x": x, "y": y,
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


@pytest.fixture(scope="module")
def eng():
    e = Engine()
    e.register_table("t", _frame(), time_column="ts")
    return e


PARITY_SQL = [
    "SELECT count(*) AS n, sum(v) AS s FROM t WHERE city = dest",
    "SELECT count(*) AS n FROM t WHERE city <> dest",
    "SELECT count(*) AS n FROM t WHERE NOT (city = dest)",
    "SELECT city, count(*) AS n FROM t WHERE city = dest GROUP BY city",
    "SELECT count(*) AS n FROM t WHERE x = y",
    # <> with NULL operands: NOT(==) matches the fallback's NaN != x;
    # a bare ExpressionFilter(!=) would exclude them (regression lock
    # for the round-4 lowering fix in planner/plan.py::_to_filter)
    "SELECT count(*) AS n FROM t WHERE x <> y",
    "SELECT count(*) AS n FROM t WHERE x + 1 <> y + 1",
    "SELECT count(*) AS n FROM t WHERE city = dest AND x = y",
]


@pytest.mark.parametrize("sql", PARITY_SQL)
def test_device_parity(eng, sql):
    dev, fb, _ = run_both(eng, sql)  # raises ParityError on fallback
    assert_frame_parity(dev, fb, ordered=False, label=sql)


def test_null_semantics_exact(eng):
    """Pin the counts, not just parity: nulls never match `=`; every
    null-operand row matches `<>` (NOT inversion)."""
    f = _frame()
    both = (f.city.notna() & f.dest.notna())
    eq = int((both & (f.city == f.dest)).sum())
    got = eng.sql("SELECT count(*) AS n FROM t WHERE city = dest")
    assert int(got.iloc[0]["n"]) == eq
    got = eng.sql("SELECT count(*) AS n FROM t WHERE city <> dest")
    assert int(got.iloc[0]["n"]) == len(f) - eq


def test_mesh_and_pallas_force():
    frame = _frame(seed=11)
    for cfg, tag in [(EngineConfig(num_shards=8), "mesh8"),
                     (EngineConfig(use_pallas="force"), "pallas-force")]:
        e = Engine(cfg)
        e.register_table("t", frame, time_column="ts")
        sql = ("SELECT city, sum(v) AS s FROM t WHERE city = dest "
               "GROUP BY city")
        dev, fb, _ = run_both(e, sql)
        assert_frame_parity(dev, fb, ordered=False, label=tag)
        if tag == "pallas-force":
            # columnComparison IS Pallas-whitelisted: the translation
            # stream enters the kernel as an int32 row (no in-kernel
            # gather), so the fused kernel must be active for this plan
            from tpu_olap.executor.lowering import lower
            plan = e.planner.plan(sql)
            phys = lower(plan.query, plan.entry.segments, e.config)
            assert phys.pallas_reason is None, phys.pallas_reason


def test_scan_path(eng):
    got = eng.sql("SELECT city, dest, v FROM t WHERE city = dest "
                  "ORDER BY v DESC LIMIT 5")
    assert len(got) == 5
    assert (got["city"] == got["dest"]).all()


def test_raw_ir_passthrough(eng):
    body = json.dumps({
        "queryType": "timeseries", "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}],
        "filter": {"type": "columnComparison",
                   "dimensions": ["city", "dest"]},
        "intervals": ["1000-01-01/3000-01-01"],
    })
    out = eng.sql(f"ON DRUID DATASOURCE t EXECUTE QUERY '{body}'")
    f = _frame()
    exp = int((f.city.notna() & (f.city == f.dest)).sum())
    assert int(out.iloc[0]["n"]) == exp


def test_serde_roundtrip():
    f = ColumnComparisonFilter(("a", "b", "c"))
    assert filter_from_json(f.to_json()) == f
    with pytest.raises(ValueError):
        filter_from_json({"type": "columnComparison", "dimensions": ["a"]})


def test_mixed_types_fall_back(eng):
    """String-vs-numeric comparison is outside the filter algebra — the
    fallback must answer it (correct-but-slow, never an error)."""
    from tpu_olap.bench.parity import ParityError
    with pytest.raises(ParityError):
        run_both(eng, "SELECT count(*) AS n FROM t WHERE city = v")


def test_ordered_string_comparison_falls_back(eng):
    from tpu_olap.bench.parity import ParityError
    with pytest.raises(ParityError):
        run_both(eng, "SELECT count(*) AS n FROM t WHERE city < dest")


def test_derived_stream_cached(eng):
    """The translation stream is built once per content token and reused
    across dispatches (the round-4 no-per-dispatch-gather rule)."""
    ds = eng.runner._datasets.get("t")
    if ds is None:
        eng.sql("SELECT count(*) AS n FROM t WHERE city = dest")
        ds = eng.runner._datasets["t"]
    eng.sql("SELECT count(*) AS n FROM t WHERE city = dest")
    n0 = len(ds._derived)
    eng.sql("SELECT sum(v) AS s FROM t WHERE city = dest")
    assert len(ds._derived) == n0  # same pair -> same token, no rebuild
