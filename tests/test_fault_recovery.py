"""Failure detection / elastic recovery (SURVEY.md §6): retryable device
dispatch with cache purge, shard degradation after injected chip loss, and
fault exhaustion surfacing the error. The reference's analog is Spark task
retry re-running a DruidRDD partition; here the "partition" is the whole
sharded dispatch and recovery re-shards the manifest."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig


def _df(n=4096, seed=9):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 30, n), unit="s"),
        "g": rng.choice(["x", "y", "z"], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })


SQL = "SELECT g, sum(v) AS s, count(*) AS n FROM t GROUP BY g ORDER BY g"


class FlakyInjector:
    """Raises on the first `fail_times` dispatch attempts."""

    def __init__(self, fail_times):
        self.fail_times = fail_times
        self.calls = 0

    def __call__(self, stage, attempt):
        self.calls += 1
        if self.calls <= self.fail_times:
            raise RuntimeError(f"injected fault #{self.calls} at {stage}")


def test_retry_recovers():
    inj = FlakyInjector(1)
    eng = Engine(EngineConfig(dispatch_retries=1, fault_injector=inj))
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    got = eng.sql(SQL)
    assert eng.history[-1]["retries"] == 1
    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=512)
    pd.testing.assert_frame_equal(got, ref.sql(SQL))


def test_retry_exhaustion_falls_back():
    """SURVEY.md §2 property 2: after retries exhaust on a non-structural
    failure, the engine answers correctly (slow path), never errors."""
    inj = FlakyInjector(10)
    eng = Engine(EngineConfig(dispatch_retries=1, fault_injector=inj))
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    got = eng.sql(SQL)
    assert eng.last_plan.fallback_reason.startswith("device failure")
    # the failed device dispatch left a record with its retry errors
    # (the fallback execution that answered records separately, after)
    failed = [h for h in eng.runner.history if h.get("failed")]
    assert failed and failed[-1]["retry_errors"]
    assert eng.runner.history[-1]["query_type"] == "fallback"
    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=512)
    pd.testing.assert_frame_equal(got, ref.sql(SQL))


def test_retry_exhaustion_raises_when_fallback_disabled():
    inj = FlakyInjector(10)
    eng = Engine(EngineConfig(dispatch_retries=1, fault_injector=inj,
                              fallback_on_device_failure=False))
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    with pytest.raises(RuntimeError, match="injected fault"):
        eng.sql(SQL)


def test_deadline_falls_back():
    """Per-query deadline (the task-kill -> query-abort analog): a wedged
    dispatch times out and the engine still answers via fallback."""
    import time as _time

    def slow_injector(stage, attempt):
        _time.sleep(2.0)

    eng = Engine(EngineConfig(query_deadline_s=0.3,
                              fault_injector=slow_injector,
                              dispatch_retries=0))
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    t0 = _time.perf_counter()
    got = eng.sql(SQL)
    assert "QueryDeadlineExceeded" in eng.last_plan.fallback_reason
    # deadline record first, then the fallback execution's own record
    assert any(h.get("deadline_exceeded") for h in eng.runner.history)
    assert eng.runner.history[-1]["query_type"] == "fallback"
    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=512)
    pd.testing.assert_frame_equal(got, ref.sql(SQL))


def test_deadline_recovery_reaches_device_again():
    """VERDICT round-2 task #5: after a timed-out query N, query N+1 must
    re-probe the device, clear the wedge, and execute on the device path
    again (no permanent engine-wide CPU downgrade). The injector wedges
    exactly once."""
    import time as _time

    class WedgeOnce:
        def __init__(self):
            self.calls = 0

        def __call__(self, stage, attempt):
            self.calls += 1
            if self.calls == 1:
                _time.sleep(1.5)

    inj = WedgeOnce()
    eng = Engine(EngineConfig(dispatch_retries=0))
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    eng.sql(SQL)  # warm the compile cache outside the deadline regime
    eng.config.query_deadline_s = 0.4
    eng.config.fault_injector = inj

    got1 = eng.sql(SQL)  # wedges -> deadline -> fallback
    assert "QueryDeadlineExceeded" in eng.last_plan.fallback_reason
    assert eng.runner._wedged

    # the point below is RECOVERY, not deadline tightness — a loaded CI
    # host must not trip the 0.4 s deadline on the legitimate re-run
    eng.config.query_deadline_s = 30.0
    got2 = eng.sql(SQL)  # reprobe succeeds -> device path again
    assert eng.last_plan.fallback_reason is None
    assert not eng.runner._wedged
    assert any(h.get("device_probe_recovered") for h in eng.runner.history)
    # the device-path record for query 2 exists and is not a fallback
    assert eng.runner.history[-1]["query_type"] == "groupBy"
    assert not eng.runner.history[-1].get("deadline_exceeded")

    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=512)
    expect = ref.sql(SQL)
    pd.testing.assert_frame_equal(got1, expect)
    pd.testing.assert_frame_equal(got2, expect)
    # let the abandoned thread drain so it cannot leak into other tests
    _time.sleep(1.3)


def test_shard_degradation():
    """Chip-loss analog: the 8-way mesh dispatch fails twice; recovery
    re-shards to 2 and the query still answers correctly."""
    inj = FlakyInjector(2)
    eng = Engine(EngineConfig(num_shards=8, dispatch_retries=2,
                              degrade_shards_on_retry=True,
                              fault_injector=inj))
    eng.register_table("t", _df(), time_column="ts", block_rows=256)
    got = eng.sql(SQL)
    h = eng.history[-1]
    assert h["retries"] == 2
    assert h["degraded_shards"] == 2
    assert h["num_shards"] == 2
    ref = Engine()
    ref.register_table("t", _df(), time_column="ts", block_rows=256)
    pd.testing.assert_frame_equal(got, ref.sql(SQL))


def test_injector_quiescent_by_default():
    eng = Engine(EngineConfig(dispatch_retries=3))
    eng.register_table("t", _df(), time_column="ts", block_rows=512)
    eng.sql(SQL)
    assert "retries" not in eng.history[-1]
