"""Cost model (planner.cost): the DruidQueryCostModel analog — strategy
choice between sharded per-chip partials + host broker merge
("historicals") and
whole-program GSPMD ("broker"), and its integration into execution and
EXPLAIN (SURVEY.md §3.2, §6)."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import check_query
from tpu_olap.executor import EngineConfig
from tpu_olap.planner import cost as cost_mod


def _table(n=4096, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime(rng.integers(725846400000, 757382400000, n),
                             unit="ms"),
        "dim": rng.choice([f"d{i}" for i in range(30)], n),
        "val": rng.integers(0, 1000, n).astype(np.int64),
    })


def _plan_for(eng, sql):
    from tpu_olap.executor.lowering import lower
    plan = eng.planner.plan(sql)
    assert plan.rewritten, plan.fallback_reason
    return lower(plan.query, plan.entry.segments, eng.config)


def test_small_groupby_prefers_historicals():
    eng = Engine()
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    phys = _plan_for(eng, "SELECT dim, sum(val) AS s FROM t GROUP BY dim")
    d = cost_mod.decide(phys, eng.config, shards=8)
    assert d.strategy == "historicals"
    assert d.shards == 8
    assert d.groups <= 64


def test_sketch_heavy_table_prefers_broker():
    # HLL state is [groups x 2048] int32: with enough groups the explicit
    # allreduce dominates any scan of a few thousand rows
    eng = Engine()
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    phys = _plan_for(eng, """
        SELECT dim, val, count(DISTINCT dim) AS u
        FROM t GROUP BY dim, val
    """)
    d = cost_mod.decide(phys, eng.config, shards=8)
    assert d.table_bytes > 100 * d.rows_scanned
    assert d.strategy == "broker"


def test_disabled_model_pins_historicals():
    eng = Engine(EngineConfig(cost_model_enabled=False))
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    phys = _plan_for(eng, """
        SELECT dim, val, count(DISTINCT dim) AS u
        FROM t GROUP BY dim, val
    """)
    d = cost_mod.decide(phys, eng.config, shards=8)
    assert d.strategy == "historicals"
    assert d.reason == "cost model disabled"


def test_single_device_is_trivially_historicals():
    eng = Engine()
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    phys = _plan_for(eng, "SELECT sum(val) AS s FROM t")
    d = cost_mod.decide(phys, eng.config, shards=1)
    assert d.strategy == "historicals"
    assert d.merge_us == 0.0


@pytest.mark.parametrize("strategy", ["historicals", "broker"])
def test_both_strategies_agree_with_fallback(strategy, monkeypatch):
    eng = Engine(EngineConfig(num_shards=8))
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    orig = cost_mod.decide

    def force(plan, config, shards):
        d = orig(plan, config, shards)
        return cost_mod.CostDecision(strategy, d.shards, d.rows_scanned,
                                     d.groups, d.table_bytes, d.scan_us,
                                     d.merge_us, "forced by test")
    monkeypatch.setattr(cost_mod, "decide", force)
    check_query(eng, """
        SELECT dim, sum(val) AS s, count() AS n, min(val) AS lo
        FROM t GROUP BY dim ORDER BY dim
    """, label=f"strategy={strategy}")
    m = eng.runner.history[-1]
    assert m["cost"]["strategy"] == strategy
    assert m["num_shards"] == 8


def test_decision_flips_at_modeled_crossover():
    """Regression for the calibrated-constants wiring (VERDICT round-2
    task #6): whatever constants decide() resolves (pinned > fitted >
    fallback), the strategy must flip exactly where the documented model
    says merge_us crosses overhead*(scan_us + lat*hops). Group count is
    swept via a numeric dim whose range sets the dense id space."""
    import math

    eng = Engine()
    shards = 8
    hops = math.ceil(math.log2(shards))
    c = cost_mod.constants(eng.config)
    n = 4096

    def decision_for(k):
        rng = np.random.default_rng(5)
        df = pd.DataFrame({
            "ts": pd.to_datetime("2024-01-01")
            + pd.to_timedelta(np.arange(n) % 9999, unit="s"),
            "g": np.concatenate(
                [np.array([0, k - 1]),
                 rng.integers(0, k, n - 2)]).astype(np.int64),
            "v": rng.integers(0, 100, n).astype(np.int64),
        })
        e = Engine()
        e.register_table("t", df, time_column="ts", block_rows=512)
        phys = _plan_for(e, "SELECT g, sum(v) AS s FROM t GROUP BY g")
        # numeric dims carry a null slot: dense space is k or k+1
        assert phys.total_groups in (k, k + 1), (phys.total_groups, k)
        return cost_mod.decide(phys, e.config, shards=shards)

    # solve the documented crossover for table bytes, then for groups,
    # using the probe decision's own scan estimate and per-group width
    probe = decision_for(8)
    width = probe.table_bytes // probe.groups
    scan_us = probe.scan_us
    bytes_star = ((c["gspmd_overhead"]
                   * (scan_us + c["collective_lat_us"] * hops) / hops
                   - c["collective_lat_us"])
                  * 1000.0 / c["merge_ns_per_byte"])
    k_star = int(bytes_star / width)
    assert k_star > 4, "constants degenerate: crossover below any K"
    below = decision_for(max(2, int(k_star * 0.5)))
    above = decision_for(int(k_star * 2.0))
    assert below.strategy == "historicals", below
    assert above.strategy == "broker", above


def test_force_strategy_override():
    eng = Engine(EngineConfig(force_strategy="broker"))
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    phys = _plan_for(eng, "SELECT dim, sum(val) AS s FROM t GROUP BY dim")
    d = cost_mod.decide(phys, eng.config, shards=8)
    assert d.strategy == "broker"
    assert d.reason == "forced by config"


def test_explain_includes_cost():
    eng = Engine()
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    out = eng.explain("SELECT dim, sum(val) AS s FROM t GROUP BY dim")
    assert out["rewritten"]
    assert out["cost"]["strategy"] == "historicals"
    assert out["cost"]["rowsScanned"] > 0


def test_tpu_fitted_terms_flip_decision():
    """VERDICT r4 missing #5: the tpu calibration entry must carry ALL
    four decision terms (no 'left to fallbacks'), and the decision must
    flip where those fitted terms say. The tpu entry is pinned via the
    config overrides (CI runs on the cpu backend, so backend-keyed
    resolution would read the cpu fit)."""
    import json
    import math
    import os

    path = os.path.join(os.path.dirname(cost_mod.__file__),
                        "cost_calibration.json")
    with open(path) as f:
        tpu = json.load(f)["tpu"]
    for term in ("scan_ns_per_row_col", "merge_ns_per_byte",
                 "collective_lat_us", "gspmd_overhead"):
        assert term in tpu, f"tpu entry missing {term}"
    assert "left to fallbacks" not in tpu.get("note", "")

    cfg = EngineConfig(
        cost_scan_ns_per_row_col=tpu["scan_ns_per_row_col"],
        cost_merge_ns_per_byte=tpu["merge_ns_per_byte"],
        cost_collective_lat_us=tpu["collective_lat_us"],
        cost_gspmd_overhead=tpu["gspmd_overhead"])
    eng = Engine(cfg)
    eng.register_table("t", _table(), time_column="ts", block_rows=512)
    shards = 8
    hops = math.ceil(math.log2(shards))
    c = cost_mod.constants(cfg)
    assert c["merge_ns_per_byte"] == tpu["merge_ns_per_byte"]

    phys = _plan_for(eng, "SELECT dim, sum(val) AS s FROM t GROUP BY dim")
    d = cost_mod.decide(phys, cfg, shards=shards)
    # solve the crossover in table bytes from the documented inequality
    bytes_star = ((c["gspmd_overhead"]
                   * (d.scan_us + c["collective_lat_us"] * hops) / hops
                   - c["collective_lat_us"])
                  * 1000.0 / c["merge_ns_per_byte"])
    assert d.table_bytes < bytes_star and d.strategy == "historicals", d
    # a sketch-heavy plan pushes table bytes past the crossover
    phys2 = _plan_for(eng, """
        SELECT dim, val, count(DISTINCT dim) AS u
        FROM t GROUP BY dim, val""")
    d2 = cost_mod.decide(phys2, cfg, shards=shards)
    assert d2.table_bytes > bytes_star and d2.strategy == "broker", d2
