"""Chunked (streamed) fallback at scale: parity vs the whole-frame
fallback interpreter on the same multi-file parquet dataset, forced by a
tiny fallback_chunk_rows threshold (VERDICT round-2 task #7 — the
"never an error" guarantee must not become an OOM at SF scale)."""

import os

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig
from tpu_olap.planner.fallback import FallbackError, execute_fallback


def _write_dataset(tmp_path, n=9000, files=3, seed=11):
    import pyarrow as pa
    import pyarrow.parquet as pq
    rng = np.random.default_rng(seed)
    paths = []
    per = n // files
    for f in range(files):
        df = pd.DataFrame({
            "ts": pd.to_datetime("2021-01-01")
            + pd.to_timedelta(rng.integers(0, 86400 * 200, per), unit="s"),
            "cat": rng.choice(["a", "b", "c", None], per,
                              p=[0.4, 0.3, 0.2, 0.1]),
            "city": rng.choice([f"c{i}" for i in range(7)], per),
            "qty": rng.integers(-20, 100, per).astype(np.int64),
            "price": rng.integers(1, 1000, per).astype(np.int64),
        })
        df.loc[rng.random(per) < 0.06, "qty"] = np.nan
        df["qty"] = df["qty"].astype("Int64")
        p = os.path.join(tmp_path, f"part-{f}.parquet")
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False), p,
                       row_group_size=512)
        paths.append(p)
    return paths


def _engines(tmp_path):
    paths = _write_dataset(str(tmp_path))
    whole = Engine(EngineConfig(fallback_chunk_rows=10**9))
    chunked = Engine(EngineConfig(fallback_chunk_rows=100,
                                  fallback_chunk_batch_rows=1024))
    for e in (whole, chunked):
        e.register_table("t", paths, time_column="ts")
        # dimension join target for the star-shaped cases
        e.register_table("d", pd.DataFrame(
            {"d_city": [f"c{i}" for i in range(7)],
             "d_zone": ["west" if i < 4 else "east" for i in range(7)]}),
            accelerate=False)
    return whole, chunked


QUERIES = [
    # global aggregates incl. arithmetic over aggs
    "SELECT sum(qty) AS s, count(*) AS n, avg(price) AS a, "
    "sum(price * qty) AS pq FROM t",
    # group-by with nulls in keys + HAVING over a nullable aggregate
    "SELECT cat, sum(qty) AS s, count(qty) AS nq FROM t GROUP BY cat "
    "HAVING sum(qty) > 0",
    # multi-dim + order + limit
    "SELECT cat, city, sum(price) AS s FROM t GROUP BY cat, city "
    "ORDER BY s DESC, cat, city LIMIT 7",
    # count distinct per group
    "SELECT cat, count(DISTINCT city) AS dc FROM t GROUP BY cat ORDER BY cat",
    # min/max incl. all-null-group behavior
    "SELECT cat, min(qty) AS lo, max(qty) AS hi FROM t GROUP BY cat "
    "ORDER BY cat",
    # join to a dimension table per chunk
    "SELECT d_zone, sum(price) AS s FROM t JOIN d ON city = d_city "
    "GROUP BY d_zone ORDER BY d_zone",
    # DISTINCT projection (grouped spelling)
    "SELECT DISTINCT cat, city FROM t ORDER BY cat, city",
    # non-aggregate scan with filter + limit
    "SELECT city, price FROM t WHERE price > 900 ORDER BY price DESC, city "
    "LIMIT 11",
    # aggregate expression ORDER BY not in the projection list
    "SELECT city, count(*) AS n FROM t GROUP BY city "
    "ORDER BY sum(price) DESC LIMIT 4",
    # SUM/AVG over DISTINCT values ride the cross-chunk pair frames
    "SELECT cat, sum(DISTINCT qty) AS sd, avg(DISTINCT qty) AS ad "
    "FROM t GROUP BY cat ORDER BY cat",
    "SELECT sum(DISTINCT price) AS sd, avg(DISTINCT qty) AS ad FROM t",
]


@pytest.mark.parametrize("sql", QUERIES)
def test_chunked_matches_whole(tmp_path, sql):
    whole, chunked = _engines(tmp_path)
    a = execute_fallback(whole.planner.plan(sql).stmt, whole.catalog,
                         whole.config)
    b = execute_fallback(chunked.planner.plan(sql).stmt, chunked.catalog,
                         chunked.config)
    if "LIMIT" in sql and "ORDER BY" not in sql:
        raise AssertionError("unreachable: all LIMIT cases are ordered")
    pd.testing.assert_frame_equal(
        a.reset_index(drop=True), b.reset_index(drop=True),
        check_dtype=False)


EDGE_QUERIES = [
    # global aggregate whose filter matches zero rows (empty-partials
    # branch must resolve real columns: count->0, sum->0)
    "SELECT sum(qty) AS s, count(*) AS n FROM t WHERE price > 99999",
    # division by a NULL aggregate (all-NULL min over a filtered group)
    "SELECT cat, sum(price) / max(qty) AS r FROM t GROUP BY cat "
    "ORDER BY cat",
]


@pytest.mark.parametrize("sql", EDGE_QUERIES)
def test_chunked_edge_parity(tmp_path, sql):
    whole, chunked = _engines(tmp_path)
    a = execute_fallback(whole.planner.plan(sql).stmt, whole.catalog,
                         whole.config)
    b = execute_fallback(chunked.planner.plan(sql).stmt, chunked.catalog,
                         chunked.config)
    pd.testing.assert_frame_equal(
        a.reset_index(drop=True), b.reset_index(drop=True),
        check_dtype=False)


def test_distinct_pair_cap_refuses(tmp_path):
    """High-cardinality COUNT(DISTINCT) must refuse with a clear error,
    not OOM: the pair frames count toward the compaction trigger and the
    cap fires inside compact()."""
    _, chunked = _engines(tmp_path)
    chunked.config.fallback_scan_row_cap = 50
    stmt = chunked.planner.plan(
        "SELECT count(DISTINCT price) AS d FROM t").stmt
    with pytest.raises(FallbackError, match="count_distinct"):
        execute_fallback(stmt, chunked.catalog, chunked.config)


def test_scan_row_cap_refuses(tmp_path):
    _, chunked = _engines(tmp_path)
    chunked.config.fallback_scan_row_cap = 100
    stmt = chunked.planner.plan("SELECT city, price FROM t").stmt
    with pytest.raises(FallbackError, match="fallback_scan_row_cap"):
        execute_fallback(stmt, chunked.catalog, chunked.config)


def test_unordered_limit_scan_bounded(tmp_path):
    """LIMIT without ORDER BY early-stops: only enough chunks stream."""
    _, chunked = _engines(tmp_path)
    chunked.config.fallback_scan_row_cap = 10**9
    stmt = chunked.planner.plan(
        "SELECT city FROM t LIMIT 5").stmt
    out = execute_fallback(stmt, chunked.catalog, chunked.config)
    assert len(out) == 5


# --- randomized chunked-vs-whole fuzzing --------------------------------
# Reuses the main parity fuzzer's query generator and table shape, but
# the oracle pair is the WHOLE-FRAME interpreter vs the CHUNKED one on
# the same parquet dataset — the chunked path's partial-aggregate merge,
# distinct-pair accumulation, and NULL-group handling under the full
# combination space.

N_FUZZ = 60


def _fuzz_engines(tmp_path, frame):
    import pyarrow as pa
    import pyarrow.parquet as pq
    paths = []
    per = len(frame) // 3
    for i in range(3):
        p = os.path.join(str(tmp_path), f"fz-{i}.parquet")
        part = frame.iloc[i * per:(i + 1) * per if i < 2 else len(frame)]
        pq.write_table(pa.Table.from_pandas(part, preserve_index=False),
                       p, row_group_size=512)
        paths.append(p)
    from tests.test_fuzz_parity import _city_dim
    whole = Engine(EngineConfig(fallback_chunk_rows=10**9))
    chunked = Engine(EngineConfig(fallback_chunk_rows=64,
                                  fallback_chunk_batch_rows=777))
    for e in (whole, chunked):
        e.register_table("t", paths, time_column="ts")
        e.register_table("citydim", _city_dim(), accelerate=False)
    return whole, chunked


@pytest.mark.parametrize("seed", range(N_FUZZ))
def test_fuzz_chunked_vs_whole(tmp_path, seed):
    from tests.test_fuzz_parity import _gen_query, _make_table
    rng = np.random.default_rng(7000 + seed)
    frame = _make_table(rng, int(rng.integers(600, 3000)))
    whole, chunked = _fuzz_engines(tmp_path, frame)
    sql = _gen_query(rng)
    a = execute_fallback(whole.planner.plan(sql).stmt, whole.catalog,
                         whole.config)
    b = execute_fallback(chunked.planner.plan(sql).stmt, chunked.catalog,
                         chunked.config)
    ordered = "ORDER BY" in sql
    if not ordered or "LIMIT" in sql:
        # unordered results (or tie-broken LIMIT windows) compare as
        # value-sorted sets — same convention as the main fuzzer
        a = a.sort_values(list(a.columns), key=lambda s: s.astype(str)) \
            .reset_index(drop=True)
        b = b.sort_values(list(b.columns), key=lambda s: s.astype(str)) \
            .reset_index(drop=True)
    try:
        pd.testing.assert_frame_equal(a, b, check_dtype=False)
    except AssertionError:
        print(f"CHUNKED FUZZ FAILURE seed={seed}\nSQL: {sql}")
        raise


def test_chunked_theta_setops(tmp_path):
    """Theta set ops at SF scale: the chunked fallback joins the
    distinct-pair frames per group — exact, bounded-memory."""
    import numpy as np
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    from tpu_olap import Engine
    from tpu_olap.executor import EngineConfig
    rng = np.random.default_rng(4)
    n = 30_000
    df = pd.DataFrame({
        "ts": pd.to_datetime("2024-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 20, n), unit="s"),
        "user": rng.integers(0, 2500, n),
        "action": rng.choice(["buy", "view"], n),
        "dev": rng.choice(["a", "b", "c"], n),
    })
    p = str(tmp_path / "ev.parquet")
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), p)
    eng = Engine(EngineConfig(fallback_chunk_rows=5_000,
                              fallback_chunk_batch_rows=4096))
    eng.register_table("ev", p, time_column="ts", accelerate=False)
    got = eng.sql(
        "SELECT dev, theta_sketch_intersect("
        "theta_sketch(user) FILTER (WHERE action = 'buy'), "
        "theta_sketch(user) FILTER (WHERE action = 'view')) AS b, "
        "theta_sketch_not("
        "theta_sketch(user) FILTER (WHERE action = 'buy'), "
        "theta_sketch(user) FILTER (WHERE action = 'view')) AS only_b "
        "FROM ev GROUP BY dev ORDER BY dev")
    for _, r in got.iterrows():
        sub = df[df.dev == r["dev"]]
        buy = set(sub[sub.action == "buy"].user)
        view = set(sub[sub.action == "view"].user)
        assert int(r["b"]) == len(buy & view)
        assert int(r["only_b"]) == len(buy - view)


def test_chunked_sum_distinct_int_exact(tmp_path):
    """Integer SUM(DISTINCT) sums above 2^53 must stay exact on the
    chunked path (a float64 lookup would round); parity vs whole-frame."""
    import pyarrow as pa
    import pyarrow.parquet as pq
    base = 1 << 55
    df = pd.DataFrame({
        "ts": pd.to_datetime("2021-01-01")
        + pd.to_timedelta(np.arange(64), unit="s"),
        "g": ["a", "b"] * 32,
        "v": (base + np.arange(64) * 3).astype(np.int64),
    })
    p = os.path.join(str(tmp_path), "big.parquet")
    pq.write_table(pa.Table.from_pandas(df, preserve_index=False), p,
                   row_group_size=8)
    whole = Engine(EngineConfig(fallback_chunk_rows=10**9))
    chunked = Engine(EngineConfig(fallback_chunk_rows=4,
                                  fallback_chunk_batch_rows=16))
    for e in (whole, chunked):
        e.register_table("b", [p], time_column="ts")
    q = "SELECT g, sum(DISTINCT v) AS sd FROM b GROUP BY g ORDER BY g"
    a, b = whole.sql(q), chunked.sql(q)
    exp = {g: int(s.sum()) for g, s in df.groupby("g")["v"]}
    assert [int(x) for x in b["sd"]] == [exp["a"], exp["b"]]
    assert [int(x) for x in a["sd"]] == [int(x) for x in b["sd"]]


@pytest.mark.parametrize("sql", QUERIES)
def test_parallel_chunked_matches_whole(tmp_path, sql):
    """Round-5 parallel chunked fallback (VERDICT r4 missing #3): the
    fork-pool row-group path must be value-identical to the whole-frame
    interpreter — including DISTINCT pair accumulation, per-chunk joins,
    and the empty-schema probe. Workers forced to 4 (this CI host has
    one core, so auto mode would stay sequential)."""
    paths = _write_dataset(str(tmp_path))
    whole = Engine(EngineConfig(fallback_chunk_rows=10**9))
    par = Engine(EngineConfig(fallback_chunk_rows=100,
                              fallback_chunk_batch_rows=1024,
                              fallback_parallel_workers=4))
    for e in (whole, par):
        e.register_table("t", paths, time_column="ts")
        e.register_table("d", pd.DataFrame(
            {"d_city": [f"c{i}" for i in range(7)],
             "d_zone": ["west" if i < 4 else "east" for i in range(7)]}),
            accelerate=False)
    a = execute_fallback(whole.planner.plan(sql).stmt, whole.catalog,
                         whole.config)
    b = execute_fallback(par.planner.plan(sql).stmt, par.catalog,
                         par.config)
    if "ORDER BY" not in sql:
        key = list(a.columns)
        a = a.sort_values(key, na_position="last").reset_index(drop=True)
        b = b.sort_values(key, na_position="last").reset_index(drop=True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)


def test_parallel_chunked_empty_result(tmp_path):
    """All chunks filtered out: the parallel path must still produce the
    correctly-typed empty/global-aggregate result via the schema probe."""
    paths = _write_dataset(str(tmp_path))
    par = Engine(EngineConfig(fallback_chunk_rows=100,
                              fallback_chunk_batch_rows=1024,
                              fallback_parallel_workers=4))
    par.register_table("t", paths, time_column="ts")
    got = execute_fallback(
        par.planner.plan(
            "SELECT sum(qty) AS s, count(*) AS n FROM t "
            "WHERE price > 999999999").stmt,
        par.catalog, par.config)
    assert int(got["n"].iloc[0]) == 0
    assert pd.isna(got["s"].iloc[0]) or int(got["s"].iloc[0]) == 0


def test_parallel_distinct_pair_cap_refuses(tmp_path):
    """The pair cap must hold on the PARALLEL path too: a fork worker
    refuses at its local compaction (bounding worker memory), which
    degrades to the sequential loop — and a genuinely over-cap query
    then refuses legibly at the TRUE cap from the sequential compact(),
    never an OOM and never a silent wrong answer."""
    paths = _write_dataset(str(tmp_path))
    par = Engine(EngineConfig(fallback_chunk_rows=100,
                              fallback_chunk_batch_rows=1024,
                              fallback_parallel_workers=4,
                              fallback_scan_row_cap=50))
    par.register_table("t", paths, time_column="ts")
    stmt = par.planner.plan(
        "SELECT count(DISTINCT price) AS d FROM t").stmt
    with pytest.raises(FallbackError, match="fallback_scan_row_cap"):
        execute_fallback(stmt, par.catalog, par.config)


def test_parallel_divided_cap_false_refusal_retries_sequentially(tmp_path):
    """Fork workers cap their LOCAL distinct sets at pair_cap // workers
    (so total in-flight pairs cannot transiently reach workers x
    pair_cap) — but interleaved row groups mean each worker's distinct
    set nearly duplicates the global universe, so a refusal at the
    divided cap is ambiguous about the real cap. It must degrade to the
    sequential loop (which enforces the configured cap exactly): a
    query whose distinct count fits the REAL cap succeeds instead of
    surfacing the worker's false refusal."""
    paths = _write_dataset(str(tmp_path))
    # price has ~999 distinct values: over 1500 // 4 = 375 per-worker,
    # under the configured 1500
    par = Engine(EngineConfig(fallback_chunk_rows=100,
                              fallback_chunk_batch_rows=1024,
                              fallback_parallel_workers=4,
                              fallback_scan_row_cap=1500))
    whole = Engine(EngineConfig(fallback_chunk_rows=10**9))
    for e in (par, whole):
        e.register_table("t", paths, time_column="ts")
    sql = "SELECT count(DISTINCT price) AS d FROM t"
    got = execute_fallback(par.planner.plan(sql).stmt, par.catalog,
                           par.config)
    want = execute_fallback(whole.planner.plan(sql).stmt, whole.catalog,
                            whole.config)
    assert int(got["d"].iloc[0]) == int(want["d"].iloc[0]) > 375
