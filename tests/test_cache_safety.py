"""Compile-cache safety: queries that share a stripped template but differ
in trace-baked structure must NOT share a jitted program (regressions for
the silent-wrong-answer cache collisions)."""

import numpy as np
import pandas as pd

from tpu_olap import Engine
from tpu_olap.executor import EngineConfig


def make_engine():
    eng = Engine(EngineConfig(platform="device"))
    df = pd.DataFrame({
        "x": [10, 20, 30, None],
        "g": ["a", "a", "b", "b"],
    })
    eng.register_table("f", df)
    return eng


def test_virtual_column_literals_not_aliased():
    eng = make_engine()
    a = eng.sql("SELECT sum(x * 2) AS s FROM f")
    b = eng.sql("SELECT sum(x * 3) AS s FROM f")
    assert a.s[0] == 120
    assert b.s[0] == 180


def test_selector_value_vs_is_null_not_aliased():
    eng = make_engine()
    a = eng.sql("SELECT count() AS n FROM f WHERE x = 30")
    b = eng.sql("SELECT count() AS n FROM f WHERE x IS NULL")
    assert a.n[0] == 1
    assert b.n[0] == 1


def test_in_list_with_and_without_null():
    eng = make_engine()
    a = eng.sql("SELECT count() AS n FROM f WHERE x IN (10, 20)")
    b = eng.sql("SELECT count() AS n FROM f WHERE x IN (10, NULL)")
    assert a.n[0] == 2
    assert b.n[0] == 2  # 10 and the null row


def test_unparseable_selector_after_parseable():
    eng = make_engine()
    a = eng.sql("SELECT count() AS n FROM f WHERE x = 10")
    b = eng.sql("SELECT count() AS n FROM f WHERE x = 'abc'")
    assert a.n[0] == 1
    assert b.n[0] == 0


def test_order_by_date_trunc_alias():
    eng = Engine(EngineConfig(platform="device"))
    df = pd.DataFrame({
        "t": pd.to_datetime(["1993-01-05", "1993-01-07", "1993-02-01",
                             "1993-03-02"]),
        "x": [1, 2, 3, 4],
    })
    eng.register_table("f", df, time_column="t")
    out = eng.sql("SELECT date_trunc('month', t) AS m, sum(x) AS s FROM f "
                  "GROUP BY date_trunc('month', t) ORDER BY m DESC LIMIT 2")
    assert eng.last_plan.rewritten, eng.last_plan.fallback_reason
    assert out.s.tolist() == [4, 3]


def test_zero_division_parity():
    eng = Engine(EngineConfig(platform="cpu"))
    df = pd.DataFrame({"x": [1, 2], "y": [0, 0], "g": ["a", "b"]})
    eng.register_table("f", df)
    dev = eng.sql("SELECT g, sum(x) / sum(y) AS r FROM f GROUP BY g")
    assert eng.last_plan.rewritten
    from tpu_olap.planner.fallback import execute_fallback
    fb = execute_fallback(eng.last_plan.stmt, eng.catalog, eng.config)
    assert dev.r.tolist() == [0.0, 0.0]
    assert fb.r.tolist() == [0.0, 0.0]
