"""SSB suite: all 13 queries rewrite to the device path and agree with the
pandas fallback row-for-row — the analog of the reference's plan-level
rewrite assertions + live-Druid parity runs (SURVEY.md §5), on the
driver's north-star workload (BASELINE.json:2)."""

import pytest

from tpu_olap import Engine
from tpu_olap.bench import QUERIES, check_query, register_ssb
from tpu_olap.bench.parity import ParityError, run_both
from tpu_olap.ir.query import GroupByQuerySpec, TimeseriesQuerySpec


@pytest.fixture(scope="module")
def ssb_engine():
    eng = Engine()
    register_ssb(eng, lineorder_rows=30_000, seed=7, block_rows=1 << 12)
    return eng


@pytest.mark.parametrize("qname", sorted(QUERIES))
def test_ssb_parity(ssb_engine, qname):
    check_query(ssb_engine, QUERIES[qname], label=qname)


def test_q1_rewrites_to_timeseries(ssb_engine):
    _, _, plan = run_both(ssb_engine, QUERIES["q1.1"])
    assert isinstance(plan.query, TimeseriesQuerySpec)
    # the d_year filter rides the denormalized column, joins are gone
    assert plan.query.data_source == "lineorder"


@pytest.mark.parametrize("qname", ["q2.1", "q3.1", "q4.1"])
def test_star_queries_rewrite_to_groupby(ssb_engine, qname):
    _, _, plan = run_both(ssb_engine, QUERIES[qname])
    assert isinstance(plan.query, GroupByQuerySpec)


def test_nonempty_results(ssb_engine):
    # guard against silently-empty parity: the generator must produce rows
    # that satisfy each query's filters
    for qname, sql in QUERIES.items():
        df = ssb_engine.sql(sql)
        assert len(df) > 0, f"{qname} returned no rows"


def test_undeclared_join_falls_back(ssb_engine):
    # join that is NOT a declared star FK edge -> transparent fallback
    sql = """
        SELECT sum(lo_revenue) AS r FROM lineorder
        JOIN part ON lo_suppkey = p_partkey
    """
    df = ssb_engine.sql(sql)
    assert not ssb_engine.last_plan.rewritten
    assert len(df) == 1


def test_parity_error_reports_query(ssb_engine):
    with pytest.raises(ParityError):
        run_both(ssb_engine, """
            SELECT sum(lo_revenue) AS r FROM lineorder
            JOIN part ON lo_suppkey = p_partkey
        """)
