"""Materialized rollup cubes (tpu_olap.cubes + planner.cuberewrite;
docs/CUBES.md): build/rewrite parity across the aggregation matrix
(SUM/COUNT/AVG/MIN/MAX/HLL/theta — exact match for exact aggs, exact
sketch-state merge for the approximate ones), coarser-grain re-rollup
from a finer cube, rewrite refusal cases (non-cube-dim filter,
uncovered agg, straddling intervals, stale generation), the ingest
invalidation contract (zero stale serves), DDL + sys.cubes +
/debug/cubes, and the advisor -> materializer loop."""

import json
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.cubes import CubeSpec, agg_signature, period_contains
from tpu_olap.executor import EngineConfig

N_ROWS = 40_000


def _df(n=N_ROWS, seed=7, days=540):
    rng = np.random.default_rng(seed)
    v = rng.integers(0, 1000, n).astype(np.int64)
    # a nullable measure: min/max/avg nullity must survive the rollup
    w = rng.integers(0, 500, n).astype(np.float64)
    w[rng.random(n) < 0.1] = np.nan
    return pd.DataFrame({
        "ts": pd.to_datetime("1997-01-01")
        + pd.to_timedelta(np.sort(rng.integers(0, 86400 * days, n)),
                          unit="s"),
        "g": rng.choice([f"g{i}" for i in range(8)], n),
        "r": rng.choice(["A", "B", "C"], n),
        "y": (1997 + rng.integers(0, 2, n)).astype(np.int64),
        "v": v,
        "w": w,
        "u": rng.integers(0, 5000, n).astype(np.int64),
    })


def _engine(df=None, **kw):
    cfg = dict(cube_auto_refresh=False)
    cfg.update(kw)
    eng = Engine(EngineConfig(**cfg))
    eng.register_table("t", df if df is not None else _df(),
                       time_column="ts", block_rows=1 << 11,
                       time_partition="month")
    return eng


FULL_DDL = ("CREATE DRUID CUBE c ON t DIMENSIONS (g, r, y) "
            "GRANULARITY month AGGREGATES (sum(v), count(*), avg(v), "
            "min(w), max(w), sum(w), approx_count_distinct(u), "
            "theta_sketch(u), sum(v * 2))")


def _cubed(df=None, **kw):
    eng = _engine(df, **kw)
    eng.sql(FULL_DDL)
    return eng


def _compare(eng, sql, expect_cube=True):
    """Run once through the rewrite pass and once on the base device
    path; assert identical frames and return the cube-run record."""
    a = eng.sql(sql)
    rec = dict(eng.history[-1])
    if expect_cube:
        assert rec.get("path") == "cube", (rec.get("path"), sql)
    else:
        assert rec.get("path") != "cube", sql
    eng.config.cube_rewrite_enabled = False
    try:
        b = eng.sql(sql)
        base = dict(eng.history[-1])
        assert base.get("path") != "cube"
    finally:
        eng.config.cube_rewrite_enabled = True
    pd.testing.assert_frame_equal(a, b)
    return rec


# ------------------------------------------------------ rewrite parity


def test_agg_matrix_parity_groupby():
    eng = _cubed()
    rec = _compare(eng, (
        "SELECT g, sum(v) AS s, count(*) AS n, avg(v) AS a, "
        "min(w) AS mn, max(w) AS mx, sum(w) AS sw, "
        "approx_count_distinct(u) AS d, theta_sketch(u) AS th "
        "FROM t GROUP BY g ORDER BY g"))
    assert rec["cube"] == "c"
    assert rec["rows_scanned"] < N_ROWS  # cube rows, not base rows
    assert rec["segments_scanned"] == 0


def test_filters_on_cube_dims_and_extractions():
    eng = _cubed()
    _compare(eng, "SELECT g, sum(v) AS s FROM t WHERE r = 'A' "
                  "GROUP BY g ORDER BY g")
    _compare(eng, "SELECT g, sum(v) AS s FROM t WHERE r IN ('A', 'C') "
                  "AND y = 1997 GROUP BY g ORDER BY g")
    _compare(eng, "SELECT g, sum(v) AS s FROM t "
                  "WHERE g LIKE 'g%' AND (r = 'A' OR r = 'B') "
                  "GROUP BY g ORDER BY g")
    # extraction over a cube dim: substr group + filter
    _compare(eng, "SELECT substr(g, 1, 1) AS p, sum(v) AS s FROM t "
                  "WHERE substr(r, 1, 1) = 'A' GROUP BY substr(g, 1, 1)"
                  " ORDER BY p")


def test_timeseries_topn_and_having():
    eng = _cubed()
    _compare(eng, "SELECT sum(v) AS s, count(*) AS n FROM t")
    _compare(eng, "SELECT g, sum(v) AS s FROM t GROUP BY g "
                  "ORDER BY s DESC LIMIT 3")  # topN shape
    _compare(eng, "SELECT g, sum(v) AS s FROM t GROUP BY g "
                  "HAVING sum(v) > 100000 ORDER BY g")


def test_filtered_aggregate_signature_match_and_refusal():
    """sum(CASE WHEN r='A' THEN v ELSE 0 END) lowers to a filtered
    aggregation; the cube serves the EXACT same filtered form (the
    filter literal is part of the stored signature) and refuses a
    different literal."""
    eng = _engine()
    eng.sql("CREATE DRUID CUBE fc ON t DIMENSIONS (g) GRANULARITY all "
            "AGGREGATES (sum(CASE WHEN r = 'A' THEN v ELSE 0 END), "
            "count(v))")
    _compare(eng, "SELECT g, sum(CASE WHEN r = 'A' THEN v ELSE 0 END) "
                  "AS s, count(v) AS n FROM t GROUP BY g ORDER BY g")
    _compare(eng, "SELECT g, sum(CASE WHEN r = 'B' THEN v ELSE 0 END) "
                  "AS s FROM t GROUP BY g ORDER BY g",
             expect_cube=False)


def test_coarser_grain_re_rollup():
    """A month-grain cube serves month, quarter, and year grains (and
    the year(ts) timeformat dim) by re-bucketing stored partials."""
    eng = _cubed()
    for unit in ("month", "quarter", "year"):
        _compare(eng, f"SELECT date_trunc('{unit}', ts) AS b, "
                      "sum(v) AS s, avg(v) AS a FROM t "
                      f"GROUP BY date_trunc('{unit}', ts) ORDER BY b")
    _compare(eng, "SELECT year(ts) AS yy, g, sum(v) AS s FROM t "
                  "GROUP BY year(ts), g ORDER BY yy, g")
    _compare(eng, "SELECT month(ts) AS mm, sum(v) AS s FROM t "
                  "GROUP BY month(ts) ORDER BY mm")


def test_interval_containment():
    eng = _cubed()
    # whole-month interval: every touched cube bucket is contained
    rec = _compare(eng, "SELECT g, sum(v) AS s FROM t "
                        "WHERE ts >= TIMESTAMP '1997-03-01' AND "
                        "ts < TIMESTAMP '1997-06-01' "
                        "GROUP BY g ORDER BY g")
    assert rec["path"] == "cube"
    # mid-month boundary straddles a cube bucket -> base path, exact
    _compare(eng, "SELECT g, sum(v) AS s FROM t "
                  "WHERE ts >= TIMESTAMP '1997-03-15' "
                  "GROUP BY g ORDER BY g", expect_cube=False)
    # year(ts) predicate extracts to a calendar-aligned interval
    _compare(eng, "SELECT g, sum(v) AS s FROM t WHERE year(ts) = 1997 "
                  "GROUP BY g ORDER BY g")


def test_smallest_covering_cube_wins():
    eng = _cubed()
    eng.sql("CREATE DRUID CUBE tiny ON t DIMENSIONS (g) "
            "GRANULARITY all AGGREGATES (sum(v))")
    rec = _compare(eng, "SELECT g, sum(v) AS s FROM t "
                        "GROUP BY g ORDER BY g")
    assert rec["cube"] == "tiny"  # fewer rows than the month cube
    # the big cube still serves what tiny can't
    rec = _compare(eng, "SELECT g, sum(v) AS s FROM t WHERE r = 'A' "
                        "GROUP BY g ORDER BY g")
    assert rec["cube"] == "c"


# ------------------------------------------------------------ refusals


def test_rewrite_refusals_fall_back_to_base():
    eng = _cubed()
    # filter on a non-cube dim
    _compare(eng, "SELECT g, sum(v) AS s FROM t WHERE u > 10 "
                  "GROUP BY g ORDER BY g", expect_cube=False)
    # uncovered aggregation (min over a column only sum is stored for)
    _compare(eng, "SELECT g, min(v) AS m FROM t GROUP BY g ORDER BY g",
             expect_cube=False)
    # grouping dim outside the cube
    _compare(eng, "SELECT u, sum(v) AS s FROM t GROUP BY u "
                  "ORDER BY u LIMIT 5", expect_cube=False)
    # finer grain than the cube materializes
    _compare(eng, "SELECT date_trunc('day', ts) AS d, sum(v) AS s "
                  "FROM t GROUP BY date_trunc('day', ts) "
                  "ORDER BY d LIMIT 5", expect_cube=False)
    refused = eng.metrics.counter("cube_rewrite_total")
    assert refused.value(result="refused") >= 4


def test_scan_and_select_never_touch_cubes():
    eng = _cubed()
    out = eng.sql("SELECT g, v FROM t LIMIT 5")
    assert len(out) == 5
    assert dict(eng.history[-1]).get("path") != "cube"


# --------------------------------------------------- invalidation/stale


def test_stale_generation_never_served_and_refresh_recovers():
    eng = _cubed()
    q = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
    assert _compare(eng, q)["path"] == "cube"
    # re-ingest DIFFERENT data: the cube is stale the same instant
    eng.register_table("t", _df(seed=99), time_column="ts",
                       block_rows=1 << 11, time_partition="month")
    n0 = len(eng.history)
    a = eng.sql(q)
    recs = [dict(m) for m in eng.history[n0:]]
    assert all(r.get("path") != "cube" for r in recs), "stale serve!"
    # the answer reflects the NEW data (base path, exact)
    expect = _df(seed=99).groupby("g", as_index=False)["v"].sum() \
        .rename(columns={"v": "s"})
    pd.testing.assert_frame_equal(
        a, expect.sort_values("g").reset_index(drop=True))
    row = eng.sql("SELECT stale, status FROM sys.cubes "
                  "WHERE name = 'c'").iloc[0]
    assert bool(row["stale"]) and row["status"] == "ready"
    # REFRESH rebuilds against the new generation; serves resume
    out = eng.sql("REFRESH DRUID CUBES")
    assert list(out["status"]) == ["ok"]
    rec = _compare(eng, q)
    assert rec["path"] == "cube"
    assert eng.metrics.counter("cube_rewrite_total") \
        .value(result="stale") >= 1


def test_drop_table_cascades_to_cubes():
    eng = _cubed()
    assert eng.catalog.maybe("__cube_c") is not None
    eng.drop_table("t")
    assert eng.cubes.names() == []
    assert eng.catalog.maybe("__cube_c") is None


def test_auto_refresh_maintainer_rebuilds():
    eng = _cubed(cube_auto_refresh=True,
                 cube_refresh_interval_s=0.05)
    eng.register_table("t", _df(seed=3), time_column="ts",
                       block_rows=1 << 11, time_partition="month")
    import time
    deadline = time.time() + 30
    while time.time() < deadline:
        e = eng.cubes.get("c")
        if e.ready and not e.snapshot_row(eng)["stale"]:
            break
        time.sleep(0.05)
    else:
        pytest.fail("maintainer did not rebuild the stale cube")
    rec = _compare(eng, "SELECT g, sum(v) AS s FROM t "
                        "GROUP BY g ORDER BY g")
    assert rec["path"] == "cube"
    eng.cubes.stop()


def test_auto_refresh_enabled_at_runtime_starts_maintainer():
    """Flipping cube_auto_refresh on AFTER the cubes were created must
    still start the maintainer at the next ingest (the lazy-start
    contract covers runtime config mutation too)."""
    eng = _cubed()  # created with cube_auto_refresh=False
    eng.config.cube_auto_refresh = True
    eng.config.cube_refresh_interval_s = 0.05
    eng.register_table("t", _df(seed=4), time_column="ts",
                       block_rows=1 << 11, time_partition="month")
    import time
    deadline = time.time() + 30
    while time.time() < deadline:
        row = eng.cubes.get("c").snapshot_row(eng)
        if row["status"] == "ready" and not row["stale"]:
            break
        time.sleep(0.05)
    else:
        pytest.fail("runtime-enabled maintainer did not rebuild")
    eng.cubes.stop()


# ------------------------------------------------------- DDL + surfaces


def test_ddl_create_sys_cubes_contract_and_drop():
    eng = _engine()
    out = eng.sql("CREATE DRUID CUBE c ON t DIMENSIONS (g, r) "
                  "GRANULARITY month AGGREGATES (sum(v), count(*))")
    assert list(out["status"]) == ["ready"]
    row = eng.sql("SELECT * FROM sys.cubes").iloc[0]
    assert row["name"] == "c" and row["base_table"] == "t"
    assert row["dims"] == "g,r" and row["granularity"] == "month"
    assert row["rows"] > 0 and row["serve_count"] == 0
    assert row["base_generation"] == row["cube_generation"]
    assert not row["stale"] and row["storage_bytes"] > 0
    # the backing store is an ordinary catalog table: queryable SQL
    stored = eng.sql("SELECT count(*) AS n FROM __cube_c")
    assert int(stored["n"][0]) == int(row["rows"])
    eng.sql("SELECT g, sum(v) AS s FROM t GROUP BY g")
    assert int(eng.sql("SELECT serve_count FROM sys.cubes")
               ["serve_count"][0]) == 1
    out = eng.sql("DROP DRUID CUBE c")
    assert list(out["status"]) == ["dropped"]
    assert len(eng.sql("SELECT * FROM sys.cubes")) == 0
    assert eng.catalog.maybe("__cube_c") is None


def test_ddl_errors_are_user_errors():
    from tpu_olap.resilience.errors import UserError
    eng = _engine()
    with pytest.raises(UserError):
        eng.sql("CREATE DRUID CUBE c ON t DIMENSIONS (nope) "
                "AGGREGATES (sum(v))")
    with pytest.raises(UserError):
        eng.sql("CREATE DRUID CUBE c ON t AGGREGATES (median(v))")
    with pytest.raises(UserError):
        eng.sql("CREATE DRUID CUBE c ON missing AGGREGATES (sum(v))")
    # a failed create must leave no half-registered serveable cube
    assert not any(eng.cubes.get(n).ready for n in eng.cubes.names())


def test_create_cubes_from_file_and_spec_roundtrip(tmp_path):
    eng = _engine()
    spec = CubeSpec(name="f1", datasource="t", dimensions=("g",),
                    granularity="month", aggregations=("sum(v)",))
    path = tmp_path / "cubes.json"
    path.write_text(json.dumps(
        {"cubes": [spec.to_json(),
                   {"name": "bad", "datasource": "missing",
                    "aggregations": ["sum(v)"]}]}))
    out = eng.sql(f"CREATE DRUID CUBES FROM '{path}'")
    by_name = {r["cube"]: r["status"] for r in out.to_dict("records")}
    assert by_name["f1"] == "ready" and by_name["bad"] == "error"
    rec = _compare(eng, "SELECT g, sum(v) AS s FROM t "
                        "GROUP BY g ORDER BY g")
    assert rec["cube"] == "f1"


def test_debug_cubes_endpoint():
    from tpu_olap.api.server import QueryServer
    eng = _cubed()
    eng.sql("SELECT g, sum(v) AS s FROM t GROUP BY g")
    srv = QueryServer(eng).start()
    try:
        with urllib.request.urlopen(srv.url + "/debug/cubes") as r:
            payload = json.loads(r.read())
    finally:
        srv.stop()
    assert payload["enabled"] is True
    (row,) = payload["cubes"]
    assert row["name"] == "c" and row["serve_count"] >= 1


def test_workload_attribution_path_cube():
    """Cube serves land in the profiler under path='cube', so
    sys.query_templates shows cube coverage per template."""
    eng = _cubed()
    sql = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
    for _ in range(3):
        eng.sql(sql)
    tid = dict(eng.history[-1])["template_id"]
    row = eng.sql(
        "SELECT paths, count FROM sys.query_templates "
        f"WHERE template_id = '{tid}'").iloc[0]
    assert json.loads(row["paths"]).get("cube") == 3


def test_ddl_quoted_literals_with_parens_and_commas():
    """Filter literals containing parens/commas are text, not list
    structure, for the CREATE DRUID CUBE clause parser."""
    eng = _engine()
    eng.sql("CREATE DRUID CUBE q ON t DIMENSIONS (g) GRANULARITY all "
            "AGGREGATES (sum(CASE WHEN r = 'A)' THEN v ELSE 0 END), "
            "sum(CASE WHEN g = 'x,(y' THEN v ELSE 0 END), count(*))")
    row = eng.sql("SELECT status FROM sys.cubes "
                  "WHERE name = 'q'").iloc[0]
    assert row["status"] == "ready"


def test_failed_build_not_retried_until_generation_moves():
    """A deterministically-failing spec is attempted once per base
    generation — the maintainer must not re-run a doomed device pass
    every tick (and refresh_now must skip it too)."""
    from tpu_olap.resilience.errors import UserError
    eng = _cubed()
    with pytest.raises(UserError):
        # median has no device lowering: the build fails the same way
        # at every generation
        eng.sql("CREATE DRUID CUBE doomed ON t DIMENSIONS (g) "
                "GRANULARITY all AGGREGATES (median(v))")
    builds0 = eng.metrics.counter("cube_builds_total") \
        .value(result="error")
    assert eng.cubes.get("doomed") not in eng.cubes.stale_cubes()
    assert eng.cubes.refresh_now() == {}  # nothing stale to retry
    assert eng.metrics.counter("cube_builds_total") \
        .value(result="error") == builds0
    # a real ingest IS a reason to retry (the new data may fit)
    eng.register_table("t", _df(seed=1), time_column="ts",
                       block_rows=1 << 11, time_partition="month")
    assert any(e.spec.name == "doomed"
               for e in eng.cubes.stale_cubes())


def test_drop_during_inflight_build_leaves_no_orphan_storage():
    """A build whose entry was dropped mid-flight must not re-register
    the storage table the drop just removed."""
    import threading
    eng = _cubed()
    entry = eng.cubes.get("c")
    gate = threading.Event()
    orig = eng.runner.compute_partials

    def slow(query, table):
        out = orig(query, table)
        gate.wait(10)  # hold the build until the drop lands
        return out

    eng.runner.compute_partials = slow
    # make the cube stale so refresh_now rebuilds it
    eng.register_table("t", _df(seed=5), time_column="ts",
                       block_rows=1 << 11, time_partition="month")
    t = threading.Thread(target=eng.cubes.refresh_now, daemon=True)
    t.start()
    import time
    time.sleep(0.2)  # let the rebuild reach the gate
    assert eng.drop_cube("c")
    gate.set()
    t.join(30)
    eng.runner.compute_partials = orig
    assert eng.catalog.maybe("__cube_c") is None, "orphaned storage"
    assert eng.cubes.names() == []


# --------------------------------------------------- advisor loop


def test_advisor_specs_close_the_loop():
    eng = _engine()
    sqls = [
        "SELECT g, sum(v) AS s FROM t WHERE r = 'A' GROUP BY g",
        "SELECT g, sum(v) AS s FROM t WHERE r = 'B' GROUP BY g",
        "SELECT year(ts) AS yy, avg(v) AS a FROM t "
        "GROUP BY year(ts) ORDER BY yy",
    ]
    for q in sqls:
        eng.sql(q)
    from tpu_olap.cubes import cube_specs_from_workload
    specs, _notes = cube_specs_from_workload(
        eng.runner.workload.snapshot(), eng)
    assert specs, "advisor produced no specs"
    for s in specs:
        eng.create_cube(s)  # accepted verbatim
    for q in sqls:
        rec = _compare(eng, q)
        assert rec["path"] == "cube", q


def test_batch_path_serves_from_cube():
    eng = _cubed()
    sqls = ["SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g",
            "SELECT r, count(*) AS n FROM t GROUP BY r ORDER BY r"]
    n0 = len(eng.history)
    outs = eng.sql_batch(sqls)
    recs = [dict(m) for m in eng.history[n0:]]
    assert all(r.get("path") == "cube" for r in recs)
    eng.config.cube_rewrite_enabled = False
    try:
        base = [eng.sql(q) for q in sqls]
    finally:
        eng.config.cube_rewrite_enabled = True
    for a, b in zip(outs, base):
        pd.testing.assert_frame_equal(a, b)


# -------------------------------------------------------- unit helpers


def test_period_containment_ladder():
    assert period_contains("P1Y", "P1M")
    assert period_contains("P3M", "P1M")
    assert period_contains("P1M", "P1D")
    assert period_contains("P1W", "P1D")
    assert not period_contains("P1M", "P1W")
    assert not period_contains("P1Y", "P1W")
    assert not period_contains("P1D", "P1M")
    assert period_contains("P1D", "P1D")


def test_agg_signature_resolves_virtual_columns():
    eng = _engine()
    p1 = eng.planner.plan("SELECT sum(v * 2) AS a FROM t")
    p2 = eng.planner.plan("SELECT sum(v * 2) AS b FROM t")
    p3 = eng.planner.plan("SELECT sum(v * 3) AS a FROM t")

    def sig(plan):
        vex = {v.name: v.expression
               for v in plan.query.virtual_columns}
        return agg_signature(plan.query.aggregations[0], vex)

    assert sig(p1) == sig(p2)
    assert sig(p1) != sig(p3)
