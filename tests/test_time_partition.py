"""Time-partitioned ingest (the Druid segmentGranularity analog,
SURVEY.md §3.4 segment store / §3.5 P4 interval pruning) and the
residual interval-mask elision it unlocks (round 5, VERDICT r4 weak #1:
__time int64 is typically the widest column a filtered aggregate reads;
when every scanned segment sits inside one query interval the row-level
mask is constant-true and the kernel should neither evaluate it nor
read __time)."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.executor.lowering import lower
from tpu_olap.segments.ingest import (ingest_pandas,
                                      resolve_time_partition)


def _table(n=120_000, years=4, seed=5):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("1993-01-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 365 * years, n),
                          unit="s"),
        "g": rng.choice(["a", "b", "c", "d"], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def test_resolve_auto_granularity():
    day, month, year = 86_400_000, 2_629_800_000, 31_557_600_000
    # plenty of blocks per day -> day
    assert resolve_time_partition("auto", 0, 10 * day, 10_000_000,
                                  4096) == "day"
    # ~244 blocks over 4 years -> month amortizes (48 <= 61), day not
    assert resolve_time_partition("auto", 0, 4 * year, 1_000_000,
                                  4096) == "month"
    # ~30 blocks over 4 years -> year
    assert resolve_time_partition("auto", 0, 4 * year, 120_000,
                                  4096) == "year"
    # too small to amortize even years -> no partitioning
    assert resolve_time_partition("auto", 0, 4 * year, 4_000,
                                  4096) is None
    # explicit values pass through; degenerate span -> None
    assert resolve_time_partition("month", 0, 1, 10, 4) == "month"
    assert resolve_time_partition("auto", 5, 5, 10, 4) is None


def test_partition_ranges_disjoint_and_exact():
    segs = ingest_pandas("t", _table(), time_column="ts",
                        block_rows=4096, time_partition="year")
    bounds = sorted((s.meta.time_min, s.meta.time_max)
                    for s in segs.segments)
    years = {pd.Timestamp(b[0], unit="ms").year for b in bounds}
    assert years == {1993, 1994, 1995, 1996}
    for lo, hi in bounds:
        assert pd.Timestamp(lo, unit="ms").year \
            == pd.Timestamp(hi, unit="ms").year
    # every row present exactly once
    assert sum(s.meta.n_valid for s in segs.segments) == 120_000


def test_partitioned_streaming_matches_memory():
    """Parquet streaming (chunk-at-a-time arrival) must produce the same
    query results as in-memory ingest, with partition-pruned scans."""
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq
    df = _table(n=80_000)
    d = tempfile.mkdtemp()
    paths = []
    for i in range(2):  # unsorted multi-file arrival
        p = f"{d}/f{i}.parquet"
        pq.write_table(pa.Table.from_pandas(
            df.iloc[i * 40_000:(i + 1) * 40_000], preserve_index=False),
            p, row_group_size=8192)
        paths.append(p)
    mem = Engine()
    mem.register_table("t", df, time_column="ts", block_rows=2048)
    par = Engine()
    par.register_table("t", paths, time_column="ts", block_rows=2048)
    sql = ("SELECT g, sum(v) AS s, count(*) AS n FROM t "
           "WHERE ts >= '1994-01-01' AND ts < '1996-01-01' "
           "GROUP BY g ORDER BY g")
    a, b = mem.sql(sql), par.sql(sql)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    m = par.runner.history[-1]
    assert m["segments_scanned"] < m["segments_total"], m


def test_covered_interval_elides_time_reads():
    eng = Engine()
    eng.register_table("t", _table(), time_column="ts",
                       block_rows=4096, time_partition="year")
    tab = eng.planner.plan("SELECT sum(v) AS s FROM t").entry.segments

    aligned = eng.planner.plan(
        "SELECT g, sum(v) AS s FROM t "
        "WHERE ts >= '1994-01-01' AND ts < '1995-01-01' GROUP BY g")
    ph = lower(aligned.query, tab, eng.config)
    assert "__time" not in ph.columns  # mask elided, no time read

    unaligned = eng.planner.plan(
        "SELECT g, sum(v) AS s FROM t "
        "WHERE ts >= '1994-03-15' AND ts < '1995-07-02' GROUP BY g")
    ph2 = lower(unaligned.query, tab, eng.config)
    assert "__time" in ph2.columns  # boundary segments keep the mask

    # parity on the boundary-straddling interval (the mask must be
    # exact where it IS evaluated)
    df = _table()
    sql = ("SELECT g, sum(v) AS s, count(*) AS n FROM t "
           "WHERE ts >= '1994-03-15' AND ts < '1995-07-02' "
           "GROUP BY g ORDER BY g")
    got = eng.sql(sql)
    sub = df[(df.ts >= "1994-03-15") & (df.ts < "1995-07-02")]
    want = sub.groupby("g")["v"].agg(["sum", "size"]).reset_index()
    assert list(got["s"]) == list(want["sum"])
    assert list(got["n"]) == list(want["size"])


def test_cached_bucket_stream_elides_time_reads():
    """Calendar/uniform bucketing rides a resident derived id stream, so
    a timeseries without raw-timestamp consumers reads no __time."""
    eng = Engine()
    eng.register_table("t", _table(), time_column="ts", block_rows=4096)
    tab = eng.planner.plan("SELECT sum(v) AS s FROM t").entry.segments
    plan = eng.planner.plan(
        "SELECT month(ts) AS m, sum(v) AS q FROM t "
        "GROUP BY month(ts) ORDER BY m")
    ph = lower(plan.query, tab, eng.config)
    assert "__time" not in ph.columns
    got = eng.sql("SELECT month(ts) AS m, sum(v) AS q FROM t "
                  "GROUP BY month(ts) ORDER BY m")
    df = _table()
    want = df.assign(m=df.ts.dt.month).groupby("m")["v"].sum()
    assert list(got["q"]) == list(want)


@pytest.mark.parametrize("shards", [None, 8])
def test_partitioned_sharded_parity(shards):
    """Partition-aligned segments under the 8-device mesh: pruned
    dispatch + psum merge stays parity-exact."""
    from tpu_olap.executor import EngineConfig
    df = _table(n=60_000)
    eng = Engine(EngineConfig(num_shards=shards))
    eng.register_table("t", df, time_column="ts", block_rows=1024,
                       time_partition="month")
    sql = ("SELECT g, sum(v) AS s FROM t "
           "WHERE ts >= '1993-06-01' AND ts < '1994-06-01' "
           "GROUP BY g ORDER BY g")
    got = eng.sql(sql)
    assert eng.last_plan.rewritten
    sub = df[(df.ts >= "1993-06-01") & (df.ts < "1994-06-01")]
    want = sub.groupby("g")["v"].sum().reset_index()
    assert list(got["s"]) == list(want["v"])


def test_numeric_bounds_prune_denormalized_dims():
    """SURVEY.md §3.5 P4 numeric-bounds leg: a selector/bound filter on
    a denormalized LONG dim (the SSB d_year pattern) prunes segments by
    the manifest's per-column min/max — with time-partitioned ingest the
    column correlates with the partition axis, so whole partitions drop
    before dispatch and the window slice covers the survivors."""
    rng = np.random.default_rng(8)
    n = 200_000
    ts = pd.to_datetime("1993-01-01") \
        + pd.to_timedelta(rng.integers(0, 86400 * 365 * 4, n), unit="s")
    df = pd.DataFrame({
        "ts": ts,
        "dyear": ts.year.astype(np.int64),
        "g": rng.choice(["a", "b"], n),
        "v": rng.integers(0, 100, n).astype(np.int64),
    })
    eng = Engine()
    eng.register_table("t", df, time_column="ts", block_rows=4096,
                       time_partition="year")
    sql = ("SELECT g, sum(v) AS s, count(*) AS n FROM t "
           "WHERE dyear = 1994 GROUP BY g ORDER BY g")
    got = eng.sql(sql)
    m = eng.runner.history[-1]
    assert m["segments_scanned"] < m["segments_total"] / 2, m
    sub = df[df.dyear == 1994]
    want = sub.groupby("g")["v"].agg(["sum", "size"]).reset_index()
    assert list(got["s"]) == list(want["sum"])
    assert list(got["n"]) == list(want["size"])
    # range predicate prunes too (inclusive envelope)
    got2 = eng.sql("SELECT count(*) AS n FROM t "
                   "WHERE dyear >= 1995 AND dyear <= 1996")
    m2 = eng.runner.history[-1]
    assert m2["segments_scanned"] < m2["segments_total"]
    assert int(got2["n"].iloc[0]) == int((df.dyear >= 1995).sum()
                                         - (df.dyear > 1996).sum())
