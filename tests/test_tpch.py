"""TPC-H-flavored suite over a denormalized orderLineItemPartSupplier
fact — the direct analog of the reference's test backbone (SURVEY.md §5:
a TPC-H denormalized fact registered once plain and once accelerated,
each query asserting WHICH path serves it and that results agree).

Queries are the BI-shaped adaptations of the classic set — all 22
query shapes (Q1-Q22) are represented: aggregates, star joins through
declared FDs, date filters, HAVING/topN, row-vs-row columnComparison
(Q5/Q7), filtered-agg ratios (Q8), virtual-expression profit sums (Q9),
plus the subquery/derived-table/correlation shapes (Q4, Q11, Q13, Q15,
Q17, Q18, Q20, Q21, Q22) the reference left to Spark and the fallback
must answer here ("correct-but-slow, never an error", SURVEY.md §2).
Each test asserts WHICH path serves the shape and that results agree
with the pandas oracle.
"""

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.bench.parity import assert_frame_parity
from tpu_olap.executor import EngineConfig
from tpu_olap.planner.fallback import execute_fallback

_NATIONS = {
    "FRANCE": "EUROPE", "GERMANY": "EUROPE", "RUSSIA": "EUROPE",
    "BRAZIL": "AMERICA", "CANADA": "AMERICA", "PERU": "AMERICA",
    "CHINA": "ASIA", "INDIA": "ASIA", "JAPAN": "ASIA",
}


def _olps(n=12_000, seed=29):
    """orderLineItemPartSupplier: one flat frame, TPC-H column names."""
    rng = np.random.default_rng(seed)
    nations = np.array(list(_NATIONS))
    df = pd.DataFrame({
        "o_orderdate": pd.to_datetime("1995-01-01")
        + pd.to_timedelta(rng.integers(0, 730, n), unit="D"),
        "l_quantity": rng.integers(1, 51, n).astype(np.int64),
        "l_extendedprice": rng.integers(900, 105_000, n).astype(np.int64),
        "l_discount": rng.integers(0, 11, n).astype(np.int64),  # percent
        "l_returnflag": rng.choice(["A", "N", "R"], n),
        "l_linestatus": rng.choice(["F", "O"], n),
        "l_shipmode": rng.choice(["AIR", "RAIL", "SHIP", "TRUCK"], n),
        "p_brand": rng.choice([f"Brand#{i}" for i in range(10, 55)], n),
        "p_type": rng.choice(
            [f"{a} {b}" for a in ("ECONOMY", "STANDARD", "PROMO")
             for b in ("BRASS", "COPPER", "STEEL")], n),
        "p_size": rng.integers(1, 51, n).astype(np.int64),
        "s_nation": rng.choice(nations, n),
        "c_nation": rng.choice(nations, n),
        "c_mktsegment": rng.choice(
            ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD",
             "MACHINERY"], n),
        "o_orderpriority": rng.choice(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-LOW", "5-NOT"], n),
    })
    df["s_region"] = df.s_nation.map(_NATIONS)
    df["c_region"] = df.c_nation.map(_NATIONS)
    return df


def _nation_dim():
    return pd.DataFrame({"n_name": list(_NATIONS),
                         "n_region": list(_NATIONS.values())})


@pytest.fixture(scope="module")
def eng():
    from tpu_olap.catalog.star import StarDimension, StarSchema
    e = Engine(EngineConfig())
    df = _olps()
    star = StarSchema(
        fact="olps",
        dimensions=(
            StarDimension("s_nat", fact_key="s_nation", dim_key="n_name",
                          column_map={"n_name": "s_nation",
                                      "n_region": "s_region"}),
            StarDimension("c_nat", fact_key="c_nation", dim_key="n_name",
                          column_map={"n_name": "c_nation",
                                      "n_region": "c_region"}),
        ))
    e.register_table("olps", df, time_column="o_orderdate",
                     star_schema=star, block_rows=2048)
    e.register_table("s_nat", _nation_dim(), accelerate=False)
    e.register_table("c_nat", _nation_dim(), accelerate=False)
    return e


def _check(eng, sql, expect_rewrite, approx_cols=()):
    dev = eng.sql(sql)
    assert eng.last_plan.rewritten == expect_rewrite, \
        (eng.last_plan.fallback_reason, sql)
    ref = execute_fallback(eng.planner.plan(sql).stmt, eng.catalog,
                           eng.config)
    assert_frame_parity(dev, ref, approx_cols=approx_cols)
    return dev


def test_q1_pricing_summary(eng):
    """Q1 shape: multi-agg pricing summary with a date ceiling."""
    _check(eng, """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base,
               sum(l_extendedprice * (100 - l_discount)) AS sum_disc,
               avg(l_quantity) AS avg_qty,
               count(*) AS count_order
        FROM olps
        WHERE o_orderdate < '1996-09-01'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus""", True)


def test_q3_segment_revenue_topn(eng):
    """Q3 shape: revenue by order attribute for one market segment,
    ordered LIMIT (TopN eligible)."""
    _check(eng, """
        SELECT o_orderpriority,
               sum(l_extendedprice * (100 - l_discount)) AS revenue
        FROM olps
        WHERE c_mktsegment = 'BUILDING'
          AND o_orderdate < '1995-06-30'
        GROUP BY o_orderpriority
        ORDER BY revenue DESC LIMIT 10""", True)


def test_q5_local_supplier_volume_star(eng):
    """Q5 shape: region-filtered volume grouped by supplier nation,
    reaching region through the declared star join."""
    _check(eng, """
        SELECT s_nation, sum(l_extendedprice) AS revenue
        FROM olps JOIN s_nat ON s_nation = n_name
        WHERE n_region = 'ASIA'
          AND o_orderdate >= '1995-01-01' AND o_orderdate < '1996-01-01'
        GROUP BY s_nation ORDER BY revenue DESC""", True)


def test_q5_row_comparison_on_device(eng):
    """True Q5 requires c_nation = s_nation (row-vs-row) — served on the
    device path via the columnComparison filter's cross-dictionary code
    translation (round 4; previously a structural fallback)."""
    _check(eng, """
        SELECT s_nation, sum(l_extendedprice) AS revenue
        FROM olps WHERE c_nation = s_nation
        GROUP BY s_nation ORDER BY s_nation""", True)


def test_q7_cross_nation_volume(eng):
    """Q7 shape: shipping volume between distinct nations — the <>
    row-vs-row comparison composes as NOT(columnComparison), plus the
    classic literal nation-pair disjunction."""
    _check(eng, """
        SELECT s_nation, c_nation, sum(l_extendedprice) AS volume
        FROM olps
        WHERE c_nation <> s_nation AND s_region = 'EUROPE'
        GROUP BY s_nation, c_nation ORDER BY volume DESC LIMIT 8""", True)
    _check(eng, """
        SELECT sum(l_extendedprice) AS volume FROM olps
        WHERE (s_nation = 'FRANCE' AND c_nation = 'GERMANY')
           OR (s_nation = 'GERMANY' AND c_nation = 'FRANCE')""", True)


def test_q4_exists_priority_counts(eng):
    """Q4 shape: order counts by priority gated on a correlated EXISTS
    semi-join — the subquery class the reference left to Spark; here the
    fallback answers it, checked against an independent pandas oracle
    (the predicate is selective: only some brands qualify)."""
    df = _olps()
    got = eng.sql("""
        SELECT o_orderpriority, count(*) AS n FROM olps o
        WHERE EXISTS (SELECT 1 FROM olps l WHERE l.p_brand = o.p_brand
                      AND l.l_quantity > 49 AND l.p_size > 46)
        GROUP BY o_orderpriority ORDER BY o_orderpriority""")
    assert not eng.last_plan.rewritten
    brands = set(df[(df.l_quantity > 49) & (df.p_size > 46)].p_brand)
    assert 0 < len(brands) < df.p_brand.nunique()  # predicate observable
    oracle = (df[df.p_brand.isin(brands)]
              .groupby("o_orderpriority").size().sort_index())
    assert list(got["o_orderpriority"]) == list(oracle.index)
    assert [int(v) for v in got["n"]] == [int(v) for v in oracle.values]


def test_q6_forecast_revenue(eng):
    """Q6 = the SSB Q1 shape: global filtered sum of a product."""
    _check(eng, """
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM olps
        WHERE o_orderdate >= '1995-01-01' AND o_orderdate < '1996-01-01'
          AND l_discount BETWEEN 3 AND 5 AND l_quantity < 24""", True)


def test_q8_market_share_ratio(eng):
    """Q8 shape: per-year national market share — a CASE-gated sum over
    a plain sum, lowered as filtered aggregation + quotient post-agg on
    the device path."""
    _check(eng, """
        SELECT year(o_orderdate) AS y,
               sum(CASE WHEN s_nation = 'BRAZIL'
                        THEN l_extendedprice ELSE 0 END)
                 / sum(l_extendedprice) AS share
        FROM olps WHERE c_region = 'AMERICA'
        GROUP BY year(o_orderdate) ORDER BY y""", True,
           approx_cols=("share",))


def test_q9_profit_by_nation_year(eng):
    """Q9 shape: product profit — sum of a compound virtual expression
    grouped by nation and year, on the device path."""
    _check(eng, """
        SELECT s_nation, year(o_orderdate) AS y,
               sum(l_extendedprice * (10 - l_discount)
                   - l_quantity * p_size) AS profit
        FROM olps GROUP BY s_nation, year(o_orderdate)
        ORDER BY s_nation, y""", True)


def test_q10_returned_revenue(eng):
    """Q10 shape: returned-item revenue ranking with a date window, on
    the device path."""
    _check(eng, """
        SELECT c_nation, sum(l_extendedprice * l_discount) AS rev
        FROM olps WHERE l_returnflag = 'R'
          AND o_orderdate >= '1995-04-01' AND o_orderdate < '1995-07-01'
        GROUP BY c_nation ORDER BY rev DESC LIMIT 20""", True)


def test_q11_having_scalar_subquery(eng):
    """Q11 shape: HAVING against a scalar aggregate subquery (value
    fraction threshold). Round 4: the uncorrelated subquery executes
    eagerly and inlines, so BOTH halves ride the device path —
    independent pandas oracle."""
    df = _olps()
    got = eng.sql("""
        SELECT p_brand, sum(l_extendedprice) AS val
        FROM olps GROUP BY p_brand
        HAVING sum(l_extendedprice) >
               (SELECT sum(l_extendedprice) * 0.024 FROM olps)
        ORDER BY val DESC""")
    assert eng.last_plan.rewritten
    by_brand = df.groupby("p_brand").l_extendedprice.sum()
    oracle = by_brand[by_brand > df.l_extendedprice.sum() * 0.024] \
        .sort_values(ascending=False)
    assert 0 < len(oracle) < len(by_brand)  # threshold is observable
    assert list(got["p_brand"]) == list(oracle.index)
    assert [int(v) for v in got["val"]] == [int(v) for v in oracle.values]


def test_q13_count_distribution(eng):
    """Q13 shape: distribution of per-key counts — an aggregate over an
    aggregating derived table; fallback path, independent oracle."""
    df = _olps()
    got = eng.sql("""
        SELECT cnt, count(*) AS dist FROM (
            SELECT p_brand, count(*) AS cnt FROM olps GROUP BY p_brand) b
        GROUP BY cnt ORDER BY dist DESC, cnt DESC LIMIT 10""")
    assert not eng.last_plan.rewritten
    oracle = (df.groupby("p_brand").size().value_counts()
              .reset_index())
    oracle.columns = ["cnt", "dist"]
    oracle = oracle.sort_values(["dist", "cnt"],
                                ascending=[False, False]).head(10)
    assert [int(v) for v in got["cnt"]] == [int(v) for v in oracle["cnt"]]
    assert [int(v) for v in got["dist"]] == \
        [int(v) for v in oracle["dist"]]


def test_q15_top_revenue_cte(eng):
    """Q15 shape: the max-revenue member of an aggregating CTE, selected
    by a scalar subquery over the same CTE; fallback path."""
    df = _olps()
    got = eng.sql("""
        WITH rev AS (SELECT s_nation, sum(l_extendedprice) AS total
                     FROM olps GROUP BY s_nation)
        SELECT s_nation, total FROM rev
        WHERE total = (SELECT max(total) FROM rev)""")
    assert not eng.last_plan.rewritten
    totals = df.groupby("s_nation").l_extendedprice.sum()
    assert len(got) == 1
    assert got.iloc[0]["s_nation"] == totals.idxmax()
    assert int(got.iloc[0]["total"]) == int(totals.max())


def test_q18_in_aggregating_subquery(eng):
    """Q18 shape: outer aggregate restricted by IN over a GROUP BY ...
    HAVING subquery. Round 4: the subquery runs eagerly (itself on the
    device) and its values inline into an in filter, so the outer
    aggregate pushes down too — independent oracle."""
    df = _olps()
    got = eng.sql("""
        SELECT p_brand, sum(l_quantity) AS q FROM olps
        WHERE p_brand IN (SELECT p_brand FROM olps GROUP BY p_brand
                          HAVING sum(l_quantity) > 7000)
        GROUP BY p_brand ORDER BY q DESC""")
    assert eng.last_plan.rewritten
    qty = df.groupby("p_brand").l_quantity.sum()
    oracle = qty[qty > 7000].sort_values(ascending=False)
    assert 0 < len(oracle) < len(qty)
    assert list(got["p_brand"]) == list(oracle.index)
    assert [int(v) for v in got["q"]] == [int(v) for v in oracle.values]


def test_q12_shipmode_priority_counts(eng):
    """Q12 shape: counts split by a CASE over priority, per ship mode."""
    _check(eng, """
        SELECT l_shipmode,
               sum(CASE WHEN o_orderpriority = '1-URGENT'
                        THEN 1 ELSE 0 END) AS high_line_count,
               count(*) AS n
        FROM olps
        WHERE l_shipmode IN ('SHIP', 'RAIL')
        GROUP BY l_shipmode ORDER BY l_shipmode""", True)


def test_q14_promo_revenue_filtered_agg(eng):
    """Q14 shape: promo share via FILTER (the modern spelling of the
    CASE ratio)."""
    _check(eng, """
        SELECT sum(l_extendedprice) FILTER (WHERE p_type LIKE 'PROMO%')
                   AS promo,
               sum(l_extendedprice) AS total
        FROM olps
        WHERE o_orderdate >= '1995-09-01'
          AND o_orderdate < '1995-10-01'""", True)


def test_q16_brand_distinct_suppliers(eng):
    """Q16 shape: approximate distinct per brand with exclusions."""
    _check(eng, """
        SELECT p_brand, approx_count_distinct(s_nation) AS supplier_cnt
        FROM olps
        WHERE NOT (p_type LIKE 'ECONOMY%') AND p_size IN (1, 4, 9, 14)
        GROUP BY p_brand ORDER BY p_brand""", True,
           approx_cols=("supplier_cnt",))


def test_q19_disjunctive_filter(eng):
    """Q19 shape: OR of bracketed conjunction groups."""
    _check(eng, """
        SELECT sum(l_extendedprice * (100 - l_discount)) AS revenue
        FROM olps
        WHERE (p_brand = 'Brand#12' AND l_quantity BETWEEN 1 AND 11)
           OR (p_brand = 'Brand#23' AND l_quantity BETWEEN 10 AND 20)
           OR (p_brand = 'Brand#34' AND l_quantity BETWEEN 20 AND 30)""",
           True)


def test_q22_cte_over_aggregate(eng):
    """Q22 shape: a CTE aggregate consumed by an outer filter — executes
    through the derived-table fallback."""
    _check(eng, """
        WITH nation_rev AS (
            SELECT c_nation, sum(l_extendedprice) AS rev, count(*) AS n
            FROM olps GROUP BY c_nation)
        SELECT c_nation, rev FROM nation_rev
        WHERE rev > (SELECT avg(rev) FROM nation_rev)
        ORDER BY c_nation""", False)


def test_q17_small_quantity_revenue_correlated(eng):
    """Q17 proper: revenue from rows under 20% of the part-brand average
    quantity — the equality-correlated scalar-aggregate shape, now
    decorrelated into a key->value map (round 4) instead of rejected."""
    df = _olps()
    got = eng.sql(
        "SELECT sum(l_extendedprice) AS rev FROM olps "
        "WHERE l_quantity < (SELECT 0.2 * avg(o2.l_quantity) FROM olps o2 "
        "WHERE o2.p_brand = olps.p_brand)")
    assert not eng.last_plan.rewritten  # fallback serves it
    avg = df.groupby("p_brand")["l_quantity"].mean()
    m = df["l_quantity"] < 0.2 * df["p_brand"].map(avg)
    assert int(got["rev"][0]) == int(df.loc[m, "l_extendedprice"].sum())


def test_q21_exists_not_exists_correlated(eng):
    """Q21 shape: semi-join + anti-join via correlated EXISTS/NOT
    EXISTS."""
    df = _olps()
    got = eng.sql(
        "SELECT count(*) AS n FROM olps WHERE "
        "EXISTS (SELECT 1 FROM olps o2 WHERE o2.p_brand = olps.p_brand "
        "AND o2.l_shipmode = 'AIR' AND o2.l_quantity > 45) "
        "AND NOT EXISTS (SELECT 1 FROM olps o3 "
        "WHERE o3.p_brand = olps.p_brand AND o3.l_discount = 10 "
        "AND o3.p_size > 48)")
    air = set(df.loc[(df.l_shipmode == "AIR")
                     & (df.l_quantity > 45), "p_brand"])
    d10 = set(df.loc[(df.l_discount == 10) & (df.p_size > 48), "p_brand"])
    exp = int((df.p_brand.isin(air) & ~df.p_brand.isin(d10)).sum())
    assert int(got["n"][0]) == exp


def test_q2_correlated_minimum(eng):
    """Q2 shape: rows whose value equals a two-key correlated minimum."""
    df = _olps()
    got = eng.sql(
        "SELECT count(*) AS n FROM olps WHERE l_extendedprice = "
        "(SELECT min(o2.l_extendedprice) FROM olps o2 "
        "WHERE o2.p_brand = olps.p_brand "
        "AND o2.s_region = olps.s_region)")
    mn = df.groupby(["p_brand", "s_region"])["l_extendedprice"] \
        .transform("min")
    assert int(got["n"][0]) == int((df.l_extendedprice == mn).sum())


def test_q20_nested_in_with_inner_correlation(eng):
    """Q20 shape: an IN subquery whose body itself contains an
    equality-correlated scalar aggregate — the middle scope is the
    correlation target, resolved recursively."""
    df = _olps()
    got = eng.sql(
        "SELECT count(*) AS n FROM olps WHERE p_brand IN "
        "(SELECT o2.p_brand FROM olps o2 WHERE o2.l_quantity > "
        " (SELECT 0.5 * avg(o3.l_quantity) FROM olps o3 "
        "  WHERE o3.p_brand = o2.p_brand))")
    avg = df.groupby("p_brand")["l_quantity"].mean()
    brands = set(df.loc[df["l_quantity"]
                        > 0.5 * df["p_brand"].map(avg), "p_brand"])
    exp = int(df["p_brand"].isin(brands).sum())
    assert int(got["n"][0]) == exp


def test_monthly_timeseries(eng):
    """Granularity bucketing over the order date (the reference's
    date-function suites)."""
    _check(eng, """
        SELECT date_trunc('month', o_orderdate) AS m,
               sum(l_extendedprice) AS rev
        FROM olps
        WHERE o_orderdate >= '1995-01-01' AND o_orderdate < '1995-07-01'
        GROUP BY date_trunc('month', o_orderdate) ORDER BY m""", True)
