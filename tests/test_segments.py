"""Segments-layer tests: dictionary encoding, blocking, pruning, metadata."""

import numpy as np
import pandas as pd
import pytest

from tpu_olap.ir import Interval
from tpu_olap.segments import (ColumnType, Dictionary, TIME_COLUMN,
                               ingest_pandas)
from tpu_olap.utils import timeutil as tu


def make_df(n=1000, seed=0):
    rng = np.random.default_rng(seed)
    t0 = tu.date_to_millis(1993, 1, 1)
    return pd.DataFrame({
        "ts": t0 + rng.integers(0, 365 * 86_400_000, n),
        "city": rng.choice(["amsterdam", "berlin", "chicago", None], n),
        "qty": rng.integers(1, 50, n).astype(np.int64),
        "price": rng.uniform(0, 100, n),
    })


def test_dictionary_build_roundtrip():
    d, codes = Dictionary.build(np.array(["b", "a", None, "b", "c"], dtype=object))
    assert list(d.values) == ["a", "b", "c"]
    assert codes.tolist() == [2, 1, 0, 2, 3]
    assert d.decode(codes).tolist() == ["b", "a", None, "b", "c"]
    assert d.id_of(None) == 0 and d.id_of("a") == 1 and d.id_of("zz") == -1


def test_dictionary_predicates():
    d, _ = Dictionary.build(np.array(["apple", "banana", "cherry"], dtype=object))
    lo, hi = d.bound_code_range("b", None, False, False)
    assert (lo, hi) == (2, 3)  # banana..cherry
    lo, hi = d.bound_code_range("banana", "banana", False, False)
    assert (lo, hi) == (2, 2)
    lo, hi = d.bound_code_range("banana", "banana", True, False)
    assert lo > hi  # empty
    t = d.regex_table("an")
    assert t.tolist() == [False, False, True, False]
    t = d.like_table("%err%")
    assert t.tolist() == [False, False, False, True]
    t = d.in_table(["apple", "zz", None])
    assert t.tolist() == [True, True, False, False]  # note: None -> id 0


def test_ingest_blocks_and_padding():
    df = make_df(1000)
    ts = ingest_pandas("t", df, time_column="ts", block_rows=256)
    assert ts.num_rows == 1000
    assert len(ts.segments) == 4
    assert ts.segments[-1].meta.n_valid == 1000 - 3 * 256
    assert ts.schema["city"] is ColumnType.STRING
    assert ts.schema["qty"] is ColumnType.LONG
    assert ts.schema["price"] is ColumnType.DOUBLE
    # time-sorted across segment boundaries
    last = None
    for s in ts.segments:
        t = s.columns[TIME_COLUMN][:s.meta.n_valid]
        assert (np.diff(t) >= 0).all()
        if last is not None:
            assert t[0] >= last
        last = t[-1]
    # decode round-trip preserves multiset of values
    d = ts.dictionaries["city"]
    decoded = np.concatenate([
        d.decode(s.columns["city"][:s.meta.n_valid]) for s in ts.segments])
    left = pd.Series(decoded).fillna("~").value_counts()
    right = df["city"].fillna("~").value_counts()
    assert left.sort_index().tolist() == right.sort_index().tolist()


def test_prune_by_interval_and_bounds():
    df = make_df(1000)
    ts = ingest_pandas("t", df, time_column="ts", block_rows=256)
    t0, t1 = ts.time_boundary
    # narrow interval touching only the first block
    first_max = ts.segments[0].meta.time_max
    pruned = ts.prune([Interval(t0, first_max + 1)])
    assert len(pruned) < 4
    # impossible numeric bound prunes everything
    pruned = ts.prune([], numeric_bounds={"qty": (1000, None)})
    assert pruned == []
    pruned = ts.prune([], numeric_bounds={"qty": (None, 49)})
    assert len(pruned) == 4


def test_column_metadata():
    ts = ingest_pandas("t", make_df(500), time_column="ts")
    md = ts.column_metadata()
    assert md["city"]["cardinality"] == 3
    assert md["qty"]["min"] >= 1 and md["qty"]["max"] <= 49
    assert md[TIME_COLUMN]["type"] == "LONG"
    assert ts.cardinality("qty") is None


def test_ingest_without_time_column():
    df = make_df(100).drop(columns=["ts"])
    ts = ingest_pandas("t", df)
    assert ts.time_boundary == (0, 0)
    assert ts.num_rows == 100


def test_nulls_in_numeric():
    df = pd.DataFrame({"x": [1.0, np.nan, 3.0], "k": ["a", "b", "a"]})
    ts = ingest_pandas("t", df)
    s = ts.segments[0]
    assert "x" in s.null_masks
    assert s.null_masks["x"][:3].tolist() == [False, True, False]


def test_unsupported_type_raises():
    import pyarrow as pa
    from tpu_olap.segments import ingest_arrow
    t = pa.table({"a": pa.array([[1, 2], [3]], type=pa.list_(pa.int64()))})
    with pytest.raises(TypeError, match="unsupported column type"):
        ingest_arrow("t", t)
