"""Observability subsystem (tpu_olap.obs): span-tree tracing, the
metrics registry + /metrics Prometheus exposition, /debug/queries,
EXPLAIN ANALYZE, the bounded history ring, and the metrics-contract
every execution path honors (stable dashboard schema)."""

import json
import math
import os
import re
import subprocess
import sys
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer
from tpu_olap.executor import EngineConfig

CORE_KEYS = {"query_id", "total_ms", "rows_scanned", "segments_scanned",
             "cache_hit", "query_type", "datasource"}


def _df(n=6000, seed=11):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2023-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 90, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(12)], n),
        "h": rng.choice([f"h{i}" for i in range(7)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _engine(**kw):
    eng = Engine(EngineConfig(**kw))
    eng.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    return eng


GROUP_SQL = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
AGG_SQL = "SELECT sum(v) AS s, count(*) AS n FROM t"


# ----------------------------------------------------------- span trees


def test_explain_analyze_span_tree():
    """EXPLAIN ANALYZE executes the query and returns its span tree as
    rows; direct-child stage durations sum to within the root total."""
    eng = _engine()
    eng.sql(GROUP_SQL)  # warm so timings are steady-state
    out = eng.sql(f"EXPLAIN ANALYZE {GROUP_SQL}")
    assert list(out.columns) == ["span", "ms", "detail"]
    names = [s.strip() for s in out["span"]]
    assert names[0] == "sql"
    for stage in ("parse", "plan", "execute", "prepare", "dispatch"):
        assert stage in names, f"missing {stage} span"
    root_ms = float(out["ms"][0])
    # direct children of the root run sequentially inside it
    kids = [float(ms) for sp, ms in zip(out["span"], out["ms"])
            if sp.startswith("  ") and not sp.startswith("    ")]
    assert kids and sum(kids) <= root_ms * 1.05 + 1.0
    head = json.loads(out["detail"][0])
    assert head["query_id"].startswith("q")
    assert head["rows_returned"] == 12
    # ... and the recorded history total agrees with the execute span
    rec = eng.history[-1]
    exec_ms = next(float(ms) for sp, ms in zip(out["span"], out["ms"])
                   if sp.strip() == "execute")
    assert rec["total_ms"] <= exec_ms * 1.5 + 5.0


def test_explain_analyze_fallback_statement():
    eng = _engine()
    eng.register_table("dim", pd.DataFrame({"k": [1, 2, 3]}),
                       accelerate=False)
    out = eng.sql("EXPLAIN ANALYZE SELECT k FROM dim ORDER BY k")
    names = [s.strip() for s in out["span"]]
    assert "fallback" in names
    assert eng.history[-1]["query_type"] == "fallback"


def test_tracer_rings_bounded_and_slow_log():
    eng = _engine(trace_history_limit=5, slow_query_ms=0.0,
                  slow_log_limit=3)
    for _ in range(8):
        eng.sql(AGG_SQL)
    snap = eng.tracer.snapshot()
    assert len(snap["recent"]) == 5
    assert len(snap["slow"]) == 3  # threshold 0: every query is "slow"
    assert snap["slow_query_ms"] == 0.0
    t = snap["recent"][0]
    assert t["name"] == "sql" and t["duration_ms"] > 0
    json.dumps(snap)  # the whole snapshot is JSON-serializable


def test_tracing_disabled_is_silent():
    eng = _engine(tracing_enabled=False)
    out = eng.sql(GROUP_SQL)
    assert len(out) == 12
    assert eng.tracer.snapshot()["recent"] == []
    # records still carry a generated query_id
    assert eng.history[-1]["query_id"].startswith("q")
    ea = eng.sql(f"EXPLAIN ANALYZE {AGG_SQL}")
    assert "no trace" in ea["span"][0]


# ------------------------------------------------------ metrics contract


def _assert_core(rec, label):
    missing = CORE_KEYS - set(rec)
    assert not missing, f"{label}: record missing {sorted(missing)}"
    json.dumps(rec)  # and it serializes


def test_metrics_contract_all_paths():
    """Every execution path emits the same core keys — the stable
    dashboard schema (ISSUE 6 satellite)."""
    eng = _engine()
    eng.register_table("dim", pd.DataFrame({"k": [1, 2]}),
                       accelerate=False)

    eng.sql(GROUP_SQL)
    _assert_core(eng.history[-1], "dense")
    assert eng.history[-1]["path"] == "dense"

    eng.sql(GROUP_SQL)  # warm template: compile-cache hit
    hit_rec = eng.history[-1]
    _assert_core(hit_rec, "cache hit")

    eng.sql("SELECT k FROM dim")  # unaccelerated: fallback
    _assert_core(eng.history[-1], "fallback")
    assert eng.history[-1]["path"] == "fallback"
    assert eng.history[-1]["query_type"] == "fallback"

    # sparse path: force by shrinking the dense budget
    sp = Engine(EngineConfig(dense_group_budget=4))
    sp.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    sp.sql("SELECT g, h, sum(v) AS s FROM t GROUP BY g, h")
    _assert_core(sp.history[-1], "sparse")
    assert sp.history[-1]["path"] == "sparse"
    assert sp.history[-1].get("sparse")

    # batch legs + dedup fan-out
    outs = eng.sql_batch([GROUP_SQL, AGG_SQL, GROUP_SQL])
    assert len(outs) == 3
    batch_recs = [h for h in eng.history if h.get("batch_id")]
    assert batch_recs, "no batch-leg records"
    ids = set()
    for rec in batch_recs:
        _assert_core(rec, "batch leg")
        assert rec["path"] == "batch"
        ids.add(rec["query_id"])
    dedups = [h for h in eng.history if h.get("batch_dedup")]
    assert dedups, "no dedup fan-out record"
    # every logical query keeps its own id across the fused dispatch
    assert len(ids) == len(batch_recs)


def test_history_ring_bounded_counters_exact():
    eng = _engine(history_limit=6, result_cache_enabled=True)
    n_rows = len(_df())
    for _ in range(15):
        eng.sql(AGG_SQL)
    assert len(eng.history) == 6  # ring evicted oldest
    c = eng.counters()
    assert c["queries"] == 15  # totals survive eviction exactly
    # only the first execution scans; the rest serve from the semantic
    # result cache (cache_hit is REAL now — ISSUE 9) with zero scans
    assert c["rows_scanned"] == n_rows
    assert c["by_query_type"] == {"timeseries": 15}
    assert c["cache_hits"] == 14  # every repeat is a tier-2 hit


def test_retry_errors_sanitized_serializable():
    """Exception-carrying metric values become short strings at record
    time — /status //debug payloads can never hit raw exception
    objects (ISSUE 6 satellite)."""
    class Unjsonable:
        def __repr__(self):
            return "unjsonable<" + "x" * 500 + ">"

    calls = {"n": 0}

    def inj(stage, attempt):
        calls["n"] += 1
        if calls["n"] <= 10:
            raise RuntimeError(Unjsonable())

    eng = _engine(dispatch_retries=1, fault_injector=inj)
    out = eng.sql(GROUP_SQL)  # retries exhaust -> fallback answers
    assert len(out) == 12
    failed = [h for h in eng.history if h.get("failed")]
    assert failed and failed[-1]["retry_errors"]
    for e in failed[-1]["retry_errors"]:
        assert isinstance(e, str) and len(e) <= 300
    json.dumps(list(eng.history))  # every record serializes


# ------------------------------------------------------- HTTP surfaces

# Prometheus text-format line grammar: metric line or HELP/TYPE comment
_METRIC_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" (?P<value>[^ ]+)$")


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.headers.get("Content-Type"), r.read().decode()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_metrics_endpoint_prometheus_grammar():
    """Scrape GET /metrics from a live QueryServer after a mixed
    single/batch/fallback workload and validate every line against the
    text-format grammar — names/labels parse, values finite, histograms
    complete (ISSUE 6 acceptance + CI satellite)."""
    eng = _engine()
    eng.register_table("dim", pd.DataFrame({"k": [1, 2]}),
                       accelerate=False)
    eng.sql(GROUP_SQL)
    eng.sql(GROUP_SQL)
    eng.sql("SELECT k FROM dim")        # fallback
    eng.sql_batch([GROUP_SQL, AGG_SQL, GROUP_SQL])  # batch + dedup
    srv = QueryServer(eng).start()
    try:
        ctype, text = _get(srv.url + "/metrics")
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
    finally:
        srv.stop()

    seen = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            line), line
            continue
        m = _METRIC_RE.match(line)
        assert m, f"bad exposition line: {line!r}"
        v = float(m.group("value"))
        assert math.isfinite(v), f"non-finite sample: {line!r}"
        seen.add(line.split("{")[0].split(" ")[0])

    # the advertised families are present after this workload
    for name in ("tpu_olap_queries_total",
                 "tpu_olap_query_latency_ms_bucket",
                 "tpu_olap_query_latency_ms_count",
                 "tpu_olap_query_latency_ms_sum",
                 "tpu_olap_rows_scanned_total",
                 "tpu_olap_segments_scanned_total",
                 "tpu_olap_compile_cache_requests_total",
                 "tpu_olap_batch_size_count",
                 "tpu_olap_history_records",
                 # workload profiler families (ISSUE 11 satellite)
                 "tpu_olap_workload_templates",
                 "tpu_olap_workload_observations_total"):
        assert name in seen, f"{name} missing from /metrics"
    # latency histogram covers the paths this workload exercised
    for path in ("dense", "fallback", "batch"):
        assert f'path="{path}"' in text, f"no latency series for {path}"


def test_latency_histogram_quantiles_derivable():
    eng = _engine()
    for _ in range(10):
        eng.sql(AGG_SQL)
    hist = eng.metrics.histogram("query_latency_ms")
    p50 = hist.quantile(0.5, query_type="timeseries", path="dense")
    p99 = hist.quantile(0.99, query_type="timeseries", path="dense")
    assert p50 is not None and p99 is not None
    assert 0 < p50 <= p99


def test_debug_queries_endpoint():
    eng = _engine(slow_query_ms=0.0)
    eng.sql(GROUP_SQL)
    eng.sql(AGG_SQL)
    srv = QueryServer(eng).start()
    try:
        _, body = _get(srv.url + "/debug/queries")
        snap = json.loads(body)
        assert snap["recent"] and snap["slow"]
        newest = snap["recent"][0]
        assert newest["name"] == "sql"
        child_names = [c["name"] for c in newest["children"]]
        assert "plan" in child_names and "execute" in child_names
        _, body = _get(srv.url + "/debug/queries?limit=1")
        assert len(json.loads(body)["recent"]) == 1
        # /status still answers (and its counters are the incremental
        # totals, not an O(history) re-sum)
        code = _post(srv.url + "/sql", {"query": AGG_SQL})
        assert code["rows"]
        _, body = _get(srv.url + "/status")
        assert json.loads(body)["counters"]["queries"] == 3
    finally:
        srv.stop()


def test_batch_shared_scan_span_nesting():
    """Fused batch legs nest under one shared-scan span in the
    submitting trace."""
    eng = _engine()
    eng.sql_batch([GROUP_SQL, AGG_SQL])
    trace = eng.tracer.last
    assert trace is not None and trace.name == "sql_batch"

    def find(span, name):
        hits = [s for _, s in span.walk() if s.name == name]
        return hits

    shared = find(trace, "shared-scan")
    assert shared, "no shared-scan span under the batch trace"
    legs = [c for c in shared[0].children if c.name == "leg"]
    assert len(legs) == 2
    leg_ids = {leg.attrs.get("query_id") for leg in legs}
    assert len(leg_ids) == 2  # per-leg attribution survived fusing


def test_bench_help_advertises_span_summary():
    """CI satellite: `bench.py --help` documents the span-summary flag
    (argparse exits before any engine/dataset setup, so this is
    fast)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--help"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert "--span-summary" in proc.stdout
    assert "--concurrency" in proc.stdout
    assert "--trace-out" in proc.stdout


def test_ssb_explain_analyze_sums():
    """ISSUE 6 acceptance: EXPLAIN ANALYZE on an SSB query returns a
    span tree whose stage durations sum to within the recorded
    total."""
    from tpu_olap.bench import QUERIES, register_ssb
    eng = Engine()
    register_ssb(eng, lineorder_rows=8_000, seed=3, block_rows=1 << 12)
    eng.sql(QUERIES["q2.1"])  # warm
    out = eng.sql(f"EXPLAIN ANALYZE {QUERIES['q2.1']}")
    assert eng.last_plan.rewritten
    root_ms = float(out["ms"][0])
    kids = [float(ms) for sp, ms in zip(out["span"], out["ms"])
            if sp.startswith("  ") and not sp.startswith("    ")]
    assert sum(kids) <= root_ms * 1.05 + 1.0
    rec = eng.history[-1]
    assert rec["query_type"] in ("groupBy", "topN", "timeseries")
    assert rec["total_ms"] <= root_ms * 1.05 + 1.0


# -------------------------------------- workload introspection (ISSUE 11)


def test_sub_ms_latency_buckets():
    """Warm-cache serves (~0.6 ms, BENCH_CACHE.json) must not collapse
    into one bucket: the histogram head now resolves 0.1/0.25/0.5 so
    cache-path p50 and p95 are distinguishable (ISSUE 11 satellite)."""
    from tpu_olap.obs.metrics import LATENCY_BUCKETS_MS, MetricsRegistry
    assert LATENCY_BUCKETS_MS[:4] == (0.1, 0.25, 0.5, 1.0)
    reg = MetricsRegistry("test")
    h = reg.histogram("warm_ms")
    for v in (0.2, 0.2, 0.2, 0.2, 0.8):
        h.observe(v)
    p50, p95 = h.quantile(0.5), h.quantile(0.95)
    assert p50 is not None and p50 <= 0.25
    assert p95 is not None and p95 > 0.5


def test_template_fingerprint_stability():
    """Same query with different WHERE literals / time intervals -> one
    template; changed dims or aggs -> different templates; fallback
    statements fingerprint from literal-masked SQL the same way."""
    eng = _engine()
    base = ("SELECT g, sum(v) AS s FROM t WHERE v > {lit} "
            "AND ts >= '{t0}' GROUP BY g")
    eng.sql(base.format(lit=100, t0="2023-03-05"))
    t_a = eng.history[-1]["template_id"]
    eng.sql(base.format(lit=700, t0="2023-04-01"))
    assert eng.history[-1]["template_id"] == t_a
    eng.sql("SELECT h, sum(v) AS s FROM t WHERE v > 100 GROUP BY h")
    t_dims = eng.history[-1]["template_id"]
    eng.sql("SELECT g, min(v) AS s FROM t WHERE v > 100 GROUP BY g")
    t_aggs = eng.history[-1]["template_id"]
    assert len({t_a, t_dims, t_aggs}) == 3

    eng.register_table("dim", pd.DataFrame({"k": [1, 2, 3]}),
                       accelerate=False)
    eng.sql("SELECT k FROM dim WHERE k > 1")
    t_f1 = eng.history[-1]["template_id"]
    eng.sql("SELECT k FROM dim WHERE k > 2")
    assert eng.history[-1]["template_id"] == t_f1
    assert eng.history[-1]["path"] == "fallback"


def test_template_fingerprint_survives_batch_and_coalescer():
    """The same logical template keeps one id across the single-query
    path, fused batch legs, dedup fan-outs, and coalesced concurrent
    submissions (ISSUE 11 satellite)."""
    import threading
    eng = _engine()
    q_a = "SELECT g, sum(v) AS s FROM t WHERE v > {lit} GROUP BY g"
    q_b = "SELECT h, count(*) AS n FROM t WHERE v < {lit} GROUP BY h"
    eng.sql(q_a.format(lit=10))
    t_a = eng.history[-1]["template_id"]
    eng.sql(q_b.format(lit=990))
    t_b = eng.history[-1]["template_id"]

    h0 = len(eng.history)
    eng.sql_batch([q_a.format(lit=200), q_b.format(lit=300),
                   q_a.format(lit=400), q_a.format(lit=400)])
    recs = list(eng.history)[h0:]
    assert len(recs) == 4
    assert {r["template_id"] for r in recs} == {t_a, t_b}
    dedups = [r for r in recs if r.get("batch_dedup")]
    assert dedups and all(r["template_id"] == t_a for r in dedups)

    # coalescer: concurrent same-template callers ride one fused
    # dispatch and still attribute to their shared template
    ceng = _engine(batch_window_ms=40.0)
    ceng.sql(q_a.format(lit=10))
    t_ca = ceng.history[-1]["template_id"]
    h0 = len(ceng.history)
    barrier = threading.Barrier(4)

    def client(lit):
        barrier.wait()
        ceng.sql(q_a.format(lit=lit))

    threads = [threading.Thread(target=client, args=(100 + i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    recs = list(ceng.history)[h0:]
    assert len(recs) == 4
    assert all(r["template_id"] == t_ca for r in recs)


def _mixed_workload(eng):
    qs = [
        "SELECT g, sum(v) AS s FROM t WHERE v > 100 GROUP BY g",
        "SELECT g, sum(v) AS s FROM t WHERE v > 500 GROUP BY g",
        "SELECT g, sum(v) AS s FROM t WHERE v > 100 GROUP BY g",  # warm
        "SELECT g, sum(v) AS s FROM t WHERE v > 100 GROUP BY g",  # warm
        "SELECT h, max(v) AS m FROM t GROUP BY h",
        "SELECT sum(v) AS s, count(*) AS n FROM t",
        "SELECT sum(v) AS s, count(*) AS n FROM t",               # warm
    ]
    for q in qs:
        eng.sql(q)


def test_sys_query_templates_matches_history_ground_truth():
    """ISSUE 11 acceptance: SELECT ... FROM sys.query_templates ORDER BY
    count DESC LIMIT 5 executes through the ordinary Engine.sql path
    after a mixed run, and every stat matches ground truth derived from
    QueryRunner.history; introspection appears nowhere in its own
    stats."""
    from tpu_olap.obs.workload import percentile
    eng = _engine(result_cache_enabled=True)
    eng.register_table("dim", pd.DataFrame({"k": [1, 2, 3]}),
                       accelerate=False)
    _mixed_workload(eng)
    eng.sql("SELECT k FROM dim WHERE k > 1")

    by_template: dict = {}
    for rec in eng.history:
        by_template.setdefault(rec["template_id"], []).append(rec)
    n_hist = len(eng.history)
    n_templates = len(eng.runner.workload.snapshot())

    top = eng.sql("SELECT * FROM sys.query_templates "
                  "ORDER BY count DESC LIMIT 5")
    assert 1 <= len(top) <= 5
    counts = list(top["count"])
    assert counts == sorted(counts, reverse=True)
    for _, row in top.iterrows():
        recs = by_template[row["template_id"]]
        assert row["count"] == len(recs)
        lats = [r["total_ms"] for r in recs]
        assert row["p50_ms"] == pytest.approx(percentile(lats, 0.50))
        assert row["p95_ms"] == pytest.approx(percentile(lats, 0.95))
        assert row["p99_ms"] == pytest.approx(percentile(lats, 0.99))
        hits = sum(1 for r in recs if r.get("cache_hit"))
        assert row["cache_hit_rate"] == pytest.approx(hits / len(recs))
        assert row["rows_scanned"] == \
            sum(r.get("rows_scanned") or 0 for r in recs)

    # no recursion: the introspection query left no record, no
    # template, no counter increment anywhere
    assert len(eng.history) == n_hist
    assert len(eng.runner.workload.snapshot()) == n_templates
    n1 = int(eng.sql("SELECT COUNT(*) AS n FROM sys.queries")["n"][0])
    n2 = int(eng.sql("SELECT COUNT(*) AS n FROM sys.queries")["n"][0])
    assert n1 == n2 == n_hist
    assert eng.counters()["queries"] == n_hist
    assert not any(str(r["datasource"]).startswith("sys.")
                   for r in eng.runner.workload.snapshot())


def test_sys_schema_surfaces():
    """sys.tables / sys.segments / sys.caches / sys.metrics /
    sys.queries answer through ordinary SQL with live engine state."""
    eng = _engine(result_cache_enabled=True)
    _mixed_workload(eng)

    tables = eng.sql("SELECT * FROM sys.tables")
    row = tables[tables["table"] == "t"].iloc[0]
    assert bool(row["accelerated"]) and int(row["rows"]) == 6000

    segs = eng.sql("SELECT * FROM sys.segments WHERE table = 't'")
    assert int(segs["rows"].sum()) == 6000
    assert (segs["time_min"] <= segs["time_max"]).all()

    caches = eng.sql("SELECT * FROM sys.caches")
    assert {"full", "segment", "jit", "plan", "arg"} \
        <= set(caches["cache"])

    metrics = eng.sql("SELECT * FROM sys.metrics "
                      "WHERE name = 'tpu_olap_queries_total'")
    assert len(metrics) >= 1 and metrics["value"].sum() > 0

    # sys.queries joins back to sys.query_templates on template_id
    joined = eng.sql(
        "SELECT q.template_id, COUNT(*) AS n FROM sys.queries q "
        "GROUP BY q.template_id ORDER BY n DESC")
    assert int(joined["n"].sum()) == len(eng.history)

    with pytest.raises(KeyError):
        eng.sql("SELECT * FROM sys.not_a_table")

    # a sys reference inside an expression subquery routes the WHOLE
    # statement onto the suppressed introspection path too
    n_hist = len(eng.history)
    n_templates = len(eng.runner.workload.snapshot())
    out = eng.sql("SELECT g FROM t WHERE v IN "
                  "(SELECT rows_returned FROM sys.queries) GROUP BY g")
    assert len(eng.history) == n_hist
    assert len(eng.runner.workload.snapshot()) == n_templates
    assert not any(str(r["datasource"]).startswith("sys.")
                   for r in eng.runner.workload.snapshot())

    # a sys self-join reads ONE consistent snapshot per statement
    # (both sides resolve the same memoized entry — no row ever
    # present on one side and missing from the other)
    joined = eng.sql(
        "SELECT COUNT(*) AS n FROM "
        "(SELECT query_id FROM sys.queries) a JOIN "
        "(SELECT query_id AS qid2 FROM sys.queries) b "
        "ON a.query_id = b.qid2")
    assert int(joined["n"][0]) == n_hist


def test_x_query_id_header_and_debug_workload():
    """POST /sql answers with an X-Query-Id correlating to the history
    record; /sql/batch carries one id per statement; GET /debug/workload
    serves the profiler + cube-advisor recommendations."""
    eng = _engine()
    _mixed_workload(eng)
    srv = QueryServer(eng).start()
    try:
        req = urllib.request.Request(
            srv.url + "/sql",
            data=json.dumps({"query": GROUP_SQL}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            qid = r.headers.get("X-Query-Id")
            json.loads(r.read())
        assert qid and qid == eng.history[-1]["query_id"]

        req = urllib.request.Request(
            srv.url + "/sql/batch",
            data=json.dumps({"queries": [GROUP_SQL, AGG_SQL]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            ids = (r.headers.get("X-Query-Id") or "").split(",")
            json.loads(r.read())
        assert len(ids) == 2 and all(i.startswith("q") for i in ids)

        # a sys statement in a batch: no dangling id (its slot is "-")
        # and no introspection spans leak into the batch trace
        req = urllib.request.Request(
            srv.url + "/sql/batch",
            data=json.dumps({"queries": [
                "SELECT COUNT(*) AS n FROM sys.queries",
                AGG_SQL]}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            ids = (r.headers.get("X-Query-Id") or "").split(",")
            body = json.loads(r.read())
        assert ids[0] == "-" and ids[1].startswith("q")
        assert body["results"][0]["rows"][0]["n"] > 0
        batch_trace = eng.tracer.last
        names = {s.name for _, s in batch_trace.walk()}
        assert not any(n.startswith("fallback") for n in names), names

        _, body = _get(srv.url + "/debug/workload")
        snap = json.loads(body)
        assert snap["totals"]["observations"] >= 7
        assert snap["templates"], "no templates in /debug/workload"
        top = snap["templates"][0]
        assert {"template_id", "count", "p50_ms", "p95_ms",
                "cache_hit_rate", "dims"} <= set(top)
        assert snap["recommendations"], "no rollup recommendations"
        rec = snap["recommendations"][0]
        assert {"datasource", "dims", "granularity", "queries",
                "est_ms_saved", "templates"} <= set(rec)
    finally:
        srv.stop()
