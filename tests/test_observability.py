"""Observability subsystem (tpu_olap.obs): span-tree tracing, the
metrics registry + /metrics Prometheus exposition, /debug/queries,
EXPLAIN ANALYZE, the bounded history ring, and the metrics-contract
every execution path honors (stable dashboard schema)."""

import json
import math
import os
import re
import subprocess
import sys
import urllib.request

import numpy as np
import pandas as pd
import pytest

from tpu_olap import Engine
from tpu_olap.api.server import QueryServer
from tpu_olap.executor import EngineConfig

CORE_KEYS = {"query_id", "total_ms", "rows_scanned", "segments_scanned",
             "cache_hit", "query_type", "datasource"}


def _df(n=6000, seed=11):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "ts": pd.to_datetime("2023-03-01")
        + pd.to_timedelta(rng.integers(0, 86400 * 90, n), unit="s"),
        "g": rng.choice([f"g{i}" for i in range(12)], n),
        "h": rng.choice([f"h{i}" for i in range(7)], n),
        "v": rng.integers(0, 1000, n).astype(np.int64),
    })


def _engine(**kw):
    eng = Engine(EngineConfig(**kw))
    eng.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    return eng


GROUP_SQL = "SELECT g, sum(v) AS s FROM t GROUP BY g ORDER BY g"
AGG_SQL = "SELECT sum(v) AS s, count(*) AS n FROM t"


# ----------------------------------------------------------- span trees


def test_explain_analyze_span_tree():
    """EXPLAIN ANALYZE executes the query and returns its span tree as
    rows; direct-child stage durations sum to within the root total."""
    eng = _engine()
    eng.sql(GROUP_SQL)  # warm so timings are steady-state
    out = eng.sql(f"EXPLAIN ANALYZE {GROUP_SQL}")
    assert list(out.columns) == ["span", "ms", "detail"]
    names = [s.strip() for s in out["span"]]
    assert names[0] == "sql"
    for stage in ("parse", "plan", "execute", "prepare", "dispatch"):
        assert stage in names, f"missing {stage} span"
    root_ms = float(out["ms"][0])
    # direct children of the root run sequentially inside it
    kids = [float(ms) for sp, ms in zip(out["span"], out["ms"])
            if sp.startswith("  ") and not sp.startswith("    ")]
    assert kids and sum(kids) <= root_ms * 1.05 + 1.0
    head = json.loads(out["detail"][0])
    assert head["query_id"].startswith("q")
    assert head["rows_returned"] == 12
    # ... and the recorded history total agrees with the execute span
    rec = eng.history[-1]
    exec_ms = next(float(ms) for sp, ms in zip(out["span"], out["ms"])
                   if sp.strip() == "execute")
    assert rec["total_ms"] <= exec_ms * 1.5 + 5.0


def test_explain_analyze_fallback_statement():
    eng = _engine()
    eng.register_table("dim", pd.DataFrame({"k": [1, 2, 3]}),
                       accelerate=False)
    out = eng.sql("EXPLAIN ANALYZE SELECT k FROM dim ORDER BY k")
    names = [s.strip() for s in out["span"]]
    assert "fallback" in names
    assert eng.history[-1]["query_type"] == "fallback"


def test_tracer_rings_bounded_and_slow_log():
    eng = _engine(trace_history_limit=5, slow_query_ms=0.0,
                  slow_log_limit=3)
    for _ in range(8):
        eng.sql(AGG_SQL)
    snap = eng.tracer.snapshot()
    assert len(snap["recent"]) == 5
    assert len(snap["slow"]) == 3  # threshold 0: every query is "slow"
    assert snap["slow_query_ms"] == 0.0
    t = snap["recent"][0]
    assert t["name"] == "sql" and t["duration_ms"] > 0
    json.dumps(snap)  # the whole snapshot is JSON-serializable


def test_tracing_disabled_is_silent():
    eng = _engine(tracing_enabled=False)
    out = eng.sql(GROUP_SQL)
    assert len(out) == 12
    assert eng.tracer.snapshot()["recent"] == []
    # records still carry a generated query_id
    assert eng.history[-1]["query_id"].startswith("q")
    ea = eng.sql(f"EXPLAIN ANALYZE {AGG_SQL}")
    assert "no trace" in ea["span"][0]


# ------------------------------------------------------ metrics contract


def _assert_core(rec, label):
    missing = CORE_KEYS - set(rec)
    assert not missing, f"{label}: record missing {sorted(missing)}"
    json.dumps(rec)  # and it serializes


def test_metrics_contract_all_paths():
    """Every execution path emits the same core keys — the stable
    dashboard schema (ISSUE 6 satellite)."""
    eng = _engine()
    eng.register_table("dim", pd.DataFrame({"k": [1, 2]}),
                       accelerate=False)

    eng.sql(GROUP_SQL)
    _assert_core(eng.history[-1], "dense")
    assert eng.history[-1]["path"] == "dense"

    eng.sql(GROUP_SQL)  # warm template: compile-cache hit
    hit_rec = eng.history[-1]
    _assert_core(hit_rec, "cache hit")

    eng.sql("SELECT k FROM dim")  # unaccelerated: fallback
    _assert_core(eng.history[-1], "fallback")
    assert eng.history[-1]["path"] == "fallback"
    assert eng.history[-1]["query_type"] == "fallback"

    # sparse path: force by shrinking the dense budget
    sp = Engine(EngineConfig(dense_group_budget=4))
    sp.register_table("t", _df(), time_column="ts", block_rows=1 << 11)
    sp.sql("SELECT g, h, sum(v) AS s FROM t GROUP BY g, h")
    _assert_core(sp.history[-1], "sparse")
    assert sp.history[-1]["path"] == "sparse"
    assert sp.history[-1].get("sparse")

    # batch legs + dedup fan-out
    outs = eng.sql_batch([GROUP_SQL, AGG_SQL, GROUP_SQL])
    assert len(outs) == 3
    batch_recs = [h for h in eng.history if h.get("batch_id")]
    assert batch_recs, "no batch-leg records"
    ids = set()
    for rec in batch_recs:
        _assert_core(rec, "batch leg")
        assert rec["path"] == "batch"
        ids.add(rec["query_id"])
    dedups = [h for h in eng.history if h.get("batch_dedup")]
    assert dedups, "no dedup fan-out record"
    # every logical query keeps its own id across the fused dispatch
    assert len(ids) == len(batch_recs)


def test_history_ring_bounded_counters_exact():
    eng = _engine(history_limit=6, result_cache_enabled=True)
    n_rows = len(_df())
    for _ in range(15):
        eng.sql(AGG_SQL)
    assert len(eng.history) == 6  # ring evicted oldest
    c = eng.counters()
    assert c["queries"] == 15  # totals survive eviction exactly
    # only the first execution scans; the rest serve from the semantic
    # result cache (cache_hit is REAL now — ISSUE 9) with zero scans
    assert c["rows_scanned"] == n_rows
    assert c["by_query_type"] == {"timeseries": 15}
    assert c["cache_hits"] == 14  # every repeat is a tier-2 hit


def test_retry_errors_sanitized_serializable():
    """Exception-carrying metric values become short strings at record
    time — /status //debug payloads can never hit raw exception
    objects (ISSUE 6 satellite)."""
    class Unjsonable:
        def __repr__(self):
            return "unjsonable<" + "x" * 500 + ">"

    calls = {"n": 0}

    def inj(stage, attempt):
        calls["n"] += 1
        if calls["n"] <= 10:
            raise RuntimeError(Unjsonable())

    eng = _engine(dispatch_retries=1, fault_injector=inj)
    out = eng.sql(GROUP_SQL)  # retries exhaust -> fallback answers
    assert len(out) == 12
    failed = [h for h in eng.history if h.get("failed")]
    assert failed and failed[-1]["retry_errors"]
    for e in failed[-1]["retry_errors"]:
        assert isinstance(e, str) and len(e) <= 300
    json.dumps(list(eng.history))  # every record serializes


# ------------------------------------------------------- HTTP surfaces

# Prometheus text-format line grammar: metric line or HELP/TYPE comment
_METRIC_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" (?P<value>[^ ]+)$")


def _get(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return r.headers.get("Content-Type"), r.read().decode()


def _post(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


def test_metrics_endpoint_prometheus_grammar():
    """Scrape GET /metrics from a live QueryServer after a mixed
    single/batch/fallback workload and validate every line against the
    text-format grammar — names/labels parse, values finite, histograms
    complete (ISSUE 6 acceptance + CI satellite)."""
    eng = _engine()
    eng.register_table("dim", pd.DataFrame({"k": [1, 2]}),
                       accelerate=False)
    eng.sql(GROUP_SQL)
    eng.sql(GROUP_SQL)
    eng.sql("SELECT k FROM dim")        # fallback
    eng.sql_batch([GROUP_SQL, AGG_SQL, GROUP_SQL])  # batch + dedup
    srv = QueryServer(eng).start()
    try:
        ctype, text = _get(srv.url + "/metrics")
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
    finally:
        srv.stop()

    seen = set()
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            assert re.match(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* ",
                            line), line
            continue
        m = _METRIC_RE.match(line)
        assert m, f"bad exposition line: {line!r}"
        v = float(m.group("value"))
        assert math.isfinite(v), f"non-finite sample: {line!r}"
        seen.add(line.split("{")[0].split(" ")[0])

    # the advertised families are present after this workload
    for name in ("tpu_olap_queries_total",
                 "tpu_olap_query_latency_ms_bucket",
                 "tpu_olap_query_latency_ms_count",
                 "tpu_olap_query_latency_ms_sum",
                 "tpu_olap_rows_scanned_total",
                 "tpu_olap_segments_scanned_total",
                 "tpu_olap_compile_cache_requests_total",
                 "tpu_olap_batch_size_count",
                 "tpu_olap_history_records"):
        assert name in seen, f"{name} missing from /metrics"
    # latency histogram covers the paths this workload exercised
    for path in ("dense", "fallback", "batch"):
        assert f'path="{path}"' in text, f"no latency series for {path}"


def test_latency_histogram_quantiles_derivable():
    eng = _engine()
    for _ in range(10):
        eng.sql(AGG_SQL)
    hist = eng.metrics.histogram("query_latency_ms")
    p50 = hist.quantile(0.5, query_type="timeseries", path="dense")
    p99 = hist.quantile(0.99, query_type="timeseries", path="dense")
    assert p50 is not None and p99 is not None
    assert 0 < p50 <= p99


def test_debug_queries_endpoint():
    eng = _engine(slow_query_ms=0.0)
    eng.sql(GROUP_SQL)
    eng.sql(AGG_SQL)
    srv = QueryServer(eng).start()
    try:
        _, body = _get(srv.url + "/debug/queries")
        snap = json.loads(body)
        assert snap["recent"] and snap["slow"]
        newest = snap["recent"][0]
        assert newest["name"] == "sql"
        child_names = [c["name"] for c in newest["children"]]
        assert "plan" in child_names and "execute" in child_names
        _, body = _get(srv.url + "/debug/queries?limit=1")
        assert len(json.loads(body)["recent"]) == 1
        # /status still answers (and its counters are the incremental
        # totals, not an O(history) re-sum)
        code = _post(srv.url + "/sql", {"query": AGG_SQL})
        assert code["rows"]
        _, body = _get(srv.url + "/status")
        assert json.loads(body)["counters"]["queries"] == 3
    finally:
        srv.stop()


def test_batch_shared_scan_span_nesting():
    """Fused batch legs nest under one shared-scan span in the
    submitting trace."""
    eng = _engine()
    eng.sql_batch([GROUP_SQL, AGG_SQL])
    trace = eng.tracer.last
    assert trace is not None and trace.name == "sql_batch"

    def find(span, name):
        hits = [s for _, s in span.walk() if s.name == name]
        return hits

    shared = find(trace, "shared-scan")
    assert shared, "no shared-scan span under the batch trace"
    legs = [c for c in shared[0].children if c.name == "leg"]
    assert len(legs) == 2
    leg_ids = {leg.attrs.get("query_id") for leg in legs}
    assert len(leg_ids) == 2  # per-leg attribution survived fusing


def test_bench_help_advertises_span_summary():
    """CI satellite: `bench.py --help` documents the span-summary flag
    (argparse exits before any engine/dataset setup, so this is
    fast)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(repo, "bench.py"), "--help"],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    assert "--span-summary" in proc.stdout
    assert "--concurrency" in proc.stdout
    assert "--trace-out" in proc.stdout


def test_ssb_explain_analyze_sums():
    """ISSUE 6 acceptance: EXPLAIN ANALYZE on an SSB query returns a
    span tree whose stage durations sum to within the recorded
    total."""
    from tpu_olap.bench import QUERIES, register_ssb
    eng = Engine()
    register_ssb(eng, lineorder_rows=8_000, seed=3, block_rows=1 << 12)
    eng.sql(QUERIES["q2.1"])  # warm
    out = eng.sql(f"EXPLAIN ANALYZE {QUERIES['q2.1']}")
    assert eng.last_plan.rewritten
    root_ms = float(out["ms"][0])
    kids = [float(ms) for sp, ms in zip(out["span"], out["ms"])
            if sp.startswith("  ") and not sp.startswith("    ")]
    assert sum(kids) <= root_ms * 1.05 + 1.0
    rec = eng.history[-1]
    assert rec["query_type"] in ("groupBy", "topN", "timeseries")
    assert rec["total_ms"] <= root_ms * 1.05 + 1.0
