"""Incremental metrics registry with Prometheus text exposition.

Counters, gauges, and fixed-bucket histograms, maintained at query
completion (QueryRunner.record) instead of re-scanned from history —
replacing the O(history) recompute behind `GET /status` with O(1)
updates, and surviving history-ring eviction exactly.

Exposition follows the Prometheus text format (version 0.0.4), stdlib
string formatting only:

    # HELP tpu_olap_queries_total Queries completed.
    # TYPE tpu_olap_queries_total counter
    tpu_olap_queries_total{path="dense",query_type="groupBy"} 42

Non-finite observations are dropped at ingest so the exposition never
emits NaN/+Inf/-Inf sample values (the `le="+Inf"` bucket LABEL is part
of the histogram grammar and always present). All mutation goes through
one registry lock; updates are a few dict ops, far below query cost.
"""

from __future__ import annotations

import math
import threading

# fixed latency buckets (ms): sub-ms through minutes, pow-ish spacing so
# p50/p95/p99 are derivable by interpolation at every scale the engine
# serves. The 0.1/0.25/0.5 head exists for the warm-cache path: a
# full-result-cache serve is ~0.6 ms (BENCH_CACHE.json), and with 1.0 as
# the first bound every warm hit collapsed into one bucket, making
# cache-path p50 and p95 indistinguishable (ISSUE 11 satellite).
LATENCY_BUCKETS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                      100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0,
                      10000.0, 30000.0)

# admission queue-wait buckets (ms): most admitted queries wait 0 or a
# few ms; the tail matters up to roughly one deadline (past that the
# controller sheds instead of queueing — resilience.admission)
QUEUE_WAIT_BUCKETS_MS = (0.1, 0.5, 1.0, 5.0, 10.0, 25.0, 50.0, 100.0,
                         250.0, 500.0, 1000.0, 5000.0)

# breaker-state gauge encoding (resilience.breaker exports the live
# mapping; duplicated here so dashboards can reference one module)
BREAKER_STATE_VALUES = {"closed": 0, "half_open": 1, "open": 2}

_NAME_OK = "abcdefghijklmnopqrstuvwxyz" \
           "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:"


def _fmt(v: float) -> str:
    """Sample value formatting: integral floats render bare (the common
    counter case), others via repr (shortest round-trip)."""
    if isinstance(v, float) and v.is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(float(v))


def _escape_label(v) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


class _Series:
    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0


class _HistSeries:
    __slots__ = ("counts", "total", "n")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.total = 0.0
        self.n = 0


class _Metric:
    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 kind: str, labelnames: tuple):
        for ch in name:
            if ch not in _NAME_OK:
                raise ValueError(f"bad metric name {name!r}")
        self.registry = registry
        self.name = name
        self.help = help
        self.kind = kind
        self.labelnames = tuple(labelnames)
        self.series: dict = {}

    def _key(self, labels: dict) -> tuple:
        if set(labels) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(labels)} != declared "
                f"{sorted(self.labelnames)}")
        return tuple(str(labels[k]) for k in self.labelnames)


class Counter(_Metric):
    def inc(self, amount: float = 1.0, **labels):
        if not math.isfinite(amount) or amount < 0:
            return
        key = self._key(labels)
        with self.registry._lock:
            s = self.series.get(key)
            if s is None:
                s = self.series[key] = _Series()
            s.value += amount

    def set_total(self, value: float, **labels):
        """Mirror an externally-maintained monotonic total (e.g. the HBM
        ledger's eviction count) — still rendered as a counter."""
        if not math.isfinite(value):
            return
        key = self._key(labels)
        with self.registry._lock:
            s = self.series.get(key)
            if s is None:
                s = self.series[key] = _Series()
            s.value = max(s.value, float(value))

    def value(self, **labels) -> float:
        s = self.series.get(self._key(labels))
        return s.value if s is not None else 0.0


class Gauge(_Metric):
    def set(self, value: float, **labels):
        if not math.isfinite(value):
            return
        key = self._key(labels)
        with self.registry._lock:
            s = self.series.get(key)
            if s is None:
                s = self.series[key] = _Series()
            s.value = float(value)

    def value(self, **labels) -> float:
        s = self.series.get(self._key(labels))
        return s.value if s is not None else 0.0


class Histogram(_Metric):
    def __init__(self, registry, name, help, labelnames,
                 buckets=LATENCY_BUCKETS_MS):
        super().__init__(registry, name, help, "histogram", labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, value: float, **labels):
        if not math.isfinite(value):
            return
        key = self._key(labels)
        with self.registry._lock:
            s = self.series.get(key)
            if s is None:
                s = self.series[key] = _HistSeries(len(self.buckets) + 1)
            i = 0
            for i, b in enumerate(self.buckets):  # noqa: B007
                if value <= b:
                    break
            else:
                i = len(self.buckets)
            s.counts[i] += 1
            s.total += float(value)
            s.n += 1

    def quantile(self, q: float, **labels) -> float | None:
        """Derive a quantile (0..1) by linear interpolation inside the
        owning bucket — how a dashboard computes p50/p95/p99 from the
        exposed cumulative buckets. None when the series is empty."""
        s = self.series.get(self._key(labels))
        if s is None or s.n == 0:
            return None
        rank = q * s.n
        seen = 0
        lo = 0.0
        for i, c in enumerate(s.counts):
            hi = self.buckets[i] if i < len(self.buckets) \
                else self.buckets[-1]
            if seen + c >= rank and c > 0:
                frac = (rank - seen) / c
                return lo + (hi - lo) * min(1.0, max(0.0, frac))
            seen += c
            lo = hi
        return self.buckets[-1]


class MetricsRegistry:
    """Name -> metric, one lock, deterministic render order."""

    def __init__(self, namespace: str = "tpu_olap"):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}

    def _full(self, name: str) -> str:
        return f"{self.namespace}_{name}" if self.namespace else name

    def _get_or_make(self, cls, name, help, labelnames, **kw):
        full = self._full(name)
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                if cls is Histogram:
                    m = Histogram(self, full, help, tuple(labelnames),
                                  **kw)
                else:
                    kind = "counter" if cls is Counter else "gauge"
                    m = cls(self, full, help, kind, tuple(labelnames))
                self._metrics[full] = m
            elif not isinstance(m, cls):
                raise ValueError(f"{full} already registered as "
                                 f"{type(m).__name__}")
            return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_make(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_make(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "", labelnames=(),
                  buckets=LATENCY_BUCKETS_MS) -> Histogram:
        return self._get_or_make(Histogram, name, help, labelnames,
                                 buckets=buckets)

    def snapshot_rows(self) -> list:
        """One dict per live series — the tabular registry view behind
        `sys.metrics` (catalog.systables): scalar metrics carry `value`,
        histogram series carry observation `count` and `total` (the
        _count/_sum pair; per-bucket counts stay on /metrics)."""
        import json
        with self._lock:
            rows = []
            for m in sorted(self._metrics.values(),
                            key=lambda m: m.name):
                for key in sorted(m.series):
                    s = m.series[key]
                    labels = json.dumps(dict(zip(m.labelnames, key)),
                                        sort_keys=True)
                    if isinstance(m, Histogram):
                        rows.append({"name": m.name, "kind": m.kind,
                                     "labels": labels, "value": None,
                                     "count": s.n, "total": s.total})
                    else:
                        rows.append({"name": m.name, "kind": m.kind,
                                     "labels": labels, "value": s.value,
                                     "count": None, "total": None})
        return rows

    # ------------------------------------------------------------ render

    @staticmethod
    def _labels_str(names: tuple, values: tuple, extra: str = "") -> str:
        parts = [f'{n}="{_escape_label(v)}"'
                 for n, v in zip(names, values)]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render(self) -> str:
        """Prometheus text exposition (content type
        `text/plain; version=0.0.4`)."""
        with self._lock:
            metrics = sorted(self._metrics.values(),
                             key=lambda m: m.name)
            lines: list[str] = []
            for m in metrics:
                if m.help:
                    lines.append(f"# HELP {m.name} {m.help}")
                lines.append(f"# TYPE {m.name} {m.kind}")
                for key in sorted(m.series):
                    s = m.series[key]
                    if isinstance(m, Histogram):
                        cum = 0
                        for i, b in enumerate(m.buckets):
                            cum += s.counts[i]
                            lab = self._labels_str(
                                m.labelnames, key, f'le="{_fmt(b)}"')
                            lines.append(
                                f"{m.name}_bucket{lab} {cum}")
                        cum += s.counts[-1]
                        lab = self._labels_str(m.labelnames, key,
                                               'le="+Inf"')
                        lines.append(f"{m.name}_bucket{lab} {cum}")
                        lab = self._labels_str(m.labelnames, key)
                        lines.append(f"{m.name}_sum{lab} "
                                     f"{_fmt(s.total)}")
                        lines.append(f"{m.name}_count{lab} {s.n}")
                    else:
                        lab = self._labels_str(m.labelnames, key)
                        lines.append(f"{m.name}{lab} {_fmt(s.value)}")
        return "\n".join(lines) + ("\n" if lines else "")
