"""Lightweight span tree tracing for the query path (SURVEY.md §6).

A `Trace` is a per-query root span carrying a `query_id`; stages open
child spans through the context-manager API:

    with tracer.trace("sql", sql=text) as root:
        with root.span("parse"):
            ...
        with span("plan") as sp:          # module-level: child of current
            sp.set("rewritten", True)

Propagation is via `contextvars`, so nested layers (engine → runner →
kernels) need no plumbing: `span(name)` attaches to whatever span is
current, and returns the no-op `NULL_SPAN` when no trace is active —
tracing costs two perf_counter() calls per stage when on, one dict probe
when off. Cross-thread dispatch (the deadline watchdog runs the device
call on a fresh thread, executor.runner._join_abandoning) propagates by
running the work inside a `contextvars.copy_context()` snapshot.

Clocks are monotonic (`time.perf_counter`); wall timestamps are recorded
once per trace root for display only. Completed traces land in the
tracer's bounded recent-ring, and traces slower than `slow_ms` also land
in the slow-query ring — both served by `GET /debug/queries`.
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time

_current_span: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_olap_current_span", default=None)
_current_qid: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_olap_current_query_id", default=None)
_nested_exec: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_olap_nested_exec", default=False)
_traceparent: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_olap_traceparent", default=None)

# attribute values are clipped at record time so a span tree is always
# JSON-small (an exception repr or a full SQL text must not bloat the
# debug ring)
_ATTR_MAX_CHARS = 300


def short_str(value, limit: int = _ATTR_MAX_CHARS) -> str:
    """Exception-safe short rendering: any value -> a bounded str."""
    if isinstance(value, BaseException):
        value = f"{type(value).__name__}: {value}"
    s = value if isinstance(value, str) else str(value)
    return s if len(s) <= limit else s[: limit - 1] + "…"


def _attr_value(value):
    """Span-attribute sanitizer: JSON-native scalars pass through,
    everything else (exceptions, numpy scalars, specs) becomes a short
    string — the span tree must always serialize."""
    if value is None or isinstance(value, (bool, int)):
        return value
    if isinstance(value, float):
        return value if value == value and abs(value) != float("inf") \
            else None
    try:  # numpy scalars quack like their python cousins
        import numpy as np
        if isinstance(value, np.bool_):
            return bool(value)
        if isinstance(value, np.integer):
            return int(value)
        if isinstance(value, np.floating):
            return _attr_value(float(value))
    except Exception:  # noqa: BLE001 — numpy absent or exotic scalar
        pass
    return short_str(value)


class Span:
    """One timed stage. Children append in call order; duration is set on
    context exit (monotonic). Thread-compatible: each span is entered and
    exited on one thread; concurrent siblings guard the children list
    with the owning trace's lock."""

    __slots__ = ("name", "attrs", "children", "t0", "start_ms",
                 "duration_ms", "_token", "_trace")

    def __init__(self, name: str, trace: "Trace | None" = None):
        self.name = name
        self.attrs: dict = {}
        self.children: list = []
        self.t0: float | None = None
        self.start_ms: float | None = None  # offset from the trace root
        self.duration_ms: float | None = None
        self._token = None
        self._trace = trace

    # ------------------------------------------------------------- build

    def span(self, name: str, **attrs) -> "Span":
        child = Span(name, self._trace)
        if attrs:
            child.set(**attrs)
        tr = self._trace
        if tr is not None:
            with tr._lock:
                self.children.append(child)
        else:
            self.children.append(child)
        return child

    def set(self, **attrs) -> "Span":
        for k, v in attrs.items():
            self.attrs[k] = _attr_value(v)
        return self

    # --------------------------------------------------------- lifecycle

    def __enter__(self) -> "Span":
        self.t0 = time.perf_counter()
        # start position on the trace timeline: offset from the root's
        # monotonic t0 (perf_counter is one clock across threads, so
        # cross-thread dispatch spans position correctly). Without it a
        # tree has durations but no layout — concurrent legs could not
        # be placed on a timeline (obs.profile's Chrome-trace export).
        tr = self._trace
        self.start_ms = 0.0 if tr is self or tr is None or tr.t0 is None \
            else (self.t0 - tr.t0) * 1000
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb):
        self.duration_ms = (time.perf_counter() - self.t0) * 1000
        if exc is not None:
            self.set(error=exc)
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        return False

    # ------------------------------------------------------------ export

    def to_json(self) -> dict:
        out = {"name": self.name,
               "start_ms": None if self.start_ms is None
               else round(self.start_ms, 3),
               "duration_ms": None if self.duration_ms is None
               else round(self.duration_ms, 3)}
        if self.attrs:
            out["attrs"] = dict(self.attrs)
        if self.children:
            out["children"] = [c.to_json() for c in self.children]
        return out

    def walk(self, depth: int = 0):
        yield depth, self
        for c in self.children:
            yield from c.walk(depth + 1)


class _NullSpan:
    """Tracing off / no active trace: every operation is a no-op, so call
    sites never branch on enablement."""

    __slots__ = ()

    def span(self, name: str, **attrs) -> "_NullSpan":
        return self

    def set(self, **attrs) -> "_NullSpan":
        return self

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def to_json(self) -> dict:
        return {}


NULL_SPAN = _NullSpan()


def current_span():
    """The active Span of this context, or NULL_SPAN."""
    cur = _current_span.get()
    return cur if cur is not None else NULL_SPAN


def current_query_id() -> str | None:
    """query_id of the active trace, or None."""
    return _current_qid.get()


def span(name: str, **attrs):
    """Open a child of the current span (context manager). No active
    trace -> NULL_SPAN, so instrumented layers pay one contextvar probe
    when tracing is off."""
    cur = _current_span.get()
    if cur is None:
        return NULL_SPAN
    return cur.span(name, **attrs)


class nested_execution:
    """Marks statements executed INSIDE another statement (grouping-sets
    legs, planner subqueries, fallback derived tables). Their records
    keep history/metrics behavior, but QueryRunner.record() excludes
    them from the SLO and the `query` event stream — one served
    response must yield exactly one event + one SLO observation, not
    one per internal leg."""

    __slots__ = ("_token",)

    def __enter__(self):
        self._token = _nested_exec.set(True)
        return self

    def __exit__(self, exc_type, exc, tb):
        _nested_exec.reset(self._token)
        return False


def in_nested_execution() -> bool:
    return _nested_exec.get()


# ------------------------------------------------- W3C trace context

# traceparent per the W3C Trace Context spec (version 00):
#   00-<32 hex trace-id>-<16 hex parent-id>-<2 hex flags>
# The engine is a participant, not an originator: a valid incoming
# header is stamped on the root span and every query record, so the
# fleet router (ROADMAP item 2) can join one distributed trace across
# replicas. Invalid headers are dropped silently per the spec.
import re as _re

_TRACEPARENT_RE = _re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


def parse_traceparent(value) -> dict | None:
    """{'traceparent', 'trace_id', 'parent_id', 'flags'} for a valid
    W3C traceparent header, else None. All-zero trace/parent ids are
    invalid per the spec; future versions (>00) are accepted as long
    as they carry the version-00 prefix fields."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    version, trace_id, parent_id, flags = m.groups()
    if version == "ff" or trace_id == "0" * 32 or parent_id == "0" * 16:
        return None
    return {"traceparent": m.group(0), "trace_id": trace_id,
            "parent_id": parent_id, "flags": flags}


class use_traceparent:
    """Propagate an incoming (already-validated) traceparent header for
    a scope, so QueryRunner.record() can stamp it onto every query
    record the scope produces. `None` is a no-op scope."""

    __slots__ = ("value", "_token")

    def __init__(self, value: str | None):
        self.value = value
        self._token = None

    def __enter__(self):
        if self.value is not None:
            self._token = _traceparent.set(self.value)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _traceparent.reset(self._token)
            self._token = None
        return False


def current_traceparent() -> str | None:
    return _traceparent.get()


class detached_trace:
    """Detach the span/query-id context for a scope: instrumented code
    inside sees no active trace, so its spans are NULL_SPAN no-ops.
    Used by sys.* introspection statements running INSIDE another
    live trace (a /sql/batch submission) — their fallback spans must
    not leak into the submitting trace's ring/Perfetto export
    (introspection appears nowhere in its own stats, ISSUE 11)."""

    __slots__ = ("_t_span", "_t_qid")

    def __enter__(self):
        self._t_span = _current_span.set(None)
        self._t_qid = _current_qid.set(None)
        return self

    def __exit__(self, exc_type, exc, tb):
        _current_span.reset(self._t_span)
        _current_qid.reset(self._t_qid)
        return False


class use_query_id:
    """Override the propagated query_id for a scope WITHOUT re-rooting
    the span tree — Engine.sql_batch runs each non-fused statement
    inside the one sql_batch trace, but every statement's history
    records must carry that statement's own id."""

    def __init__(self, query_id: str | None):
        self.query_id = query_id
        self._token = None

    def __enter__(self):
        if self.query_id is not None:
            self._token = _current_qid.set(self.query_id)
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._token is not None:
            _current_qid.reset(self._token)
            self._token = None
        return False


class Trace(Span):
    """Root span of one query. Carries the query_id (propagated through
    a second contextvar so flat metric records can stamp it without a
    parent pointer walk) and hands itself to the tracer's rings on
    exit."""

    __slots__ = ("query_id", "started_at", "_qid_token", "_lock",
                 "_tracer")

    def __init__(self, name: str, query_id: str, tracer: "Tracer"):
        super().__init__(name, trace=None)
        self._trace = self  # children funnel through this trace's lock
        self._lock = threading.Lock()
        self.query_id = query_id
        self.started_at = time.time()  # display only; durations are mono
        self._qid_token = None
        self._tracer = tracer

    def __enter__(self) -> "Trace":
        self._qid_token = _current_qid.set(self.query_id)
        return super().__enter__()

    def __exit__(self, exc_type, exc, tb):
        super().__exit__(exc_type, exc, tb)
        _current_qid.reset(self._qid_token)
        self._tracer._finished(self)
        return False

    def to_json(self) -> dict:
        out = super().to_json()
        out["query_id"] = self.query_id
        out["started_at"] = round(self.started_at, 3)
        # flat per-stage summary of the graph's `stage:<name>` spans
        # (executor/stages.py), so GET /debug/queries readers get the
        # stage walk without re-walking the span tree
        stages = [{"stage": s.name[6:],
                   "run_ms": round(s.duration_ms, 3),
                   "wait_ms": s.attrs.get("queue_wait_ms", 0.0)}
                  for _, s in self.walk()
                  if s.name.startswith("stage:")
                  and s.duration_ms is not None]
        if stages:
            out["stages"] = stages
        return out


class Tracer:
    """Engine-level trace factory + bounded retention.

    `recent` keeps the last `ring_limit` completed traces; `slow` keeps
    the last `slow_limit` traces whose root duration met `slow_ms`
    (the slow-query log, GET /debug/queries?). Both are plain ring
    lists under one lock — appends are O(1) amortized and the rings are
    small by construction, so a long-running server's memory is flat."""

    def __init__(self, enabled: bool = True, ring_limit: int = 128,
                 slow_ms: float = 250.0, slow_limit: int = 64):
        self.enabled = enabled
        self.ring_limit = max(1, int(ring_limit))
        self.slow_ms = float(slow_ms)
        self.slow_limit = max(1, int(slow_limit))
        self.recent: list = []
        self.slow: list = []
        self.last: Trace | None = None
        self._lock = threading.Lock()
        self._seq = itertools.count(1)
        # distinct engines in one process must not collide on query_ids
        self._stamp = f"{os.getpid() & 0xffff:04x}{id(self) & 0xfff:03x}"

    def new_query_id(self) -> str:
        return f"q{self._stamp}-{next(self._seq):06d}"

    def trace(self, name: str, query_id: str | None = None, **attrs):
        """Start a root span (context manager). Disabled -> NULL_SPAN."""
        if not self.enabled:
            return NULL_SPAN
        t = Trace(name, query_id or self.new_query_id(), self)
        if attrs:
            t.set(**attrs)
        return t

    def _finished(self, trace: Trace):
        with self._lock:
            self.last = trace
            self.recent.append(trace)
            if len(self.recent) > self.ring_limit:
                del self.recent[0]
            if (trace.duration_ms or 0.0) >= self.slow_ms:
                self.slow.append(trace)
                if len(self.slow) > self.slow_limit:
                    del self.slow[0]

    def recent_traces(self, limit: int | None = None) -> list:
        """Completed Trace OBJECTS from the recent ring (oldest first),
        for exporters that need spans rather than the JSON snapshot
        (obs.profile.chrome_trace)."""
        with self._lock:
            recent = list(self.recent)
        if limit is None:
            return recent
        return recent[-limit:] if limit > 0 else []

    def snapshot(self, limit: int | None = None) -> dict:
        """JSON view for GET /debug/queries: recent span trees (newest
        first) + the slow-query ring."""
        with self._lock:
            recent = list(self.recent)
            slow = list(self.slow)
        if limit is not None:
            # -0 would slice the WHOLE list: n=0 must mean "none"
            recent = recent[-limit:] if limit > 0 else []
            slow = slow[-limit:] if limit > 0 else []
        return {
            "slow_query_ms": self.slow_ms,
            "recent": [t.to_json() for t in reversed(recent)],
            "slow": [t.to_json() for t in reversed(slow)],
        }


def phase_totals(root: Span) -> dict:
    """Per-phase SELF time (duration minus timed children), summed by
    name over the whole tree — the per-phase summary bench.py banks
    (`--span-summary`). Self time makes phases additive: container spans
    (execute, dispatch-with-host-transfer, shared-scan) contribute only
    their own overhead, so the phases sum to within the root's total
    instead of double-counting every nesting level."""
    out: dict = {}
    for depth, s in root.walk():
        if depth == 0 or s.duration_ms is None:
            continue
        self_ms = s.duration_ms - sum(
            c.duration_ms for c in s.children
            if c.duration_ms is not None)
        out[s.name] = out.get(s.name, 0.0) + max(0.0, self_ms)
    return out
