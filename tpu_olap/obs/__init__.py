"""Observability — tracing spans + metrics registry (SURVEY.md §6).

The reference stack leaned on Druid broker metrics and the Spark query UI
to explain where an accelerated query spent its time; this package is the
in-process analog: `trace` yields a per-query span tree (parse → plan →
lower → prepare → dispatch → host-transfer → finalize → post-agg →
assemble, with batch legs nested under their shared-scan span), `metrics`
maintains incrementally-updated counters/gauges/histograms rendered in
Prometheus text exposition format, `profile` exports span trees as
Chrome-trace/Perfetto timelines and wraps on-demand jax.profiler
captures, `events` is the structured JSON-lines event log, `slo`
tracks latency objectives with a burn-rate gauge, and `workload` is the
query-template profiler behind `sys.query_templates` and the cube
advisor's demand signal (ISSUE 11). No new dependencies — monotonic
clocks, contextvars propagation, stdlib formatting only.
"""

from tpu_olap.obs.events import EventLog  # noqa: F401
from tpu_olap.obs.metrics import (Counter, Gauge, Histogram,  # noqa: F401
                                  LATENCY_BUCKETS_MS, MetricsRegistry)
from tpu_olap.obs.profile import (annotate_dispatch,  # noqa: F401
                                  capture_device_profile, chrome_trace)
from tpu_olap.obs.slo import SloTracker  # noqa: F401
from tpu_olap.obs.trace import (NULL_SPAN, Span, Trace,  # noqa: F401
                                Tracer, current_query_id, current_span,
                                span)
from tpu_olap.obs.workload import (Fingerprint,  # noqa: F401
                                   WorkloadProfiler, fingerprint_ir,
                                   fingerprint_sql, in_introspection,
                                   introspection_execution,
                                   recommend_rollups)
