"""Structured event log: the package's first logging layer.

One JSON object per engine-level occurrence, emitted at the existing
chokepoints (QueryRunner.record, breaker transitions, admission sheds,
cache clears, ingest) — the machine-greppable narrative a latency
histogram cannot tell ("the p99 spike at 14:02 was a breaker trip
followed by 40 sheds"). Two sinks:

- a bounded in-memory ring (`EngineConfig.event_log_limit`), served
  newest-first by `GET /debug/events` — flat memory for a long-running
  server, same contract as the trace rings;
- an optional append-only JSON-lines file (`EngineConfig.
  event_log_path`) for durable shipping into whatever log pipeline the
  deployment runs. File writes happen on a dedicated daemon writer
  thread behind a bounded queue, so a sink that HANGS (dead NFS, full
  blocking pipe) — not just one that raises — can never stall the
  serving threads that emit; write failures back off and retry
  (`_SINK_RETRY_S`), and drops (queue overflow, failed writes) are
  counted in `sink_errors`, surfaced by `GET /debug/events`.

Event shape: `{"ts": epoch-seconds, "seq": N, "event": kind, ...}` with
every field sanitized to JSON-native scalars (via the span-attribute
sanitizer: exceptions and numpy scalars become short strings/numbers),
so the ring and the file always serialize. `emit()` never raises — the
event log observes the query path, it must not be able to fail it.
"""

from __future__ import annotations

import itertools
import json
import threading
import time
from collections import deque

from tpu_olap.obs.trace import _attr_value


def _clean(v, _depth: int = 0):
    """Event-field sanitizer: shallow containers recurse, scalars go
    through the span-attribute sanitizer (one shared implementation:
    JSON-native passthrough, non-finite floats -> None, numpy scalar
    coercion, bounded-string fallback)."""
    if _depth < 3:
        if isinstance(v, (list, tuple)):
            return [_clean(x, _depth + 1) for x in v]
        if isinstance(v, dict):
            return {str(k): _clean(x, _depth + 1) for k, x in v.items()}
    return _attr_value(v)


class EventLog:
    """Thread-safe bounded event ring + optional async JSONL file sink."""

    # seconds to back off after a sink write failure: a transient full
    # disk recovers (the stream resumes, dropped events counted in
    # sink_errors) instead of one EIO silently killing the sink forever
    _SINK_RETRY_S = 30.0
    # pending-write bound: a stalled sink drops (and counts) events past
    # this depth instead of growing host memory without limit
    _SINK_QUEUE_MAX = 4096

    def __init__(self, limit: int = 2048, path: str | None = None,
                 max_bytes: int = 0, rotate_keep: int = 3):
        self.limit = max(1, int(limit))
        self.path = path
        # size-based sink rotation (ISSUE 17 satellite): past max_bytes
        # the file rotates to path.1 (shifting .1 -> .2 ..., keeping
        # `rotate_keep` rotated files) and a sink_rotate event records
        # the roll. 0 = unbounded (the pre-rotation behavior).
        self.max_bytes = max(0, int(max_bytes or 0))
        self.rotate_keep = max(1, int(rotate_keep))
        self.rotations = 0
        self._file_bytes = 0
        self._ring: deque = deque(maxlen=self.limit)
        self._lock = threading.Lock()  # ring only
        self._seq = itertools.count(1)
        self.sink_errors = 0
        # writer-thread state, all under _wcv: emitters enqueue and
        # return; only the daemon writer touches the file
        self._wcv = threading.Condition()
        self._wq: deque = deque()
        self._writer_started = False
        self._writing = False
        self._closed = False
        self._file = None
        self._file_fail_until = 0.0  # monotonic backoff deadline

    # ------------------------------------------------------------- emit

    def emit(self, event: str, **fields) -> dict:
        """Append one event. Never raises, never blocks on the sink."""
        rec = {"ts": round(time.time(), 3), "seq": next(self._seq),
               "event": str(event)}
        for k, v in fields.items():
            rec[k] = _clean(v)
        with self._lock:
            self._ring.append(rec)
        if self.path is not None:
            self._enqueue(rec)
        return rec

    def snapshot(self, n: int | None = None) -> list:
        """Newest-first copy of the ring (bounded by `n`)."""
        with self._lock:
            out = list(self._ring)
        out.reverse()
        return out if n is None else out[: max(0, int(n))]

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # -------------------------------------------------------- file sink

    def _enqueue(self, rec: dict):
        with self._wcv:
            if self._closed:
                return
            if not self._writer_started:
                self._writer_started = True
                threading.Thread(target=self._drain, daemon=True,
                                 name="tpu-olap-event-sink").start()
            if len(self._wq) >= self._SINK_QUEUE_MAX:
                self.sink_errors += 1  # stalled sink: drop, count, go on
                return
            self._wq.append(rec)
            self._wcv.notify_all()

    def _drain(self):
        """Writer thread: the ONLY place file I/O happens. Two racing
        writers can't exist, so writes need no lock and a hang costs
        this daemon thread alone — emitters just see the queue fill."""
        while True:
            with self._wcv:
                while not self._wq and not self._closed:
                    self._wcv.wait(1.0)
                if not self._wq and self._closed:
                    return
                rec = self._wq.popleft()
                self._writing = True
            try:
                self._write_rec(rec)
            finally:
                with self._wcv:
                    self._writing = False
                    self._wcv.notify_all()

    def _write_rec(self, rec: dict):
        if time.monotonic() < self._file_fail_until:
            with self._wcv:
                self.sink_errors += 1
            return
        try:
            if self._file is None:
                self._file = open(self.path, "a", buffering=1)
                import os
                try:
                    self._file_bytes = os.path.getsize(self.path)
                except OSError:
                    self._file_bytes = 0
            line = json.dumps(rec, default=str) + "\n"
            self._file.write(line)
            self._file_bytes += len(line)
            if self.max_bytes and self._file_bytes >= self.max_bytes:
                self._rotate()
        except Exception:  # noqa: BLE001 — sink failure ≠ query failure
            with self._wcv:
                self.sink_errors += 1
            self._file_fail_until = time.monotonic() + self._SINK_RETRY_S
            if self._file is not None:
                try:
                    self._file.close()
                except Exception:  # noqa: BLE001
                    pass
                self._file = None

    def _rotate(self):
        """Size-based roll, on the writer thread (the only file-I/O
        site, so no locking): path -> path.1, shifting existing .N up
        and dropping past rotate_keep. The sink_rotate event lands in
        the ring AND (via the queue) as the fresh file's first lines."""
        import os
        try:
            self._file.close()
        except Exception:  # noqa: BLE001
            pass
        self._file = None
        rotated_bytes = self._file_bytes
        self._file_bytes = 0
        try:
            drop = f"{self.path}.{self.rotate_keep}"
            if os.path.exists(drop):
                os.unlink(drop)
            for i in range(self.rotate_keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            with self._wcv:
                self.sink_errors += 1
            return
        self.rotations += 1
        self.emit("sink_rotate", path=self.path,
                  rotated_bytes=rotated_bytes, keep=self.rotate_keep,
                  rotations=self.rotations)

    def flush(self, timeout: float = 5.0) -> bool:
        """Block until queued sink writes drain (tests, shutdown).
        False if the sink did not catch up within `timeout`."""
        if self.path is None:
            return True
        deadline = time.monotonic() + timeout
        with self._wcv:
            while self._wq or self._writing:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._wcv.wait(min(remaining, 0.1))
        return True

    def close(self):
        with self._wcv:
            self._closed = True
            self._wcv.notify_all()
        self.flush(1.0)
        if self._file is not None:
            try:
                self._file.close()
            except Exception:  # noqa: BLE001
                pass
            self._file = None
