"""Device-side profiling: Chrome-trace export + on-demand XLA capture.

Two complementary views of where a query's time goes:

1. **Span-tree timelines** (`chrome_trace`): the host-side span trees
   obs.trace already records, exported in the Chrome Trace Event format
   (the JSON flavor Perfetto / chrome://tracing load natively). Spans
   become complete (`"ph": "X"`) events positioned by the trace root's
   wall-clock `started_at` plus each span's monotonic `start_ms` offset,
   so concurrent queries — and the legs of a fused shared-scan batch —
   lay out side by side on one timeline. Served by `GET /debug/profile`
   and banked by `bench.py --trace-out`.

2. **XLA op-level capture** (`capture_device_profile`): an on-demand
   `jax.profiler` trace window (`POST /debug/profile?ms=N`). While a
   capture is live, QueryRunner._dispatch wraps each device call in
   `jax.profiler.TraceAnnotation(query_id)` so the XLA ops in the
   profile nest under the query that dispatched them. The annotation
   costs one module-flag probe when no capture is active, and the whole
   feature degrades gracefully (a structured "unavailable" result, not
   an exception) where `jax.profiler` cannot run.

No new dependencies: the Chrome trace format is plain JSON, and the
jax.profiler import is deferred + guarded.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

# ----------------------------------------------------- chrome-trace export

# one pid for the whole engine process; tids are assigned per trace so
# concurrent queries stack as separate rows under one process group
_PID = os.getpid()


def _span_events(trace, tid: int) -> list:
    """One trace -> complete events. Every span of a trace shares the
    trace's tid (a query is one logical timeline): batch legs therefore
    land on the same row as their shared-scan parent, nested by ts/dur
    containment — exactly how Perfetto renders sub-slices."""
    base_us = trace.started_at * 1e6
    events = []
    for depth, s in trace.walk():
        if s.start_ms is None or s.duration_ms is None:
            continue  # never entered / still open: not placeable
        args = dict(s.attrs)
        if depth == 0:
            args.setdefault("query_id", trace.query_id)
        events.append({
            "name": s.name,
            "ph": "X",
            "cat": "query",
            "ts": base_us + s.start_ms * 1000.0,
            "dur": max(0.0, s.duration_ms * 1000.0),
            "pid": _PID,
            "tid": tid,
            **({"args": args} if args else {}),
        })
    return events


def chrome_trace(traces) -> dict:
    """Export completed Trace objects (obs.trace.Tracer rings) as a
    Chrome Trace Event JSON object: {"traceEvents": [...]} with `ts` /
    `dur` in microseconds — loads directly in Perfetto. Traces get one
    tid each, named by query_id via thread_name metadata events."""
    events = []
    for i, t in enumerate(traces):
        tid = i + 1
        events.append({
            "name": "thread_name", "ph": "M", "pid": _PID, "tid": tid,
            "args": {"name": f"query {getattr(t, 'query_id', tid)}"},
        })
        events.extend(_span_events(t, tid))
    return {
        "traceEvents": [{
            "name": "process_name", "ph": "M", "pid": _PID, "tid": 0,
            "args": {"name": "tpu_olap"},
        }] + events,
        "displayTimeUnit": "ms",
    }


# ------------------------------------------------- on-demand XLA capture

# serialize captures: jax.profiler supports one trace at a time, and the
# flag below is what makes per-dispatch annotation free when idle
_capture_lock = threading.Lock()
_capture_active = False

# bounds for POST /debug/profile?ms=N — a capture blocks one handler
# thread and profiler buffers grow with the window
CAPTURE_MS_DEFAULT = 1000
CAPTURE_MS_MAX = 60_000


def capture_active() -> bool:
    return _capture_active


# one shared no-op context: nullcontext is stateless/re-enterable, so
# every non-captured dispatch reuses this instance allocation-free
_NULL_CM = contextlib.nullcontext()


def annotate_dispatch(query_id: str | None):
    """Context manager wrapping one device dispatch. While an on-demand
    capture is live, it is jax.profiler.TraceAnnotation(query_id), so
    the XLA ops of this dispatch nest under their query in the captured
    profile; otherwise (the perpetual common case) it is a no-op that
    cost one module-flag probe."""
    if not _capture_active or query_id is None:
        return _NULL_CM
    try:
        import jax
        return jax.profiler.TraceAnnotation(str(query_id))
    except Exception:  # noqa: BLE001 — annotation is best-effort
        return _NULL_CM


def capture_device_profile(ms: float, trace_dir: str | None = None) -> dict:
    """Run a jax.profiler capture for `ms` milliseconds and return a
    structured result:

        {"ok": true, "trace_dir": ..., "ms": N}            on success
        {"ok": false, "reason": ...}                       degraded

    The capture is synchronous (the caller's thread sleeps out the
    window) but the engine keeps serving — dispatches that land inside
    the window are annotated with their query_id (annotate_dispatch).
    Exactly one capture runs at a time; a second request while one is
    live degrades with "capture already in progress" instead of
    corrupting the profiler's global state."""
    global _capture_active
    ms = max(1.0, min(float(ms), float(CAPTURE_MS_MAX)))
    try:
        import jax
        profiler = jax.profiler
    except Exception as e:  # noqa: BLE001 — jax absent/broken: degrade
        return {"ok": False, "reason": f"jax.profiler unavailable: {e}"}
    if not _capture_lock.acquire(blocking=False):
        return {"ok": False, "reason": "capture already in progress"}
    try:
        if trace_dir is None:
            import tempfile
            trace_dir = tempfile.mkdtemp(prefix="tpu_olap_profile_")
        try:
            profiler.start_trace(trace_dir)
        except Exception as e:  # noqa: BLE001 — backend refused: degrade
            return {"ok": False,
                    "reason": f"jax.profiler.start_trace failed: {e}"}
        _capture_active = True
        try:
            time.sleep(ms / 1000.0)
        finally:
            _capture_active = False
            try:
                profiler.stop_trace()
            except Exception as e:  # noqa: BLE001 — partial capture
                return {"ok": False, "trace_dir": trace_dir,
                        "reason": f"jax.profiler.stop_trace failed: {e}"}
        return {"ok": True, "trace_dir": trace_dir, "ms": ms}
    finally:
        _capture_lock.release()
