"""Latency SLOs: good/bad event accounting + burn-rate.

The bench's north star is a latency objective ("every SSB query < 500 ms
p50"); this module makes the serving-time version of that objective a
first-class metric instead of something recomputed from bench artifacts:

- every completed query is classified **good** (total_ms <= the
  objective, and it did not fail) or **bad** — counted in
  `tpu_olap_slo_events_total{outcome=...}`;
- the **burn rate** over a sliding window is
  `bad_fraction / error_budget` where `error_budget = 1 - slo_target`
  — the standard SRE multiple-of-budget-consumption number: 1.0 means
  the service is spending its error budget exactly as fast as the
  objective allows; 2.0 means twice as fast (alert); 0 means no bad
  events in the window. Exposed as `tpu_olap_slo_burn_rate` and in
  `GET /status`.

Knobs (EngineConfig): `slo_latency_ms` (objective; default 500 matching
BASELINE.md), `slo_target` (good fraction; default 0.99),
`slo_window_s` (burn-rate window; default 3600).

The window is a deque of per-second [second, events, bad] buckets
(pruned on write and on read), so memory is O(window_s) — independent
of QPS, keeping the "flat memory for a long-running server" contract at
any load. Burn-rate granularity is therefore one second, far below any
sane alerting window.
"""

from __future__ import annotations

import threading
import time
from collections import deque


class SloTracker:
    def __init__(self, latency_ms: float = 500.0, target: float = 0.99,
                 window_s: float = 3600.0, metrics=None):
        self.latency_ms = float(latency_ms)
        self.target = min(max(float(target), 0.0), 0.999999)
        self.window_s = max(1.0, float(window_s))
        self._lock = threading.Lock()
        self._buckets: deque = deque()  # [monotonic second, n, bad]
        self._win_n = 0
        self._win_bad = 0
        self.good_total = 0
        self.bad_total = 0
        self._m_events = self._m_burn = None
        if metrics is not None:
            self._m_events = metrics.counter(
                "slo_events_total",
                "Queries classified against the latency SLO.",
                ("outcome",))
            self._m_burn = metrics.gauge(
                "slo_burn_rate",
                "Error-budget burn rate over the SLO window "
                "(1.0 = spending the budget exactly at the allowed "
                "rate).")
            self._m_burn.set(0.0)

    def _prune(self, now: float):
        # caller holds self._lock
        horizon = now - self.window_s
        b = self._buckets
        while b and b[0][0] < horizon:
            _, n, bad = b.popleft()
            self._win_n -= n
            self._win_bad -= bad

    def observe(self, total_ms: float, failed: bool = False):
        """Classify one completed query. `failed` queries are bad
        whatever their latency (a fast error is not a good event)."""
        bad = bool(failed) or not (total_ms <= self.latency_ms)
        now = time.monotonic()
        with self._lock:
            self._prune(now)
            sec = int(now)
            if self._buckets and self._buckets[-1][0] == sec:
                bucket = self._buckets[-1]
                bucket[1] += 1
                bucket[2] += 1 if bad else 0
            else:
                self._buckets.append([sec, 1, 1 if bad else 0])
            self._win_n += 1
            if bad:
                self._win_bad += 1
                self.bad_total += 1
            else:
                self.good_total += 1
            burn = self._burn_locked()
        if self._m_events is not None:
            self._m_events.inc(outcome="bad" if bad else "good")
        if self._m_burn is not None:
            self._m_burn.set(burn)

    def _burn_locked(self) -> float:
        if self._win_n == 0:
            return 0.0
        return (self._win_bad / self._win_n) / (1.0 - self.target)

    def burn_rate(self) -> float:
        with self._lock:
            self._prune(time.monotonic())
            return self._burn_locked()

    def snapshot(self) -> dict:
        """JSON view for GET /status."""
        with self._lock:
            self._prune(time.monotonic())
            return {
                "latency_objective_ms": self.latency_ms,
                "target": self.target,
                "window_s": self.window_s,
                "good_total": self.good_total,
                "bad_total": self.bad_total,
                "window_events": self._win_n,
                "window_bad": self._win_bad,
                "burn_rate": round(self._burn_locked(), 4),
            }
