"""Workload introspection — the query-template profiler (ISSUE 11).

The reference stack is observable *through its own query language*:
Druid's `sys` schema and the broker query history are what drive
precomputation decisions. This module is the engine's equivalent of the
broker-side workload record: every completed query record
(QueryRunner.record, the one chokepoint every path passes through) is
fingerprinted into a literal/interval-normalized **template**, and the
profiler maintains bounded per-template rolling stats — count, latency
percentiles over a rolling window, rows/segments scanned, cache
hit-rate by tier, grouping dims, time-granularity histogram, last-seen.

Two normalization flavors share one id space:

* `fingerprint_ir(query, datasource)` — device-path query IR: the query
  JSON with the top-level `intervals` stripped (the one field a moving
  dashboard window changes — exactly `ResultCache.template_key`'s rule)
  AND the WHERE/HAVING literal values masked to `?`, so `delta = 1993`
  and `delta = 1994` are one template. Dimension specs, aggregations,
  virtual columns, and granularity are kept verbatim: changed dims or
  measures ARE a different template.
* `fingerprint_sql(sql, stmt, datasource)` — fallback-path statements:
  the SQL text with string/numeric literals masked and whitespace/case
  normalized (grouping dims recovered from the parsed statement).

The profiler output is the demand signal the ROADMAP-item-1 cube
advisor consumes: `recommend_rollups` ranks (datasource, dim-set,
finest-granularity) groups by total wall spent — the dim-set × grain
candidates a materialized rollup cube would have served.

Introspection suppression: `sys.*` statements (catalog/systables) run
inside `introspection_execution()`; `QueryRunner.record` drops their
records entirely — no history, no metrics, no SLO, no profiler
observation — so introspection can never recurse into its own stats.
"""

from __future__ import annotations

import contextvars
import hashlib
import json
import math
import re
import threading
import time
from collections import deque

__all__ = [
    "Fingerprint", "WorkloadProfiler", "fingerprint_ir",
    "fingerprint_sql", "in_introspection", "introspection_execution",
    "percentile", "recommend_rollups",
]

# ------------------------------------------------- introspection context

_introspection: contextvars.ContextVar = contextvars.ContextVar(
    "tpu_olap_introspection", default=None)


class introspection_execution:
    """Marks the dynamic extent of a `sys.*` introspection statement:
    QueryRunner.record drops records emitted inside it (no history, no
    metrics/SLO, no profiler observation) and the result caches bypass,
    so a query over sys.queries can never appear in sys.queries. The
    context value is a per-statement dict the SysTableProvider uses to
    memoize resolved entries, so one statement sees ONE consistent
    snapshot of each sys table (a self-join's two sides must not read
    two different moments of a live ring)."""

    def __enter__(self):
        self._token = _introspection.set({})
        return self

    def __exit__(self, *exc):
        _introspection.reset(self._token)
        return False


def in_introspection() -> bool:
    return _introspection.get() is not None


def introspection_scope() -> dict | None:
    """The active introspection statement's memo dict, or None."""
    return _introspection.get()


# ------------------------------------------------------- fingerprinting

class Fingerprint:
    """A precomputed template identity, stamped on a record under the
    transient `_wl` key by whichever site still holds the query object
    (runner._execute, the full-result cache serve, fused batch legs,
    the engine's fallback record) and consumed by record()."""

    __slots__ = ("template_id", "template", "query_type", "datasource",
                 "dims", "granularity")

    def __init__(self, template: str, query_type: str, datasource: str,
                 dims: tuple = (), granularity: str = "all"):
        self.template = template
        self.query_type = query_type
        self.datasource = datasource
        self.dims = tuple(dims)
        self.granularity = granularity
        self.template_id = "t" + hashlib.sha1(
            template.encode()).hexdigest()[:10]


# literal-bearing keys inside filter/having spec JSON (SelectorFilter
# value, InFilter values, LikeFilter pattern, BoundFilter lower/upper,
# having value) — masked so a changed WHERE literal keeps the template
_LITERAL_KEYS = frozenset(("value", "values", "pattern", "lower",
                           "upper"))
# SQL literal masks: quoted strings first (so numbers inside them are
# gone before the numeric pass), then standalone numbers
_STR_LIT_RE = re.compile(r"'(?:[^']|'')*'")
_NUM_LIT_RE = re.compile(r"\b\d+(?:\.\d+)?(?:[eE][+-]?\d+)?\b")
_WS_RE = re.compile(r"\s+")


def _mask_sql_literals(s: str) -> str:
    return _NUM_LIT_RE.sub("?", _STR_LIT_RE.sub("?", s))


def _mask_filter_tree(node):
    """Literal values -> '?' throughout a filter/having subtree.
    Expression filters carry their literals embedded in a rendered
    expression string — masked with the SQL regexes."""
    if isinstance(node, dict):
        out = {}
        for k, v in node.items():
            if k in _LITERAL_KEYS:
                out[k] = "?"
            elif k == "expression" and isinstance(v, str):
                out[k] = _mask_sql_literals(v)
            else:
                out[k] = _mask_filter_tree(v)
        return out
    if isinstance(node, list):
        return [_mask_filter_tree(x) for x in node]
    return node


def _granularity_label(g) -> str:
    """Short display form of a granularity JSON ('all', 'P1D', ...)."""
    if g is None:
        return "all"
    if isinstance(g, str):
        return g
    if isinstance(g, dict):
        return g.get("period") or g.get("duration") \
            or g.get("type") or "all"
    return str(g)


# timeFormat extraction formats -> the calendar grain they demand: a
# GROUP BY year(__time) is time bucketing spelled as a dimension, and
# the cube advisor must see it as a grain, not an opaque __time dim
_TIMEFMT_GRAIN = {"YYYY": "year", "Q": "quarter", "MM": "month",
                  "dd": "day", "HH": "hour", "mm": "minute",
                  "ss": "second"}


def _dims_of(qjson: dict) -> tuple[tuple, str | None]:
    """(grouping dimension source names, time grain demanded by a
    timeFormat extraction dim or None) from a query-spec JSON — the
    dim-set half of the cube advisor's demand signal."""
    dims, tf_grain = [], None
    specs = list(qjson.get("dimensions") or ())
    one = qjson.get("dimension")  # topN carries a single dimension spec
    if one is not None:
        specs.append(one)
    for d in specs:
        if not isinstance(d, dict):
            dims.append(str(d))
            continue
        fn = d.get("extractionFn")
        fmt = fn.get("format") if isinstance(fn, dict) else None
        if d.get("dimension") == "__time" and fmt in _TIMEFMT_GRAIN:
            tf_grain = _TIMEFMT_GRAIN[fmt]
            continue
        dims.append(str(d.get("dimension") or d.get("outputName")))
    return tuple(dims), tf_grain


def fingerprint_ir(query, datasource: str) -> Fingerprint:
    """Template of a device-path query spec: full query JSON minus the
    top-level intervals (ResultCache.template_key's rule), WHERE/HAVING
    literals masked. Dims/aggs/virtual columns/granularity are kept —
    they define the template."""
    qjson = query.to_json()
    norm = {}
    for k, v in qjson.items():
        if k == "intervals":
            continue
        if k in ("filter", "having") and v is not None:
            v = _mask_filter_tree(v)
        norm[k] = v
    template = "ir:" + json.dumps(norm, sort_keys=True, default=str)
    dims, tf_grain = _dims_of(qjson)
    gran = _granularity_label(qjson.get("granularity"))
    if gran == "all" and tf_grain is not None:
        gran = tf_grain
    return Fingerprint(
        template, getattr(query, "query_type", "?") or "?", datasource,
        dims=dims, granularity=gran)


_TIME_FN_NAMES = frozenset(("year", "quarter", "month", "day",
                            "dayofmonth", "hour", "minute", "second"))


def _stmt_dims_granularity(stmt) -> tuple[tuple, str]:
    """(grouping dims, granularity label) recovered from a parsed
    fallback statement: date_trunc / calendar extractors on the time
    column read as granularity, everything else as a dimension."""
    from tpu_olap.ir.expr import Col, FuncCall
    from tpu_olap.planner.exprutil import render
    dims, gran = [], "all"
    for g in getattr(stmt, "group_by", None) or ():
        if isinstance(g, FuncCall) and g.name == "date_trunc" and \
                len(g.args) == 2 and getattr(g.args[0], "value", None):
            gran = str(g.args[0].value).lower()
            continue
        if isinstance(g, FuncCall) and g.name in _TIME_FN_NAMES:
            gran = g.name
            continue
        dims.append(g.name if isinstance(g, Col) else render(g))
    return tuple(dims), gran


def fingerprint_sql(sql: str, stmt=None,
                    datasource: str = "?") -> Fingerprint:
    """Template of a fallback-path statement: the SQL text with literals
    masked, whitespace collapsed, and case folded. With no SQL text (an
    internal statement built from a parsed tree), a rendered skeleton of
    the statement stands in."""
    from tpu_olap.planner.exprutil import render
    text = sql or ""
    if not text and stmt is not None:
        try:
            parts = [render(e) for e, _ in stmt.projections]
            text = ("select " + ",".join(parts) + " from "
                    + str(getattr(stmt, "table", "?")))
            if getattr(stmt, "group_by", None):
                text += " group by " + ",".join(
                    render(g) for g in stmt.group_by)
        except Exception:  # noqa: BLE001 — profiling must never raise
            text = str(getattr(stmt, "table", "?"))
    norm = _WS_RE.sub(" ", _mask_sql_literals(text)).strip().lower()
    dims, gran = ((), "all") if stmt is None \
        else _stmt_dims_granularity(stmt)
    return Fingerprint("sql:" + norm, "fallback", datasource,
                       dims=dims, granularity=gran)


# ----------------------------------------------------------- percentile

def percentile(values, q: float) -> float | None:
    """Nearest-rank percentile over raw observations (q in 0..1) — the
    one definition shared by the profiler snapshot and its tests, so
    template percentiles match history-derived ground truth exactly."""
    if not values:
        return None
    vals = sorted(values)
    idx = max(0, math.ceil(q * len(vals)) - 1)
    return float(vals[min(idx, len(vals) - 1)])


# ------------------------------------------------------------- profiler

class _TemplateStats:
    __slots__ = ("template", "query_type", "datasource", "dims",
                 "count", "failures", "total_ms", "rows_scanned",
                 "segments_scanned", "cache_full_hits",
                 "cache_segment_hits", "segments_cached",
                 "latencies", "granularities", "paths",
                 "first_seen_ms", "last_seen_ms")

    def __init__(self, fp: Fingerprint | None, m: dict, window: int):
        self.template = fp.template if fp else None
        self.query_type = fp.query_type if fp \
            else str(m.get("query_type", "?"))
        self.datasource = fp.datasource if fp \
            else str(m.get("datasource", "?"))
        self.dims = fp.dims if fp else ()
        self.count = 0
        self.failures = 0
        self.total_ms = 0.0
        self.rows_scanned = 0
        self.segments_scanned = 0
        self.cache_full_hits = 0
        self.cache_segment_hits = 0   # queries with >= 1 tier-1 hit
        self.segments_cached = 0      # tier-1 segments served from cache
        self.latencies = deque(maxlen=max(16, int(window)))
        self.granularities: dict = {}
        self.paths: dict = {}
        self.first_seen_ms = self.last_seen_ms = 0


class WorkloadProfiler:
    """Bounded per-template rolling stats, fed by QueryRunner.record.

    Observation is a few dict/deque ops under one lock — far below any
    query's cost (the bench gate: < 2% qps on the warm HTTP path).
    Capacity is bounded at `max_templates`; the least-recently-SEEN
    template evicts, so a changing workload ages out naturally."""

    def __init__(self, max_templates: int = 512,
                 latency_window: int = 512, enabled: bool = True,
                 metrics=None):
        self.enabled = bool(enabled)
        self.max_templates = max(1, int(max_templates))
        self.latency_window = max(16, int(latency_window))
        self._lock = threading.Lock()
        self._templates: dict[str, _TemplateStats] = {}
        self._observations = 0
        self._m_templates = self._m_obs = self._m_evict = None
        if metrics is not None:
            self._m_templates = metrics.gauge(
                "workload_templates",
                "Query templates tracked by the workload profiler.")
            self._m_obs = metrics.counter(
                "workload_observations_total",
                "Query records folded into the workload profiler.")
            self._m_evict = metrics.counter(
                "workload_template_evictions_total",
                "Templates evicted by the profiler's capacity bound "
                "(least-recently-seen first).")

    # ------------------------------------------------------------ ingest

    def observe(self, m: dict, fp: Fingerprint | None = None):
        """Fold one completed-query record into its template's stats.
        `fp` is the precomputed fingerprint when the record site had
        the query; a record carrying only `template_id` (a batch dedup
        fan-out copy) updates the already-registered template."""
        if not self.enabled:
            return
        tid = fp.template_id if fp is not None else m.get("template_id")
        if tid is None:
            return
        now = int(time.time() * 1000)
        evicted = 0
        with self._lock:
            st = self._templates.get(tid)
            if st is None:
                st = self._templates[tid] = _TemplateStats(
                    fp, m, self.latency_window)
                st.first_seen_ms = st.last_seen_ms = now
                while len(self._templates) > self.max_templates:
                    victim = min(self._templates,
                                 key=lambda k:
                                 self._templates[k].last_seen_ms)
                    del self._templates[victim]
                    evicted += 1
            elif st.template is None and fp is not None:
                st.template = fp.template   # filled by a later full obs
                st.dims = fp.dims
            st.count += 1
            st.last_seen_ms = now
            st.total_ms += float(m.get("total_ms") or 0.0)
            st.rows_scanned += int(m.get("rows_scanned") or 0)
            st.segments_scanned += int(m.get("segments_scanned") or 0)
            if m.get("failed") or m.get("deadline_exceeded"):
                st.failures += 1
            tier = m.get("cache_tier")
            if tier == "full":
                st.cache_full_hits += 1
            elif tier == "segment":
                st.cache_segment_hits += 1
            st.segments_cached += int(m.get("segments_cached") or 0)
            st.latencies.append(float(m.get("total_ms") or 0.0))
            gran = fp.granularity if fp is not None else None
            if gran:
                st.granularities[gran] = st.granularities.get(gran, 0) + 1
            path = m.get("path")
            if path:
                st.paths[path] = st.paths.get(path, 0) + 1
            self._observations += 1
            n_live = len(self._templates)
        if self._m_obs is not None:
            self._m_obs.inc()
            self._m_templates.set(n_live)
            if evicted:
                self._m_evict.inc(evicted)

    # ----------------------------------------------------------- queries

    def snapshot(self, limit: int | None = None) -> list[dict]:
        """Per-template stat rows, most-queried first — the payload
        behind sys.query_templates, GET /debug/workload, and the
        workload_report CLI."""
        with self._lock:
            items = [(tid, st, list(st.latencies))
                     for tid, st in self._templates.items()]
        rows = []
        for tid, st, lat in items:
            hits = st.cache_full_hits + st.cache_segment_hits
            rows.append({
                "template_id": tid,
                "datasource": st.datasource,
                "query_type": st.query_type,
                "count": st.count,
                "failures": st.failures,
                "p50_ms": percentile(lat, 0.50),
                "p95_ms": percentile(lat, 0.95),
                "p99_ms": percentile(lat, 0.99),
                "mean_ms": (st.total_ms / st.count) if st.count else None,
                "total_ms": round(st.total_ms, 3),
                "rows_scanned": st.rows_scanned,
                "segments_scanned": st.segments_scanned,
                "cache_hit_rate": (hits / st.count) if st.count else 0.0,
                "cache_full_hits": st.cache_full_hits,
                "cache_segment_hits": st.cache_segment_hits,
                "segments_cached": st.segments_cached,
                "dims": ",".join(st.dims),
                "granularities": json.dumps(st.granularities,
                                            sort_keys=True),
                "paths": json.dumps(st.paths, sort_keys=True),
                "first_seen_ms": st.first_seen_ms,
                "last_seen_ms": st.last_seen_ms,
                "template": st.template,
            })
        rows.sort(key=lambda r: (-r["count"], r["template_id"]))
        return rows[:limit] if limit else rows

    def totals(self) -> dict:
        with self._lock:
            return {"templates": len(self._templates),
                    "observations": self._observations,
                    "max_templates": self.max_templates,
                    "latency_window": self.latency_window,
                    "enabled": self.enabled}

    def clear(self):
        with self._lock:
            self._templates.clear()
        if self._m_templates is not None:
            self._m_templates.set(0)


# ---------------------------------------------------------- cube advisor

# coarse -> fine; a rollup cube must be built at the FINEST granularity
# its templates request to serve all of them by re-aggregation
_GRAIN_ORDER = ("all", "year", "P1Y", "quarter", "P3M", "month", "P1M",
                "week", "P1W", "day", "P1D", "hour", "PT1H",
                "minute", "PT1M", "second", "PT1S")
_GRAIN_RANK = {g: i for i, g in enumerate(_GRAIN_ORDER)}


def _finest_grain(granularities: dict) -> str:
    best, rank = "all", -1
    for g in granularities or {"all": 1}:
        r = _GRAIN_RANK.get(g, len(_GRAIN_ORDER))  # unknown = finest
        if r > rank:
            best, rank = g, r
    return best


def recommend_rollups(rows, top: int = 5) -> list[dict]:
    """Rank (datasource, dim-set, finest grain) groups by total wall
    spent — the demand signal for ROADMAP item 1's cube materializer.
    A group's `est_ms_saved` is the aggregate wall its queries burned;
    a covering rollup cube would have served them as lookups."""
    groups: dict = {}
    for r in rows:
        if r.get("query_type") not in ("groupBy", "timeseries", "topN",
                                       "fallback"):
            continue
        ds = str(r.get("datasource") or "")
        if not ds or ds.startswith("__") or ds.startswith("(") \
                or ds.startswith("sys."):
            # rewrite pseudo-tables ("__winagg", "(derived)"): real
            # demand, but not a datasource a rollup cube can be
            # materialized over — excluded from the advisor signal
            continue
        dims = tuple(sorted(d for d in (r.get("dims") or "").split(",")
                            if d))
        grain = _finest_grain(json.loads(r.get("granularities") or "{}"))
        key = (r.get("datasource"), dims, grain)
        g = groups.setdefault(key, {
            "datasource": key[0], "dims": list(dims),
            "granularity": grain, "queries": 0, "est_ms_saved": 0.0,
            "templates": []})
        g["queries"] += r.get("count", 0)
        g["est_ms_saved"] += float(r.get("total_ms") or 0.0)
        g["templates"].append(r.get("template_id"))
    out = sorted(groups.values(),
                 key=lambda g: (-g["est_ms_saved"], g["datasource"]))
    for g in out:
        g["est_ms_saved"] = round(g["est_ms_saved"], 3)
    return out[:top]
