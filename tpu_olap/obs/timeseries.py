"""Metrics history: bounded per-series time-series rings (ISSUE 17).

`/metrics` is point-in-time — a scrape shows where the counters are
NOW, not how they got there. The TimeseriesSampler closes that gap
in-process: a periodic `telemetry` background graph on the stage
scheduler (executor/stages.py) snapshots every live metric series into
a bounded ring per series, so the engine answers SQL over its own
recent history (`SELECT ... FROM sys.metrics_history`) and serves it
over HTTP (`GET /debug/timeseries`) with no external TSDB.

Sample shape per tick and series:

  scalar (counter/gauge)  (ts_ms, value)
  histogram               (ts_ms, total, n)  — the _sum/_count pair;
                          per-bucket history would multiply cardinality
                          for little diagnostic value (rates and means
                          derive from sum/count deltas)

Retention is per series (EngineConfig.telemetry_retention): the rings
are deques, so a long-running server's telemetry memory is flat —
series_count x retention tuples. Series that disappear from the
registry (a zeroed table gauge stays; series are never deleted today)
keep their history until process exit.

The sampler READS the registry under its lock and writes nothing back
except its own `telemetry_samples_total` counter — sampled like any
other series, one tick behind. It executes no SQL and produces no
query records, so it cannot self-attribute (the ISSUE 11 no-recursion
contract extends to the telemetry plane).
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque

from tpu_olap.obs.metrics import Histogram


class TimeseriesSampler:
    """Bounded per-series history over a MetricsRegistry."""

    def __init__(self, registry, retention: int = 360):
        self.registry = registry
        self.retention = max(2, int(retention))
        # (name, labels_json) -> deque of (ts_ms, value, count);
        # count is None for scalar series
        self._rings: dict[tuple, deque] = {}
        self._lock = threading.Lock()  # rings only; registry has its own
        self.samples = 0
        self.last_sample_ms = None
        self._m_samples = registry.counter(
            "telemetry_samples_total",
            "Sampler ticks recorded into the metrics-history rings.")

    def sample_once(self, now_ms: int | None = None) -> int:
        """Snapshot every live series. Returns the series count."""
        ts = int(now_ms if now_ms is not None else time.time() * 1000)
        points = []
        reg = self.registry
        with reg._lock:
            for m in reg._metrics.values():
                hist = isinstance(m, Histogram)
                for key, s in m.series.items():
                    labels = json.dumps(dict(zip(m.labelnames, key)),
                                        sort_keys=True)
                    if hist:
                        points.append(((m.name, labels), m.kind,
                                       float(s.total), int(s.n)))
                    else:
                        points.append(((m.name, labels), m.kind,
                                       float(s.value), None))
        with self._lock:
            for rkey, kind, value, count in points:
                ring = self._rings.get(rkey)
                if ring is None:
                    ring = self._rings[rkey] = deque(
                        maxlen=self.retention)
                ring.append((ts, kind, value, count))
            self.samples += 1
            self.last_sample_ms = ts
        self._m_samples.inc()
        return len(points)

    def rows(self, limit_per_series: int | None = None) -> list[dict]:
        """Flat tabular view — the frame behind sys.metrics_history.
        One dict per retained sample, oldest-first within a series."""
        out = []
        with self._lock:
            items = sorted(self._rings.items())
            for (name, labels), ring in items:
                pts = list(ring)
                if limit_per_series is not None:
                    pts = pts[-max(0, int(limit_per_series)):]
                for ts, kind, value, count in pts:
                    out.append({"ts_ms": ts, "name": name, "kind": kind,
                                "labels": labels, "value": value,
                                "count": count})
        return out

    def snapshot(self, limit_per_series: int | None = None) -> dict:
        """GET /debug/timeseries payload: rings grouped per series."""
        series = []
        with self._lock:
            for (name, labels), ring in sorted(self._rings.items()):
                pts = list(ring)
                if limit_per_series is not None:
                    pts = pts[-max(0, int(limit_per_series)):]
                series.append({
                    "name": name, "labels": json.loads(labels),
                    "kind": pts[-1][1] if pts else None,
                    "points": [[p[0], p[2]] if p[3] is None
                               else [p[0], p[2], p[3]] for p in pts]})
            meta = {"samples": self.samples,
                    "retention": self.retention,
                    "series": len(self._rings),
                    "last_sample_ms": self.last_sample_ms}
        return {**meta, "timeseries": series}
