"""Regression sentinel: the engine watches itself for drift (ISSUE 17).

Regressions used to be caught when a human re-ran a bench. The sentinel
closes the loop in-process, from the two signals the engine already
produces at query completion (QueryRunner.record):

- **per-template latency baselines** — an EWMA plus a raw-moment
  accumulator (n, Σx, Σx² — moments merge by addition, the
  moment-sketch property of PAPERS.md 1803.01969, so per-replica
  baselines can later merge fleet-wide by summing) for every query
  template's served latency;
- **per-stage baselines** — an EWMA of each stage's busy (run_ms) and
  wait (wait_ms) from the record's `stages` list (executor/stages.py),
  so a drifted query is attributed to the STAGE whose time moved, not
  just flagged slow.

A served query slower than max(floor, factor × template EWMA) after
`sentinel_min_samples` warmup raises a `latency_drift` alert naming
the worst-moved stage. Anomalous samples do NOT update the EWMA (an
incident must not teach the baseline that slow is normal); the moment
accumulator keeps every sample so mean/variance stay honest.

Resource checks run on the telemetry tick (obs.timeseries' background
graph), over probes wired in by the runner/engine: HBM pressure vs
budget, eviction thrash, WAL sync lag, breaker-open, admission sheds.

Alert lifecycle: fire -> re-confirm (count++) while the condition
holds -> auto-clear when not re-confirmed for `sentinel_clear_after_s`.
Transitions emit `alert` / `alert_clear` events; live state is the
`alerts_active{kind}` gauge, `sys.alerts`, and the GET /debug/health
verdict. The sentinel observes ONLY non-introspection records —
record() returns before the sentinel for sys.* statements, so telemetry
queries never appear in their own baselines (ISSUE 11 contract).
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque

from tpu_olap.obs.workload import in_introspection

# alert kinds, in /debug/health display order
ALERT_KINDS = ("latency_drift", "hbm_pressure", "eviction_thrash",
               "wal_lag", "breaker_open", "admission_shed")


class _Baseline:
    """Per-template latency baseline: EWMA + raw moments + per-stage
    EWMAs of busy/wait. Moments are a 3-vector (n, Σx, Σx²) that merges
    with another baseline's by elementwise addition."""

    __slots__ = ("n", "ewma", "moments", "stage_ewma", "anomalies",
                 "last_ms")

    def __init__(self):
        self.n = 0
        self.ewma = None
        self.moments = [0, 0.0, 0.0]
        self.stage_ewma: dict = {}  # stage -> [run_ewma, wait_ewma]
        self.anomalies = 0
        self.last_ms = None

    def update(self, total_ms: float, stages, alpha: float,
               anomalous: bool):
        self.moments[0] += 1
        self.moments[1] += total_ms
        self.moments[2] += total_ms * total_ms
        self.last_ms = total_ms
        if anomalous:
            self.anomalies += 1
            return
        self.n += 1
        self.ewma = total_ms if self.ewma is None else \
            (1 - alpha) * self.ewma + alpha * total_ms
        for s in stages:
            name = s.get("stage")
            if not name:
                continue
            run = float(s.get("run_ms") or 0.0)
            wait = float(s.get("wait_ms") or 0.0)
            e = self.stage_ewma.get(name)
            if e is None:
                self.stage_ewma[name] = [run, wait]
            else:
                e[0] = (1 - alpha) * e[0] + alpha * run
                e[1] = (1 - alpha) * e[1] + alpha * wait

    def mean(self) -> float | None:
        n = self.moments[0]
        return self.moments[1] / n if n else None

    def variance(self) -> float | None:
        n = self.moments[0]
        if n < 2:
            return None
        m = self.moments[1] / n
        return max(0.0, self.moments[2] / n - m * m)


class RegressionSentinel:
    """Baselines + active-alert registry behind one lock."""

    def __init__(self, config, metrics=None, events=None):
        self.config = config
        self.events = events
        self._lock = threading.Lock()
        self._templates: dict[str, _Baseline] = {}
        self._active: dict[tuple, dict] = {}  # (kind, subject) -> alert
        self._history: deque = deque(
            maxlen=max(1, int(getattr(config, "sentinel_alert_limit",
                                      256))))
        self._seq = itertools.count(1)
        self._probes: dict = {}
        self._last_evictions = None
        self._last_sheds = None
        self.checks = 0
        self.observed = 0
        self._m_active = None
        if metrics is not None:
            self._m_active = metrics.gauge(
                "alerts_active",
                "Active sentinel alerts, by kind (obs.sentinel).",
                ("kind",))
            for kind in ALERT_KINDS:
                self._m_active.set(0, kind=kind)

    @property
    def enabled(self) -> bool:
        return bool(getattr(self.config, "sentinel_enabled", True))

    def add_probe(self, name: str, fn):
        """Register a resource probe (a zero-arg callable returning a
        dict) consulted on each check() tick. Later registrations with
        the same name replace (engine re-wiring after close)."""
        with self._lock:
            self._probes[name] = fn

    # ------------------------------------------------------- observe

    def observe(self, m: dict):
        """Fold one completed SERVED query record into the baselines;
        fire latency_drift when it lands past the template threshold.
        Called from QueryRunner.record() after the introspection
        early-return — introspection never reaches here, and the
        explicit guard keeps that true even for direct callers."""
        if not self.enabled or in_introspection():
            return
        total = m.get("total_ms")
        if total is None or m.get("failed") \
                or m.get("deadline_exceeded"):
            return
        total = float(total)
        tid = m.get("template_id") or \
            f"{m.get('query_type', '?')}:{m.get('datasource', '?')}"
        stages = m.get("stages") or []
        cfg = self.config
        alert = None
        with self._lock:
            b = self._templates.get(tid)
            if b is None:
                b = self._templates[tid] = _Baseline()
            anomalous = False
            if b.n >= int(cfg.sentinel_min_samples) \
                    and b.ewma is not None:
                threshold = max(float(cfg.sentinel_latency_floor_ms),
                                float(cfg.sentinel_latency_factor)
                                * b.ewma)
                if total > threshold:
                    anomalous = True
                    stage, delta = self._attribute(b, stages)
                    alert = {"subject": tid, "stage": stage,
                             "total_ms": round(total, 3),
                             "baseline_ms": round(b.ewma, 3),
                             "threshold_ms": round(threshold, 3),
                             "stage_delta_ms": round(delta, 3),
                             "query_id": m.get("query_id")}
            b.update(total, stages, float(cfg.sentinel_ewma_alpha),
                     anomalous)
            self.observed += 1
        if alert is not None:
            self.fire("latency_drift", **alert)

    @staticmethod
    def _attribute(b: _Baseline, stages) -> tuple:
        """The stage whose busy+wait moved most above its own baseline
        — 'transfer got slow', not just 'the query got slow'. Records
        without a stages block (cache hits, fallback) attribute to
        'total'."""
        worst, worst_delta = "total", 0.0
        for s in stages:
            name = s.get("stage")
            if not name:
                continue
            cur = float(s.get("run_ms") or 0.0) \
                + float(s.get("wait_ms") or 0.0)
            e = b.stage_ewma.get(name)
            delta = cur - ((e[0] + e[1]) if e is not None else 0.0)
            if delta > worst_delta:
                worst, worst_delta = name, delta
        return worst, worst_delta

    # --------------------------------------------------- alert state

    def fire(self, kind: str, subject: str = "engine", **detail):
        """Fire or re-confirm the (kind, subject) alert."""
        now_ms = int(time.time() * 1000)
        key = (kind, str(subject))
        with self._lock:
            a = self._active.get(key)
            new = a is None
            if new:
                a = {"alert_id": f"a{next(self._seq):05d}",
                     "kind": kind, "subject": str(subject),
                     "status": "active", "fired_at_ms": now_ms,
                     "last_seen_ms": now_ms, "cleared_at_ms": None,
                     "count": 1}
                a.update(detail)
                self._active[key] = a
                self._history.append(a)
            else:
                a["count"] += 1
                a["last_seen_ms"] = now_ms
                a.update(detail)
            self._refresh_gauge_locked()
        if new and self.events is not None:
            self.events.emit("alert", **{k: v for k, v in a.items()
                                         if k != "status"})

    def _clear_stale_locked(self, now_ms: int) -> list:
        clear_after_ms = float(self.config.sentinel_clear_after_s) \
            * 1000.0
        cleared = []
        for key, a in list(self._active.items()):
            if now_ms - a["last_seen_ms"] >= clear_after_ms:
                a["status"] = "cleared"
                a["cleared_at_ms"] = now_ms
                del self._active[key]
                cleared.append(a)
        if cleared:
            self._refresh_gauge_locked()
        return cleared

    def _refresh_gauge_locked(self):
        if self._m_active is None:
            return
        counts = {k: 0 for k in ALERT_KINDS}
        for kind, _subject in self._active:
            counts[kind] = counts.get(kind, 0) + 1
        for kind, n in counts.items():
            self._m_active.set(n, kind=kind)

    # ----------------------------------------------------- check tick

    def check(self):
        """Resource-drift checks + stale-alert clearing; runs on the
        telemetry background tick. Probe failures are swallowed — the
        sentinel observes the engine, it must not be able to fail it."""
        if not self.enabled:
            return
        cfg = self.config
        snaps = {}
        with self._lock:
            probes = dict(self._probes)
        for name, fn in probes.items():
            try:
                snaps[name] = fn() or {}
            except Exception:  # noqa: BLE001 — observer, not server
                snaps[name] = {}
        hbm = snaps.get("hbm", {})
        budget = hbm.get("budget")
        in_use = hbm.get("bytes_in_use")
        if budget and in_use is not None \
                and in_use / budget >= float(cfg.sentinel_hbm_pressure):
            self.fire("hbm_pressure", subject="hbm",
                      bytes_in_use=int(in_use), budget_bytes=int(budget),
                      fraction=round(in_use / budget, 4))
        evictions = hbm.get("evictions")
        if evictions is not None:
            prev, self._last_evictions = self._last_evictions, evictions
            if prev is not None and \
                    evictions - prev >= int(cfg.sentinel_eviction_thrash):
                self.fire("eviction_thrash", subject="hbm",
                          evictions_tick=int(evictions - prev),
                          evictions_total=int(evictions))
        for table, lag in (snaps.get("wal", {}) or {}).items():
            if lag >= int(cfg.sentinel_wal_lag_records):
                self.fire("wal_lag", subject=table,
                          lag_records=int(lag))
        state = snaps.get("breaker", {}).get("state")
        if state == "open":
            self.fire("breaker_open", subject="device", state=state)
        sheds = snaps.get("admission", {}).get("shed_total")
        if sheds is not None:
            prev, self._last_sheds = self._last_sheds, sheds
            if prev is not None and sheds > prev:
                self.fire("admission_shed", subject="admission",
                          sheds_tick=int(sheds - prev),
                          sheds_total=int(sheds))
        now_ms = int(time.time() * 1000)
        with self._lock:
            cleared = self._clear_stale_locked(now_ms)
            self.checks += 1
        for a in cleared:
            if self.events is not None:
                self.events.emit(
                    "alert_clear", alert_id=a["alert_id"],
                    kind=a["kind"], subject=a["subject"],
                    count=a["count"], fired_at_ms=a["fired_at_ms"])

    # ------------------------------------------------------ exports

    def active(self) -> list[dict]:
        with self._lock:
            return [dict(a) for a in self._active.values()]

    def alert_rows(self) -> list[dict]:
        """History rows (active + cleared, oldest-first) behind
        sys.alerts."""
        with self._lock:
            return [dict(a) for a in self._history]

    def counts(self) -> dict:
        """{fired, active} — the bench detail's alert census."""
        with self._lock:
            return {"fired": len(self._history),
                    "active": len(self._active)}

    def health(self) -> dict:
        """GET /debug/health verdict: ok iff no active alerts."""
        with self._lock:
            active = [dict(a) for a in self._active.values()]
            templates = len(self._templates)
            checks, observed = self.checks, self.observed
        active.sort(key=lambda a: a["fired_at_ms"])
        return {"ok": not active, "alerts": active,
                "enabled": self.enabled, "checks": checks,
                "observed": observed, "templates": templates}

    def baseline(self, template_id: str) -> dict | None:
        """One template's baseline (tests / debugging): EWMA, moment
        vector, per-stage EWMAs."""
        with self._lock:
            b = self._templates.get(template_id)
            if b is None:
                return None
            return {"n": b.n, "ewma_ms": b.ewma,
                    "moments": list(b.moments),
                    "mean_ms": b.mean(), "variance": b.variance(),
                    "anomalies": b.anomalies,
                    "stages": {k: {"run_ms": v[0], "wait_ms": v[1]}
                               for k, v in b.stage_ewma.items()}}
