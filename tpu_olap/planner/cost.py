"""Cost-based dispatch: the DruidQueryCostModel analog (SURVEY.md §3.2).

The reference chooses between two physical strategies for every rewritten
query: send one query to the Druid *broker* (Druid fans out internally and
merges) or fan out per-historical queries with Spark running the final
merge ("direct historicals"), driven by estimated result cardinality,
scan/transport/merge costs and knobs like `histMergeFactor` /
`queryOutputSizeEstimate`.

The TPU translation keeps the same decision shape with the same inputs:

- "**broker**"  -> hand the WHOLE jitted program to XLA's GSPMD
  partitioner over the mesh: plain group keys, replicated outputs,
  compiler-inserted psum/all-gather (the fan-out/merge is opaque, like
  Druid's broker). The only strategy on a multi-host (DCN) mesh, where
  remote shards are not host-addressable.
- "**historicals**" -> chip-extended group keys under
  `jax.jit(..., out_shardings=P('chips'))`: each chip's explicit
  partial dense group table stays SHARDED in its own HBM (zero
  cross-chip traffic in the reduce), one fetch pulls every chip's
  shard concurrently, and the host BROKER merges the D unfinalized
  tables with the segment-cache algebra (the analog of per-historical
  partial aggregates + Spark's final merge-aggregate, SURVEY.md §3.5
  P2; executor/sharding.py).

Explicit partials pay the [D·K] host merge instead of a device
collective, so they win while the group table is small relative to the
scan; a huge dense table (K within the dense budget but millions of
groups x several aggregators) makes the fixed-size merge dominate,
where the compiler's freedom to schedule (reduce-scatter, fusion into
the scatter) is worth more. Both strategies are semantically identical
— this model only picks the faster one, and
`EngineConfig.cost_model_enabled=False` pins "historicals" (the
reference's default fan-out path).

Constants are per-chip throughput guesses, deliberately coarse — the
decision only needs the crossover magnitude, and every term is exposed in
the explain payload so a misprediction is visible (the reference logs its
cost decisions the same way, SURVEY.md §6 observability).
"""

from __future__ import annotations

from dataclasses import dataclass
from math import ceil, log2

# coarse per-chip fallback constants (v5e-class guesses) — superseded by
# fitted per-backend values from cost_calibration.json when present
# (tools/calibrate_cost.py measures and writes them)
SCAN_NS_PER_ROW_COL = 0.05     # fused filter+reduce, HBM-bound
MERGE_NS_PER_BYTE = 0.05       # ICI allreduce per byte per hop (~20 GB/s)
COLLECTIVE_LAT_US = 25.0       # per-hop collective launch latency
GSPMD_OVERHEAD = 1.35          # generic partitioner vs hand-written merge

_FALLBACKS = {
    "scan_ns_per_row_col": SCAN_NS_PER_ROW_COL,
    "merge_ns_per_byte": MERGE_NS_PER_BYTE,
    "collective_lat_us": COLLECTIVE_LAT_US,
    "gspmd_overhead": GSPMD_OVERHEAD,
}
_calibration_cache: dict | None = None


def _calibration() -> dict:
    """Fitted constants for the current backend, {} when never fitted."""
    global _calibration_cache
    if _calibration_cache is None:
        import json
        import os
        path = os.path.join(os.path.dirname(__file__),
                            "cost_calibration.json")
        data = {}
        if os.path.exists(path):
            with open(path) as f:
                data = json.load(f)
        _calibration_cache = data
    import jax
    return _calibration_cache.get(jax.default_backend(), {})


def constants(config) -> dict:
    """Resolve the four model constants: explicit config pin > fitted
    calibration for this backend > coarse fallback."""
    cal = _calibration()
    out = {}
    for name, fb in _FALLBACKS.items():
        pinned = getattr(config, "cost_" + name, None)
        out[name] = pinned if pinned is not None else cal.get(name, fb)
    return out


@dataclass(frozen=True)
class CostDecision:
    strategy: str            # "historicals" (sharded partials + host
    #                           broker merge) | "broker" (GSPMD)
    shards: int
    rows_scanned: int
    groups: int
    table_bytes: int         # merged group-table size (all aggregators)
    scan_us: float           # per-chip scan estimate
    merge_us: float          # explicit-partials merge estimate
    reason: str

    def to_json(self) -> dict:
        return {
            "strategy": self.strategy, "shards": self.shards,
            "rowsScanned": self.rows_scanned, "groups": self.groups,
            "tableBytes": self.table_bytes,
            "scanUs": round(self.scan_us, 1),
            "mergeUs": round(self.merge_us, 1),
            "reason": self.reason,
        }


def estimate_groups(plan) -> int:
    """Expected non-empty groups: the dense id space capped by the rows
    that can populate it (the reference estimates result cardinality from
    segment-metadata per-column cardinalities the same way)."""
    rows = sum(plan.table.segments[i].meta.n_valid for i in plan.pruned_ids)
    return max(1, min(plan.total_groups, rows))


def table_width_bytes(plan) -> int:
    """Bytes per group across all partial-aggregate state (what the
    allreduce actually moves): accumulators + per-plan null counters +
    sketch state."""
    from tpu_olap.kernels.hll import NUM_REGISTERS

    width = 4  # _rows int32
    for p in plan.agg_plans:
        if p.kind == "hll":
            width += 4 * NUM_REGISTERS
        elif p.kind == "theta":
            width += 8 * p.theta_k
        else:
            import numpy as np
            width += np.dtype(p.acc_dtype).itemsize
            if p.kind in ("sum", "min", "max"):
                width += 4  # _nn_<name>
    return width


def decide(plan, config, shards: int) -> CostDecision:
    """Pick the dispatch strategy for an aggregate plan on a mesh."""
    rows = sum(plan.table.segments[i].meta.n_valid for i in plan.pruned_ids)
    groups = plan.total_groups
    n_cols = max(1, len(plan.columns))
    width = table_width_bytes(plan)
    table_bytes = groups * width
    c = constants(config)

    scan_us = (rows * n_cols * c["scan_ns_per_row_col"] / 1000.0
               / max(1, shards))
    hops = max(1, ceil(log2(max(2, shards))))
    merge_us = hops * (c["collective_lat_us"]
                       + table_bytes * c["merge_ns_per_byte"] / 1000.0
                       * config.shard_merge_factor)

    if shards <= 1:
        return CostDecision("historicals", 1, rows, groups, table_bytes,
                            scan_us, 0.0, "single device")
    if config.force_strategy is not None:
        return CostDecision(config.force_strategy, shards, rows, groups,
                            table_bytes, scan_us, merge_us,
                            "forced by config")
    if not config.cost_model_enabled:
        return CostDecision("historicals", shards, rows, groups,
                            table_bytes, scan_us, merge_us,
                            "cost model disabled")
    # broker (GSPMD) wins when the explicit merge dwarfs its own scan —
    # the compiler can overlap/restructure what the fixed psum cannot
    if merge_us > c["gspmd_overhead"] * (scan_us
                                         + c["collective_lat_us"] * hops):
        return CostDecision("broker", shards, rows, groups, table_bytes,
                            scan_us, merge_us,
                            "merge dominates scan; defer to partitioner")
    return CostDecision("historicals", shards, rows, groups, table_bytes,
                        scan_us, merge_us, "explicit partials cheaper")
