"""Shared expression helpers — the ExprUtil analog (SURVEY.md §3.2):
normalization/inspection used by both the rewriter and the fallback
interpreter so the two paths can't drift.
"""

from __future__ import annotations

import json

from tpu_olap.ir.expr import BinOp, Col, FuncCall, Lit
from tpu_olap.planner.sqlparse import AGG_FUNCS


def split_and(e):
    if e is None:
        return []
    if isinstance(e, BinOp) and e.op == "&&":
        return split_and(e.left) + split_and(e.right)
    return [e]


def contains_agg(e) -> bool:
    if isinstance(e, FuncCall):
        if e.name in AGG_FUNCS:
            return True
        return any(contains_agg(a) for a in e.args)
    if isinstance(e, BinOp):
        return contains_agg(e.left) or contains_agg(e.right)
    return False


def contains_window(e) -> bool:
    """Shared window-presence predicate (the planner's grouped-window
    rewrite and the chunked-fallback guard must agree on it)."""
    from tpu_olap.ir.expr import WindowCall
    if isinstance(e, WindowCall):
        return True
    if isinstance(e, BinOp):
        return contains_window(e.left) or contains_window(e.right)
    if isinstance(e, FuncCall):
        return any(contains_window(a) for a in e.args)
    return False


def expr_key(e) -> str:
    """Structural identity for dedup/alias maps."""
    return json.dumps(e.to_json(), sort_keys=True) \
        if hasattr(e, "to_json") else repr(e)


_FOLD_ARITH = {
    "+": lambda a, b: a + b, "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "%": lambda a, b: a % b if b else None,
}
_FOLD_CMP = {
    "==": lambda a, b: a == b, "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b, "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b, ">=": lambda a, b: a >= b,
}
_CAST_FOLD = {
    "cast_long": lambda v: int(v),
    "cast_double": lambda v: float(v),
    "cast_string": lambda v: str(v),
}


def simplify(e):
    """Expression normalization — the ExprUtil analog (SURVEY.md §3.2):
    constant folding (arithmetic, comparisons, casts of literals),
    double-negation elimination, boolean identity pruning (x AND true,
    x OR false), and null-safe arithmetic identities (x+0, x*1, x/1 —
    all preserve NULL operands, unlike x*0 which must NOT fold to 0).
    Applied to the parsed statement before planning, so the rewriter and
    the fallback interpreter both see the same normalized tree."""
    if e is None or isinstance(e, (Col, Lit)):
        return e
    if isinstance(e, BinOp):
        left = simplify(e.left)
        right = simplify(e.right)
        lv = left.value if isinstance(left, Lit) else _MISS
        rv = right.value if isinstance(right, Lit) else _MISS
        if e.op in _FOLD_ARITH and lv is not _MISS and rv is not _MISS:
            if lv is None or rv is None:
                return Lit(None)
            try:
                folded = _FOLD_ARITH[e.op](lv, rv)
            except Exception:
                folded = _MISS
            if folded is not _MISS and folded is not None:
                return Lit(folded)
        if e.op == "/" and lv is not _MISS and rv is not _MISS:
            if lv is None or rv is None:
                return Lit(None)
            if rv:
                try:
                    return Lit(lv / rv)
                except Exception:
                    pass  # non-numeric literals: leave for runtime
        if e.op in _FOLD_CMP and lv is not _MISS and rv is not _MISS \
                and lv is not None and rv is not None \
                and type(lv) is type(rv):
            return Lit(bool(_FOLD_CMP[e.op](lv, rv)))
        if e.op == "&&":
            if lv is True:
                return right
            if rv is True:
                return left
            if lv is False or rv is False:
                return Lit(False)
        if e.op == "||":
            if lv is False:
                return right
            if rv is False:
                return left
            if lv is True or rv is True:
                return Lit(True)
        # null-safe identities. INT identity elements only: x+0.0 / x*1.0
        # coerce an int operand to double (and True==1 is bool), so the
        # fold would change the result dtype
        def int_ident(v, ident):
            return type(v) is int and v == ident

        if e.op in ("+", "-") and int_ident(rv, 0):
            return left
        if e.op == "+" and int_ident(lv, 0):
            return right
        if e.op in ("*", "/") and int_ident(rv, 1):
            return left
        if e.op == "*" and int_ident(lv, 1):
            return right
        return BinOp(e.op, left, right)
    if isinstance(e, FuncCall):
        args = tuple(simplify(a) for a in e.args)
        if e.name == "not":
            a = args[0]
            if isinstance(a, FuncCall) and a.name == "not":
                return a.args[0]  # NOT NOT x -> x
            if isinstance(a, Lit) and isinstance(a.value, bool):
                return Lit(not a.value)
        if e.name in _CAST_FOLD and isinstance(args[0], Lit):
            v = args[0].value
            if v is None:
                return Lit(None)
            try:
                return Lit(_CAST_FOLD[e.name](v))
            except (TypeError, ValueError):
                pass  # unparseable literal: leave for runtime semantics
        if e.name == "if" and isinstance(args[0], Lit) \
                and isinstance(args[0].value, bool):
            return args[1] if args[0].value else args[2]
        return FuncCall(e.name, args)
    return e


_MISS = object()


def map_stmt_exprs(stmt, fn):
    """Copy a SelectStmt with `fn` applied to every expression position
    (projections, where, having, group by, join conditions, order by) —
    the single traversal shared by normalization passes so a future
    expression-bearing clause is added in one place."""
    import copy
    out = copy.copy(stmt)
    out.projections = [(fn(e), a) for e, a in stmt.projections]
    out.where = fn(stmt.where) if stmt.where is not None else None
    out.having = fn(stmt.having) if stmt.having is not None else None
    out.group_by = [fn(g) for g in stmt.group_by]
    import dataclasses
    out.joins = [dataclasses.replace(
        j, on=fn(j.on) if j.on is not None else None)
        for j in stmt.joins]
    out.order_by = [dataclasses.replace(o, expr=fn(o.expr))
                    for o in stmt.order_by]
    if getattr(stmt, "grouping_sets", None) is not None:
        out.grouping_sets = [[fn(e) for e in s]
                             for s in stmt.grouping_sets]
    return out


def simplify_stmt(stmt):
    """Apply simplify() across a parsed SelectStmt; a WHERE/HAVING that
    folds to literal TRUE is dropped entirely (a tautology left in place
    would still read as an untranslatable literal predicate and force
    the fallback path)."""
    out = map_stmt_exprs(stmt, simplify)
    if out.where == Lit(True):
        out.where = None
    if out.having == Lit(True):
        out.having = None
    return out


def render(e) -> str:
    if isinstance(e, Col):
        return e.name.split(".")[-1]
    if isinstance(e, Lit):
        return repr(e.value)
    if isinstance(e, BinOp):
        return f"({render(e.left)} {e.op} {render(e.right)})"
    if isinstance(e, FuncCall):
        return f"{e.name}({', '.join(render(a) for a in e.args)})"
    return repr(e)
