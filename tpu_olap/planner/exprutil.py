"""Shared expression helpers — the ExprUtil analog (SURVEY.md §3.2):
normalization/inspection used by both the rewriter and the fallback
interpreter so the two paths can't drift.
"""

from __future__ import annotations

import json

from tpu_olap.ir.expr import BinOp, Col, FuncCall, Lit
from tpu_olap.planner.sqlparse import AGG_FUNCS


def split_and(e):
    if e is None:
        return []
    if isinstance(e, BinOp) and e.op == "&&":
        return split_and(e.left) + split_and(e.right)
    return [e]


def contains_agg(e) -> bool:
    if isinstance(e, FuncCall):
        if e.name in AGG_FUNCS:
            return True
        return any(contains_agg(a) for a in e.args)
    if isinstance(e, BinOp):
        return contains_agg(e.left) or contains_agg(e.right)
    return False


def expr_key(e) -> str:
    """Structural identity for dedup/alias maps."""
    return json.dumps(e.to_json(), sort_keys=True) \
        if hasattr(e, "to_json") else repr(e)


def render(e) -> str:
    if isinstance(e, Col):
        return e.name.split(".")[-1]
    if isinstance(e, Lit):
        return repr(e.value)
    if isinstance(e, BinOp):
        return f"({render(e.left)} {e.op} {render(e.right)})"
    if isinstance(e, FuncCall):
        return f"{e.name}({', '.join(render(a) for a in e.args)})"
    return repr(e)
