"""Aggregate rewrite onto materialized rollup cubes (docs/CUBES.md).

Before a rewritten aggregate dispatches against the base table, this
pass asks whether a registered cube COVERS it — dims a subset of the
cube's dims, time grain a whole multiple of the cube grain, filters and
HAVING only over cube dims, every aggregation derivable from stored
partials, and the query's intervals decomposable into whole cube
buckets. A covered query is then served by folding a few thousand cube
rows on the host instead of scanning the base table on the device:

1.  the ORIGINAL query's lowered plan supplies the exact output layout
    (bucket grid, dense dim id spaces incl. filter-restricted remaps,
    agg plans) — reused verbatim, so assembly/HAVING/ORDER/LIMIT/topN
    semantics are the device path's own code, not a re-implementation;
2.  cube rows map into that layout (bucket ids from the plan's bucket
    grid, dim ids through the plan's own DimPlan.ids over base-code /
    value arrays the cube retained at build);
3.  stored partials merge with the same algebra the per-segment cache
    uses — counts/sums add, min/max fold, HLL registers max-merge,
    theta tables re-merge losslessly (k smallest of a union of per-part
    k-smallest sets IS the union's k smallest, so sketch results are
    bit-identical to the base path);
4.  `finalize_aggs` + `eval_post_aggs` + `QueryRunner._assemble_agg`
    finish exactly like a device execution.

Staleness: a cube is only consulted while its recorded base generation
matches the live table (the PR 9 cache contract — stale state is
unservable at check time, before any maintenance runs). Every refusal
is counted (`cube_rewrite_total{result}`) and the serve records stamp
`path="cube"` so sys.query_templates shows cube coverage directly.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_olap.cubes.spec import agg_signature, period_contains
from tpu_olap.ir.granularity import AllGranularity, PeriodGranularity
from tpu_olap.ir.query import (GroupByQuerySpec, TimeseriesQuerySpec,
                               TopNQuerySpec)
from tpu_olap.kernels.groupby import build_group_key, partials_radix
from tpu_olap.kernels.hll import NUM_REGISTERS
from tpu_olap.kernels.theta import EMPTY as THETA_EMPTY
from tpu_olap.obs.trace import span as _span
from tpu_olap.segments.segment import TIME_COLUMN

__all__ = ["try_serve_cube"]

_AGG_TYPES = (TimeseriesQuerySpec, GroupByQuerySpec, TopNQuerySpec)

# timeFormat formats a cube can reproduce from bucket starts, mapped to
# the calendar unit they demand: year('97) of every row in a month/day/
# hour bucket equals year(bucket start) because those grains nest inside
# years — kernels.timebucket's format ladder, restricted to formats with
# a well-defined containment unit
_FMT_UNIT = {"YYYY": "P1Y", "yyyy": "P1Y", "%Y": "P1Y",
             "Q": "P3M",
             "MM": "P1M", "%m": "P1M",
             "dd": "P1D", "DD": "P1D", "%d": "P1D",
             "HH": "PT1H", "hh": "PT1H", "%H": "PT1H",
             "mm": "PT1M", "%M": "PT1M",
             "ss": "PT1S", "%S": "PT1S"}


def _filter_has_column_comparison(f) -> bool:
    from tpu_olap.ir import filters as F
    if isinstance(f, F.ColumnComparisonFilter):
        return True
    for sub in (getattr(f, "fields", None) or ()):
        if _filter_has_column_comparison(sub):
            return True
    inner = getattr(f, "field", None)
    return inner is not None and _filter_has_column_comparison(inner)


def _covering_reason(query, phys, spec, data, config) -> str | None:
    """None when the cube (spec + one build snapshot) covers this
    (query, plan); else why not."""
    cube_dims = set(spec.dimensions)
    period = spec.period

    if phys.kind != "agg":
        return "not an aggregation plan"
    if phys.sparse:
        return "sparse plan (dense layout needed for the cube fold)"
    if phys.empty:
        return "query intervals do not touch the table"

    # ---- time grain: query grain must be a whole multiple of the cube's
    qg = query.granularity
    if isinstance(qg, AllGranularity):
        pass  # any cube grain folds into one bucket
    elif isinstance(qg, PeriodGranularity):
        if qg.origin is not None:
            return "custom-origin granularity"
        if period is None:
            return (f"query grain {qg.period} finer than cube grain "
                    "'all'")
        if qg.time_zone != config.time_zone:
            return "granularity timezone differs from the cube's"
        if not period_contains(qg.period, period):
            return (f"query grain {qg.period} is not a multiple of "
                    f"cube grain {period}")
    else:
        return f"granularity {type(qg).__name__} not cube-servable"

    # ---- dimensions: subset of cube dims (or time-derived at >= grain)
    dim_specs = query.dimensions if isinstance(query, GroupByQuerySpec) \
        else ((query.dimension,) if isinstance(query, TopNQuerySpec)
              else ())
    for ds, dp in zip(dim_specs, phys.dim_plans):
        if dp.kind == "timeformat":
            fn = getattr(ds, "extraction_fn", None)
            fmt = getattr(fn, "format", None)
            unit = _FMT_UNIT.get(fmt)
            if unit is None:
                return f"timeFormat {fmt!r} has no containment unit"
            if period is None or not (unit == period
                                      or period_contains(unit, period)):
                return (f"timeFormat {fmt!r} needs grain <= {unit}, "
                        f"cube is {period or 'all'}")
            continue
        if dp.source_col not in cube_dims:
            return f"dimension {dp.source_col!r} not in the cube"
        if dp.kind not in ("codes", "remap", "numeric"):
            return f"dimension plan kind {dp.kind!r} not cube-servable"

    # ---- filter: only over cube dims, no cross-column comparison
    vexprs = {v.name: v.expression for v in query.virtual_columns}
    if query.filter is not None:
        if _filter_has_column_comparison(query.filter):
            return "columnComparison filter"
        for c in query.filter.columns():
            if c in vexprs:
                return f"filter over virtual column {c!r}"
            if c == TIME_COLUMN:
                return "row-level __time filter"
            if c not in cube_dims:
                return f"filter column {c!r} not a cube dimension"

    # ---- aggregations: every partial must be stored (+ wide-enough k)
    for a, p in zip(query.aggregations, phys.agg_plans):
        hit = data.aggs.get(agg_signature(a, vexprs))
        if hit is None:
            return f"aggregation {a.name!r} not materialized"
        sa = hit[0]
        if sa.kind != p.kind:
            return f"aggregation {a.name!r} kind mismatch"
        if p.kind == "theta" and sa.theta_k < p.theta_k:
            return (f"stored theta width {sa.theta_k} narrower than "
                    f"the query's {p.theta_k}")

    # ---- fold state budget (same shape as the segment-cache guard)
    radix = partials_radix(phys.agg_plans)
    if phys.total_groups * radix > config.cube_serve_state_budget:
        return (f"fold state {phys.total_groups}x{radix} exceeds "
                "cube_serve_state_budget")
    return None


# --------------------------------------------------------------- serving

def _interval_keep_mask(query, data):
    """Boolean keep-mask over cube rows for the query's intervals, or
    None when some cube bucket STRADDLES an interval edge (the bucket's
    rows can't be split, so the cube must refuse). Bucket ends clip at
    the base table's build-time max timestamp: an interval covering all
    real rows of the last, partially-filled calendar bucket still
    contains it."""
    intervals = query.intervals
    if not intervals:
        return np.ones(data.n_rows, bool)
    t, e = data.times, np.minimum(data.ends, data.base_tmax + 1)
    inside = np.zeros(data.n_rows, bool)
    touched = np.zeros(data.n_rows, bool)
    for iv in intervals:
        inside |= (t >= iv.start) & (e <= iv.end)
        touched |= (t < iv.end) & (e > iv.start)
    if bool((touched & ~inside).any()):
        return None
    return inside


def _dim_env(phys, data, keep):
    """Plan-space env over the KEPT cube rows: string dims as base-
    dictionary codes, numeric dims as values (+ null masks), plus the
    bucket-start timestamps for timeformat dims. DimPlan.ids() then
    produces exactly the dense ids the device kernel would."""
    cols = {TIME_COLUMN: data.times[keep]}
    nulls: dict = {}
    for col, packed in data.dims.items():
        if packed[0] == "codes":
            cols[col] = packed[1][keep]
        else:
            cols[col] = packed[1][keep]
            if packed[2] is not None:
                nulls[col] = packed[2][keep]
    return {"cols": cols, "nulls": nulls}


def _filter_mask(query, phys, env, n_kept: int):
    """Row mask of the query's WHERE over kept cube rows, evaluated by
    the ordinary filter compiler against the BASE table (the cube keeps
    base-dictionary codes, so selector/IN/bound/LIKE/extraction filters
    compile to the same predicate tables the device path uses). `env`
    is the kept-row plan-space environment built once per serve."""
    if query.filter is None:
        return np.ones(n_kept, bool)
    from tpu_olap.kernels.filtereval import ConstPool, compile_filter
    pool = ConstPool()
    fn = compile_filter(query.filter, phys.table, pool, {})
    return np.asarray(fn(env, pool.consts), bool)


def _theta_fold(tables: np.ndarray, key: np.ndarray, total: int,
                k: int) -> np.ndarray:
    """Group-merge of per-row theta tables: k smallest DISTINCT unit
    hashes per group (kernels.theta.theta_merge's semantics, folded
    once over all rows of each group)."""
    n, ks = tables.shape
    g = np.repeat(key.astype(np.int64), ks)
    v = tables.reshape(-1)
    m = v < THETA_EMPTY
    g, v = g[m], v[m]
    out = np.full((total, k), THETA_EMPTY, np.float64)
    if len(g) == 0:
        return out
    order = np.lexsort((v, g))
    g, v = g[order], v[order]
    first = np.concatenate(
        [[True], (g[1:] != g[:-1]) | (v[1:] != v[:-1])])
    g, v = g[first], v[first]
    starts = np.searchsorted(g, np.arange(total))
    rank = np.arange(len(g)) - starts[g]
    ok = rank < k
    out[g[ok], rank[ok].astype(np.int64)] = v[ok]
    return out


def _fold_partials(query, phys, data, env, keep, fmask):
    """Kept+filtered cube rows -> dense partial arrays in the plan's
    [total_groups] layout — the same dict shape group_reduce emits.
    `data` is ONE build snapshot (registry.serveable) — never the live
    entry, whose data a concurrent refresh may swap; `env` is the
    kept-row plan-space environment shared with the filter pass."""
    consts = phys.pool.consts
    rows_idx = np.nonzero(fmask)[0]
    times = env["cols"][TIME_COLUMN][rows_idx]

    ids, radix = [], []
    if phys.bucket_plan.kind != "all":
        ids.append(np.asarray(
            phys.bucket_plan.ids(times, consts), np.int64))
        radix.append(phys.sizes[0])
    sub_env = {"cols": {c: a[rows_idx] for c, a in env["cols"].items()},
               "nulls": {c: a[rows_idx]
                         for c, a in env["nulls"].items()}}
    for dp, size in zip(phys.dim_plans, phys.sizes[1:]):
        ids.append(np.asarray(dp.ids(sub_env, consts, np), np.int64))
        radix.append(size)
    if ids:
        key, _ = build_group_key(ids, radix, np)
        key = np.asarray(key, np.int64)
    else:
        key = np.zeros(len(rows_idx), np.int64)

    total = phys.total_groups
    kept_rows = np.nonzero(keep)[0][rows_idx]
    vexprs = {v.name: v.expression for v in query.virtual_columns}
    out: dict = {}
    rows_w = data.rows[kept_rows]
    acc = np.zeros(total, rows_w.dtype)
    np.add.at(acc, key, rows_w)
    out["_rows"] = acc
    from tpu_olap.kernels.groupby import _ident
    for a, p in zip(query.aggregations, phys.agg_plans):
        if p.name in out:
            continue  # deduped spelling of an already-folded partial
        sa, vals, nn, sketch = data.aggs[agg_signature(a, vexprs)]
        if p.kind in ("count", "sum"):
            accv = np.zeros(total, p.acc_dtype)
            np.add.at(accv, key, vals[kept_rows].astype(p.acc_dtype))
            out[p.name] = accv
        elif p.kind in ("min", "max"):
            accv = np.full(total, _ident(p.acc_dtype, p.kind),
                           p.acc_dtype)
            red = np.minimum if p.kind == "min" else np.maximum
            red.at(accv, key, vals[kept_rows].astype(p.acc_dtype))
            out[p.name] = accv
        elif p.kind == "hll":
            regs = np.zeros((total, NUM_REGISTERS), np.int32)
            np.maximum.at(regs, key,
                          sketch[kept_rows].astype(np.int32))
            out[p.name] = regs
        elif p.kind == "theta":
            out[p.name] = _theta_fold(sketch[kept_rows], key, total,
                                      p.theta_k)
        if nn is not None and p.kind in ("sum", "min", "max"):
            accn = np.zeros(total, np.int32)
            np.add.at(accn, key, nn[kept_rows].astype(np.int32))
            out[f"_nn_{p.name}"] = accn
    return out, len(rows_idx)


def _delta_fold_reason(phys, delta_ids, config) -> str | None:
    """None when the delta remainder can fold through the base path
    (QueryRunner._run_seg_partials), else why the cube must refuse —
    the same shape guards the tier-1 segment cache applies."""
    if phys.key_fn is None:
        return "delta fold needs a dense key_fn plan"
    radix = partials_radix(phys.agg_plans)
    W = max(delta_ids) - min(delta_ids) + 1
    if W * phys.total_groups * radix \
            > config.segment_cache_state_budget:
        return (f"delta fold state {W}x{phys.total_groups}x{radix} "
                "exceeds segment_cache_state_budget")
    if W * phys.total_groups >= (1 << 31):
        return "delta fold key space overflows int32"
    return None


def _merge_delta_partials(engine, runner, phys, partials, delta_ids,
                          table_name):
    """Compute the delta segments' partials on the device (one pass,
    per-segment keyed — QueryRunner._run_seg_partials, the machinery
    the tier-1 cache already trusts) and merge them into the cube's
    sealed-scope fold. Runs under its own admission slot: the cube
    serve path never entered QueryRunner.execute, and background-vs-
    foreground fairness must hold for the delta dispatch too."""
    import functools as _ft

    from tpu_olap.kernels.groupby import merge_partials

    dmet: dict = {}
    with runner.admission.slot(engine.config.query_deadline_s):
        runner.breaker.check()
        fresh = runner._dispatch(
            lambda: runner._run_seg_partials(phys, dmet,
                                             sorted(delta_ids)),
            dmet, table_name)
    dparts = _ft.reduce(
        lambda a, b: merge_partials(a, b, phys.agg_plans),
        fresh.values())
    return merge_partials(partials, dparts, phys.agg_plans), \
        int(dmet.get("rows_scanned") or 0)


def try_serve_cube(engine, plan_result):
    """Serve `plan_result.query` from the smallest covering cube, or
    return None (the caller proceeds to the base-table device path).
    Never raises: any internal failure counts as `error` and falls
    through — cube serving must uphold the engine's structural
    'never an error' property."""
    registry = engine.cubes
    query = plan_result.query
    entry = plan_result.entry
    if not isinstance(query, _AGG_TYPES) or entry is None \
            or not entry.is_accelerated:
        return None
    from tpu_olap.obs.workload import in_introspection
    if in_introspection():
        return None
    table = entry.segments
    # SEALED-scope generation (docs/INGEST.md): a cube is current as
    # long as the sealed set it was built from is — delta-only appends
    # do not stale it; their rows fold through the base path below
    candidates = registry.serveable(entry.name, table.sealed_generation)
    if not candidates:
        # distinguish "stale only" from "nothing registered" so an
        # operator can see invalidation working in /metrics
        if any(e.spec.datasource == entry.name and e.ready
               for e in map(registry.get, registry.names())
               if e is not None):
            registry.count_request("stale")
        else:
            registry.count_request("no_cube")
        return None
    t0 = time.perf_counter()
    runner = engine.runner
    try:
        from tpu_olap.executor.resultcache import _config_sig
        with _span("cube-rewrite") as sp:
            # tier-2 first: an identical repeat is cheaper as a cache
            # hit than a re-fold, and the PR 9 semantics stay primary
            hit = runner._serve_full_cache(query, table)
            if hit is not None:
                sp.set(served="result-cache")
                return hit
            phys = runner._lower_cached(query, table)
            cfg_sig = _config_sig(engine.config)
            reason = "no candidate"
            for cube, data, cube_cfg in candidates:
                if cube_cfg != cfg_sig:
                    reason = "result-affecting config changed"
                    continue
                reason = _covering_reason(query, phys, cube.spec, data,
                                          engine.config)
                if reason is not None:
                    continue
                keep = _interval_keep_mask(query, data)
                if keep is None:
                    reason = "intervals straddle a cube bucket"
                    continue
                # serve-cost bailout: the fold moves ~4x fewer rows/ms
                # than the pruned columnar scan (the config comment has
                # the measurement), so a cube that isn't a clear row-
                # count win would PESSIMIZE a query manifest pruning
                # already made cheap — leave those on the base path
                kept_n = int(np.count_nonzero(keep))
                min_red = float(
                    engine.config.cube_serve_min_reduction or 0.0)
                if min_red > 1.0:
                    base_rows = sum(
                        phys.table.segments[i].meta.n_valid
                        for i in phys.pruned_ids)
                    if kept_n * min_red > base_rows:
                        reason = (f"{kept_n} cube rows are not a "
                                  f">={min_red:g}x reduction of the "
                                  f"{base_rows}-row base scan")
                        continue
                # delta remainder (docs/INGEST.md): rows appended since
                # the sealed set the cube covers fold through the BASE
                # path — exact per-segment partials in this plan's own
                # layout (interval + WHERE handled by key_fn, the same
                # code the tier-1 cache trusts for straddlers), merged
                # with the cube's sealed-scope fold before finalize.
                # Zero stale serves by construction: sealed rows come
                # from the cube, delta rows from the live snapshot,
                # and the scopes are disjoint.
                delta_ids = [sid for sid in phys.pruned_ids
                             if not table.segment_sealed(sid)]
                if delta_ids:
                    reason = _delta_fold_reason(phys, delta_ids,
                                                engine.config)
                    if reason is not None:
                        continue
                env = _dim_env(phys, data, keep)
                fmask = _filter_mask(query, phys, env, kept_n)
                partials, scanned = _fold_partials(
                    query, phys, data, env, keep, fmask)
                if delta_ids:
                    partials, delta_scanned = _merge_delta_partials(
                        engine, runner, phys, partials, delta_ids,
                        entry.name)
                    scanned += delta_scanned
                res = _finish(runner, query, phys, partials)
                sp.set(cube=cube.spec.name, cube_rows_scanned=scanned,
                       delta_segments=len(delta_ids))
                registry.note_serve(cube)
                registry.count_request("served")
                m = {"query_type": query.query_type,
                     "datasource": entry.name,
                     "cube": cube.spec.name,
                     "cube_rows": data.n_rows,
                     "rows_scanned": int(scanned),
                     "delta_segments": len(delta_ids),
                     "segments_scanned": len(delta_ids),
                     "segments_total": len(table.segments),
                     "cache_hit": False,
                     "rows_returned": len(res.rows),
                     "_wl": runner.fingerprint(query, entry.name),
                     "total_ms": (time.perf_counter() - t0) * 1000}
                res.metrics = m
                fp = m.get("_wl")
                runner.record(m)
                runner._store_full_cache(query, table, res, fp)
                return res
            sp.set(refused=reason)
            registry.count_request("refused")
            return None
    except Exception:  # noqa: BLE001 — base path answers instead
        registry.count_request("error")
        return None


def _finish(runner, query, phys, partials):
    """Partials -> QueryResult through the device path's own tail."""
    from tpu_olap.executor.results import (agg_specs_by_name,
                                           eval_post_aggs,
                                           finalize_aggs,
                                           theta_raw_fields)
    specs = agg_specs_by_name(query.aggregations)
    keep_raw = theta_raw_fields(query.post_aggregations)
    with _span("finalize"):
        arrays = finalize_aggs(partials, phys.agg_plans, specs,
                               keep_raw)
    with _span("post-agg"):
        eval_post_aggs(arrays, query.post_aggregations)
    with _span("assemble"):
        return runner._assemble_agg(query, phys, arrays)
