"""DruidPlanner analog: SELECT statement -> QuerySpec (or fallback).

Implements the reference's rewrite pipeline in its order (SURVEY.md §4.2):
join collapse against the declared star schema, projection/filter pushdown
with interval extraction (IntervalConditionExtractor), aggregate
translation (AVG -> sum/count post-agg, COUNT DISTINCT -> HLL cardinality,
sum over expressions -> virtual columns), and limit/topN selection
(allowTopN). Any non-expressible construct raises RewriteError, which the
engine turns into transparent pandas-fallback execution — never an error
(SURVEY.md §2 property 2).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from tpu_olap.catalog.catalog import TableEntry
from tpu_olap.ir import filters as F
from tpu_olap.ir.aggregations import (CardinalityAggregation,
                                      CountAggregation, MaxAggregation,
                                      MinAggregation, SumAggregation,
                                      ThetaSketchAggregation)
from tpu_olap.ir.dimensions import (DefaultDimensionSpec,
                                    ExtractionDimensionSpec,
                                    TimeFormatExtractionFn, VirtualColumn)
from tpu_olap.ir.expr import BinOp, Col, Expr, FuncCall, Lit
from tpu_olap.ir.granularity import AllGranularity, PeriodGranularity
from tpu_olap.ir.interval import ETERNITY, Interval
from tpu_olap.ir.limit import LimitSpec, OrderByColumnSpec
from tpu_olap.ir.having import (AndHaving, EqualToHaving, GreaterThanHaving,
                                LessThanHaving, NotHaving, OrHaving)
from tpu_olap.ir.postaggs import (ArithmeticPostAgg, ConstantPostAgg,
                                  FieldAccessPostAgg)
from tpu_olap.ir.query import (GroupByQuerySpec, ScanQuerySpec,
                               TimeseriesQuerySpec, TopNQuerySpec)
from tpu_olap.planner.exprutil import (contains_agg as _contains_agg,
                                       expr_key as _key, render as _render,
                                       split_and as _split_and)
from tpu_olap.planner.sqlparse import (AGG_FUNCS, OrderItem, SelectStmt,
                                       parse_sql)
from tpu_olap.segments.segment import ColumnType, TIME_COLUMN
from tpu_olap.utils import timeutil


class RewriteError(Exception):
    """Query shape not expressible on the device path -> fallback."""


_CMP = ("==", "!=", "<", "<=", ">", ">=")
_TIME_FUNCS = {"year": ("YYYY", "int"), "month": ("MM", "int"),
               "day": ("dd", "int"), "dayofmonth": ("dd", "int"),
               "quarter": ("Q", "int"), "hour": ("HH", "int"),
               "minute": ("mm", "int"), "second": ("ss", "int")}
_TRUNC_UNITS = {"second": "PT1S", "minute": "PT1M", "hour": "PT1H",
                "day": "P1D", "week": "P1W", "month": "P1M",
                "quarter": "P3M", "year": "P1Y"}
# scalar functions the device expression evaluator implements
# (kernels.exprs._call) — anything else in a virtual column or expression
# filter must fall back BEFORE dispatch, not die inside the kernel
_DEVICE_FUNCS = {"abs", "floor", "ceil", "sqrt", "log", "exp", "pow", "if",
                 "min", "max", "least", "greatest", "cast_long",
                 "cast_double"}


@dataclass
class OutputColumn:
    name: str           # SQL output name
    source: str         # key in executor result rows
    cast: str | None = None  # None | "int" | "datetime"


@dataclass
class PlanResult:
    stmt: SelectStmt
    entry: TableEntry
    query: object = None            # QuerySpec when rewritten
    outputs: list = field(default_factory=list)
    fallback_reason: str | None = None
    sql: str | None = None
    # set when the device circuit breaker (resilience.breaker) rerouted
    # this statement to the interpreter: the record stamps
    # path="fallback_breaker" so degraded serving is visible
    breaker_fallback: bool = False

    @property
    def rewritten(self) -> bool:
        return self.query is not None

    def explain(self) -> dict:
        """The `EXPLAIN DRUID REWRITE` payload (SURVEY.md §4.5)."""
        if self.rewritten:
            return {"rewritten": True, "datasource": self.entry.name,
                    "query": self.query.to_json(),
                    "outputs": [o.name for o in self.outputs]}
        return {"rewritten": False, "reason": self.fallback_reason,
                "table": self.entry.name if self.entry is not None
                else self.stmt.table}


def _outside_subset(stmt) -> str | None:
    """'subquery' / 'window function' when the statement contains a
    construct the rewrite rules don't cover, else None."""
    from tpu_olap.ir.expr import Subquery, WindowCall

    def walk(e):
        if isinstance(e, Subquery):
            return "subquery"
        if isinstance(e, WindowCall):
            return "window function"
        if isinstance(e, BinOp):
            return walk(e.left) or walk(e.right)
        if isinstance(e, FuncCall):
            if e.name == "in_subquery":
                return "subquery"
            for a in e.args:
                r = walk(a)
                if r:
                    return r
        return None

    exprs = ([e for e, _ in stmt.projections] + stmt.group_by
             + [stmt.where, stmt.having]
             + [o.expr for o in stmt.order_by]
             + [j.on for j in stmt.joins])
    for e in exprs:
        if e is not None:
            r = walk(e)
            if r:
                return r
    return None


_FALLBACK_FUNCS = ("corr_scalar_map", "corr_exists_map", "corr_in_map",
                   "corr_exists_cmp_map")


def _scan_stmt_nodes(stmt):
    """One traversal over every expression-bearing clause (via
    map_stmt_exprs, the shared walker — incl. grouping_sets) collecting
    what subquery inlining needs to know up front: nested SELECTs,
    window-function presence (inlining would be discarded, so don't
    execute anything), and decorrelated corr_* map nodes (only the
    fallback evaluator applies those). Returns (substmts, has_window,
    has_corr_nodes)."""
    from tpu_olap.ir.expr import Subquery, WindowCall
    from tpu_olap.planner.exprutil import map_stmt_exprs
    subs: list = []
    flags = {"window": False, "corr": False}

    def visit(e):
        if isinstance(e, Subquery):
            subs.append(e.stmt)
        elif isinstance(e, WindowCall):
            flags["window"] = True
            for a in e.args:
                visit(a)
            for p in e.partition_by:
                visit(p)
            for oe, _ in e.order_by:
                visit(oe)
        elif isinstance(e, BinOp):
            visit(e.left)
            visit(e.right)
        elif isinstance(e, FuncCall):
            if e.name in _FALLBACK_FUNCS:
                flags["corr"] = True
            for a in e.args:
                visit(a)
        return e

    map_stmt_exprs(stmt, visit)
    return subs, flags["window"], flags["corr"]


def _apply_windows_over_groups(stmt):
    """Recursive application of the grouped-window rewrite: union parts,
    derived tables (incl. inlined CTEs), and join subqueries each get
    the same treatment as the top-level statement."""
    from tpu_olap.planner.sqlparse import UnionStmt
    if isinstance(stmt, UnionStmt):
        stmt.parts = [_apply_windows_over_groups(p) for p in stmt.parts]
        return stmt
    if stmt.derived is not None:
        stmt.derived = _apply_windows_over_groups(stmt.derived)
    for j in stmt.joins:
        if j.derived is not None:
            j.derived = _apply_windows_over_groups(j.derived)
    return _windows_over_groups(stmt)


def _windows_over_groups(stmt):
    """Standard SQL evaluates window functions AFTER grouping, over the
    grouped rows. The fallback interpreter already evaluates windows
    over derived tables, so a grouped query containing a window rewrites
    to exactly that: an inner SELECT doing the grouping (group keys +
    every aggregate the outer mentions, auto-named), and an outer SELECT
    evaluating the windows over it. `SELECT cat, rank() OVER (ORDER BY
    sum(p) DESC) FROM t GROUP BY cat` becomes `SELECT cat, rank() OVER
    (ORDER BY __a0 DESC) FROM (SELECT cat, sum(p) AS __a0 ... GROUP BY
    cat)`. (The reference served these through Spark SQL, SURVEY.md
    §3.1.)"""
    from tpu_olap.ir.expr import WindowCall
    from tpu_olap.planner.exprutil import contains_window
    from tpu_olap.planner.sqlparse import AGG_FUNCS, SelectStmt

    outer_exprs = [p for p, _ in stmt.projections] \
        + [o.expr for o in stmt.order_by]
    if not stmt.group_by or not any(contains_window(e)
                                    for e in outer_exprs):
        return stmt

    aggs: dict = {}  # expr key -> FuncCall

    def collect(e):
        if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
            aggs.setdefault(_key(e), e)
            return
        if isinstance(e, BinOp):
            collect(e.left)
            collect(e.right)
        elif isinstance(e, WindowCall):
            for a in e.args:
                collect(a)
            for p in e.partition_by:
                collect(p)
            for oe, _ in e.order_by:
                collect(oe)
        elif isinstance(e, FuncCall):
            for a in e.args:
                collect(a)

    for e in outer_exprs:
        collect(e)

    # inner projections: group keys first (plain Cols keep their name,
    # computed keys get stable synthetic names), then the aggregates
    sub: dict = {}  # expr key -> replacement Col
    inner_proj = []
    for i, g in enumerate(stmt.group_by):
        name = g.name if isinstance(g, Col) else f"__g{i}"
        inner_proj.append((g, None if isinstance(g, Col) else name))
        sub[_key(g)] = Col(name)
    for j, (k, a) in enumerate(sorted(aggs.items())):
        inner_proj.append((a, f"__a{j}"))
        sub[k] = Col(f"__a{j}")

    from tpu_olap.ir.expr import map_expr

    def rewrite(e):
        return map_expr(e, lambda x: sub.get(_key(x)))

    inner = SelectStmt(
        projections=inner_proj, table=stmt.table, joins=stmt.joins,
        where=stmt.where, group_by=stmt.group_by, having=stmt.having,
        table_alias=stmt.table_alias, grouping_sets=stmt.grouping_sets,
        derived=stmt.derived)
    outer = SelectStmt(
        # unaliased projections keep the ORIGINAL expression's rendered
        # name — the rewritten tree would leak __a0/__g0 into headers
        projections=[(rewrite(p), alias or _render(p))
                     for p, alias in stmt.projections],
        table="__winagg", derived=inner, distinct=stmt.distinct,
        limit=stmt.limit, offset=stmt.offset)
    for o in stmt.order_by:
        o.expr = rewrite(o.expr)
    outer.order_by = stmt.order_by
    return outer


class DruidPlanner:
    """Registers no global state — one instance per Engine (the reference's
    DruidPlanner(sqlContext) kept per-session rule lists, SURVEY.md §3.2)."""

    def __init__(self, catalog, config):
        self.catalog = catalog
        self.config = config
        # stmt -> DataFrame executor the Engine wires in: lets the
        # planner evaluate uncorrelated subqueries eagerly (device path
        # when rewritable) so the OUTER query can still push down
        self.run_subquery = None

    def plan(self, sql: str) -> PlanResult:
        return self.plan_stmt(parse_sql(sql), sql)

    def _scope_columns(self, stmt) -> set:
        """Source column names visible to this statement's GROUP BY /
        ORDER BY: base/join tables from the catalog (footer-cheap) plus
        derived-table output names. Best-effort — an unknown table just
        contributes nothing, and alias substitution stays conservative
        (a name that might be a column is never treated as an alias)."""
        from tpu_olap.ir.expr import Col
        from tpu_olap.planner.sqlparse import UnionStmt
        cols: set = set()

        def add_derived(d):
            sel = d.parts[0] if isinstance(d, UnionStmt) else d
            for p, alias in sel.projections:
                if alias:
                    cols.add(alias)
                elif isinstance(p, Col):
                    cols.add(p.name)

        def add_entry(name):
            ent = self.catalog.maybe(name)
            if ent is not None:
                try:
                    cols.update(ent.column_names())
                except Exception:  # noqa: BLE001 — unreadable footer etc.
                    pass

        if stmt.derived is not None:
            add_derived(stmt.derived)
        elif stmt.table:
            add_entry(stmt.table)
        for j in stmt.joins:
            if j.derived is not None:
                add_derived(j.derived)
            else:
                add_entry(j.table)
        return cols

    def _resolve_aliases(self, stmt):
        """Apply output-alias resolution to a statement tree: each
        SELECT scope (union parts, derived tables, join subqueries)
        resolves against its own FROM columns."""
        from tpu_olap.planner.sqlparse import (UnionStmt,
                                               resolve_output_aliases)
        if isinstance(stmt, UnionStmt):
            for p in stmt.parts:
                self._resolve_aliases(p)
            return stmt
        if stmt.derived is not None:
            self._resolve_aliases(stmt.derived)
        for j in stmt.joins:
            if j.derived is not None:
                self._resolve_aliases(j.derived)
        # cheap early-out before touching catalog metadata: resolution
        # can only matter when some projection is aliased AND a
        # GROUP BY / ORDER BY clause exists to reference it
        if not ((stmt.group_by or stmt.order_by or stmt.grouping_sets)
                and any(alias for _, alias in stmt.projections)):
            return stmt
        return resolve_output_aliases(stmt, self._scope_columns(stmt))

    def plan_stmt(self, stmt, sql: str = "") -> PlanResult:
        # shapes outside the rewrite rules run on the fallback path (the
        # reference delegated them to full Spark SQL, SURVEY.md §3.1) —
        # declined here, never an error
        from tpu_olap.planner.exprutil import simplify_stmt
        from tpu_olap.planner.sqlparse import UnionStmt
        stmt = self._resolve_aliases(stmt)
        stmt = _apply_windows_over_groups(stmt)
        if not isinstance(stmt, UnionStmt):
            # normalize expressions once so the rewriter and the fallback
            # interpreter see the same tree (ExprUtil, SURVEY.md §3.2)
            stmt = simplify_stmt(stmt)
        if isinstance(stmt, UnionStmt):
            entry = self.catalog.maybe(stmt.table)
            return PlanResult(
                stmt=stmt, entry=entry, sql=sql,
                fallback_reason=f"{stmt.op.upper()} executes on the "
                                "fallback path")
        if stmt.derived is not None:
            return PlanResult(
                stmt=stmt, entry=None, sql=sql,
                fallback_reason="derived table (FROM subquery) executes "
                                "on the fallback path")
        outside = _outside_subset(stmt)
        if outside == "subquery" and self.run_subquery is not None:
            # the reference's architecture for this shape: Spark executed
            # the subquery, the rewritten outer query pushed to Druid
            # (SURVEY.md §3.1). Inline uncorrelated subquery results as
            # literals and try the device path for the outer query;
            # anything that doesn't fully inline keeps the fallback.
            alt = self._inline_uncorrelated(stmt)
            if alt is not None:
                entry = self.catalog.get(stmt.table)
                # the inlined statement is the one to keep for ANY
                # execution path: its subqueries already ran, so a
                # fallback after a failed outer rewrite replays literals
                # instead of re-executing the inner aggregates
                result = PlanResult(stmt=alt, entry=entry, sql=sql)
                try:
                    _Rewriter(self, alt, entry, result).run()
                    return result
                except RewriteError as e:
                    result.query = None
                    result.fallback_reason = str(e)
                    return result
        if outside is not None:
            return PlanResult(
                stmt=stmt, entry=self.catalog.get(stmt.table), sql=sql,
                fallback_reason=f"{outside} executes on the fallback path")
        entry = self.catalog.get(stmt.table)
        result = PlanResult(stmt=stmt, entry=entry, sql=sql)
        try:
            _Rewriter(self, stmt, entry, result).run()
        except RewriteError as e:
            result.query = None
            result.fallback_reason = str(e)
        return result

    def _inline_uncorrelated(self, stmt):
        """Execute every uncorrelated scalar/IN/EXISTS subquery via
        run_subquery and inline the results as literals. None when
        nothing inlined, the statement still carries subquery constructs
        (correlated shapes resolve to corr_* map nodes only the fallback
        evaluator understands), or resolution failed."""
        from tpu_olap.planner import fallback as fb
        from tpu_olap.planner.exprutil import simplify_stmt
        # pre-scan BEFORE any execution: a correlated member can only
        # resolve to corr_* map nodes we would discard, a window
        # function keeps the whole statement on the fallback anyway,
        # and _resolve_subqueries runs inner statements eagerly —
        # bailing here keeps that work single-execution
        subs, has_window, _ = _scan_stmt_nodes(stmt)
        if has_window or not subs:
            return None
        for sub in subs:
            if not fb._uncorrelated(sub):
                return None
        try:
            resolved = fb._resolve_subqueries(
                stmt, self.catalog, self.config, run=self.run_subquery)
        except fb.FallbackError:
            return None
        if resolved is stmt:
            return None
        resolved = simplify_stmt(resolved)
        if _outside_subset(resolved) is not None:
            return None
        _, _, has_corr = _scan_stmt_nodes(resolved)
        if has_corr:
            return None
        return resolved


class _Rewriter:
    def __init__(self, planner: DruidPlanner, stmt, entry, result):
        self.planner = planner
        self.catalog = planner.catalog
        self.config = planner.config
        self.stmt = stmt
        self.entry = entry
        self.result = result
        self.table = entry.segments
        self.rename: dict[str, str] = {}
        self.vcols: list[VirtualColumn] = []
        self.aggs: list = []
        self.postaggs: list = []
        self._agg_by_key: dict = {}
        self._names = (f"a{i}" for i in itertools.count())
        self.alias_of: dict = {}  # structural expr key -> SQL alias

    # ------------------------------------------------------------- pipeline

    def run(self):
        if not self.entry.is_accelerated:
            raise RewriteError(f"table {self.entry.name!r} is not "
                               "druid-backed (no segment index)")
        stmt = self.stmt
        if stmt.grouping_sets is not None:
            raise RewriteError(
                "GROUPING SETS/ROLLUP/CUBE execute on the fallback path")
        conjuncts = _split_and(stmt.where)
        conjuncts = self._collapse_joins(conjuncts)
        conjuncts = [self._resolve(e) for e in conjuncts]
        intervals, conjuncts = self._extract_intervals(conjuncts)
        filter_spec = None
        if conjuncts:
            filter_spec = F.and_of(*[self._to_filter(e) for e in conjuncts])

        group_exprs = [self._resolve(e) for e in stmt.group_by]
        projections = []
        for e, a in stmt.projections:
            r = self._resolve(e)
            if a is None and r != e and not (isinstance(e, Col)
                                             and "." in e.name):
                # star-join renames (r_name -> c_region) and time-column
                # mapping (ts -> __time) must not leak into the output
                # header: the column is named by what the user wrote
                a = _render(e)
            elif a is None and isinstance(e, Col) and "." in e.name:
                a = e.name.split(".")[-1]
            projections.append((r, a))
        if stmt.distinct:
            if self._has_agg(projections):
                raise RewriteError("SELECT DISTINCT with aggregates")
            if group_exprs:
                raise RewriteError("SELECT DISTINCT with GROUP BY")
            group_exprs = [e for e, _ in projections]

        for e, a in projections:
            if a is not None:
                self.alias_of[_key(e)] = a

        if not group_exprs and not self._has_agg(projections):
            return self._build_scan(projections, filter_spec, intervals)
        return self._build_agg(projections, group_exprs, filter_spec,
                               intervals)

    # ---------------------------------------------------------------- joins

    def _collapse_joins(self, conjuncts):
        """JoinTransform (SURVEY.md §4.3): every joined table must be a
        declared star dimension whose FK edge appears as an equi-join
        condition AND whose fact-side linking column is derivable from the
        denormalized fact — directly (a fact column), through an earlier
        collapsed dimension (snowflake dim⋈dim chains), or through the
        declared FunctionalDependencies' closure (SURVEY.md §3.4: the
        reference validates the join tree against StarSchema FK chains +
        FDs). Dim columns then rename to fact columns."""
        stmt = self.stmt
        if not stmt.joins:
            return conjuncts
        if any(j.using is not None for j in stmt.joins):
            raise RewriteError("USING joins execute on the fallback path")
        if any(j.derived is not None for j in stmt.joins):
            raise RewriteError("derived table / CTE in JOIN position "
                               "executes on the fallback path")
        star = self.entry.star
        if star is None:
            raise RewriteError("join query but no star schema declared")
        conjuncts = list(conjuncts)
        # columns derivable from the denormalized fact row, in bare-name
        # space (grows as dimensions collapse — chain joins link through
        # earlier dims' columns)
        known = set(self.table.schema)
        if self.entry.time_column:
            known.add(self.entry.time_column)
        known = star.fd_closure(known)

        def collapse(j):
            """Collapse one join into (renames, new conjuncts); returns an
            error string when the join cannot collapse YET (it may become
            collapsible after another dimension provides its link)."""
            nonlocal conjuncts, known
            sd = star.dim(j.table)
            if sd is None:
                raise RewriteError(
                    f"joined table {j.table!r} is not a declared star "
                    "dimension")
            cand = _split_and(j.on) if j.on is not None else conjuncts
            found = None
            for c in cand:
                pair = _equi_join_cols(c)
                if pair and star.matches_join(j.table, *pair):
                    found = c
                    break
            if found is None:
                return f"no FK join condition for star dimension {j.table!r}"
            if sd.fact_key not in known:
                return (
                    f"join to {j.table!r} is not subsumed by the star "
                    f"schema: linking column {sd.fact_key!r} is not on "
                    "the fact table, not provided by another collapsed "
                    "dimension, and not implied by any declared "
                    "functional dependency")
            if j.on is not None:
                conjuncts.extend(
                    c for c in _split_and(j.on) if c is not found)
            else:
                conjuncts.remove(found)
            # rename dim columns -> denormalized fact columns; every dim
            # column (mapped or not) joins the known set so snowflake
            # chains can link through it
            dim_entry = self.catalog.maybe(j.table)
            dim_cols = (list(dim_entry.frame.columns)
                        if dim_entry is not None else [])
            known.add(sd.dim_key)
            for c in dim_cols:
                known.add(c)
                fact_col = sd.fact_column(c)
                if fact_col in self.table.schema or \
                        fact_col == self.entry.time_column:
                    self.rename[c] = fact_col
                    self.rename[f"{j.table}.{c}"] = fact_col
            known = star.fd_closure(known)
            return None

        # fixed point over the join list: SQL join order need not follow
        # the chain direction (the reference walks the whole tree too)
        pending = list(stmt.joins)
        for j in pending:
            if j.kind != "inner":
                raise RewriteError(f"{j.kind} join not collapsible")
        while pending:
            errors = []
            still = []
            for j in pending:
                err = collapse(j)
                if err is not None:
                    errors.append(err)
                    still.append(j)
            if len(still) == len(pending):  # no progress
                raise RewriteError(errors[0])
            pending = still
        return conjuncts

    # ---------------------------------------------------- column resolution

    def _resolve(self, e: Expr) -> Expr:
        if e is None:
            return None
        if isinstance(e, Col):
            name = e.name
            if "." in name:
                qual, base = name.split(".", 1)
                if qual == self.entry.name:
                    name = base
                elif name in self.rename:
                    name = self.rename[name]
                else:
                    name = base
            name = self.rename.get(name, name)
            if name == self.entry.time_column:
                name = TIME_COLUMN
            return Col(name)
        if isinstance(e, BinOp):
            return BinOp(e.op, self._resolve(e.left), self._resolve(e.right))
        if isinstance(e, FuncCall):
            return FuncCall(e.name, tuple(self._resolve(a) for a in e.args))
        return e

    def _check_col(self, name: str) -> str:
        if name == "*":
            raise RewriteError("* not valid here")
        if name not in self.table.schema:
            raise RewriteError(f"unknown column {name!r}")
        return name

    def _col_type(self, name: str):
        return self.table.schema[self._check_col(name)]

    # ----------------------------------------------------- interval extract

    def _extract_intervals(self, conjuncts):
        """IntervalConditionExtractor analog (SURVEY.md §3.2): conjuncts
        over the time column become query intervals. A conjunct that is an
        OR of pure time ranges becomes a multi-interval list (the SQL
        spelling of Druid's interval arrays) — intervals across conjuncts
        intersect pairwise, and overlapping results coalesce."""
        sets = []  # each conjunct's interval alternatives (OR = union)
        rest = []
        for c in conjuncts:
            got = self._time_condition(c)
            if got is not None:
                sets.append([got])
                continue
            alts = self._or_intervals(c)
            if alts is not None:
                sets.append(alts)
                continue
            if _mentions_time_fn(c):
                raise RewriteError(
                    f"time condition not extractable: {c!r}")
            rest.append(c)
        acc = [ETERNITY]
        for s in sets:
            acc = [x for a in acc for b in s
                   if (x := a.intersect(b)) is not None]
            if not acc:
                acc = [Interval(0, 0)]
                break
        acc.sort(key=lambda iv: iv.start)
        merged = []
        for iv in acc:
            if merged and iv.start <= merged[-1].end:
                if iv.end > merged[-1].end:
                    merged[-1] = Interval(merged[-1].start, iv.end)
            else:
                merged.append(iv)
        intervals = () if merged == [ETERNITY] else tuple(merged)
        return intervals, rest

    def _or_intervals(self, e):
        """Intervals for a disjunction of pure time ranges (each branch
        may be an AND of time conditions); None when any branch involves
        non-time predicates."""
        if isinstance(e, BinOp) and e.op == "||":
            left = self._or_intervals(e.left)
            right = self._or_intervals(e.right)
            if left is None or right is None:
                return None
            return left + right
        iv = None
        for p in _split_and(e):
            got = self._time_condition(p)
            if got is None:
                return None
            if iv is None:
                iv = got
            else:
                x = iv.intersect(got)
                iv = x if x is not None else Interval(0, 0)
        return [iv] if iv is not None else None

    def _time_condition(self, e) -> Interval | None:
        if not isinstance(e, BinOp) or e.op not in _CMP:
            return None
        left, right = e.left, e.right
        op = e.op
        if isinstance(right, (Col, FuncCall)) and isinstance(left, Lit):
            left, right = right, left
            op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
        if not isinstance(right, Lit):
            return None
        # year(__time) CMP N
        if isinstance(left, FuncCall) and left.name == "year" and \
                len(left.args) == 1 and left.args[0] == Col(TIME_COLUMN) and \
                isinstance(right.value, int):
            y = right.value
            lo = timeutil.date_to_millis(y)
            hi = timeutil.date_to_millis(y + 1)
            return {"==": Interval(lo, hi),
                    "<": Interval(-(2**62), lo),
                    "<=": Interval(-(2**62), hi),
                    ">": Interval(hi, 2**62),
                    ">=": Interval(lo, 2**62)}.get(op)
        # __time CMP 'date literal' / epoch-millis number
        if left == Col(TIME_COLUMN):
            v = right.value
            if isinstance(v, str):
                try:
                    ms = timeutil.parse_iso_datetime(v)
                except ValueError:
                    return None
            elif isinstance(v, (int, float)):
                ms = int(v)
            else:
                return None
            return {"==": Interval(ms, ms + 1),
                    "<": Interval(-(2**62), ms),
                    "<=": Interval(-(2**62), ms + 1),
                    ">": Interval(ms + 1, 2**62),
                    ">=": Interval(ms, 2**62)}.get(op)
        return None

    # -------------------------------------------------------------- filters

    def _to_filter(self, e) -> F.FilterSpec:
        if isinstance(e, Lit):
            # constant predicates appear when subquery inlining folds
            # e.g. EXISTS(...) to TRUE/FALSE
            if e.value:
                return None  # and_of drops the no-op conjunct
            raise RewriteError("statically false predicate")
        if isinstance(e, BinOp) and e.op == "&&":
            return F.and_of(self._to_filter(e.left), self._to_filter(e.right))
        if isinstance(e, BinOp) and e.op == "||":
            return F.OrFilter((self._to_filter(e.left),
                               self._to_filter(e.right)))
        if isinstance(e, FuncCall) and e.name == "not":
            return F.NotFilter(self._to_filter(e.args[0]))
        if isinstance(e, FuncCall) and e.name == "is_null":
            col = self._filter_col(e.args[0])
            return F.SelectorFilter(col, None)
        if isinstance(e, FuncCall) and e.name == "in_list":
            vals = []
            for a in e.args[1:]:
                if not isinstance(a, Lit):
                    raise RewriteError("non-literal IN list")
                vals.append(a.value)
            if not isinstance(e.args[0], Col):
                # extraction IN: upper(g) IN (...) -> in filter with an
                # extractionFn (one predicate table, one device gather)
                ext = self._extraction_of(e.args[0])
                if ext is not None:
                    col, fn = ext
                    return F.InFilter(col, tuple(vals), fn)
            col = self._filter_col(e.args[0])
            return F.InFilter(col, tuple(vals))
        if isinstance(e, FuncCall) and e.name == "in_list_packed":
            # inlined IN-subquery result: one Lit holding every value
            vals = tuple(e.args[1].value)
            lhs = e.args[0]
            if not isinstance(lhs, Col):
                ext = self._extraction_of(lhs)
                if ext is not None:
                    col, fn = ext
                    return F.InFilter(col, vals, fn)
            col = self._filter_col(lhs)
            if self._col_type(col) is not ColumnType.STRING \
                    and len(vals) > 8192:
                # numeric in-lists broadcast rows x values on the device;
                # string lists compile to a dictionary-sized table and
                # have no such limit
                raise RewriteError(
                    f"packed numeric IN list of {len(vals)} values "
                    "exceeds the device broadcast budget")
            return F.InFilter(col, vals)
        if isinstance(e, FuncCall) and e.name == "like":
            col = self._filter_col(e.args[0])
            pat = e.args[1]
            if not isinstance(pat, Lit) or not isinstance(pat.value, str):
                raise RewriteError("LIKE pattern must be a string literal")
            if self._col_type(col) is not ColumnType.STRING:
                raise RewriteError(f"LIKE over non-string column {col!r}")
            return F.LikeFilter(col, pat.value)
        if isinstance(e, BinOp) and e.op in _CMP:
            left, right, op = e.left, e.right, e.op
            if isinstance(left, Lit) and (isinstance(right, Col) or
                                          isinstance(right, FuncCall)):
                left, right = right, left
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
            if isinstance(right, Lit) and right.value is None:
                # comparison with a NULL literal (e.g. an empty scalar
                # subquery inlined as Lit(None)) matches no rows — the
                # fallback's guard rule. SelectorFilter(col, None) would
                # read it as IS NULL; IS NULL itself arrives as the
                # is_null FuncCall, not a comparison.
                raise RewriteError(
                    "comparison with NULL literal matches no rows")
            if isinstance(right, Lit) and op in ("==", "!="):
                ext = self._extraction_of(left)
                if ext is not None:
                    col, fn = ext
                    f = F.SelectorFilter(col, right.value, fn)
                    return F.NotFilter(f) if op == "!=" else f
            if isinstance(right, Lit) and isinstance(right.value, str) \
                    and op in ("<", "<=", ">", ">="):
                # range over an extraction: substr(c, 1, 2) BETWEEN ...
                ext = self._extraction_of(left)
                if ext is not None:
                    col, fn = ext
                    v = right.value
                    if op in ("<", "<="):
                        return F.BoundFilter(
                            col, upper=v, upper_strict=(op == "<"),
                            extraction_fn=fn)
                    return F.BoundFilter(
                        col, lower=v, lower_strict=(op == ">"),
                        extraction_fn=fn)
            if isinstance(left, Col) and isinstance(right, Col):
                ca = self._check_col(left.name)
                cb = self._check_col(right.name)
                sa = self._col_type(ca) is ColumnType.STRING
                sb = self._col_type(cb) is ColumnType.STRING
                if sa != sb:
                    raise RewriteError(
                        f"comparison between string and numeric columns "
                        f"({ca!r}, {cb!r})")
                # row-vs-row equality: the columnComparison filter
                # (TPC-H Q5/Q7 `c_nation = s_nation`); <> composes as
                # NOT, under which NULL rows match — same as the
                # fallback's pandas semantics. Numeric pairs take the
                # same filter (not ExpressionFilter) so they stay
                # Pallas-eligible; ordered numeric comparisons fall
                # through to the expression path below.
                if op == "==":
                    return F.ColumnComparisonFilter((ca, cb))
                if op == "!=":
                    return F.NotFilter(F.ColumnComparisonFilter((ca, cb)))
                if sa:
                    raise RewriteError(
                        "ordered comparison between string columns")
            if op == "!=":
                # general `a <> b` must lower as NOT(a = b): a bare
                # ExpressionFilter(!=) would exclude NULL operands
                # (boolean leaf rule) while the fallback's pandas
                # `NaN != x` is True — NOT(==) matches the fallback
                inner = self._to_filter(BinOp("==", left, right))
                return F.NotFilter(inner)
            if isinstance(left, Col) and isinstance(right, Lit):
                col = self._check_col(left.name)
                v = right.value
                typ = self._col_type(col)
                ordering = ("lexicographic"
                            if typ is ColumnType.STRING
                            and isinstance(v, str) else "numeric")
                if op == "==":
                    return F.SelectorFilter(col, v)
                if op == "!=":
                    return F.NotFilter(F.SelectorFilter(col, v))
                if op in ("<", "<="):
                    return F.BoundFilter(col, upper=v,
                                         upper_strict=(op == "<"),
                                         ordering=ordering)
                return F.BoundFilter(col, lower=v,
                                     lower_strict=(op == ">"),
                                     ordering=ordering)
            # general expression comparison
            return self._expression_filter(e)
        raise RewriteError(f"cannot translate predicate {e!r}")

    def _filter_col(self, e) -> str:
        if not isinstance(e, Col):
            raise RewriteError(f"expected a column, got {e!r}")
        return self._check_col(e.name)

    def _expression_filter(self, e) -> F.FilterSpec:
        _check_device_expr(e)
        for c in e.columns():
            if self._col_type(c) is ColumnType.STRING:
                raise RewriteError(
                    f"expression predicate over string column {c!r}")
        return F.ExpressionFilter(e)

    def _extraction_of(self, e) -> tuple[str, object] | None:
        """substr/substring/regexp_extract over a string column with
        literal args -> (column, ExtractionFunctionSpec) — the SQL
        spelling of the reference's extraction dimensions/filters
        (SURVEY.md §3.3)."""
        from tpu_olap.ir.dimensions import (RegexExtractionFn,
                                            SubstringExtractionFn)
        if not (isinstance(e, FuncCall) and e.args
                and isinstance(e.args[0], Col)):
            return None
        if e.name in ("substr", "substring") and len(e.args) in (2, 3) \
                and all(isinstance(a, Lit) for a in e.args[1:]):
            col = self._check_col(e.args[0].name)
            if self._col_type(col) is not ColumnType.STRING:
                raise RewriteError(
                    f"{e.name} over non-string column {col!r}")
            start = int(e.args[1].value)
            if start < 1:
                raise RewriteError("substr start index is 1-based")
            length = int(e.args[2].value) if len(e.args) == 3 else None
            return col, SubstringExtractionFn(start - 1, length)
        if e.name in ("upper", "lower") and len(e.args) == 1:
            from tpu_olap.ir.dimensions import CaseExtractionFn
            col = self._check_col(e.args[0].name)
            if self._col_type(col) is not ColumnType.STRING:
                raise RewriteError(
                    f"{e.name} over non-string column {col!r}")
            return col, CaseExtractionFn(e.name)
        if e.name == "regexp_extract" and len(e.args) == 2 and \
                isinstance(e.args[1], Lit) and isinstance(e.args[1].value,
                                                          str):
            col = self._check_col(e.args[0].name)
            if self._col_type(col) is not ColumnType.STRING:
                raise RewriteError(
                    f"regexp_extract over non-string column {col!r}")
            return col, RegexExtractionFn(e.args[1].value)
        if e.name == "lookup_map" and len(e.args) == 2 and \
                isinstance(e.args[1], Lit):
            # subquery resolution inlines lookup() as lookup_map with the
            # mapping items baked in; same extraction, no catalog read
            from tpu_olap.ir.dimensions import LookupExtractionFn
            col = self._check_col(e.args[0].name)
            if self._col_type(col) is not ColumnType.STRING:
                raise RewriteError(
                    f"lookup over non-string column {col!r}")
            return col, LookupExtractionFn(tuple(e.args[1].value))
        if e.name == "lookup" and len(e.args) == 2 and \
                isinstance(e.args[1], Lit) and isinstance(e.args[1].value,
                                                          str):
            from tpu_olap.ir.dimensions import LookupExtractionFn
            lname = e.args[1].value
            mapping = self.catalog.lookups.get(lname)
            if mapping is None:
                raise RewriteError(f"unknown lookup {lname!r}")
            col = self._check_col(e.args[0].name)
            if self._col_type(col) is not ColumnType.STRING:
                raise RewriteError(
                    f"lookup over non-string column {col!r}")
            return col, LookupExtractionFn(tuple(mapping.items()))
        return None

    # ----------------------------------------------------------- aggregates

    def _has_agg(self, projections) -> bool:
        return any(_contains_agg(e) for e, _ in projections)

    def _name_for(self, e) -> str:
        return self.alias_of.get(_key(e)) or next(self._names)

    def _vcol_for(self, e: Expr) -> tuple[str, str]:
        """Expression -> (virtual column name, value type)."""
        _check_device_expr(e)
        for c in e.columns():
            if self._col_type(c) is ColumnType.STRING:
                raise RewriteError(f"aggregate over string column {c!r}")
        vt = "long"
        for c in e.columns():
            if self.table.schema[c] is ColumnType.DOUBLE:
                vt = "double"
        if _has_division(e) or _has_float_lit(e) or _has_cast_double(e):
            vt = "double"
        for v in self.vcols:
            if v.expression == e:
                return v.name, v.output_type
        name = f"v{len(self.vcols)}"
        self.vcols.append(VirtualColumn(name, e, vt))
        return name, vt

    def _agg_field(self, e: Expr) -> tuple[str, str]:
        """Aggregate input -> (field name, "long"|"double")."""
        if isinstance(e, Col):
            col = self._check_col(e.name)
            typ = self._col_type(col)
            if typ is ColumnType.STRING:
                raise RewriteError(f"aggregate over string column {col!r}")
            return col, ("double" if typ is ColumnType.DOUBLE else "long")
        return self._vcol_for(e)

    def _make_agg(self, e: FuncCall) -> str:
        """Aggregate call -> IR aggregation (deduped); returns output name."""
        k = _key(e)
        if k in self._agg_by_key:
            return self._agg_by_key[k]
        name = self._name_for(e)
        fn = e.name
        if fn == "count" and not e.args:
            self.aggs.append(CountAggregation(name))
        elif fn in ("sum", "min", "max"):
            if len(e.args) != 1:
                raise RewriteError(f"{fn} takes one argument")
            arg = e.args[0]
            if fn == "sum" and self._case_to_filter(arg, name):
                pass  # sum(CASE WHEN c THEN x ELSE 0) -> filtered agg
            else:
                fieldn, vt = self._agg_field(arg)
                cls = {"sum": SumAggregation, "min": MinAggregation,
                       "max": MaxAggregation}[fn]
                self.aggs.append(cls(name, fieldn, vt))
        elif fn == "count":  # count(col): non-null count
            fieldn, _ = self._agg_field(e.args[0])
            from tpu_olap.ir.aggregations import FilteredAggregation
            self.aggs.append(FilteredAggregation(
                F.NotFilter(F.SelectorFilter(fieldn, None)),
                CountAggregation(name)))
        elif fn in ("count_distinct", "approx_count_distinct"):
            if fn == "count_distinct" and not self.config.allow_count_distinct:
                raise RewriteError(
                    "COUNT(DISTINCT) disabled (allow_count_distinct=False); "
                    "exact distinct runs on the fallback path")
            cols = []
            for a in e.args:
                if not isinstance(a, Col):
                    raise RewriteError("COUNT(DISTINCT expr) not supported")
                cols.append(self._check_col(a.name))
            self.aggs.append(CardinalityAggregation(name, tuple(cols),
                                                    by_row=len(cols) > 1))
        elif fn == "theta_sketch":
            if len(e.args) != 1:
                raise RewriteError("theta_sketch takes one column")
            col = self._filter_col(e.args[0])
            self.aggs.append(ThetaSketchAggregation(name, col))
        elif fn == "avg":
            fieldn, vt = self._agg_field(e.args[0])
            s = next(self._names)
            c = next(self._names)
            self.aggs.append(SumAggregation(s, fieldn, vt))
            self.aggs.append(CountAggregation(c))
            # "quotient": a GLOBAL aggregate over zero matching rows
            # still emits its one row, and AVG of nothing is NULL per
            # SQL — the "/" post-agg's x/0 -> 0 rule would say 0
            # (grouped rows always have count >= 1, so no difference
            # there; found by fuzz seed 664)
            self.postaggs.append(ArithmeticPostAgg(
                name, "quotient",
                (FieldAccessPostAgg(s), FieldAccessPostAgg(c))))
        elif fn == "agg_filter":
            # standard-SQL `agg(...) FILTER (WHERE cond)` -> the IR's
            # FilteredAggregation (SURVEY.md §3.3 "filtered aggregator")
            self._make_filtered_agg(e, name)
        else:
            raise RewriteError(f"unknown aggregate {fn!r}")
        self._agg_by_key[k] = name
        return name

    def _case_to_filter(self, arg, name: str) -> bool:
        """sum(CASE WHEN cond THEN x ELSE 0 END) -> filtered aggregator
        (Druid's own translation). Lets conditions over STRING columns
        ride the filter machinery — as a virtual-column expression the
        string codes would be rejected. Returns True when handled."""
        from tpu_olap.ir.aggregations import FilteredAggregation
        if not (isinstance(arg, FuncCall) and arg.name == "if"
                and len(arg.args) == 3):
            return False
        cond, then, other = arg.args
        # ELSE 0 only: with ELSE NULL an all-non-matching group sums to
        # SQL NULL, not the filtered aggregator's empty-sum 0
        if not (isinstance(other, Lit) and other.value == 0
                and other.value is not False):
            return False
        try:
            fs = self._to_filter(cond)
        except RewriteError:
            return False  # condition outside the filter algebra
        if isinstance(then, Lit) and then.value == 1 \
                and then.value is not True:
            self.aggs.append(FilteredAggregation(fs, CountAggregation(name)))
            return True
        if isinstance(then, Lit):
            return False  # sum of a non-unit constant: no direct agg
        fieldn, vt = self._agg_field(then)
        self.aggs.append(FilteredAggregation(
            fs, SumAggregation(name, fieldn, vt)))
        return True

    def _make_filtered_agg(self, e: FuncCall, name: str) -> None:
        import dataclasses

        from tpu_olap.ir.aggregations import FilteredAggregation
        inner, cond = e.args
        if not isinstance(inner, FuncCall) or inner.name == "agg_filter":
            raise RewriteError("FILTER must wrap a single plain aggregate")
        fs = self._to_filter(cond)
        if inner.name == "avg":
            # filtered avg = filtered sum / filtered row count
            fieldn, vt = self._agg_field(inner.args[0])
            s = next(self._names)
            c = next(self._names)
            self.aggs.append(FilteredAggregation(
                fs, SumAggregation(s, fieldn, vt)))
            self.aggs.append(FilteredAggregation(fs, CountAggregation(c)))
            # "quotient" (true division): a group with NO filter-matching
            # rows divides 0 by 0 and must render NULL per SQL AVG
            # semantics — the "/" post-agg's x/0 -> 0 rule would say 0
            self.postaggs.append(ArithmeticPostAgg(
                name, "quotient",
                (FieldAccessPostAgg(s), FieldAccessPostAgg(c))))
            return
        # build the inner spec through the normal path, then re-own it:
        # pop it if newly created (and forget its dedup entry so a later
        # unfiltered use gets its own), or clone it if it was shared
        ik = _key(inner)
        fresh = ik not in self._agg_by_key
        n_before = len(self.aggs)
        inner_name = self._make_agg(inner)
        if fresh and len(self.aggs) == n_before + 1:
            spec = self.aggs.pop()
            del self._agg_by_key[ik]
        else:
            spec = next(
                a for a in self.aggs
                if (a.aggregator.name if isinstance(a, FilteredAggregation)
                    else a.name) == inner_name)
        if isinstance(spec, FilteredAggregation):
            # count(col) lowers to a not-null-filtered count: AND the two
            base = dataclasses.replace(spec.aggregator, name=name)
            self.aggs.append(FilteredAggregation(
                F.and_of(fs, spec.filter), base))
        else:
            self.aggs.append(FilteredAggregation(
                fs, dataclasses.replace(spec, name=name)))

    def _agg_output(self, e: Expr) -> str:
        """Projection expr (aggregate or arithmetic over aggregates) ->
        output name, creating aggs/post-aggs as needed."""
        if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
            return self._make_agg(e)
        k = _key(e)
        if k in self._agg_by_key:
            return self._agg_by_key[k]
        name = self._name_for(e)
        self.postaggs.append(self._to_postagg(e, name))
        self._agg_by_key[k] = name
        return name

    _THETA_SET_FNS = {"theta_sketch_intersect": "INTERSECT",
                      "theta_sketch_union": "UNION",
                      "theta_sketch_not": "NOT"}

    def _to_postagg(self, e: Expr, name: str = ""):
        if isinstance(e, Lit):
            return ConstantPostAgg(float(e.value), name)
        if isinstance(e, FuncCall) and e.name in self._THETA_SET_FNS:
            return self._theta_setop(e, name)
        if isinstance(e, FuncCall) and e.name == "theta_sketch_estimate" \
                and len(e.args) == 1:
            from tpu_olap.ir.postaggs import ThetaSketchEstimatePostAgg
            inner = e.args[0]
            if isinstance(inner, FuncCall) and \
                    inner.name in self._THETA_SET_FNS:
                return ThetaSketchEstimatePostAgg(
                    "", name, self._theta_setop(inner))
            return ThetaSketchEstimatePostAgg(
                self._theta_field(inner, "theta_sketch_estimate"), name)
        if isinstance(e, FuncCall) and e.name in AGG_FUNCS:
            return FieldAccessPostAgg(self._make_agg(e), name)
        if isinstance(e, BinOp) and e.op in ("+", "-", "*", "/"):
            return ArithmeticPostAgg(name, e.op,
                                     (self._to_postagg(e.left),
                                      self._to_postagg(e.right)))
        raise RewriteError(f"cannot translate aggregate expression {e!r}")

    def _theta_field(self, e: Expr, ctx: str) -> str:
        """An argument of `ctx` must BE a theta sketch: either
        theta_sketch(col) or theta_sketch(col) FILTER (WHERE ...)."""
        inner = e
        if isinstance(e, FuncCall) and e.name == "agg_filter":
            inner = e.args[0]
        if not (isinstance(inner, FuncCall)
                and inner.name == "theta_sketch"):
            raise RewriteError(
                f"{ctx} takes theta_sketch(...) arguments "
                f"(optionally with FILTER), got {inner!r}")
        return self._make_agg(e)

    def _theta_setop(self, e: FuncCall, name: str = ""):
        """SQL spelling of the datasketches set ops (SURVEY.md §3.3):
        theta_sketch_intersect/union/not over theta sketches -> the
        thetaSketchSetOp post-aggregation tree."""
        from tpu_olap.ir.postaggs import ThetaSketchSetOpPostAgg
        if len(e.args) < 2:
            raise RewriteError(f"{e.name} takes at least two arguments")
        fields = []
        for a in e.args:
            if isinstance(a, FuncCall) and a.name in self._THETA_SET_FNS:
                fields.append(self._theta_setop(a))
            else:
                fields.append(FieldAccessPostAgg(
                    self._theta_field(a, e.name)))
        return ThetaSketchSetOpPostAgg(self._THETA_SET_FNS[e.name],
                                       tuple(fields), name)

    # ------------------------------------------------------------- group by

    def _classify_groups(self, group_exprs):
        """Group exprs -> (dimension specs, granularity, time outputs)."""
        dims = []
        granularity = AllGranularity()
        outputs = {}  # expr key -> OutputColumn
        trunc_seen = False
        for e in group_exprs:
            alias = self.alias_of.get(_key(e))
            if isinstance(e, Col):
                col = self._check_col(e.name)
                if col == TIME_COLUMN:
                    raise RewriteError("GROUP BY raw __time not supported "
                                       "(use date_trunc)")
                name = alias or col
                dims.append(DefaultDimensionSpec(col, name))
                outputs[_key(e)] = OutputColumn(name, name)
                continue
            if isinstance(e, FuncCall) and e.name in _TIME_FUNCS and \
                    len(e.args) == 1 and e.args[0] == Col(TIME_COLUMN):
                fmt, cast = _TIME_FUNCS[e.name]
                name = alias or _render(e)  # match fallback auto-naming
                dims.append(ExtractionDimensionSpec(
                    TIME_COLUMN,
                    TimeFormatExtractionFn(fmt, self.config.time_zone),
                    name))
                outputs[_key(e)] = OutputColumn(name, name, cast)
                continue
            ext = self._extraction_of(e)
            if ext is not None:
                col, fn = ext
                name = alias or _render(e)
                dims.append(ExtractionDimensionSpec(col, fn, name))
                outputs[_key(e)] = OutputColumn(name, name)
                continue
            if isinstance(e, FuncCall) and e.name == "date_trunc" and \
                    len(e.args) == 2 and isinstance(e.args[0], Lit) and \
                    e.args[1] == Col(TIME_COLUMN):
                unit = str(e.args[0].value).lower()
                if unit not in _TRUNC_UNITS:
                    raise RewriteError(f"unknown date_trunc unit {unit!r}")
                if trunc_seen:
                    raise RewriteError("multiple date_trunc group columns")
                trunc_seen = True
                granularity = PeriodGranularity(_TRUNC_UNITS[unit],
                                                self.config.time_zone)
                name = alias or _render(e)  # match fallback auto-naming
                outputs[_key(e)] = OutputColumn(name, "timestamp",
                                                "datetime")
                continue
            if isinstance(e, (BinOp, FuncCall)) and not _contains_agg(e) \
                    and not _mentions_time_fn(e) \
                    and TIME_COLUMN not in e.columns():
                # GROUP BY <integer expression> (histogram bucketing):
                # lower as a virtual column + dense numeric dimension;
                # _vcol_for types it, and anything non-LONG (division,
                # float literals, string inputs) rejects into fallback
                vname, vt = self._vcol_for(e)
                if vt != "long":
                    raise RewriteError(
                        f"GROUP BY expression {_render(e)!r} is not "
                        "integer-typed")
                name = alias or _render(e)
                dims.append(DefaultDimensionSpec(vname, name))
                outputs[_key(e)] = OutputColumn(name, name)
                continue
            raise RewriteError(f"cannot group by {e!r}")
        return dims, granularity, outputs

    # ------------------------------------------------------------- builders

    def _build_agg(self, projections, group_exprs, filter_spec, intervals):
        dims, granularity, group_outputs = \
            self._classify_groups(group_exprs)

        outputs = []
        for e, alias in projections:
            k = _key(e)
            if k in group_outputs:
                oc = group_outputs[k]
                outputs.append(OutputColumn(alias or oc.name, oc.source,
                                            oc.cast))
            elif _contains_agg(e):
                name = self._agg_output(e)
                outputs.append(OutputColumn(alias or _render(e), name))
            else:
                raise RewriteError(
                    f"projection {_render(e)} is neither grouped nor "
                    "aggregated")

        having_spec = None
        if self.stmt.having is not None:
            having_spec = self._to_having(self._resolve(self.stmt.having))

        limit_spec, topn = self._limit_transform(dims, granularity, outputs,
                                                 group_outputs)

        common = dict(
            data_source=self.entry.name,
            intervals=intervals,
            filter=filter_spec,
            virtual_columns=tuple(self.vcols),
            # SQL GROUP BY emits only non-empty buckets; but a global
            # aggregate (granularity=all, no dims) must emit its one row
            # even when nothing matches
            context=(("skipEmptyBuckets",
                      not isinstance(granularity, AllGranularity)),),
        )
        if not dims and having_spec is not None and \
                isinstance(granularity, AllGranularity):
            # a GLOBAL aggregate emits its one row even over empty input,
            # and HAVING then filters that row — the groupBy assembler
            # drops empty groups, and timeseries has no having clause,
            # so neither device shape preserves the semantics
            raise RewriteError(
                "global aggregate with HAVING executes on the fallback")
        if topn is not None and having_spec is None:
            metric, threshold, inverted = topn
            query = TopNQuerySpec(
                dimension=dims[0], metric=metric, threshold=threshold,
                inverted=inverted, granularity=granularity,
                aggregations=tuple(self.aggs),
                post_aggregations=tuple(self.postaggs), **common)
        elif not dims and limit_spec is None and having_spec is None:
            # HAVING forces the GroupBy shape: Druid's timeseries query
            # has no having clause, so lowering one here would silently
            # drop the filter (found by fuzz seed 1300 — a HAVING over a
            # rarely-zero aggregate made the drop visible)
            query = TimeseriesQuerySpec(
                granularity=granularity, aggregations=tuple(self.aggs),
                post_aggregations=tuple(self.postaggs), **common)
        else:
            query = GroupByQuerySpec(
                dimensions=tuple(dims), granularity=granularity,
                aggregations=tuple(self.aggs),
                post_aggregations=tuple(self.postaggs),
                having=having_spec, limit_spec=limit_spec, **common)
        self.result.query = query
        self.result.outputs = outputs

    def _to_having(self, e):
        if isinstance(e, BinOp) and e.op == "&&":
            return AndHaving((self._to_having(e.left),
                              self._to_having(e.right)))
        if isinstance(e, BinOp) and e.op == "||":
            return OrHaving((self._to_having(e.left),
                             self._to_having(e.right)))
        if isinstance(e, FuncCall) and e.name == "not":
            return NotHaving(self._to_having(e.args[0]))
        if isinstance(e, BinOp) and e.op in _CMP:
            left, right, op = e.left, e.right, e.op
            if isinstance(left, Lit):
                left, right = right, left
                op = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}.get(op, op)
            if isinstance(left, Col) and not _contains_agg(left):
                # HAVING may address an aggregate by its projection alias
                # (Druid havingSpec names output aggregations)
                for pe, alias in self.stmt.projections:
                    if alias == left.name and _contains_agg(pe):
                        left = self._resolve(pe)
                        break
            if not isinstance(right, Lit) or not _contains_agg(left):
                raise RewriteError(f"HAVING predicate not on an aggregate: "
                                   f"{_render(e)}")
            name = self._agg_output(left)
            v = float(right.value)
            if op == ">":
                return GreaterThanHaving(name, v)
            if op == "<":
                return LessThanHaving(name, v)
            if op == "==":
                return EqualToHaving(name, v)
            if op == ">=":
                return NotHaving(LessThanHaving(name, v))
            if op == "<=":
                return NotHaving(GreaterThanHaving(name, v))
            if op == "!=":
                return NotHaving(EqualToHaving(name, v))
        raise RewriteError(f"cannot translate HAVING {_render(e)}")

    def _limit_transform(self, dims, granularity, outputs,
                         group_outputs=None):
        """ORDER BY + LIMIT -> LimitSpec; TopN eligibility per the
        reference's allowTopN rule (SURVEY.md §3.2 LimitTransform)."""
        stmt = self.stmt
        if not stmt.order_by and stmt.limit is None:
            return None, None
        by_source = {}
        for o in outputs:
            by_source.setdefault(o.name, o.source)
            by_source.setdefault(o.source, o.source)
        # ORDER BY a grouped EXPRESSION (e.g. the source column of an
        # aliased dim): resolve through the group-expr key map, not just
        # output names
        group_by_key = {k: oc.source
                        for k, oc in (group_outputs or {}).items()}
        cols = []
        for item in stmt.order_by:
            if item.nulls is not None:
                raise RewriteError(
                    "explicit NULLS FIRST/LAST ordering executes on the "
                    "fallback path")
            e = self._resolve(item.expr)
            key = _key(e)
            if key in self._agg_by_key:
                src = self._agg_by_key[key]
            elif isinstance(e, Col) and e.name in by_source:
                src = by_source[e.name]
            elif isinstance(item.expr, Col) and \
                    item.expr.name.split(".")[-1] in by_source:
                # the written name: star-join renames (r_name -> c_region)
                # resolve the expr away from the output header it matches
                src = by_source[item.expr.name.split(".")[-1]]
            elif key in group_by_key:
                src = group_by_key[key]
            elif _contains_agg(e):
                src = self._agg_output(e)
            else:
                raise RewriteError(
                    f"ORDER BY {_render(e)} is not an output column")
            dim_names = {d.name for d in dims}
            # physical columns take precedence over same-named virtual
            # columns (mirrors compile_dimension's resolution order)
            vlong = {v.name for v in self.vcols if v.output_type == "long"}
            long_dims = {d.name for d in dims
                         if isinstance(d, DefaultDimensionSpec)
                         and (self.table.schema.get(d.dimension)
                              is ColumnType.LONG
                              or (d.dimension not in self.table.schema
                                  and d.dimension in vlong))}
            order = ("lexicographic"
                     if src in dim_names and src not in long_dims
                     else "numeric")
            cols.append(OrderByColumnSpec(
                src, "descending" if item.descending else "ascending",
                order))
        limit_spec = LimitSpec(stmt.limit, tuple(cols), stmt.offset)

        topn = None
        agg_names = {a.name for a in self.aggs} | \
            {p.name for p in self.postaggs}
        if (self.config.allow_topn and len(dims) == 1
                and isinstance(granularity, AllGranularity)
                and stmt.limit is not None and stmt.offset == 0
                and stmt.limit <= self.config.topn_max_threshold
                and len(cols) == 1 and cols[0].dimension in agg_names):
            topn = (cols[0].dimension, stmt.limit,
                    cols[0].direction == "ascending")
        return limit_spec, topn

    def _build_scan(self, projections, filter_spec, intervals):
        cols = []
        outputs = []
        for e, alias in projections:
            if isinstance(e, Col) and e.name == "*":
                for c in self.table.schema:
                    cols.append(c)
                    outputs.append(OutputColumn(c, c))
                continue
            if not isinstance(e, Col):
                raise RewriteError(
                    "computed projections without GROUP BY are not pushed "
                    "down")
            c = self._check_col(e.name)
            cols.append(c)
            outputs.append(OutputColumn(alias or e.name, c))
        order = "none"
        if self.stmt.order_by:
            if len(self.stmt.order_by) != 1:
                raise RewriteError("scan ORDER BY must be the time column")
            item = self.stmt.order_by[0]
            e = self._resolve(item.expr)
            if e != Col(TIME_COLUMN):
                raise RewriteError("scan ORDER BY must be the time column")
            order = "descending" if item.descending else "ascending"
        query = ScanQuerySpec(
            data_source=self.entry.name,
            intervals=intervals,
            filter=filter_spec,
            virtual_columns=tuple(self.vcols),
            columns=tuple(cols),
            limit=self.stmt.limit,
            offset=self.stmt.offset,
            order=order,
        )
        self.result.query = query
        self.result.outputs = outputs


# ---------------------------------------------------------------------------


def _equi_join_cols(e):
    if isinstance(e, BinOp) and e.op == "==" and \
            isinstance(e.left, Col) and isinstance(e.right, Col):
        return (e.left.name.split(".")[-1], e.right.name.split(".")[-1])
    return None


def _mentions_time_fn(e) -> bool:
    if isinstance(e, FuncCall):
        if e.name in _TIME_FUNCS or e.name == "date_trunc":
            if any(Col(TIME_COLUMN) == a for a in e.args):
                return True
        return any(_mentions_time_fn(a) for a in e.args)
    if isinstance(e, BinOp):
        return _mentions_time_fn(e.left) or _mentions_time_fn(e.right)
    if isinstance(e, Col):
        return False
    return False


def _has_division(e) -> bool:
    if isinstance(e, BinOp):
        return e.op == "/" or _has_division(e.left) or _has_division(e.right)
    if isinstance(e, FuncCall):
        return any(_has_division(a) for a in e.args)
    return False


def _has_float_lit(e) -> bool:
    if isinstance(e, Lit):
        return isinstance(e.value, float)
    if isinstance(e, BinOp):
        return _has_float_lit(e.left) or _has_float_lit(e.right)
    if isinstance(e, FuncCall):
        return any(_has_float_lit(a) for a in e.args)
    return False


def _has_cast_double(e) -> bool:
    if isinstance(e, FuncCall):
        return e.name == "cast_double" or \
            any(_has_cast_double(a) for a in e.args)
    if isinstance(e, BinOp):
        return _has_cast_double(e.left) or _has_cast_double(e.right)
    return False


def _check_device_expr(e) -> None:
    """Reject expressions the device evaluator cannot run (unknown
    functions, NULL literals from CASE-without-ELSE) so the planner falls
    back cleanly instead of failing inside a jitted kernel."""
    if isinstance(e, Lit):
        if e.value is None:
            raise RewriteError(
                "NULL literal inside a device expression (add an ELSE "
                "branch to CASE)")
        return
    if isinstance(e, Col):
        return
    if isinstance(e, BinOp):
        _check_device_expr(e.left)
        _check_device_expr(e.right)
        return
    if isinstance(e, FuncCall):
        if e.name not in _DEVICE_FUNCS:
            raise RewriteError(
                f"function {e.name!r} not supported in device expressions")
        for a in e.args:
            _check_device_expr(a)
        return
    raise RewriteError(f"cannot compile expression {e!r}")


